"""North-star benchmark: batched BLS signature-set verification on TPU.

ALWAYS prints exactly ONE JSON line {"metric", "value", "unit",
"vs_baseline", ...} and exits 0 -- the orchestrator never lets a flaky
backend, a compile timeout, or a kernel bug turn into a missing artifact.

Metric: aggregate-attestation signature sets verified per second on one
chip, against the BASELINE.md target ("batch-verify 10k aggregate
attestation signatures in <200 ms on a single TPU v4 chip", i.e. 50k
sets/s). vs_baseline = achieved_sets_per_s / 50_000.

Structure (the parent process never imports jax):
  1. PROBE: a subprocess checks backend init (`jax.devices()`), retried
     with backoff for up to ~3 minutes -- the TPU tunnel is known to flap.
  2. RUN: a subprocess runs the measured bench on the probed platform and
     prints its own JSON (compile time and steady-state time separated).
  3. FALLBACK: on any failure, re-run the child forced to CPU (smaller
     batch -- CPU pairing math is slow) and record the TPU failure in an
     "error" field. Even total failure emits value 0.0.

CPU forcing is done via `jax.config.update("jax_platforms", "cpu")` in
the child, NOT the JAX_PLATFORMS env var: the axon sitecustomize
registers its backend at interpreter start and the env var is captured
too early to override it (same rationale as tests/conftest.py).

Methodology: one warm jitted call over a bucket of synthetic
fast_aggregate_verify sets (distinct messages, multi-pubkey aggregates,
pre-marshaled device inputs -- steady-state marshaling is index gathers
from the device-resident pubkey table, so the kernel is the contract).
Fixtures are generated once via the pure-Python oracle, disk-cached under
.bench_fixtures/, and tiled to the requested batch size (tiling valid
sets keeps the batch valid and the per-set device work identical).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
TARGET_SETS_PER_S = 10_000 / 0.200  # BASELINE.md north star
LAST_TPU_PATH = os.path.join(HERE, ".bench_last_tpu.json")


def _emit(payload: dict) -> None:
    print(json.dumps(payload))
    sys.stdout.flush()


def _load_last_tpu() -> dict | None:
    """Most recent real-TPU measurement, persisted across runs so a tunnel
    flap during the driver window still yields a TPU-attributed number
    (clearly labeled as historical, with its capture time)."""
    try:
        with open(LAST_TPU_PATH) as f:
            obj = json.load(f)
        return obj if isinstance(obj, dict) else None
    except (OSError, json.JSONDecodeError):
        return None


def _attach_last_tpu(payload: dict) -> dict:
    last = _load_last_tpu()
    if last is not None:
        payload["last_known_tpu"] = last
    return payload


def _save_last_tpu(result: dict) -> None:
    try:
        with open(LAST_TPU_PATH, "w") as f:
            json.dump(result, f)
    except OSError:
        pass


def _run_child(mode: str, env_extra: dict, timeout_s: float):
    """Run `bench.py --<mode>` in a subprocess; return (ok, json|None, err)."""
    env = dict(os.environ)
    env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), f"--{mode}"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
            cwd=HERE,
        )
    except subprocess.TimeoutExpired:
        return False, None, f"{mode} timed out after {int(timeout_s)}s"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
        return False, None, f"{mode} rc={proc.returncode}: {' | '.join(tail)}"
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            return True, obj, None
    return False, None, f"{mode} produced no JSON"


def orchestrate() -> None:
    budget = float(os.environ.get("BENCH_BUDGET_S", "720"))
    t_start = time.monotonic()

    def remaining() -> float:
        return budget - (time.monotonic() - t_start)

    errors = []
    timed_out = []

    def run_phase(name: str, mode: str, env_extra: dict, timeout_s: float):
        """_run_child plus phase-timeout bookkeeping: a phase that hits
        its time box lands in `timed_out` (surfaced in the artifact) and
        the orchestrator moves on -- partial results, never a dead run."""
        ok, obj, err = _run_child(mode, env_extra, timeout_s)
        if not ok and err and "timed out" in err:
            timed_out.append(name)
        return ok, obj, err

    # Phase 0: warm the CPU fallback BEFORE probing. BENCH_r05 starved:
    # six 75 s probes ate the window, then the cold fallback paid 70.8 s
    # of XLA compile inside its reserve. Running the CPU child first (a)
    # persists its executables to .jax_cache so any later fallback is
    # load+run, (b) measures compile/steady cost so the probe budget is
    # sized from DATA, and (c) doubles as the fallback measurement -- if
    # the tunnel never comes up, the warm result IS the artifact and no
    # reserve slice is needed at all.
    warm = None
    if os.environ.get("BENCH_SKIP_WARM") != "1":
        warm_timeout = min(
            float(os.environ.get("BENCH_WARM_TIMEOUT_S", "300")),
            max(45.0, remaining() - 150.0),
        )
        ok, warm, err = run_phase(
            "warm", "child",
            {
                "BENCH_PLATFORM": "cpu",
                "BENCH_SETS": os.environ.get("BENCH_SETS_CPU", "16"),
                "BENCH_REPS": os.environ.get("BENCH_REPS_CPU", "2"),
            },
            timeout_s=warm_timeout,
        )
        if not ok:
            errors.append(f"warm: {err}")
            warm = None

    # Phase 1: probe backend init with retry/backoff (the tunnel flaps on
    # hours timescales; round 4 lost its TPU artifact to a 170 s probe
    # window). With a warm result banked the fallback reserve shrinks to
    # an emission buffer and the probes get the rest of the budget;
    # without one, keep a reserve sized off the measured compile cost
    # (cache now warm: load+run, not a cold compile).
    platform = None
    probe_timeout = 75.0
    if warm is not None:
        fallback_reserve = 10.0
    else:
        fallback_reserve = float(
            os.environ.get("BENCH_FALLBACK_RESERVE_S", "150")
        )
    probe_deadline = max(probe_timeout, budget - fallback_reserve)
    attempt = 0
    while remaining() > 30.0:
        elapsed = time.monotonic() - t_start
        # always probe at least once; retries must fit the probe window
        if attempt > 0 and elapsed + probe_timeout > probe_deadline:
            break
        attempt += 1
        ok, info, err = run_phase(
            f"probe#{attempt}", "probe",
            {},
            timeout_s=min(probe_timeout, max(20.0, remaining() - 20.0)),
        )
        if ok and info and info.get("platform"):
            platform = info["platform"]
            break
        errors.append(f"probe#{attempt}: {err}")
        time.sleep(10.0)

    # Phase 2: measured run on the probed platform. A cache-warm TPU child
    # needs ~120 s minimum; if a late probe success leaves less than that
    # PLUS the fallback reserve, skip straight to the fallback — starting
    # a doomed TPU run would eat the reserve and lose the artifact.
    result = None
    if platform and platform != "cpu":
        if remaining() < 120.0 + fallback_reserve:
            errors.append(
                "tpu-run: skipped (tunnel up late; "
                f"{int(remaining())}s left < child+fallback budget)"
            )
        else:
            ok, result, err = run_phase(
                "tpu-run", "child",
                {},
                timeout_s=min(
                    max(120.0, remaining() - fallback_reserve),
                    max(30.0, remaining() - 5.0),
                ),
            )
            if not ok:
                errors.append(f"tpu-run: {err}")
                result = None
    elif platform == "cpu":
        # Ambient platform is already CPU: the phase-0 warm run doubles
        # as the primary measurement ONLY if it ran the shape the
        # operator asked for; otherwise honor BENCH_SETS[_CPU] with a
        # fresh run (against the now-warmer cache).
        want_sets = os.environ.get(
            "BENCH_SETS_CPU", os.environ.get("BENCH_SETS", "64")
        )
        if warm is not None and warm.get("n_sets") == int(want_sets):
            result = warm
        else:
            ok, result, err = run_phase(
                "cpu-run", "child",
                {"BENCH_SETS": want_sets},
                timeout_s=max(30.0, remaining() - 5.0),
            )
            if not ok:
                errors.append(f"cpu-run: {err}")
                result = warm  # the small-shape number beats no number

    # Phase 3: CPU fallback if the TPU path yielded nothing. The banked
    # warm measurement serves directly; a rerun happens only when warming
    # failed (and then against the cache the failed warm may still have
    # partially populated).
    if result is None and platform != "cpu":
        if warm is not None:
            result = warm
        else:
            ok, result, err = run_phase(
                "cpu-fallback", "child",
                {
                    "BENCH_PLATFORM": "cpu",
                    # 16 sets: a shape kept warm in .jax_cache/cpu so the
                    # fallback is load+run, not a 6-minute XLA compile
                    "BENCH_SETS": os.environ.get("BENCH_SETS_CPU", "16"),
                    "BENCH_REPS": os.environ.get("BENCH_REPS_CPU", "2"),
                },
                timeout_s=max(30.0, remaining() - 5.0),
            )
            if not ok:
                errors.append(f"cpu-fallback: {err}")
                result = None

    if result is None:
        _emit(
            _attach_last_tpu(
                {
                    "metric": "bls_signature_sets_verified_per_s_per_chip",
                    "value": 0.0,
                    "unit": "sets/s",
                    "vs_baseline": 0.0,
                    "platform": platform or "none",
                    "error": "; ".join(errors) or "unknown",
                    "timed_out": timed_out,
                }
            )
        )
        return

    # Phase 4: mainnet-shaped traffic profile -- the SAME batch through
    # the per-set path and the message-aggregated mega-pairing, with
    # pairing counts and the aggregation ratio in the artifact (ISSUE 6).
    # Its own child + time box: a slow profile compile can degrade the
    # artifact's profile field, never lose the main measurement.
    if os.environ.get("BENCH_PROFILE") != "0":
        prof_timeout = min(
            float(os.environ.get("BENCH_PROFILE_TIMEOUT_S", "300")),
            remaining() - 10.0,
        )
        if prof_timeout > 30.0:
            env_extra = {}
            if result.get("platform") != "tpu":
                env_extra["BENCH_PLATFORM"] = "cpu"
            ok, prof, err = run_phase(
                "profile", "profile", env_extra, timeout_s=prof_timeout
            )
            if ok:
                result["mainnet_profile"] = prof
            else:
                errors.append(f"profile: {err}")
                result["mainnet_profile"] = {"error": err}
        else:
            result["mainnet_profile"] = {
                "error": "skipped (budget exhausted)"
            }

    if result.get("platform") == "tpu":
        # persist for future flapped runs (timestamped: it is historical
        # context in any artifact it later appears in, not a fresh number)
        saved = dict(result)
        saved["measured_at_unix"] = int(time.time())
        _save_last_tpu(saved)
    else:
        _attach_last_tpu(result)
    if errors:
        result["error"] = "; ".join(errors)
    if timed_out:
        result["timed_out"] = timed_out
    _emit(result)


def _force_platform() -> None:
    """Apply BENCH_PLATFORM=cpu via the live config (env vars are captured
    before the axon sitecustomize override and do not work)."""
    if os.environ.get("BENCH_PLATFORM") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def probe() -> None:
    import jax

    _force_platform()
    devs = jax.devices()
    _emit({"platform": devs[0].platform, "n_devices": len(devs)})


def child() -> None:
    n_sets = int(os.environ.get("BENCH_SETS", "1024"))
    k_pk = int(os.environ.get("BENCH_PUBKEYS_PER_SET", "2"))
    reps = int(os.environ.get("BENCH_REPS", "3"))
    distinct = int(os.environ.get("BENCH_DISTINCT", "32"))

    sys.path.insert(0, HERE)
    import jax

    _force_platform()
    from __graft_entry__ import _arm_compilation_cache, _example_batch

    _arm_compilation_cache()
    from lighthouse_tpu.crypto.bls.backends.jax_tpu import (
        _bucket,
        verify_device,
    )

    t0 = time.perf_counter()
    args = _example_batch(n_sets, k_pk, distinct=distinct, dedup=True)
    fixture_s = time.perf_counter() - t0

    # Compile + warm, retried: the remote compile endpoint drops long
    # requests, but every stage that compiles persists to .jax_cache, so a
    # retry resumes at the first uncompiled stage (the staged pipeline
    # exists exactly for this).
    t0 = time.perf_counter()
    last = None
    for _ in range(max(1, int(os.environ.get("BENCH_COMPILE_RETRIES", "4")))):
        try:
            ok = bool(jax.block_until_ready(verify_device(*args)))
            last = None
            break
        except Exception as exc:  # noqa: BLE001 -- remote compile flake
            last = exc
    if last is not None:
        raise last
    compile_s = time.perf_counter() - t0
    assert ok, "bench batch failed to verify"

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(verify_device(*args))
        times.append(time.perf_counter() - t0)
    best = min(times)
    sets_per_s = n_sets / best

    # Pipelined throughput + counters: the same warm kernel driven
    # through the async VerifyPipeline (double-buffered submit_call), so
    # the artifact carries the pipeline's observable surface — depth,
    # occupancy high-water, batch count — next to the blocking number.
    # The run also exports a Chrome trace artifact (utils/tracing.py):
    # pipeline submit/resolve spans on a real wall clock, loadable in
    # Perfetto next to the JSON number.
    import random as _random

    from lighthouse_tpu.crypto.bls.pipeline import VerifyPipeline
    from lighthouse_tpu.obs import ledger as launch_ledger
    from lighthouse_tpu.utils import metrics as M
    from lighthouse_tpu.utils import tracing

    class _PerfClock:
        # bench is an injection boundary: wall time enters HERE and is
        # handed to the tracer as an injected clock
        def now(self):
            return time.perf_counter()

    tracer = tracing.configure(clock=_PerfClock(), rng=_random.Random(0))
    led = launch_ledger.configure()  # rides the same injected clock
    trace_path = os.path.join(HERE, ".bench_trace.json")

    pipe_batches = int(os.environ.get("BENCH_PIPELINE_BATCHES", "4"))
    pipe = VerifyPipeline(depth=2)  # spans ride the configured tracer
    t0 = time.perf_counter()
    with tracer.span("bench_pipeline", batches=pipe_batches, sets=n_sets):
        futs = [
            pipe.submit_call(verify_device, *args, n_sets=n_sets)
            for _ in range(pipe_batches)
        ]
        pipe_ok = all(f.result() for f in futs)
    pipe_s = time.perf_counter() - t0
    try:
        # one Perfetto document: the span "X" events plus the ledger's
        # per-kind counter tracks (real vs padded set counts over time)
        trace_doc = tracer.chrome_trace()
        trace_doc["traceEvents"].extend(led.chrome_counter_events())
        with open(trace_path, "w") as f:
            f.write(json.dumps(trace_doc, sort_keys=True))
        trace_events = tracer.status()["recorded"]
    except OSError:
        trace_path, trace_events = None, 0

    _emit(
        {
            "metric": "bls_signature_sets_verified_per_s_per_chip",
            "value": round(sets_per_s, 2),
            "unit": "sets/s",
            "vs_baseline": round(sets_per_s / TARGET_SETS_PER_S, 4),
            "platform": jax.devices()[0].platform,
            "n_sets": n_sets,
            "pubkeys_per_set": k_pk,
            "distinct_messages": min(distinct, n_sets),
            "fixture_s": round(fixture_s, 2),
            "compile_s": round(compile_s, 2),
            # keyed by the dispatcher's bucketed shape (n x k x m x g;
            # g=0 is the per-set path) -- the same names `cli warm`
            # publishes on tpu_warm_compile_seconds
            "compile_s_per_bucket": {
                "x".join(
                    str(v)
                    for v in (
                        _bucket(n_sets),
                        _bucket(k_pk),
                        _bucket(min(distinct, n_sets)),
                        0,
                    )
                ): round(compile_s, 2)
            },
            "steady_s": round(best, 4),
            "pipeline": {
                "depth": int(M.BLS_PIPELINE_DEPTH.value),
                "batches": pipe_batches,
                "occupancy_peak": int(M.BLS_PIPELINE_OCCUPANCY_PEAK.value),
                "all_valid": bool(pipe_ok),
                "pipelined_sets_per_s": round(
                    pipe_batches * n_sets / pipe_s, 2
                ),
                "shard_mesh_devices": int(M.BLS_SHARD_MESH_SIZE.value),
                "bisection_calls": int(M.BLS_BISECTION_CALLS.value),
            },
            "device_telemetry": {
                "compile_cache_misses": int(
                    M.TPU_COMPILE_CACHE_MISSES.value
                ),
                "compile_cache_hits": int(M.TPU_COMPILE_CACHE_HITS.value),
                "transfer_bytes_total": int(M.TPU_TRANSFER_BYTES.value),
            },
            "trace_path": trace_path,
            "trace_events": trace_events,
        }
    )


def profile_child() -> None:
    """The mainnet-shaped traffic profile (ISSUE 6): one batch of n sets
    over d distinct messages through BOTH device layouts -- the per-set
    staged path (~n+1 Miller pairs) and the message-aggregated
    mega-pairing (~d+1 pairs) -- reporting pairing counts, the
    aggregation ratio, and the sets/s of each. Real attestation traffic
    is thousands of sets over a handful of messages, so the speedup here
    is the sets/s multiplier the aggregation buys at mainnet shapes."""
    sys.path.insert(0, HERE)
    import jax

    _force_platform()
    from __graft_entry__ import _arm_compilation_cache, _example_batch

    _arm_compilation_cache()
    from lighthouse_tpu.crypto.bls.backends.jax_tpu import (
        _bucket,
        grid_bucket,
        verify_device,
        verify_device_aggregated,
    )

    from lighthouse_tpu.obs import ledger as launch_ledger

    led = launch_ledger.configure()

    platform = jax.devices()[0].platform
    # n/m = 64 on both defaults; the CPU shape is sized to compile inside
    # the profile time box (the TPU shape is the BASELINE.md mainnet one)
    default_n, default_d = ("1024", "16") if platform == "tpu" else ("128", "2")
    n = int(os.environ.get("BENCH_PROFILE_SETS", default_n))
    d = int(os.environ.get("BENCH_PROFILE_DISTINCT", default_d))
    k = int(os.environ.get("BENCH_PUBKEYS_PER_SET", "2"))
    reps = int(os.environ.get("BENCH_REPS", "3"))
    retries = max(1, int(os.environ.get("BENCH_COMPILE_RETRIES", "4")))

    def timed(fn, args):
        """(compile+warm seconds, best steady seconds) of one layout;
        compile retried like the main child (remote-endpoint flake)."""
        t0 = time.perf_counter()
        last = None
        for _ in range(retries):
            try:
                ok = bool(jax.block_until_ready(fn(*args)))
                last = None
                break
            except Exception as exc:  # noqa: BLE001 -- remote compile flake
                last = exc
        if last is not None:
            raise last
        compile_s = time.perf_counter() - t0
        assert ok, "profile batch failed to verify"
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            times.append(time.perf_counter() - t0)
        return compile_s, min(times)

    unagg_compile, unagg_best = timed(
        verify_device, _example_batch(n, k, distinct=d, dedup=True)
    )
    agg_compile, agg_best = timed(
        verify_device_aggregated, _example_batch(n, k, distinct=d, agg=True)
    )
    # one warm-kind ledger record per timed layout, keyed like cli warm
    key_unagg = "x".join(str(v) for v in (_bucket(n), _bucket(k), _bucket(d), 0))
    key_agg = "x".join(
        str(v)
        for v in (_bucket(n), _bucket(k), _bucket(d), grid_bucket(_bucket(n)))
    )
    launch_ledger.record(
        "warm", bucket=key_unagg, real_sets=n, padded_sets=_bucket(n),
        compile_seconds=unagg_compile, cache_hit=False,
    )
    launch_ledger.record(
        "warm", bucket=key_agg, real_sets=n, padded_sets=_bucket(n),
        compile_seconds=agg_compile, cache_hit=False,
    )
    pairs_agg = _bucket(d) + 1
    _emit(
        {
            "profile": "mainnet_traffic_shape",
            "platform": platform,
            "n_sets": n,
            "distinct_messages": d,
            "pubkeys_per_set": k,
            "pairs_unaggregated": _bucket(n) + 1,
            "pairs_aggregated": pairs_agg,
            "aggregation_ratio": round(n / pairs_agg, 2),
            "unaggregated_sets_per_s": round(n / unagg_best, 2),
            "aggregated_sets_per_s": round(n / agg_best, 2),
            "speedup": round(unagg_best / agg_best, 2),
            "compile_s": {
                "unaggregated": round(unagg_compile, 2),
                "aggregated": round(agg_compile, 2),
            },
            # per-bucket compile wall next to the sets/s numbers, keyed
            # like the warm pass (n x k x m x g)
            "compile_s_per_bucket": {
                "x".join(
                    str(v)
                    for v in (_bucket(n), _bucket(k), _bucket(d), 0)
                ): round(unagg_compile, 2),
                "x".join(
                    str(v)
                    for v in (
                        _bucket(n),
                        _bucket(k),
                        _bucket(d),
                        grid_bucket(_bucket(n)),
                    )
                ): round(agg_compile, 2),
            },
            "ledger": led.stats(),
        }
    )


def speculate_bench() -> None:
    """`bench.py --speculate`: seeded slots of committee-shaped aggregate
    traffic through the REAL verification entrypoint
    (batch_verify_aggregates) with the duty-driven precompute ON vs OFF,
    reporting critical-path sets/s for both plus the
    hit/correction/miss/confirm ratios. Same artifact contract as the
    main bench: exactly ONE JSON line, exit 0 even on failure."""
    try:
        _speculate_bench_inner()
    except BaseException as exc:  # never lose the artifact
        _emit(
            {
                "metric": "speculate_aggregate_sets_per_s",
                "value": 0.0,
                "unit": "sets/s",
                "error": f"speculate bench: {type(exc).__name__}: {exc}",
            }
        )


def _speculate_bench_inner() -> None:
    sys.path.insert(0, HERE)
    _force_platform()
    from lighthouse_tpu.crypto.bls import set_backend

    # default: the pure-Python oracle backend -- every pairing is real,
    # sized small; the interesting delta (zero per-set pubkey aggregation
    # + confirm-by-lookup dropping the indexed set) is backend-agnostic
    set_backend(os.environ.get("BENCH_SPECULATE_BACKEND", "cpu"))
    from lighthouse_tpu.chain.attestation_verification import (
        batch_verify_aggregates,
    )
    from lighthouse_tpu.harness import BeaconChainHarness
    from lighthouse_tpu.pool import ObservedAggregates, ObservedAggregators
    from lighthouse_tpu.speculate import attach_speculation
    from lighthouse_tpu.state_transition import clone_state, process_slots
    from lighthouse_tpu.types import ChainSpec, MINIMAL

    validators = int(os.environ.get("BENCH_SPECULATE_VALIDATORS", "16"))
    slots = int(os.environ.get("BENCH_SPECULATE_SLOTS", "4"))
    reps = int(os.environ.get("BENCH_SPECULATE_REPS", "2"))
    seed = int(os.environ.get("BENCH_SPECULATE_SEED", "7"))

    h = BeaconChainHarness(
        validators, MINIMAL, ChainSpec.interop(), sign=True
    )
    h.extend_chain(slots + 1)
    chain = h.chain
    sub = attach_speculation(
        chain, signature_source=h.producer.aggregate_signature_source()
    )

    # seeded committee-shaped traffic: one signed aggregate per
    # (slot, committee) over the last `slots` slots, all inside the
    # gossip propagation window of the head
    state = process_slots(
        clone_state(chain.head_state),
        int(chain.head_state.slot) + 1,
        MINIMAL,
        h.spec,
    )
    from lighthouse_tpu.state_transition import ConsensusContext
    from lighthouse_tpu.types import compute_epoch_at_slot

    ctxt = ConsensusContext(MINIMAL, h.spec)
    traffic = []
    head_slot = int(chain.head_state.slot)
    for slot in range(head_slot - slots + 1, head_slot + 1):
        epoch = compute_epoch_at_slot(slot, MINIMAL)
        cache = ctxt.committee_cache(state, epoch)
        for index in range(cache.committees_per_slot):
            traffic.append(
                h.producer.make_signed_aggregate(state, slot, index)
            )
    sets_per_agg = 3  # selection proof + aggregate-and-proof + indexed

    def run_pass():
        t0 = time.perf_counter()
        verified, rejected = batch_verify_aggregates(
            chain, traffic, ObservedAggregates(), ObservedAggregators()
        )
        return time.perf_counter() - t0, len(verified), len(rejected)

    # OFF: the flag-off baseline (per-set host pubkey aggregation)
    sub.enabled = False
    off_times, off_ok = [], None
    for _ in range(reps):
        dt, nv, nr = run_pass()
        off_times.append(dt)
        off_ok = (nv, nr)

    # ON (precompute only): the memo is empty, so every aggregate rides
    # the committee-aggregate cache -- this pass yields the hit ratios
    sub.enabled = True
    pre_stats = dict(sub.precompute.stats)
    on_times, on_ok = [], None
    for _ in range(reps):
        dt, nv, nr = run_pass()
        on_times.append(dt)
        on_ok = (nv, nr)
    d_pre = {
        k: sub.precompute.stats[k] - pre_stats[k] for k in pre_stats
    }

    # ON (+speculation): pre-verify the traffic slots during "idle time",
    # then the same aggregates are confirmed by memo lookup on arrival
    ver_stats = dict(sub.verifier.stats)
    for slot in range(head_slot - slots + 1, head_slot + 1):
        sub.verifier.speculate_slot(slot)
    spec_times, spec_ok = [], None
    for _ in range(reps):
        dt, nv, nr = run_pass()
        spec_times.append(dt)
        spec_ok = (nv, nr)
    d_ver = {k: sub.verifier.stats[k] - ver_stats[k] for k in ver_stats}

    n = len(traffic)
    looked_up = max(
        1, d_pre["full_hits"] + d_pre["corrections"] + d_pre["misses"]
    )
    off_best = min(off_times)
    on_best = min(on_times)
    spec_best = min(spec_times)
    _emit(
        {
            "metric": "speculate_aggregate_sets_per_s",
            "value": round(n * sets_per_agg / spec_best, 2),
            "unit": "sets/s",
            "seed": seed,
            "validators": validators,
            "slots": slots,
            "aggregates": n,
            "verified": spec_ok,
            "verdicts_match_off_path": on_ok == off_ok == spec_ok,
            "off_sets_per_s": round(n * sets_per_agg / off_best, 2),
            "precompute_sets_per_s": round(n * sets_per_agg / on_best, 2),
            "speculate_sets_per_s": round(n * sets_per_agg / spec_best, 2),
            "precompute_speedup": round(off_best / on_best, 3),
            "speculate_speedup": round(off_best / spec_best, 3),
            "precompute": {
                "full_hit_ratio": round(d_pre["full_hits"] / looked_up, 3),
                "correction_ratio": round(
                    d_pre["corrections"] / looked_up, 3
                ),
                "miss_ratio": round(d_pre["misses"] / looked_up, 3),
            },
            "speculation": {
                "preverified": d_ver["preverified"],
                "confirms": d_ver["confirms"],
                "confirm_misses": d_ver["confirm_misses"],
                "mismatches": d_ver["mismatches"],
            },
        }
    )


def latency_bench() -> None:
    """`bench.py --latency`: bursty gossip arrivals through the
    continuous-batching scheduler vs the whole-batch baseline, reporting
    per-lane time-to-verdict p50/p95 against the replayed arrival clock
    plus the pad-waste ratio. Same artifact contract as the main bench:
    exactly ONE JSON line, exit 0 even on failure."""
    try:
        _latency_bench_inner()
    except BaseException as exc:  # never lose the artifact
        _emit(
            {
                "metric": "cont_batch_ttv_p95_speedup",
                "value": 0.0,
                "unit": "x",
                "error": f"latency bench: {type(exc).__name__}: {exc}",
            }
        )


def _percentile(samples: list[float], q: float) -> float:
    xs = sorted(samples)
    if not xs:
        return 0.0
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


def _poisson(rng, lam: float) -> int:
    """Knuth sampler -- small lambdas only (burst sizes)."""
    import math

    limit, k, p = math.exp(-lam), 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1


def _bursty_schedule(rng, slots: int, slot_s: float, burst: float):
    """Seeded arrival schedule: (offset_s, lane, n_sets, slot) tuples.
    Real lanes burst right after each slot boundary (Poisson burst
    sizes, exponentially clustered offsets -- the gossip shape); the
    block proposal lands mid-slot; speculation trickles uniformly."""
    schedule = []
    for slot in range(slots):
        t0 = slot * slot_s
        schedule.append((t0 + 0.35 * slot_s, "block", 4, slot))
        for lane, lam, spread in (
            ("aggregate", burst, 0.10),
            ("unaggregated", 2.0 * burst, 0.15),
            ("sync", 0.5 * burst, 0.10),
        ):
            for _ in range(_poisson(rng, lam)):
                off = min(rng.expovariate(1.0 / (spread * slot_s)), slot_s)
                schedule.append(
                    (t0 + off, lane, 1 + rng.randrange(3), slot)
                )
        for _ in range(2):
            schedule.append(
                (t0 + rng.random() * slot_s, "speculative", 1, slot)
            )
    schedule.sort(key=lambda a: a[0])
    return schedule


def _latency_bench_inner() -> None:
    import random
    import threading

    sys.path.insert(0, HERE)
    _force_platform()
    from lighthouse_tpu.crypto.bls import (
        SecretKey,
        SignatureSet,
        set_backend,
    )
    from lighthouse_tpu.crypto.bls import api as bls_api
    from lighthouse_tpu.crypto.bls import pipeline as bls_pipeline
    from lighthouse_tpu.crypto.bls import scheduler as bls_scheduler
    from lighthouse_tpu.obs import ledger as launch_ledger
    from lighthouse_tpu.utils import metrics as M

    # default: the fake backend. The bench measures QUEUEING dynamics
    # (batch-formation wait vs merge-at-next-boundary), which are
    # backend-agnostic; BENCH_LATENCY_BACKEND=cpu pays real pairings.
    set_backend(os.environ.get("BENCH_LATENCY_BACKEND", "fake"))

    slots = int(os.environ.get("BENCH_LATENCY_SLOTS", "8"))
    slot_s = float(os.environ.get("BENCH_LATENCY_SLOT_MS", "150")) / 1e3
    burst = float(os.environ.get("BENCH_LATENCY_BURST", "6"))
    seed = int(os.environ.get("BENCH_LATENCY_SEED", "7"))

    # a small pool of real signed sets, cycled across arrivals
    pool = []
    for i in range(16):
        sk = SecretKey(i + 1)
        msg = bytes([i]) * 32
        pool.append(
            SignatureSet.single_pubkey(sk.sign(msg), sk.public_key(), msg)
        )
    rng = random.Random(seed)
    schedule = _bursty_schedule(rng, slots, slot_s, burst)
    arrivals = [
        (off, lane, [pool[(i + j) % len(pool)] for j in range(n)], slot)
        for i, (off, lane, n, slot) in enumerate(schedule)
    ]

    def replay_baseline():
        """Whole-batch dispatch: every slot's arrivals wait for the slot
        to finish forming, then verify as ONE pipeline batch (the
        pre-scheduler seam shape). Per-arrival verdicts recover exactly
        as callers do: conjunction when True, per-arrival re-verify
        when False."""
        bls_pipeline.configure()
        lat = {i: None for i in range(len(arrivals))}
        verdicts = {}
        start = time.perf_counter()
        by_slot: dict[int, list[int]] = {}
        for i, (off, _lane, _sets, slot) in enumerate(arrivals):
            by_slot.setdefault(slot, []).append(i)
        for slot in sorted(by_slot):
            boundary = (slot + 1) * slot_s
            now = time.perf_counter() - start
            if now < boundary:
                time.sleep(boundary - now)
            merged = [s for i in by_slot[slot] for s in arrivals[i][2]]
            ok = bls_pipeline.default_pipeline().submit(merged).result()
            if not ok:
                for i in by_slot[slot]:
                    verdicts[i] = bool(
                        bls_api.verify_signature_sets(arrivals[i][2])
                    )
            done = time.perf_counter() - start
            for i in by_slot[slot]:
                verdicts.setdefault(i, bool(ok))
                lat[i] = done - arrivals[i][0]
        bls_pipeline.default_pipeline().drain()
        return lat, verdicts

    def replay_cont():
        """The same arrivals through the continuous-batching scheduler:
        the driver submits at each arrival offset, a resolver thread
        blocks on verdicts in arrival order -- every result() is a
        launch boundary that merges whatever queued behind it."""
        bls_pipeline.configure()
        sched = bls_scheduler.configure()
        lat = {i: None for i in range(len(arrivals))}
        verdicts = {}
        import queue as queue_mod

        q: queue_mod.Queue = queue_mod.Queue()
        start = time.perf_counter()

        def resolver():
            while True:
                item = q.get()
                if item is None:
                    return
                i, fut = item
                verdicts[i] = bool(fut.result())
                lat[i] = (time.perf_counter() - start) - arrivals[i][0]

        t = threading.Thread(target=resolver, daemon=True)
        t.start()
        for i, (off, lane, sets, slot) in enumerate(arrivals):
            now = time.perf_counter() - start
            if now < off:
                time.sleep(off - now)
            fut = bls_api.verify_signature_sets_async(
                sets, lane=lane, slot=slot
            )
            q.put((i, fut))
        q.put(None)
        t.join()
        sched.drain()
        return lat, verdicts, dict(sched.stats)

    prior = os.environ.get("LIGHTHOUSE_TPU_CONT_BATCH")
    os.environ["LIGHTHOUSE_TPU_CONT_BATCH"] = "1"
    try:
        # warm pass (unmeasured): compiles every shape the replay will
        # touch, so the measured pass is steady-state
        replay_cont()
        # fresh ledger so the artifact's launch accounting covers ONLY
        # the measured pass
        led = launch_ledger.configure()
        misses0 = M.TPU_COMPILE_CACHE_MISSES.value
        cont_lat, cont_verdicts, stats = replay_cont()
        cache_misses = M.TPU_COMPILE_CACHE_MISSES.value - misses0
        ledger_stats = led.stats()
    finally:
        if prior is None:
            os.environ.pop("LIGHTHOUSE_TPU_CONT_BATCH", None)
        else:
            os.environ["LIGHTHOUSE_TPU_CONT_BATCH"] = prior
    base_lat, base_verdicts = replay_baseline()

    lanes = {}
    for lane in bls_scheduler.LANES:
        idx = [i for i, a in enumerate(arrivals) if a[1] == lane]
        if not idx:
            continue
        c = [cont_lat[i] for i in idx]
        b = [base_lat[i] for i in idx]
        lanes[lane] = {
            "arrivals": len(idx),
            "p50_ms": round(1e3 * _percentile(c, 0.50), 2),
            "p95_ms": round(1e3 * _percentile(c, 0.95), 2),
            "baseline_p50_ms": round(1e3 * _percentile(b, 0.50), 2),
            "baseline_p95_ms": round(1e3 * _percentile(b, 0.95), 2),
        }
    real_idx = [
        i for i, a in enumerate(arrivals) if a[1] != "speculative"
    ]
    cont_p95 = _percentile([cont_lat[i] for i in real_idx], 0.95)
    base_p95 = _percentile([base_lat[i] for i in real_idx], 0.95)
    pad, real = stats["pad_sets"], stats["real_sets"]
    payload = {
        "metric": "cont_batch_ttv_p95_speedup",
        "value": round(base_p95 / cont_p95, 3) if cont_p95 else 0.0,
        "unit": "x",
        "seed": seed,
        "slots": slots,
        "slot_ms": round(1e3 * slot_s, 1),
        "arrivals": len(arrivals),
        "lanes": lanes,
        "pad_waste_ratio": (
            round(pad / (pad + real), 4) if (pad + real) else 0.0
        ),
        "scheduler": stats,
        "ledger": ledger_stats,
        "compile_cache_misses_after_warm": cache_misses,
        "verdicts_match_baseline": cont_verdicts == base_verdicts,
    }
    if cont_verdicts != base_verdicts:
        bad = [
            i
            for i in cont_verdicts
            if cont_verdicts.get(i) != base_verdicts.get(i)
        ]
        payload["error"] = (
            f"verdict divergence on {len(bad)} arrivals: {bad[:8]}"
        )
    _emit(payload)


def scale_bench() -> None:
    """`bench.py --scale`: million-validator state sharded over the mesh.
    Times the mesh-sharded epoch processor (per_epoch_mesh.py) over a
    validator-count curve up to 2M on a simulated multi-device CPU mesh,
    and measures the per-device pubkey-table bytes of the sharded table
    against whole-table replication. Same artifact contract as the main
    bench: exactly ONE JSON line, exit 0 even on failure."""
    try:
        _scale_bench_inner()
    except BaseException as exc:  # never lose the artifact
        _emit(
            {
                "metric": "epoch_transition_mesh_2m_s",
                "value": 0.0,
                "unit": "s",
                "error": f"scale bench: {type(exc).__name__}: {exc}",
            }
        )


def _scale_bench_inner() -> None:
    sys.path.insert(0, HERE)
    # the virtual mesh must be forced BEFORE the XLA backend initializes
    # (first jax.devices() call); if the orchestrator already initialized
    # it, run with whatever device count exists and report it
    n_dev = int(os.environ.get("BENCH_SCALE_DEVICES", "4"))
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_dev}"
        ).strip()
    _force_platform()
    import jax

    n_dev = len(jax.devices())

    import numpy as np

    from bench_local import _synthetic_state
    from lighthouse_tpu.crypto.bls.backends import jax_tpu
    from lighthouse_tpu.utils import metrics as M

    sizes = [
        int(s)
        for s in os.environ.get(
            "BENCH_SCALE_VALIDATORS", "250000,1000000,2000000"
        ).split(",")
    ]
    reps = int(os.environ.get("BENCH_SCALE_REPS", "2"))

    # --- pubkey-table HBM: sharded per-device bytes vs replication -------
    # The table contents are irrelevant to placement (limb rows are
    # opaque int32), so the 2M-row table is synthesized directly instead
    # of decompressing 2M real pubkeys on the host.
    table_rows = max(sizes)
    rng = np.random.default_rng(7)
    table = jax_tpu.PubkeyTable()
    table._host = rng.integers(
        0, 2**28, size=(table_rows, 3, jax_tpu.W), dtype=np.int64
    ).astype(np.int32)
    dev = table.device_table()
    bucket_rows = int(dev.shape[0])
    replicated_bytes = bucket_rows * 3 * jax_tpu.W * 4
    if table.sharded:
        per_device = max(
            M.TPU_PUBKEY_TABLE_BYTES.get(str(d.id))
            for d in dev.sharding.mesh.devices.flat
        )
    else:
        per_device = replicated_bytes
    gather_idx = rng.integers(0, table_rows, size=(1024,)).astype(np.int32)
    t0 = time.perf_counter()
    jax.block_until_ready(table.gather(gather_idx))
    gather_cold_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    jax.block_until_ready(table.gather(gather_idx))
    gather_warm_s = time.perf_counter() - t0
    del table, dev  # free ~1 GB before the epoch states

    # --- epoch-transition curve over the sharded column passes -----------
    os.environ["LIGHTHOUSE_TPU_EPOCH_MESH"] = "1"
    from lighthouse_tpu.state_transition.per_epoch import process_epoch
    from lighthouse_tpu.types import MINIMAL, ChainSpec

    spec = ChainSpec.interop(altair_fork_epoch=0)
    curve = []
    for n in sizes:
        state = _synthetic_state(n, "altair")
        state.slot = 3 * MINIMAL.slots_per_epoch - 1
        times = []
        for _ in range(max(1, reps) + 1):
            t0 = time.perf_counter()
            process_epoch(state, MINIMAL, spec)
            times.append(time.perf_counter() - t0)
        curve.append(
            {
                "n_validators": n,
                # rep 0 pays program compiles + the cold column
                # extraction; a live node's steady state is the warm rep
                # (identity-cached columns, warm executables)
                "cold_s": round(times[0], 3),
                "warm_s": round(min(times[1:]), 3),
            }
        )
        del state

    # prove the curve went through the mesh programs, not a silent
    # VectorGuard fallback to the single-device vec path
    from lighthouse_tpu.state_transition import per_epoch_mesh

    top = curve[-1]
    _emit(
        {
            "metric": "epoch_transition_mesh_2m_s",
            "value": top["warm_s"],
            "unit": "s",
            "n_devices": n_dev,
            "mesh_path_used": bool(per_epoch_mesh._PROGRAMS),
            "slot_budget_s": 12.0,
            "within_slot": top["warm_s"] < 12.0,
            "curve": curve,
            "pubkey_table": {
                "rows": table_rows,
                "bucket_rows": bucket_rows,
                "replicated_bytes_per_device": replicated_bytes,
                "sharded_bytes_per_device": per_device,
                "per_device_fraction": round(
                    per_device / replicated_bytes, 4
                ),
                "gather_1k_cold_s": round(gather_cold_s, 4),
                "gather_1k_warm_s": round(gather_warm_s, 4),
            },
            "note": "virtual devices share one host CPU: correctness + "
            "per-device memory scaling, not a wall-clock speedup claim",
        }
    )


def serving_bench() -> None:
    """`bench.py --serving`: the serving-tier load generator (cached vs
    uncached requests/s over a real server). Same artifact contract as
    the BLS bench: exactly ONE JSON line, exit 0 even on failure."""
    argv = [a for a in sys.argv[1:] if a != "--serving"]
    try:
        from tools.serving_load import main as serving_main

        serving_main(argv)
    except BaseException as exc:  # never lose the artifact
        _emit(
            {
                "metric": "serving_cached_requests_per_s",
                "value": 0.0,
                "unit": "req/s",
                "error": f"serving bench: {type(exc).__name__}: {exc}",
            }
        )


def main() -> None:
    if "--probe" in sys.argv:
        probe()
    elif "--serving" in sys.argv:
        serving_bench()
    elif "--speculate" in sys.argv:
        speculate_bench()
    elif "--latency" in sys.argv:
        latency_bench()
    elif "--scale" in sys.argv:
        scale_bench()
    elif "--profile" in sys.argv:
        profile_child()
    elif "--child" in sys.argv:
        child()
    else:
        # an external SIGTERM (driver timeout) must still yield an artifact:
        # surface it as an exception so the fallback emit below runs
        import signal

        def _sigterm(signum, frame):
            raise RuntimeError("terminated by external signal")

        try:
            signal.signal(signal.SIGTERM, _sigterm)
        except (ValueError, OSError):
            pass
        try:
            orchestrate()
        except BaseException as exc:  # never lose the artifact
            _emit(
                _attach_last_tpu(
                    {
                        "metric": "bls_signature_sets_verified_per_s_per_chip",
                        "value": 0.0,
                        "unit": "sets/s",
                        "vs_baseline": 0.0,
                        "platform": "none",
                        "error": f"orchestrator: {type(exc).__name__}: {exc}",
                    }
                )
            )


if __name__ == "__main__":
    main()
