"""One-off TPU warm-compile + measurement, outside any bench timeout.

Arms the persistent compile cache and compiles the STAGED verifier at the
bench shapes (16-set small bucket first, then the 1024-set primary),
retrying through remote-compile drops -- every stage that compiles lands
in .jax_cache/tpu, so retries resume at the first uncompiled stage. Then
measures steady state. After this succeeds, bench.py children are
load+run instead of a >387s cold compile.
"""

import sys
import time

sys.path.insert(0, ".")

from __graft_entry__ import _arm_compilation_cache, _example_batch

_arm_compilation_cache()

import jax

print("devices:", jax.devices(), flush=True)

from lighthouse_tpu.crypto.bls.backends.jax_tpu import verify_device

RETRIES = 8

# The dedup split gives _stage_hash its own compile-shape axis (the
# distinct-message bucket m_b). Warm the spread of buckets gossip batches
# actually hit -- up through 128 (one aggregate per committee at 64
# committees/slot buckets to 64; headroom above that) -- so production
# batches never cold-compile the hash stage mid-verify; each warm run is
# cheap once cached.
HASH_BUCKETS = (4, 8, 16, 32, 64, 128)

# WARM_SETS=16,1024,4096 to also stage bigger buckets (throughput scales
# with batch: the final exponentiation is batch-fixed)
import os  # noqa: E402

SET_SIZES = tuple(
    int(x)
    for x in os.environ.get("WARM_SETS", "16,1024").split(",")
    if x.strip()
) or (16, 1024)

for n_sets in SET_SIZES:
    t0 = time.perf_counter()
    args = _example_batch(n_sets, 2, distinct=min(32, n_sets), dedup=True)
    print(f"n={n_sets} fixtures {time.perf_counter() - t0:.1f}s", flush=True)
    ok = None
    for attempt in range(RETRIES):
        t0 = time.perf_counter()
        try:
            ok = bool(jax.block_until_ready(verify_device(*args)))
        except Exception as exc:
            print(
                f"n={n_sets} attempt {attempt}: {type(exc).__name__} "
                f"after {time.perf_counter() - t0:.1f}s: "
                f"{str(exc).splitlines()[0][:120]}",
                flush=True,
            )
            time.sleep(5)
            continue
        print(
            f"n={n_sets} compile+first-run {time.perf_counter() - t0:.1f}s "
            f"ok={ok} (attempt {attempt})",
            flush=True,
        )
        break
    assert ok, f"n={n_sets}: never compiled in {RETRIES} attempts"
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(verify_device(*args))
        times.append(time.perf_counter() - t0)
    best = min(times)
    print(
        f"n={n_sets} steady {best * 1e3:.1f} ms  -> {n_sets / best:.1f} sets/s",
        flush=True,
    )

from lighthouse_tpu.crypto.bls.backends.jax_tpu import _stage_hash  # noqa: E402

for b in HASH_BUCKETS:
    u_b, _, _, _, _, _ = _example_batch(b, 2, distinct=b, dedup=True)
    for attempt in range(RETRIES):
        t0 = time.perf_counter()
        try:
            jax.block_until_ready(_stage_hash(u_b))
        except Exception as exc:
            print(
                f"hash m_b={b} attempt {attempt}: {type(exc).__name__} "
                f"after {time.perf_counter() - t0:.1f}s",
                flush=True,
            )
            time.sleep(5)
            continue
        print(f"hash m_b={b} warm {time.perf_counter() - t0:.1f}s", flush=True)
        break
