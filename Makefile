# Convenience targets; CI (.github/workflows/ci.yml) runs the same two.

PY ?= python

.PHONY: lint lint-changed lint-baseline test test-lint test-chaos \
	test-crash test-scenario test-serving test-speculate test-kernels \
	test-fuzz fuzz test-adversary fuzz-adversary bench-serving \
	bench-speculate bench-latency bench-scale test-sharded warm-compile \
	ledger-report

## lint: per-file + interprocedural project pass (tools/lint, stdlib-only);
## times itself and fails over the 10s budget so it never becomes a
## pre-commit tax
lint:
	$(PY) -m tools.lint --project --budget-s 10

## lint-changed: pre-commit fast path -- only files git says changed
lint-changed:
	$(PY) -m tools.lint --project --changed-only --budget-s 10

## lint-baseline: regenerate the ratchet file after burning down debt
lint-baseline:
	$(PY) -m tools.lint --project --write-baseline

## test: tier-1 suite (CPU, excludes slow/TPU-only)
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider \
		-p no:xdist -p no:randomly

## test-lint: just the linter's own fixture suite
test-lint:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_lint.py -q \
		-p no:cacheprovider

## test-chaos: deterministic fault-injection suite (the CI chaos job)
test-chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_resilience.py -q \
		-m chaos -p no:cacheprovider

## test-crash: crash-injection matrix + WAL recovery + fsck (the CI crash job)
test-crash:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_crash_safety.py -q \
		-p no:cacheprovider

## test-scenario: full adversarial scenario matrix incl. the combined
## plans, Byzantine validator clients, serving-under-chaos, wire
## transport, and slow scale runs (the CI scenario job; tier-1 keeps
## only the small seeded scenarios)
test-scenario:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_scenarios.py \
		tests/test_byzantine_vc.py -q -m scenario -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_scenarios.py -q \
		-m wire -p no:cacheprovider

## test-fuzz: fuzzing machinery unit tests + tier-1 replay of the pinned
## corpus reproducers under their recorded plants
test-fuzz:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fuzz.py -q \
		-p no:cacheprovider

## fuzz: a budgeted seeded fuzz window (the CI fuzz job); exit code is
## the number of findings, minimized reproducers land in fuzz-findings/
fuzz:
	JAX_PLATFORMS=cpu $(PY) -m tools.fuzz_cli --start-seed 0 \
		--iterations 12 --budget-s 1200 --corpus-dir fuzz-findings

## test-adversary: aggregation-soundness suite IN FULL — all five probe
## families through the five-path differential rejection matrix (cpu
## oracle, jax per-set, jax aggregated, mesh grouped, fallback
## mid-trip), planted weaknesses, import seams (the CI adversary job;
## tier-1 keeps the fast cpu-oracle subset)
test-adversary:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_bls_adversary.py -q \
		-p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_pubkey_table.py -q \
		-p no:cacheprovider

## fuzz-adversary: budgeted fuzz window with the adversary grammar —
## every generated plan carries aggregation-soundness probes audited
## against the real cpu oracle at scenario end
fuzz-adversary:
	JAX_PLATFORMS=cpu $(PY) -m tools.fuzz_cli --start-seed 100 \
		--iterations 8 --budget-s 1200 --grammar adversary \
		--corpus-dir fuzz-findings

## test-serving: serving-tier suite (cache, SSE fan-out, admission)
test-serving:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serving.py -q \
		-p no:cacheprovider

## test-speculate: duty-driven precompute & speculative verification —
## the forgery/property suite plus the storm scenario with speculation
## attached (the CI speculate job)
test-speculate:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_speculation.py -q \
		-p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_scenarios.py -q \
		-m speculate -p no:cacheprovider

## test-kernels: full Pallas kernel parity matrix incl. the slow fused
## tower/Miller kernels in interpret mode (the CI kernels job)
test-kernels:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_pallas_kernels.py -q \
		-p no:cacheprovider

## bench-serving: cached-vs-uncached requests/s (the CI serving job)
bench-serving:
	JAX_PLATFORMS=cpu $(PY) bench.py --serving --out bench-serving.json

## bench-speculate: critical-path aggregate sets/s with the precompute
## off / on / on+speculation, plus hit/correction/miss ratios (one JSON
## line on stdout — the artifact)
bench-speculate:
	BENCH_PLATFORM=cpu JAX_PLATFORMS=cpu $(PY) bench.py --speculate \
		| tee bench-speculate.json

## bench-latency: bursty-arrival per-lane time-to-verdict p50/p95
## through the continuous-batching scheduler vs the whole-batch
## baseline, plus the pad-waste ratio (one JSON line — the artifact)
bench-latency:
	BENCH_PLATFORM=cpu JAX_PLATFORMS=cpu $(PY) bench.py --latency \
		| tee bench-latency.json

## ledger-report: run the latency bench, then print the launch-ledger
## occupancy / pad-waste / compile-tax table (+ per-lane p50/p95
## time-to-verdict) from its artifact — the same renderer as
## `cli ledger --report` and /lighthouse/ledger/report
ledger-report: bench-latency
	JAX_PLATFORMS=cpu $(PY) -m tools.ledger_report bench-latency.json

## bench-scale: 2M-validator epoch transition on the simulated 4-device
## mesh + sharded pubkey-table per-device bytes (one JSON line — the
## artifact the CI sharded-state job uploads)
bench-scale:
	BENCH_PLATFORM=cpu JAX_PLATFORMS=cpu $(PY) bench.py --scale \
		| tee bench-scale.json

## test-sharded: the sharded-state differential matrix on a forced
## 4-device mesh (the CI sharded-state job; in-suite tier-1 runs the
## same file on the conftest 8-device mesh minus the slow chip-fault
## test, which compiles the full verify_jit program)
test-sharded:
	JAX_PLATFORMS=cpu \
	XLA_FLAGS=--xla_force_host_platform_device_count=4 \
	$(PY) -m pytest tests/test_sharded_state.py -q -p no:cacheprovider

## warm-compile: AOT-compile every verifier shape bucket into ./datadir's
## persistent compile cache (deploy-time warm pass; `cli warm`)
warm-compile:
	$(PY) -m lighthouse_tpu.cli warm --datadir $${DATADIR:-./datadir}
