"""Local (CPU-only) performance floor: numbers rounds can diff even when
the TPU tunnel is down (VERDICT r3 weak-8). Writes BENCH_LOCAL.json at
the repo root with one entry per config from BASELINE.md:

  * verifier_mesh_sets_per_s -- the sharded batch verifier on the
    8-virtual-device CPU mesh (BASELINE config 5's local stand-in)
  * epoch_transition_s       -- process_slots across an epoch boundary
    on a synthetic N-validator state (BASELINE config 4)
  * cached_tree_hash_speedup -- steady-state re-root vs from-scratch
    merkleization at N validators (reference criterion benches)
  * op_pool_pack_s           -- max-cover packing over 4,096 pooled
    aggregates (BASELINE config 2/3)

Sizes shrink via BENCH_LOCAL_SCALE=mini for the in-suite smoke test.
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)


def _force_cpu():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except RuntimeError:
        pass
    from __graft_entry__ import _arm_compilation_cache

    _arm_compilation_cache()


def bench_verifier_mesh(n_sets: int = 8) -> dict:
    """Sharded verify on the 8-device CPU mesh, warm, one set/device."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from __graft_entry__ import _example_batch
    from lighthouse_tpu.parallel import make_sharded_verify, sets_mesh

    devices = jax.devices("cpu")[:8]
    mesh = sets_mesh(devices)
    fn = make_sharded_verify(mesh)
    args = _example_batch(n_sets=n_sets, k_pk=2, distinct=min(n_sets, 8))
    sharding = NamedSharding(mesh, PartitionSpec("sets"))
    args = tuple(jax.device_put(a, sharding) for a in args)
    t0 = time.perf_counter()
    ok = bool(fn(*args))  # compile (cached) + run
    compile_s = time.perf_counter() - t0
    assert ok
    times = []
    for _ in range(2):
        t0 = time.perf_counter()
        bool(fn(*args))
        times.append(time.perf_counter() - t0)
    best = min(times)
    return {
        "metric": "verifier_mesh_sets_per_s",
        "value": round(n_sets / best, 2),
        "n_sets": n_sets,
        "n_devices": 8,
        "compile_s": round(compile_s, 2),
    }


def bench_verifier_mesh_curve(per_device_sets: int = 1) -> dict:
    """Weak-scaling curve over mesh sizes 1/2/4/8 (BASELINE config 5,
    block_signature_verifier.rs:374-384's rayon analogue): fixed per-device
    sets, growing mesh. NOTE the honest caveat: these virtual devices share
    ONE host CPU, so wall time GROWS with mesh size here — the curve
    demonstrates sharding correctness and bounded collective overhead, not
    speedup. Linear-throughput claims need real chips; the driver's
    dryrun_multichip validates the same program compiles and executes on
    an N-device mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from __graft_entry__ import _example_batch
    from lighthouse_tpu.parallel import make_sharded_verify, sets_mesh

    devices = jax.devices("cpu")
    curve = []
    for n_dev in (1, 2, 4, 8):
        if len(devices) < n_dev:
            break
        n_sets = per_device_sets * n_dev
        mesh = sets_mesh(devices[:n_dev])
        fn = make_sharded_verify(mesh)
        args = _example_batch(
            n_sets=n_sets, k_pk=2, distinct=min(n_sets, 8)
        )
        sharding = NamedSharding(mesh, PartitionSpec("sets"))
        d_args = tuple(jax.device_put(a, sharding) for a in args)
        t0 = time.perf_counter()
        ok = bool(fn(*d_args))
        compile_s = time.perf_counter() - t0
        assert ok, f"mesh={n_dev} rejected a valid batch"
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            bool(fn(*d_args))
            times.append(time.perf_counter() - t0)
        best = min(times)
        curve.append(
            {
                "n_devices": n_dev,
                "n_sets": n_sets,
                "steady_s": round(best, 3),
                "sets_per_s": round(n_sets / best, 2),
                "compile_s": round(compile_s, 2),
            }
        )
    return {
        "metric": "verifier_mesh_weak_scaling",
        "value": curve[-1]["sets_per_s"] if curve else 0.0,
        "curve": curve,
        "note": "virtual devices share one host CPU: correctness + "
        "overhead curve, not a speedup claim",
    }


def _synthetic_state(n_validators: int, fork: str = "phase0"):
    from lighthouse_tpu.types import MINIMAL, types_for
    from lighthouse_tpu.types.chain_spec import FAR_FUTURE_EPOCH
    from lighthouse_tpu.types.containers import Validator, state_class_for

    t = types_for(MINIMAL)
    state = state_class_for(t, fork).default()
    rng = random.Random(7)
    state.validators = tuple(
        Validator(
            pubkey=rng.randbytes(48),
            withdrawal_credentials=rng.randbytes(32),
            effective_balance=32 * 10**9,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH,
        )
        for _ in range(n_validators)
    )
    state.balances = tuple(32 * 10**9 for _ in range(n_validators))
    if fork != "phase0":
        # ~98% full participation, a sprinkle of partials — production-like
        state.previous_epoch_participation = tuple(
            7 if rng.random() < 0.98 else rng.choice([0, 1, 3])
            for _ in range(n_validators)
        )
        state.current_epoch_participation = tuple(
            7 if rng.random() < 0.98 else 0 for _ in range(n_validators)
        )
        state.inactivity_scores = (0,) * n_validators
    return state


def bench_epoch_transition(
    n_validators: int = 100_000, fork: str = "phase0"
) -> dict:
    """One epoch boundary via process_slots (BASELINE config 4). The
    altair variant exercises the vectorized participation-flag path
    (state_transition/per_epoch_vec.py); phase0 is the PendingAttestation
    loop oracle. Cost includes the incremental-hash cache build."""
    from lighthouse_tpu.state_transition import process_slots
    from lighthouse_tpu.types import MINIMAL, ChainSpec

    if fork == "phase0":
        spec = ChainSpec.interop(
            altair_fork_epoch=None, bellatrix_fork_epoch=None
        )
    else:
        spec = ChainSpec.interop(altair_fork_epoch=0)
    state = _synthetic_state(n_validators, fork)
    # start late enough that justification weighing runs (epoch > 1)
    start = 3 * MINIMAL.slots_per_epoch - 1
    state.slot = start
    # steady-state: a live node's incremental-hash cache is always warm;
    # the cold build is a one-time cost measured by cached_tree_hash below
    from lighthouse_tpu.ssz import cached_root

    cached_root(state)
    t0 = time.perf_counter()
    process_slots(state, start + 2, MINIMAL, spec)
    dt = time.perf_counter() - t0
    return {
        "metric": f"epoch_transition_{fork}_s",
        "value": round(dt, 3),
        "n_validators": n_validators,
    }


def bench_block_replay(
    n_validators: int = 500_000, n_slots: int = 8, fork: str = "altair"
) -> dict:
    """Empty-slot block-range replay rate at scale (BASELINE config 4's
    historical-replay shape; reference block_replayer.rs): slots/s through
    process_slots incl. one epoch boundary, steady-state hash cache."""
    from lighthouse_tpu.state_transition import process_slots
    from lighthouse_tpu.types import MINIMAL, ChainSpec

    spec = ChainSpec.interop(altair_fork_epoch=0)
    state = _synthetic_state(n_validators, fork)
    start = 3 * MINIMAL.slots_per_epoch - 1
    state.slot = start
    # build the incremental-hash cache outside the timed region (a replayer
    # holds its state across the whole range; the build amortizes away)
    from lighthouse_tpu.ssz import cached_root

    cached_root(state)
    t0 = time.perf_counter()
    process_slots(state, start + n_slots, MINIMAL, spec)
    dt = time.perf_counter() - t0
    return {
        "metric": "block_replay_slots_per_s",
        "value": round(n_slots / dt, 2),
        "n_validators": n_validators,
        "n_slots": n_slots,
    }


def bench_cached_tree_hash(n_validators: int = 16_384) -> dict:
    from lighthouse_tpu.ssz import cached_root

    state = _synthetic_state(n_validators)
    t0 = time.perf_counter()
    fresh = state.tree_hash_root()
    fresh_s = time.perf_counter() - t0
    assert cached_root(state) == fresh  # cold cache build
    bal = list(state.balances)
    for i in random.Random(1).sample(range(n_validators), 10):
        bal[i] += 1
    state.balances = tuple(bal)
    t0 = time.perf_counter()
    cached_root(state)
    cached_s = time.perf_counter() - t0
    return {
        "metric": "cached_tree_hash_speedup",
        "value": round(fresh_s / max(cached_s, 1e-9), 1),
        "fresh_s": round(fresh_s, 3),
        "cached_s": round(cached_s, 5),
        "n_validators": n_validators,
    }


def bench_op_pool_pack(n_attestations: int = 4096, validators: int = 256) -> dict:
    from lighthouse_tpu.harness.chain import StateHarness
    from lighthouse_tpu.pool import OperationPool
    from lighthouse_tpu.state_transition import clone_state, process_slots
    from lighthouse_tpu.types import MINIMAL, types_for

    h = StateHarness(validators, MINIMAL, sign=False)
    t = types_for(MINIMAL)
    target_slot = 2 * MINIMAL.slots_per_epoch
    state = process_slots(
        clone_state(h.state), target_slot, MINIMAL, h.spec
    )
    pool = OperationPool(MINIMAL, h.spec)
    rng = random.Random(3)
    # fill until the pool RETAINS n_attestations distinct aggregates
    # (subset variants are deduped on insert), with an attempt cap
    attempts = 0
    while pool.num_attestations() < n_attestations and attempts < 20 * n_attestations:
        slot = rng.randrange(state.slot - MINIMAL.slots_per_epoch + 1, state.slot)
        for att in h.attestations_for_slot(state, slot):
            bits = [rng.random() < 0.5 for _ in att.aggregation_bits]
            if not any(bits):
                bits[0] = True
            pool.insert_attestation(
                t.Attestation(
                    aggregation_bits=bits,
                    data=att.data,
                    signature=att.signature,
                )
            )
            attempts += 1
            if pool.num_attestations() >= n_attestations:
                break
    t0 = time.perf_counter()
    packed = pool.get_attestations(state)
    dt = time.perf_counter() - t0
    return {
        "metric": "op_pool_pack_s",
        "value": round(dt, 3),
        "pooled": pool.num_attestations(),
        "packed": len(packed),
    }


def main() -> None:
    mini = os.environ.get("BENCH_LOCAL_SCALE") == "mini"
    _force_cpu()
    results = []
    if not mini:
        # compile-bound (minutes when the XLA cache is cold): full runs only
        results.append(bench_verifier_mesh(8))
        results.append(bench_verifier_mesh_curve())
    results += [
        bench_epoch_transition(2_000 if mini else 100_000),
        bench_epoch_transition(2_000 if mini else 500_000, fork="altair"),
        bench_block_replay(2_000 if mini else 500_000),
        bench_cached_tree_hash(2_048 if mini else 16_384),
        bench_op_pool_pack(256 if mini else 4096, 64 if mini else 256),
    ]
    payload = {
        "scale": "mini" if mini else "full",
        "platform": "cpu",
        "results": results,
    }
    out = os.path.join(HERE, "BENCH_LOCAL.json")
    with open(out, "w") as f:
        json.dump(payload, f, indent=1)
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
