"""Signature-set batch verification sharded across a device mesh.

The TPU-native replacement for the reference's rayon parallel batch verify
(consensus/state_processing/src/per_block_processing/block_signature_verifier.rs:374-384,
rayon chunks -> per-chunk blst multi-pairing): signature sets are sharded
over a 1-D `sets` mesh axis with `shard_map`; each chip runs hash-to-G2,
ladders, and Miller loops for its shard; the two tiny cross-set reductions
(one G2 point, one Fp12 element) ride ICI all_gathers; the shared final
exponentiation is replicated.

This is the "v4-8 pod / 1M-validator synthetic network" configuration of
BASELINE.md: throughput scales with mesh size because per-set work
dominates and the collective payload is constant (~4.6 KB per chip).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map

    SHARD_MAP_NATIVE = True
except ImportError:  # pre-0.6 jax: the experimental namespace. The
    # import must not hard-fail -- MeshVerifier's breaker mechanics and
    # single-device path work everywhere; only the >1-device programs
    # need shard_map itself.
    from jax.experimental.shard_map import shard_map

    SHARD_MAP_NATIVE = False

from ..crypto.bls.backends.jax_tpu import (
    verify_body,
    verify_body_grouped,
    verify_grouped_jit,
    verify_jit,
)
from ..obs import ledger as launch_ledger
from ..resilience.primitives import CircuitBreaker, EventLog
from ..utils import metrics, tracing

AXIS = "sets"

# The VALIDATOR-STATE axis: the pubkey table and the epoch-transition
# columns shard their validator-index dimension over this 1-D mesh
# (ROADMAP million-validator item). It is a different *name* from the
# batch `sets` axis -- batches shard by set, state shards by validator
# index -- but rides the same physical devices as the MeshVerifier.
VALIDATOR_AXIS = "validators"


def sets_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name 'sets'."""
    devices = list(jax.devices()) if devices is None else list(devices)
    return Mesh(np.array(devices), (AXIS,))


def pow2_device_prefix(devices=None) -> list:
    """The largest power-of-two prefix of the device list: sharded state
    divides bucketed (power-of-two) row counts evenly, so odd device
    counts park the remainder devices rather than pad unevenly."""
    devices = list(jax.devices()) if devices is None else list(devices)
    size = 1
    while size * 2 <= len(devices):
        size *= 2
    return devices[:size]


def validators_mesh(devices=None) -> Mesh:
    """1-D validator-state mesh over a power-of-two device prefix."""
    return Mesh(np.array(pow2_device_prefix(devices)), (VALIDATOR_AXIS,))


def make_sharded_gather(mesh: Mesh):
    """Returns a jitted gather over `mesh`: the table (rows, 3, W) shards
    its leading validator-index axis; the (m,) index vector replicates.
    Each shard gathers the indices it OWNS (its contiguous row block)
    and zero-fills the rest; one int32 psum of the (m, 3, W) gathered
    rows -- every index is owned by exactly one shard -- completes the
    batch's rows on every chip. Out-of-range indices clip to the last
    row (the callers' `mode="clip"` contract; clipped rows are padding
    and masked downstream).

    The returned callable carries ``arg_specs`` so DeviceExecutor-style
    placement helpers know the table shards while indices replicate."""

    n_shards = int(mesh.devices.size)

    def gather_fn(table_shard, idx):
        rows = table_shard.shape[0]
        idx = jnp.clip(idx, 0, rows * n_shards - 1)
        off = jax.lax.axis_index(VALIDATOR_AXIS).astype(jnp.int32) * rows
        local = idx - off
        owned = (local >= 0) & (local < rows)
        vals = jnp.take(table_shard, jnp.where(owned, local, 0), axis=0)
        vals = jnp.where(owned[:, None, None], vals, 0)
        return jax.lax.psum(vals, VALIDATOR_AXIS)

    specs = (P(VALIDATOR_AXIS), P())
    kw = dict(mesh=mesh, in_specs=specs, out_specs=P())
    try:
        body = shard_map(gather_fn, check_vma=False, **kw)
    except TypeError:  # pre-0.6 jax spells the flag check_rep
        body = shard_map(gather_fn, check_rep=False, **kw)
    fn = jax.jit(body)

    # a plain wrapper because jit objects reject attribute assignment
    def call(table, idx):
        return fn(table, idx)

    call.arg_specs = specs
    return call


def make_sharded_verify(mesh: Mesh):
    """Returns a jitted verifier over `mesh`: inputs are globally-shaped
    arrays sharded on their leading (set) axis; output is a replicated
    scalar bool. Set counts must divide evenly by the mesh size (callers
    pad to bucket sizes, which are powers of two)."""

    spec = P(AXIS)
    rep = P()

    shard_fn = lambda u, pk, sig, r, real: verify_body(  # noqa: E731
        u, pk, sig, r, real, axis_name=AXIS
    )
    kw = dict(mesh=mesh, in_specs=(spec,) * 5, out_specs=rep)
    try:
        body = shard_map(shard_fn, check_vma=False, **kw)
    except TypeError:  # pre-0.6 jax spells the flag check_rep
        body = shard_map(shard_fn, check_rep=False, **kw)
    return jax.jit(body)


def make_sharded_verify_grouped(mesh: Mesh):
    """The GROUPED (mega-pairing) sharded verifier over `mesh`: per-set
    arrays and the (n, m) membership mask shard on the sets axis, while
    the distinct-message draws and real-message mask replicate -- the
    layout `verify_body_grouped` expects. Each shard reduces its local
    per-message pubkey partial sums; one all_gather of m tiny G1 points
    completes them, so the batch pays ~m Miller pairs instead of ~n.

    The returned callable carries ``arg_specs`` (one PartitionSpec per
    argument) so :class:`DeviceExecutor` can place the mixed
    sharded/replicated argument list correctly."""

    spec = P(AXIS)
    rep = P()
    specs = (rep, spec, spec, spec, spec, spec, rep)

    def shard_fn(u, pk, sig, r, real, member, msg_real):
        return verify_body_grouped(
            u, pk, sig, r, real, member, msg_real, axis_name=AXIS
        )

    kw = dict(mesh=mesh, in_specs=specs, out_specs=rep)
    try:
        body = shard_map(shard_fn, check_vma=False, **kw)
    except TypeError:  # pre-0.6 jax spells the flag check_rep
        body = shard_map(shard_fn, check_rep=False, **kw)
    fn = jax.jit(body)

    # a plain wrapper because jit objects reject attribute assignment
    def call(*args):
        return fn(*args)

    call.arg_specs = specs
    return call


# -- the resilient mesh (per-device breakers; ROADMAP pmap open item) --------


class MeshEmpty(ConnectionError):
    """Every device's breaker is open: there is no mesh to shard over.
    The FallbackBackend treats this like any other primary fault and
    degrades the batch to the cpu oracle -- the ONLY condition that
    should ever trip the whole backend off the accelerator."""


class DeviceExecutor:
    """Places globally-shaped batch arrays onto the mesh sharding and
    runs the compiled program. A separate object so chaos tests can wrap
    it in a FaultyProxy (resilience/faults.py) and inject a chip fault
    at exactly this boundary."""

    def run(self, fn, args, devices):
        if len(devices) == 1:
            placed = tuple(jax.device_put(a, devices[0]) for a in args)
        else:
            # per-set programs shard every arg; grouped programs publish
            # per-arg specs (replicated message draws + sharded masks)
            specs = getattr(fn, "arg_specs", None) or (P(AXIS),) * len(args)
            mesh = sets_mesh(devices)
            placed = tuple(
                jax.device_put(a, NamedSharding(mesh, s))
                for a, s in zip(args, specs)
            )
        return fn(*placed)


class DeviceProber:
    """Post-fault chip attribution: a trivial transfer + add on one
    device proves the chip (and its transport) is alive. Wrapped by
    chaos tests to script which chip 'died'."""

    def probe(self, device) -> bool:
        try:
            out = jax.device_put(jnp.zeros((), jnp.int32), device) + 1
            return int(out) == 1
        except Exception:  # noqa: BLE001 -- ANY device/transport fault
            # means this chip is unusable; the caller opens its breaker
            return False


class MeshVerdict:
    """Async verdict of a sharded batch: device work is enqueued;
    ``bool()`` blocks for the answer, and a chip fault surfacing at
    materialisation re-shards the batch over survivors before
    answering. ``is_ready()`` polls the underlying device buffer so
    schedulers (VerifyFuture.done) never have to block to ask."""

    __slots__ = ("_mesh", "_args", "_devs", "_out", "_value")

    def __init__(self, mesh, args, devs, out):
        self._mesh, self._args = mesh, args
        self._devs, self._out = devs, out
        self._value = None

    def is_ready(self) -> bool:
        if self._value is not None:
            return True
        ready = getattr(self._out, "is_ready", None)
        return bool(ready()) if callable(ready) else True

    def __bool__(self) -> bool:
        if self._value is None:
            self._value = self._mesh._materialize(
                self._devs, self._out, self._args
            )
        return self._value


class MeshVerifier:
    """Sharded batch verification with per-device circuit breakers.

    The resilience upgrade over `make_sharded_verify`: a chip fault
    mid-batch must cost one re-shard, not the whole accelerator backend
    (ROADMAP open item). Each device carries its own ``CircuitBreaker``
    (the mesh-agnostic primitives from ``resilience/``); a failed batch
    probes the participating chips, opens the breakers of the dead ones,
    and re-runs the SAME global batch over the surviving devices -- the
    shard programs are pure functions of globally-shaped arrays, so
    results are bit-identical at every mesh size (test_multichip's
    contract). Open breakers mature half-open on their denied budget, so
    a recovered chip re-probes back into the mesh automatically.

    Mesh sizes are powers of two (bucketed batches divide evenly); one
    eligible device runs the plain single-device program -- the "mesh of
    one" IS the single-chip path. No eligible device raises
    :class:`MeshEmpty`.
    """

    def __init__(
        self,
        devices=None,
        events: EventLog | None = None,
        breaker_factory=None,
        executor=None,
        prober=None,
        program_factory=None,
        grouped_program_factory=None,
    ):
        self.devices = (
            list(jax.devices()) if devices is None else list(devices)
        )
        self.events = events
        self.executor = executor or DeviceExecutor()
        self.prober = prober or DeviceProber()
        # devices-tuple -> compiled program; injectable so fake-device
        # unit tests never touch shard_map/Mesh
        self.program_factory = program_factory or (
            lambda devs: make_sharded_verify(sets_mesh(list(devs)))
        )
        self.grouped_program_factory = grouped_program_factory or (
            lambda devs: make_sharded_verify_grouped(sets_mesh(list(devs)))
        )
        if breaker_factory is None:
            # clock-free: after `denied_budget` skipped batches the lost
            # chip gets one half-open probe batch (tests inject clocked
            # or tighter-budget breakers)
            def breaker_factory(device):
                return CircuitBreaker(
                    failure_threshold=1,
                    denied_budget=8,
                    half_open_probes=1,
                    name=f"bls_mesh/{device.id}",
                    events=events,
                )

        self.breakers = {
            d.id: breaker_factory(d) for d in self.devices
        }
        self._compiled: dict[tuple, object] = {}

    # -- mesh formation ------------------------------------------------------

    def _select_mesh(self, n_sets: int, include_recovering=True) -> list:
        """The devices for this batch: healthy (closed-breaker) chips
        first, then recovering ones whose breaker admits a half-open
        probe -- the probe batch IS the re-probe. Power-of-two sized so
        bucketed batches divide evenly. Empty means no usable device.

        ``include_recovering=False`` is the post-fault re-shard path:
        recovery probes belong to FUTURE batches -- re-admitting a
        maturing chip while re-sharding around a fault would let a
        small-budget breaker wedge the batch on the same dead chip."""
        closed, recovering = [], []
        for d in self.devices:
            b = self.breakers[d.id]
            if b.state == CircuitBreaker.CLOSED:
                closed.append(d)
            elif include_recovering and b.allow():
                # allow() consumes the denied budget / probe slot
                recovering.append(d)
        mesh_devs = self._pow2_prefix(closed + recovering, n_sets)
        seated = {d.id for d in mesh_devs}
        unseated = [d for d in recovering if d.id not in seated]
        if unseated and mesh_devs:
            # a matured probe is GUARANTEED a seat: when the closed set
            # alone already fills the pow2 mesh, swap probes in for tail
            # seats (same mesh size). Otherwise a recovered chip whose
            # maturity never coincides with a mesh-size boundary would
            # burn its probe slot forever and the mesh would stay pinned
            # below the healthy device count.
            k = min(len(unseated), max(1, len(mesh_devs) // 2))
            mesh_devs = mesh_devs[: len(mesh_devs) - k] + unseated[:k]
            unseated = unseated[k:]
        for d in unseated:
            # probe slot spent with no seat available this batch: reopen
            # so the budget machinery keeps cycling instead of wedging
            # half-open with zero probes left
            self.breakers[d.id].record_failure()
        return mesh_devs

    @staticmethod
    def _pow2_prefix(devices, n_sets: int) -> list:
        if not devices:
            return []
        size = 1
        while size * 2 <= len(devices) and size * 2 <= n_sets:
            size *= 2
        return devices[:size]

    def _program(self, mesh_devices: tuple, grouped: bool = False):
        key = (("grouped",) if grouped else ()) + tuple(
            d.id for d in mesh_devices
        )
        fn = self._compiled.get(key)
        if fn is None:
            factory = (
                self.grouped_program_factory
                if grouped
                else self.program_factory
            )
            fn = self._compiled[key] = factory(mesh_devices)
        return fn

    @staticmethod
    def _n_sets(args) -> int:
        """The batch's bucketed set count: `real`'s length. The grouped
        7-arg layout carries it at position 4 (the trailing args are the
        membership and message masks); the per-set 5-arg layout last."""
        return int(args[4].shape[0] if len(args) == 7 else args[-1].shape[0])

    # -- verification --------------------------------------------------------

    def verify(self, args):
        """One batch over the current mesh: `args` is the 5-tuple of
        globally-shaped per-set arrays (u, pk, sig, scalars, real), or
        the grouped 7-tuple (u, pk, sig, scalars, real, member,
        msg_real) for the per-message group reduction.
        Dispatches the device work NOW and returns a :class:`MeshVerdict`
        whose ``bool()`` materialises the answer -- JAX surfaces
        execution faults at materialisation, not dispatch, so breaker
        accounting and survivor re-sharding both live behind the verdict
        (a fault at either point re-shards the SAME batch over the
        surviving devices before answering). Raises MeshEmpty when no
        device remains."""
        n_sets = self._n_sets(args)
        mesh_devs = self._select_mesh(n_sets)
        if not mesh_devs:
            raise MeshEmpty(
                f"all {len(self.devices)} mesh devices are broken open"
            )
        try:
            out = self._dispatch(mesh_devs, args)
        except Exception as exc:  # noqa: BLE001 -- placement/compile/
            # transport fault at dispatch: attribute by probing and fall
            # through to the blocking re-shard loop
            self._on_mesh_fault(mesh_devs, exc)
            return self._verify_blocking(args)
        return MeshVerdict(self, args, mesh_devs, out)

    def tracer(self):
        # the PROCESS tracer (see pipeline.tracer): mesh spans must land
        # in the same ring as the pipeline spans that dispatched them
        return tracing.default_tracer()

    def _dispatch(self, mesh_devs, args):
        metrics.BLS_SHARD_MESH_SIZE.set(len(mesh_devs))
        # a mesh of one runs the plain single-chip program: same
        # computation, no shard_map/collective overhead, and the
        # "survivor" path is literally the single-chip path
        grouped = len(args) == 7
        if len(mesh_devs) == 1:
            fn = verify_grouped_jit if grouped else verify_jit
        else:
            fn = self._program(tuple(mesh_devs), grouped)
        with self.tracer().span("mesh_dispatch", devices=len(mesh_devs)):
            return self.executor.run(fn, args, mesh_devs)

    def _record_chip_timing(
        self, mesh_devs, seconds: float, n_sets: int | None = None
    ) -> None:
        """Per-chip shard timing: a sharded batch is one collective, so
        every participating chip is charged the batch wall (tracer
        clock); the per-chip labels make a straggling chip visible as a
        LARGER last-batch wall once the mesh drops it. Also the mesh's
        launch-ledger seam: the per-chip wall is only known here, at
        materialisation."""
        for d in mesh_devs:
            metrics.MESH_CHIP_BATCH_SECONDS.set(str(d.id), seconds)
        launch_ledger.record(
            "mesh",
            bucket=n_sets,
            padded_sets=n_sets,
            devices=len(mesh_devs),
            chip_seconds=seconds,
        )

    def _materialize(self, mesh_devs, out, args) -> bool:
        """Block on a dispatched verdict; success/failure lands on the
        participating breakers HERE, because this is where XLA actually
        reports a chip death. A fault re-runs the batch on survivors."""
        tracer = self.tracer()
        t0 = tracer.clock.now()
        try:
            with tracer.span("mesh_materialize", devices=len(mesh_devs)):
                out = jax.block_until_ready(out)
        except Exception as exc:  # noqa: BLE001 -- a chip died between
            # dispatch and materialisation; re-shard the same batch
            self._on_mesh_fault(mesh_devs, exc)
            return self._verify_blocking(args)
        self._record_chip_timing(
            mesh_devs, tracer.clock.now() - t0, n_sets=self._n_sets(args)
        )
        self._record_mesh_success(mesh_devs)
        return bool(out)

    def _verify_blocking(self, args) -> bool:
        """The post-fault path: re-shard over survivors until the batch
        completes, materialising each attempt before trusting it. Fault
        rounds are bounded by the device count: recovery probes belong
        to FUTURE batches, so one batch can never spin on a mesh whose
        breakers keep maturing mid-call."""
        n_sets = self._n_sets(args)
        # lint: allow[retry-no-backoff] -- not a retry of the same
        # resource: each round runs on a DIFFERENT (shrunken) mesh, and
        # waiting out a backoff would stall consensus on a healthy
        # survivor set; pacing for the lost chip is the breaker budget
        for _ in range(len(self.devices) + 1):
            mesh_devs = self._select_mesh(n_sets, include_recovering=False)
            if not mesh_devs:
                break
            tracer = self.tracer()
            t0 = tracer.clock.now()
            try:
                with tracer.span(
                    "mesh_materialize", devices=len(mesh_devs)
                ):
                    out = jax.block_until_ready(
                        self._dispatch(mesh_devs, args)
                    )
            except Exception as exc:  # noqa: BLE001 -- any failure here
                # is a device/runtime fault (injected or real);
                # attribution happens by probing, never by parsing the
                # exception
                self._on_mesh_fault(mesh_devs, exc)
                continue
            self._record_chip_timing(
                mesh_devs, tracer.clock.now() - t0, n_sets=n_sets
            )
            self._record_mesh_success(mesh_devs)
            return bool(out)
        raise MeshEmpty(
            f"all {len(self.devices)} mesh devices are broken open"
        )

    def _record_mesh_success(self, mesh_devs) -> None:
        for d in mesh_devs:
            self.breakers[d.id].record_success()
        metrics.BLS_SHARDED_BATCHES.inc()
        if self.events is not None:
            self.events.record("mesh_verify", devices=len(mesh_devs))

    def _probe_ok(self, device) -> bool:
        try:
            return bool(self.prober.probe(device))
        except Exception:  # noqa: BLE001 -- a probe that RAISES (real
            # transport error or injected FaultyProxy fault) is a dead
            # chip, same as one that returns False
            return False

    def _on_mesh_fault(self, mesh_devs, exc) -> None:
        dead = [d for d in mesh_devs if not self._probe_ok(d)]
        if not dead:
            # unattributable fault (e.g. a compile error): charge every
            # participant so a persistent failure still opens the mesh
            # instead of looping forever
            dead = list(mesh_devs)
        for d in dead:
            self.breakers[d.id].record_failure()
        metrics.BLS_MESH_SHRINKS.inc()
        if self.events is not None:
            self.events.record(
                "mesh_shrink",
                error=type(exc).__name__,
                lost=len(dead),
                was=len(mesh_devs),
            )
