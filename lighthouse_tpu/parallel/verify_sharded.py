"""Signature-set batch verification sharded across a device mesh.

The TPU-native replacement for the reference's rayon parallel batch verify
(consensus/state_processing/src/per_block_processing/block_signature_verifier.rs:374-384,
rayon chunks -> per-chunk blst multi-pairing): signature sets are sharded
over a 1-D `sets` mesh axis with `shard_map`; each chip runs hash-to-G2,
ladders, and Miller loops for its shard; the two tiny cross-set reductions
(one G2 point, one Fp12 element) ride ICI all_gathers; the shared final
exponentiation is replicated.

This is the "v4-8 pod / 1M-validator synthetic network" configuration of
BASELINE.md: throughput scales with mesh size because per-set work
dominates and the collective payload is constant (~4.6 KB per chip).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..crypto.bls.backends.jax_tpu import verify_body

AXIS = "sets"


def sets_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name 'sets'."""
    import numpy as np

    devices = list(jax.devices()) if devices is None else list(devices)
    return Mesh(np.array(devices), (AXIS,))


def make_sharded_verify(mesh: Mesh):
    """Returns a jitted verifier over `mesh`: inputs are globally-shaped
    arrays sharded on their leading (set) axis; output is a replicated
    scalar bool. Set counts must divide evenly by the mesh size (callers
    pad to bucket sizes, which are powers of two)."""

    spec = P(AXIS)
    rep = P()

    body = shard_map(
        lambda u, pk, sig, r, real: verify_body(
            u, pk, sig, r, real, axis_name=AXIS
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec),
        out_specs=rep,
        check_vma=False,
    )
    return jax.jit(body)
