"""Multi-chip parallelism for the TPU framework.

The reference's concurrency inventory (SURVEY.md section 2.5) maps here:
rayon batch map-reduce -> sharded batch kernels over a `jax.sharding.Mesh`
with XLA collectives on ICI; the p2p fabric stays host-side.
"""

from .verify_sharded import (  # noqa: F401
    DeviceExecutor,
    DeviceProber,
    MeshEmpty,
    MeshVerifier,
    make_sharded_gather,
    make_sharded_verify,
    pow2_device_prefix,
    sets_mesh,
    validators_mesh,
)
