"""Altair participation flags, weights, and base rewards (spec constants;
reference consensus/types/src/participation_flags.rs and
state_processing altair helpers)."""

from __future__ import annotations

from ..types import compute_epoch_at_slot
from ..types.helpers import (
    get_block_root,
    get_block_root_at_slot,
    get_total_active_balance,
)
from ..types.presets import Preset
from ..utils.math import integer_squareroot

TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2

TIMELY_SOURCE_WEIGHT = 14
TIMELY_TARGET_WEIGHT = 26
TIMELY_HEAD_WEIGHT = 14
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64

PARTICIPATION_FLAG_WEIGHTS = [
    TIMELY_SOURCE_WEIGHT,
    TIMELY_TARGET_WEIGHT,
    TIMELY_HEAD_WEIGHT,
]


def has_flag(flags: int, flag_index: int) -> bool:
    return bool(flags & (1 << flag_index))


def add_flag(flags: int, flag_index: int) -> int:
    return flags | (1 << flag_index)


def get_attestation_participation_flag_indices(
    state, data, inclusion_delay: int, preset: Preset, spec
) -> list[int]:
    """Which timeliness flags an attestation earns (spec
    get_attestation_participation_flag_indices)."""
    justified = (
        state.current_justified_checkpoint
        if data.target.epoch == compute_epoch_at_slot(state.slot, preset)
        else state.previous_justified_checkpoint
    )
    is_matching_source = data.source == justified
    if not is_matching_source:
        raise ValueError("attestation source does not match justified")
    is_matching_target = is_matching_source and bytes(
        data.target.root
    ) == bytes(get_block_root(state, data.target.epoch, preset))
    is_matching_head = is_matching_target and bytes(
        data.beacon_block_root
    ) == bytes(get_block_root_at_slot(state, data.slot, preset))

    flags = []
    if is_matching_source and inclusion_delay <= integer_squareroot(
        preset.slots_per_epoch
    ):
        flags.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and inclusion_delay <= preset.slots_per_epoch:
        flags.append(TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == spec.min_attestation_inclusion_delay:
        flags.append(TIMELY_HEAD_FLAG_INDEX)
    return flags


def get_base_reward_per_increment(state, preset: Preset, spec) -> int:
    return (
        spec.effective_balance_increment
        * spec.base_reward_factor
        // integer_squareroot(get_total_active_balance(state, preset, spec))
    )


def get_base_reward_altair(
    state, index: int, base_reward_per_increment: int, preset: Preset, spec
) -> int:
    increments = (
        state.validators[index].effective_balance
        // spec.effective_balance_increment
    )
    return increments * base_reward_per_increment
