"""Signature-set builders: one function per signed consensus message kind
(reference consensus/state_processing/src/per_block_processing/
signature_sets.rs:74-573). Every builder returns a `SignatureSet`
{signature, pubkeys, signing_root} ready for the batch verifier -- the
builders never verify anything themselves.

Pubkeys are resolved through a `get_pubkey(validator_index) -> PublicKey`
closure so callers can plug the device-resident pubkey table (the
reference threads its ValidatorPubkeyCache the same way,
block_verification.rs:1858-1890).
"""

from __future__ import annotations

import functools

from ..crypto.bls import PublicKey, Signature, SignatureSet
from ..types import (
    ChainSpec,
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_CONTRIBUTION_AND_PROOF,
    DOMAIN_DEPOSIT,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    DOMAIN_VOLUNTARY_EXIT,
    compute_domain,
    compute_epoch_at_slot,
    compute_signing_root,
    get_domain,
)
from ..types.containers import DepositMessage, SigningData
from ..types.presets import Preset
from ..ssz import uint64


class SignatureSetError(ValueError):
    pass


@functools.lru_cache(maxsize=65536)
def _decompress(pubkey_bytes: bytes) -> PublicKey:
    return PublicKey.from_bytes(pubkey_bytes)


def state_pubkey_getter(state):
    """Default get_pubkey closure: decompress from the state registry with
    an LRU (the cache-less fallback path; production uses PubkeyTable)."""

    def get_pubkey(index: int) -> PublicKey:
        if index >= len(state.validators):
            raise SignatureSetError(f"unknown validator index {index}")
        return _decompress(bytes(state.validators[index].pubkey))

    return get_pubkey


def _sig(signature_bytes: bytes) -> Signature:
    try:
        return Signature.from_bytes(bytes(signature_bytes))
    except Exception as e:
        raise SignatureSetError(f"malformed signature: {e}") from None


# --- block proposal & randao (signature_sets.rs:74-178) --------------------


def block_proposal_signature_set(
    state, get_pubkey, signed_block, preset: Preset, spec: ChainSpec
) -> SignatureSet:
    block = signed_block.message
    epoch = compute_epoch_at_slot(block.slot, preset)
    domain = get_domain(state, DOMAIN_BEACON_PROPOSER, epoch, preset)
    root = compute_signing_root(block, domain)
    return SignatureSet.single_pubkey(
        _sig(signed_block.signature), get_pubkey(block.proposer_index), root
    )


def randao_signature_set(
    state, get_pubkey, proposer_index: int, randao_reveal, preset, spec
) -> SignatureSet:
    epoch = compute_epoch_at_slot(state.slot, preset)
    domain = get_domain(state, DOMAIN_RANDAO, epoch, preset)
    root = SigningData(
        object_root=uint64.hash_tree_root(epoch), domain=domain
    ).tree_hash_root()
    return SignatureSet.single_pubkey(
        _sig(randao_reveal), get_pubkey(proposer_index), root
    )


# --- slashings (signature_sets.rs:180-260) ---------------------------------


def proposer_slashing_signature_sets(
    state, get_pubkey, slashing, preset, spec
) -> list[SignatureSet]:
    out = []
    for signed_header in (slashing.signed_header_1, slashing.signed_header_2):
        header = signed_header.message
        epoch = compute_epoch_at_slot(header.slot, preset)
        domain = get_domain(state, DOMAIN_BEACON_PROPOSER, epoch, preset)
        root = compute_signing_root(header, domain)
        out.append(
            SignatureSet.single_pubkey(
                _sig(signed_header.signature),
                get_pubkey(header.proposer_index),
                root,
            )
        )
    return out


def indexed_attestation_signature_set(
    state, get_pubkey, indexed_attestation, preset, spec
) -> SignatureSet:
    data = indexed_attestation.data
    domain = get_domain(state, DOMAIN_BEACON_ATTESTER, data.target.epoch, preset)
    root = compute_signing_root(data, domain)
    pubkeys = [get_pubkey(i) for i in indexed_attestation.attesting_indices]
    if not pubkeys:
        raise SignatureSetError("indexed attestation with no attesters")
    return SignatureSet.multiple_pubkeys(
        _sig(indexed_attestation.signature), pubkeys, root
    )


def attester_slashing_signature_sets(
    state, get_pubkey, slashing, preset, spec
) -> list[SignatureSet]:
    return [
        indexed_attestation_signature_set(
            state, get_pubkey, slashing.attestation_1, preset, spec
        ),
        indexed_attestation_signature_set(
            state, get_pubkey, slashing.attestation_2, preset, spec
        ),
    ]


# --- deposits (signature_sets.rs:262-300) ----------------------------------


def deposit_signature_set(deposit_data, spec: ChainSpec) -> SignatureSet:
    """Deposits sign with the genesis-version domain and NO
    genesis_validators_root (they predate the state)."""
    message = DepositMessage(
        pubkey=deposit_data.pubkey,
        withdrawal_credentials=deposit_data.withdrawal_credentials,
        amount=deposit_data.amount,
    )
    domain = compute_domain(
        DOMAIN_DEPOSIT, spec.genesis_fork_version, bytes(32)
    )
    root = compute_signing_root(message, domain)
    pubkey = PublicKey.from_bytes(bytes(deposit_data.pubkey))
    return SignatureSet.single_pubkey(_sig(deposit_data.signature), pubkey, root)


# --- exits (signature_sets.rs:302-330) -------------------------------------


def exit_signature_set(
    state, get_pubkey, signed_exit, preset, spec
) -> SignatureSet:
    exit_msg = signed_exit.message
    domain = get_domain(state, DOMAIN_VOLUNTARY_EXIT, exit_msg.epoch, preset)
    root = compute_signing_root(exit_msg, domain)
    return SignatureSet.single_pubkey(
        _sig(signed_exit.signature), get_pubkey(exit_msg.validator_index), root
    )


# --- aggregate and proof (signature_sets.rs:332-420) -----------------------


def selection_proof_signature_set(
    state, get_pubkey, signed_aggregate, preset, spec
) -> SignatureSet:
    msg = signed_aggregate.message
    slot = msg.aggregate.data.slot
    domain = get_domain(
        state,
        DOMAIN_SELECTION_PROOF,
        compute_epoch_at_slot(slot, preset),
        preset,
    )
    root = SigningData(
        object_root=uint64.hash_tree_root(slot), domain=domain
    ).tree_hash_root()
    return SignatureSet.single_pubkey(
        _sig(msg.selection_proof), get_pubkey(msg.aggregator_index), root
    )


def aggregate_and_proof_signature_set(
    state, get_pubkey, signed_aggregate, preset, spec
) -> SignatureSet:
    msg = signed_aggregate.message
    epoch = compute_epoch_at_slot(msg.aggregate.data.slot, preset)
    domain = get_domain(state, DOMAIN_AGGREGATE_AND_PROOF, epoch, preset)
    root = compute_signing_root(msg, domain)
    return SignatureSet.single_pubkey(
        _sig(signed_aggregate.signature), get_pubkey(msg.aggregator_index), root
    )


# --- sync committee (signature_sets.rs:422-573) ----------------------------


def sync_committee_message_set(
    state, get_pubkey, message, preset, spec
) -> SignatureSet:
    epoch = compute_epoch_at_slot(message.slot, preset)
    domain = get_domain(state, DOMAIN_SYNC_COMMITTEE, epoch, preset)
    root = SigningData(
        object_root=bytes(message.beacon_block_root), domain=domain
    ).tree_hash_root()
    return SignatureSet.single_pubkey(
        _sig(message.signature), get_pubkey(message.validator_index), root
    )


def sync_aggregate_signature_set(
    state,
    get_pubkey_bytes,
    sync_aggregate,
    slot: int,
    beacon_block_root: bytes,
    committee_pubkeys: list[bytes],
    preset,
    spec,
) -> SignatureSet | None:
    """Set for a block's sync aggregate: participants are the bit-selected
    subset of the CURRENT sync committee. Signs the PREVIOUS slot's block
    root at the previous slot's epoch domain. Returns None for the empty
    aggregate with the infinity signature (valid by spec)."""
    bits = list(sync_aggregate.sync_committee_bits)
    participants = [
        pk for pk, bit in zip(committee_pubkeys, bits) if bit
    ]
    sig = _sig(sync_aggregate.sync_committee_signature)
    if not participants:
        if sig.is_infinity():
            return None
        raise SignatureSetError("non-infinity signature with no participants")
    prev_slot = max(slot - 1, 0)
    epoch = compute_epoch_at_slot(prev_slot, preset)
    domain = get_domain(state, DOMAIN_SYNC_COMMITTEE, epoch, preset)
    root = SigningData(
        object_root=bytes(beacon_block_root), domain=domain
    ).tree_hash_root()
    resolve = get_pubkey_bytes or _decompress
    pubkeys = [resolve(bytes(pk)) for pk in participants]
    return SignatureSet.multiple_pubkeys(sig, pubkeys, root)


def sync_selection_proof_signature_set(
    state, get_pubkey, signed_contribution, preset, spec
) -> SignatureSet:
    from ..types.containers import SyncAggregatorSelectionData

    msg = signed_contribution.message
    contribution = msg.contribution
    epoch = compute_epoch_at_slot(contribution.slot, preset)
    domain = get_domain(
        state, DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch, preset
    )
    data = SyncAggregatorSelectionData(
        slot=contribution.slot,
        subcommittee_index=contribution.subcommittee_index,
    )
    root = compute_signing_root(data, domain)
    return SignatureSet.single_pubkey(
        _sig(msg.selection_proof), get_pubkey(msg.aggregator_index), root
    )


def contribution_and_proof_signature_set(
    state, get_pubkey, signed_contribution, preset, spec
) -> SignatureSet:
    msg = signed_contribution.message
    epoch = compute_epoch_at_slot(msg.contribution.slot, preset)
    domain = get_domain(state, DOMAIN_CONTRIBUTION_AND_PROOF, epoch, preset)
    root = compute_signing_root(msg, domain)
    return SignatureSet.single_pubkey(
        _sig(signed_contribution.signature), get_pubkey(msg.aggregator_index), root
    )


def sync_committee_contribution_signature_set(
    state, signed_contribution, subcommittee_pubkeys, preset, spec,
    resolve_pubkey=None,
) -> SignatureSet | None:
    contribution = signed_contribution.message.contribution
    bits = list(contribution.aggregation_bits)
    participants = [
        pk for pk, bit in zip(subcommittee_pubkeys, bits) if bit
    ]
    sig = _sig(contribution.signature)
    if not participants:
        if sig.is_infinity():
            return None
        raise SignatureSetError("non-infinity signature with no participants")
    epoch = compute_epoch_at_slot(contribution.slot, preset)
    domain = get_domain(state, DOMAIN_SYNC_COMMITTEE, epoch, preset)
    root = SigningData(
        object_root=bytes(contribution.beacon_block_root), domain=domain
    ).tree_hash_root()
    resolve = resolve_pubkey or _decompress
    pubkeys = [resolve(bytes(pk)) for pk in participants]
    return SignatureSet.multiple_pubkeys(sig, pubkeys, root)
