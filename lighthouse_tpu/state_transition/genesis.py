"""Spec genesis: state initialization from eth1 deposits + validity.

Reference consensus/state_processing/src/genesis.rs
(initialize_beacon_state_from_eth1, is_valid_genesis_state) and the
beacon_node/genesis crate's service that watches the deposit contract
until a valid genesis forms. The incremental deposit root recomputed
per applied deposit is the SSZ list root the spec prescribes -- the
same mix-in-count root DepositDataTree produces.
"""

from __future__ import annotations

from ..eth1.deposit_tree import DepositDataTree
from ..types.chain_spec import ChainSpec
from ..types.containers import BeaconBlockHeader, Eth1Data, Fork, types_for
from ..types.helpers import get_active_validator_indices
from ..types.presets import Preset
from .context import ConsensusContext
from .per_block import process_deposit
from .upgrades import upgrade_to_altair, upgrade_to_bellatrix


def initialize_beacon_state_from_eth1(
    eth1_block_hash: bytes,
    eth1_timestamp: int,
    deposits,
    preset: Preset,
    spec: ChainSpec,
    execution_payload_header=None,
):
    """Spec initialize_beacon_state_from_eth1: a candidate phase0 genesis
    state from an eth1 block + its deposit list. Deposit proofs are
    verified against the incrementally-growing list root, exactly as
    during block processing (genesis.rs builds the same tree)."""
    t = types_for(preset)
    state = t.BeaconState.default()
    state.genesis_time = eth1_timestamp + spec.genesis_delay
    state.fork = Fork(
        previous_version=spec.genesis_fork_version,
        current_version=spec.genesis_fork_version,
        epoch=0,
    )
    state.eth1_data = Eth1Data(
        deposit_root=bytes(32),
        deposit_count=len(deposits),
        block_hash=bytes(eth1_block_hash),
    )
    state.latest_block_header = BeaconBlockHeader(
        body_root=t.BeaconBlockBody.default().tree_hash_root()
    )
    state.randao_mixes = tuple(
        bytes(eth1_block_hash)
        for _ in range(preset.epochs_per_historical_vector)
    )

    tree = DepositDataTree()
    ctxt = ConsensusContext(preset, spec)
    for i, deposit in enumerate(deposits):
        tree.push(deposit.data)
        state.eth1_data = Eth1Data(
            deposit_root=tree.root(i + 1),
            deposit_count=len(deposits),
            block_hash=bytes(eth1_block_hash),
        )
        process_deposit(state, deposit, preset, spec, ctxt)

    # post-deposit fix-up: snap effective balances, activate full stakes
    # (safe to mutate: every Validator here was freshly built by
    # apply_deposit above, nothing aliases them yet)
    for index, v in enumerate(state.validators):
        balance = state.balances[index]
        v.effective_balance = min(
            balance - balance % spec.effective_balance_increment,
            spec.max_effective_balance,
        )
        if v.effective_balance == spec.max_effective_balance:
            v.activation_eligibility_epoch = 0
            v.activation_epoch = 0
    from ..types.helpers import validators_registry_root

    state.genesis_validators_root = validators_registry_root(state)

    # forks active at genesis upgrade the candidate in place
    # (genesis/src/lib.rs upgrades through the schedule before returning)
    name = spec.fork_name_at_epoch(0)
    if name in ("altair", "bellatrix"):
        state = upgrade_to_altair(state, preset, spec)
        # genesis.rs:54-67: a fork active AT genesis has no predecessor;
        # previous_version equals the fork's own version
        state.fork.previous_version = spec.altair_fork_version
    if name == "bellatrix":
        state = upgrade_to_bellatrix(state, preset, spec)
        state.fork.previous_version = spec.bellatrix_fork_version
        if execution_payload_header is not None:
            # merge-at-genesis testnets seed the header directly (spec
            # bellatrix initialize_beacon_state_from_eth1 extension)
            state.latest_execution_payload_header = execution_payload_header
    return state


def is_valid_genesis_state(state, preset: Preset, spec: ChainSpec) -> bool:
    """Spec is_valid_genesis_state: enough time and enough full stakes."""
    if state.genesis_time < spec.min_genesis_time:
        return False
    return (
        len(get_active_validator_indices(state, 0))
        >= spec.min_genesis_active_validator_count
    )


def try_genesis_from_eth1(service, preset: Preset, spec: ChainSpec):
    """Genesis waiter over an Eth1Service: scan cached eth1 blocks oldest-
    first for the first whose deposit snapshot forms a valid genesis
    (beacon_node/genesis/src/lib.rs Eth1GenesisService). Returns the
    genesis state or None if no cached block qualifies yet; call after
    each service.update()."""
    for blk in service.block_cache:
        if blk.timestamp + spec.genesis_delay < spec.min_genesis_time:
            continue
        if blk.deposit_count < spec.min_genesis_active_validator_count:
            # necessary condition for validity; skips the expensive proof
            # rebuild + replay on every poll tick while deposits trickle in
            continue
        # incremental proofs: deposit i proves against root(i + 1), the
        # growing list root initialize verifies step by step
        deposits = [
            service.deposit_tree.deposit(i, service._deposit_data[i], i + 1)
            for i in range(blk.deposit_count)
        ]
        state = initialize_beacon_state_from_eth1(
            blk.hash, blk.timestamp, deposits, preset, spec
        )
        if is_valid_genesis_state(state, preset, spec):
            return state
    return None
