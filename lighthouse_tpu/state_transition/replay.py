"""Block replayer: apply a range of blocks to a state with configurable
signature strategy and per-slot/-block hooks (reference
consensus/state_processing/src/block_replayer.rs -- used by historical
state reconstruction and the database's block-range replay)."""

from __future__ import annotations

from ..types.presets import Preset
from .per_block import BlockSignatureStrategy, per_block_processing
from .per_slot import clone_state, process_slots


class BlockReplayer:
    def __init__(
        self,
        state,
        preset: Preset,
        spec,
        strategy: BlockSignatureStrategy = BlockSignatureStrategy.NO_VERIFICATION,
        state_root_provider=None,
        pre_block_hook=None,
        pre_slot_hook=None,
    ):
        self.state = clone_state(state)
        self.preset = preset
        self.spec = spec
        self.strategy = strategy
        self.state_root_provider = state_root_provider
        self.pre_block_hook = pre_block_hook
        self.pre_slot_hook = pre_slot_hook

    def apply_blocks(self, blocks, target_slot: int | None = None):
        for signed_block in blocks:
            block = signed_block.message
            if self.pre_slot_hook:
                self.pre_slot_hook(self.state)
            self.state = process_slots(
                self.state, block.slot, self.preset, self.spec
            )
            if self.pre_block_hook:
                self.pre_block_hook(self.state, signed_block)
            per_block_processing(
                self.state,
                signed_block,
                self.preset,
                self.spec,
                strategy=self.strategy,
            )
        if target_slot is not None and target_slot > self.state.slot:
            self.state = process_slots(
                self.state, target_slot, self.preset, self.spec
            )
        return self
