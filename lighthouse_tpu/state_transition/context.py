"""Per-block processing context: memoizes proposer index and indexed
attestations so gossip verification, signature batching, and state
transition share one computation (reference
consensus/state_processing/src/consensus_context.rs:136)."""

from __future__ import annotations

from ..types import CommitteeCache, compute_epoch_at_slot
from ..types.presets import Preset


class BlockProcessingError(ValueError):
    pass


class ConsensusContext:
    def __init__(self, preset: Preset, spec):
        self.preset = preset
        self.spec = spec
        self.proposer_index: int | None = None
        self._indexed: dict[bytes, object] = {}
        self._committee_caches: dict[int, CommitteeCache] = {}
        self._pubkey_map: dict[bytes, int] | None = None
        self._pubkey_map_len = 0
        # engine hook for process_execution_payload (payload -> bool or a
        # PayloadVerificationStatus); None = no engine round trip (replay)
        self.notify_new_payload = None
        # set by the hook's caller after import, for optimistic tracking
        self.payload_verification_status = None

    def pubkey_to_index(self, state, pubkey: bytes) -> int | None:
        """Registry pubkey -> validator index, built once and extended
        incrementally as deposits append validators (avoids an O(V) scan
        per deposit)."""
        n = len(state.validators)
        if self._pubkey_map is None:
            self._pubkey_map = {
                bytes(v.pubkey): i for i, v in enumerate(state.validators)
            }
            self._pubkey_map_len = n
        elif self._pubkey_map_len < n:
            for i in range(self._pubkey_map_len, n):
                self._pubkey_map[bytes(state.validators[i].pubkey)] = i
            self._pubkey_map_len = n
        return self._pubkey_map.get(bytes(pubkey))

    def get_proposer_index(self, state) -> int:
        """Memoized proposer for the block's slot (consensus_context.rs
        proposer_index): the weighted-sampling loop is O(active set), and a
        block consults it once per attestation/slashing/sync-aggregate."""
        if self.proposer_index is None:
            from .per_slot import get_beacon_proposer_index

            self.proposer_index = get_beacon_proposer_index(
                state, self.preset, self.spec
            )
        return self.proposer_index

    def committee_cache(self, state, epoch: int) -> CommitteeCache:
        cache = self._committee_caches.get(epoch)
        if cache is None:
            current = compute_epoch_at_slot(state.slot, self.preset)
            if epoch not in (current, current - 1, current + 1):
                raise BlockProcessingError(
                    f"committee cache for epoch {epoch} unavailable at {current}"
                )
            cache = CommitteeCache(state, epoch, self.preset, self.spec)
            self._committee_caches[epoch] = cache
        return cache

    def get_indexed_attestation(self, state, attestation):
        """Committee-sorted indexed form, memoized by attestation root
        (consensus_context.rs get_indexed_attestation)."""
        key = attestation.tree_hash_root()
        hit = self._indexed.get(key)
        if hit is not None:
            return hit
        data = attestation.data
        epoch = compute_epoch_at_slot(data.slot, self.preset)
        cache = self.committee_cache(state, epoch)
        committee = cache.get_beacon_committee(data.slot, data.index)
        bits = list(attestation.aggregation_bits)
        if len(bits) != len(committee):
            raise BlockProcessingError(
                f"aggregation bits {len(bits)} != committee {len(committee)}"
            )
        indices = sorted(i for i, b in zip(committee, bits) if b)
        from ..types import types_for

        t = types_for(self.preset)
        indexed = t.IndexedAttestation(
            attesting_indices=tuple(indices),
            data=data,
            signature=attestation.signature,
        )
        self._indexed[key] = indexed
        return indexed
