"""Per-epoch state transition, phase0 + altair (reference
consensus/state_processing/src/per_epoch_processing.rs and its
per_epoch_processing/{base,altair} modules).

Runs at the last slot of each epoch (before the slot increments), so
"current epoch" below is the epoch being closed.
"""

from __future__ import annotations

from ..types import (
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    compute_activation_exit_epoch,
    compute_epoch_at_slot,
    get_active_validator_indices,
    is_active_validator,
)
from ..types.containers import Checkpoint
from ..types.helpers import (
    apply_balance_deltas,
    decrease_balance,
    get_block_root,
    get_block_root_at_slot,
    get_total_balance,
)
from ..types.presets import Preset
from ..utils.math import integer_squareroot
from .participation import (
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    get_base_reward_per_increment,
    has_flag,
)

BASE_REWARDS_PER_EPOCH = 4


def process_epoch(state, preset: Preset, spec):
    if state.fork_name == "phase0":
        _process_epoch_base(state, preset, spec)
        return
    import os

    if os.environ.get("LIGHTHOUSE_TPU_EPOCH_ORACLE"):
        _process_epoch_altair(state, preset, spec)
        return
    from .per_epoch_vec import VectorGuard, process_epoch_altair_vec

    if os.environ.get("LIGHTHOUSE_TPU_EPOCH_MESH") == "1":
        from .per_epoch_mesh import process_epoch_altair_mesh

        try:
            process_epoch_altair_mesh(state, preset, spec)
            return
        except VectorGuard:
            pass  # fall through: vec, then (via its guard) the oracle
    try:
        process_epoch_altair_vec(state, preset, spec)
    except VectorGuard:
        # magnitude guard tripped (pathological state): the arbitrary-
        # precision loop oracle is always exact
        _process_epoch_altair(state, preset, spec)


def compute_unrealized_checkpoints(state, preset: Preset, spec):
    """What (justified, finalized) WOULD become if the next epoch boundary
    processed this state's attestations right now -- the fork-choice
    unrealized-justification input (reference fork_choice.rs
    compute_unrealized_checkpoints / state_processing's
    per_epoch_processing::altair::participation_cache justifiability).

    Runs the real weigh function against the live state, then restores the
    four fields it mutates -- no state clone."""
    current_epoch = _current_epoch(state, preset)
    jc = (
        state.current_justified_checkpoint.epoch,
        bytes(state.current_justified_checkpoint.root),
    )
    fc = (
        state.finalized_checkpoint.epoch,
        bytes(state.finalized_checkpoint.root),
    )
    if current_epoch <= GENESIS_EPOCH + 1:
        return jc, fc
    if not hasattr(state, "previous_justified_checkpoint"):
        # reduced/stub states without the justification machinery (test
        # doubles): nothing unrealized to compute
        return jc, fc

    saved = (
        state.previous_justified_checkpoint,
        state.current_justified_checkpoint,
        state.justification_bits,
        state.finalized_checkpoint,
    )
    try:
        total_balance, prev_bal, cur_bal = _justification_target_balances(
            state, preset, spec
        )
        _weigh_justification_and_finalization(
            state, total_balance, prev_bal, cur_bal, preset
        )
        ujc = (
            state.current_justified_checkpoint.epoch,
            bytes(state.current_justified_checkpoint.root),
        )
        ufc = (
            state.finalized_checkpoint.epoch,
            bytes(state.finalized_checkpoint.root),
        )
        return ujc, ufc
    finally:
        (
            state.previous_justified_checkpoint,
            state.current_justified_checkpoint,
            state.justification_bits,
            state.finalized_checkpoint,
        ) = saved


def _justification_target_balances(state, preset: Preset, spec):
    """(total_active, prev_target, cur_target) balances feeding
    weigh_justification_and_finalization — the ONE implementation behind
    the full transitions, the isolated EF sub-transition, and the
    fork-choice unrealized-checkpoint computation."""
    current_epoch = _current_epoch(state, preset)
    total = _total_active_balance(state, preset, spec)
    if state.fork_name == "phase0":
        cache_map: dict = {}
        prev = _attesting_indices(
            state,
            _matching_target_attestations(
                state, _previous_epoch(state, preset), preset
            ),
            preset,
            spec,
            cache_map,
        )
        try:
            cur_matching = _matching_target_attestations(
                state, current_epoch, preset
            )
        except ValueError:
            # a state AT its epoch-start slot has no current-epoch block
            # root yet (and necessarily no current-epoch attestations)
            cur_matching = []
        cur = _attesting_indices(state, cur_matching, preset, spec, cache_map)
    else:
        prev = _unslashed_participating_indices(
            state,
            TIMELY_TARGET_FLAG_INDEX,
            _previous_epoch(state, preset),
            preset,
        )
        cur = _unslashed_participating_indices(
            state, TIMELY_TARGET_FLAG_INDEX, current_epoch, preset
        )
    return (
        total,
        get_total_balance(state, prev, spec),
        get_total_balance(state, cur, spec),
    )


def _rotate_participation(state) -> None:
    """End-of-epoch participation rotation, both flavors."""
    if state.fork_name == "phase0":
        state.previous_epoch_attestations = state.current_epoch_attestations
        state.current_epoch_attestations = ()
    else:
        state.previous_epoch_participation = state.current_epoch_participation
        state.current_epoch_participation = tuple(0 for _ in state.validators)


def run_epoch_sub_transition(state, handler: str, preset: Preset, spec):
    """Run ONE epoch sub-transition by its EF-vector handler name
    (testing/ef_tests/src/cases/epoch_processing.rs maps the same names
    to the same isolated spec functions). The official epoch_processing
    vectors' post-states reflect only the named step, so the runner must
    not execute the full transition."""
    phase0 = state.fork_name == "phase0"
    current_epoch = _current_epoch(state, preset)
    if handler == "justification_and_finalization":
        if current_epoch <= GENESIS_EPOCH + 1:
            return
        total, prev_bal, cur_bal = _justification_target_balances(
            state, preset, spec
        )
        _weigh_justification_and_finalization(
            state, total, prev_bal, cur_bal, preset
        )
    elif handler == "inactivity_updates":
        if not phase0 and current_epoch > GENESIS_EPOCH:
            _process_inactivity_updates(state, preset, spec)
    elif handler == "rewards_and_penalties":
        if current_epoch <= GENESIS_EPOCH:
            return
        total = _total_active_balance(state, preset, spec)
        if phase0:
            rewards, penalties = _attestation_deltas(
                state, preset, spec, {}, total
            )
        else:
            rewards, penalties = _flag_deltas(state, preset, spec, total)
        apply_balance_deltas(state, rewards, penalties)
    elif handler == "registry_updates":
        _process_registry_updates(state, preset, spec)
    elif handler == "slashings":
        _process_slashings(
            state,
            preset,
            spec,
            spec.proportional_slashing_multiplier_for(state.fork_name),
        )
    elif handler == "eth1_data_reset":
        _process_eth1_data_reset(state, preset)
    elif handler == "effective_balance_updates":
        _process_effective_balance_updates(state, spec)
    elif handler == "slashings_reset":
        _process_slashings_reset(state, preset)
    elif handler == "randao_mixes_reset":
        _process_randao_mixes_reset(state, preset)
    elif handler in ("historical_roots_update", "historical_summaries_update"):
        _process_historical_roots_update(state, preset)
    elif handler in (
        "participation_record_updates",
        "participation_flag_updates",
    ):
        _rotate_participation(state)
    elif handler == "sync_committee_updates":
        _process_sync_committee_updates(state, preset, spec)
    else:
        raise ValueError(f"unknown epoch sub-transition {handler!r}")


# ===========================================================================
# shared machinery
# ===========================================================================


def _current_epoch(state, preset):
    return compute_epoch_at_slot(state.slot, preset)


def _previous_epoch(state, preset):
    cur = _current_epoch(state, preset)
    return cur - 1 if cur > GENESIS_EPOCH else GENESIS_EPOCH


def _total_active_balance(state, preset, spec):
    return get_total_balance(
        state,
        get_active_validator_indices(state, _current_epoch(state, preset)),
        spec,
    )


def _finality_delay(state, preset):
    return (
        _previous_epoch(state, preset)
        - state.finalized_checkpoint.epoch
    )


def _is_in_inactivity_leak(state, preset, spec):
    return _finality_delay(state, preset) > spec.min_epochs_to_inactivity_penalty


def _eligible_validator_indices(state, preset):
    prev = _previous_epoch(state, preset)
    return [
        i
        for i, v in enumerate(state.validators)
        if is_active_validator(v, prev)
        or (v.slashed and prev + 1 < v.withdrawable_epoch)
    ]


def _weigh_justification_and_finalization(
    state,
    total_active_balance: int,
    previous_target_balance: int,
    current_target_balance: int,
    preset: Preset,
):
    """Spec weigh_justification_and_finalization -- shared by both forks."""
    previous_epoch = _previous_epoch(state, preset)
    current_epoch = _current_epoch(state, preset)
    old_previous_justified = state.previous_justified_checkpoint
    old_current_justified = state.current_justified_checkpoint

    state.previous_justified_checkpoint = state.current_justified_checkpoint
    bits = list(state.justification_bits)
    bits = [False] + bits[:-1]

    if previous_target_balance * 3 >= total_active_balance * 2:
        state.current_justified_checkpoint = Checkpoint(
            epoch=previous_epoch,
            root=get_block_root(state, previous_epoch, preset),
        )
        bits[1] = True
    if current_target_balance * 3 >= total_active_balance * 2:
        state.current_justified_checkpoint = Checkpoint(
            epoch=current_epoch,
            root=get_block_root(state, current_epoch, preset),
        )
        bits[0] = True
    state.justification_bits = tuple(bits)

    # finalization
    if all(bits[1:4]) and old_previous_justified.epoch + 3 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[1:3]) and old_previous_justified.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[0:3]) and old_current_justified.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_current_justified
    if all(bits[0:2]) and old_current_justified.epoch + 1 == current_epoch:
        state.finalized_checkpoint = old_current_justified


def _process_registry_updates(state, preset, spec):
    current_epoch = _current_epoch(state, preset)
    vals = list(state.validators)
    for v in vals:
        if (
            v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
            and v.effective_balance == spec.max_effective_balance
        ):
            v.activation_eligibility_epoch = current_epoch + 1
        if (
            is_active_validator(v, current_epoch)
            and v.effective_balance <= spec.ejection_balance
        ):
            from .per_block import initiate_validator_exit

            state.validators = tuple(vals)
            initiate_validator_exit(state, vals.index(v), preset, spec)
            vals = list(state.validators)

    activation_queue = sorted(
        (
            i
            for i, v in enumerate(vals)
            if v.activation_eligibility_epoch <= state.finalized_checkpoint.epoch
            and v.activation_epoch == FAR_FUTURE_EPOCH
        ),
        key=lambda i: (vals[i].activation_eligibility_epoch, i),
    )
    active = len(get_active_validator_indices(state, current_epoch))
    churn_limit = max(
        spec.min_per_epoch_churn_limit, active // spec.churn_limit_quotient
    )
    for i in activation_queue[:churn_limit]:
        vals[i].activation_epoch = compute_activation_exit_epoch(
            current_epoch, spec
        )
    state.validators = tuple(vals)


def _process_slashings(state, preset, spec, multiplier: int):
    epoch = _current_epoch(state, preset)
    total_balance = _total_active_balance(state, preset, spec)
    adjusted = min(sum(state.slashings) * multiplier, total_balance)
    incr = spec.effective_balance_increment
    for i, v in enumerate(state.validators):
        if (
            v.slashed
            and epoch + preset.epochs_per_slashings_vector // 2
            == v.withdrawable_epoch
        ):
            penalty = (
                v.effective_balance // incr * adjusted // total_balance * incr
            )
            decrease_balance(state, i, penalty)


def _process_eth1_data_reset(state, preset):
    next_epoch = _current_epoch(state, preset) + 1
    if next_epoch % preset.epochs_per_eth1_voting_period == 0:
        state.eth1_data_votes = ()


def _process_effective_balance_updates(state, spec):
    incr = spec.effective_balance_increment
    hysteresis_increment = incr // spec.hysteresis_quotient
    down = hysteresis_increment * spec.hysteresis_downward_multiplier
    up = hysteresis_increment * spec.hysteresis_upward_multiplier
    vals = list(state.validators)
    for i, v in enumerate(vals):
        balance = state.balances[i]
        if (
            balance + down < v.effective_balance
            or v.effective_balance + up < balance
        ):
            v.effective_balance = min(
                balance - balance % incr, spec.max_effective_balance
            )
    state.validators = tuple(vals)


def _process_slashings_reset(state, preset):
    next_epoch = _current_epoch(state, preset) + 1
    s = list(state.slashings)
    s[next_epoch % preset.epochs_per_slashings_vector] = 0
    state.slashings = tuple(s)


def _process_randao_mixes_reset(state, preset):
    current = _current_epoch(state, preset)
    next_epoch = current + 1
    mixes = list(state.randao_mixes)
    mixes[next_epoch % preset.epochs_per_historical_vector] = mixes[
        current % preset.epochs_per_historical_vector
    ]
    state.randao_mixes = tuple(mixes)


def _process_historical_roots_update(state, preset):
    next_epoch = _current_epoch(state, preset) + 1
    if (
        next_epoch
        % (preset.slots_per_historical_root // preset.slots_per_epoch)
        == 0
    ):
        from ..types.containers import types_for

        t = types_for(preset)
        batch = t.HistoricalBatch(
            block_roots=state.block_roots, state_roots=state.state_roots
        )
        state.historical_roots = (
            *state.historical_roots,
            batch.tree_hash_root(),
        )


# ===========================================================================
# phase0
# ===========================================================================


def _matching_source_attestations(state, epoch, preset):
    if epoch == _current_epoch(state, preset):
        return list(state.current_epoch_attestations)
    if epoch == _previous_epoch(state, preset):
        return list(state.previous_epoch_attestations)
    raise ValueError("epoch out of attestation range")


def _matching_target_attestations(state, epoch, preset):
    target_root = get_block_root(state, epoch, preset)
    return [
        a
        for a in _matching_source_attestations(state, epoch, preset)
        if bytes(a.data.target.root) == bytes(target_root)
    ]


def _matching_head_attestations(state, epoch, preset):
    return [
        a
        for a in _matching_target_attestations(state, epoch, preset)
        if bytes(a.data.beacon_block_root)
        == bytes(get_block_root_at_slot(state, a.data.slot, preset))
    ]


def _attesting_indices(state, attestations, preset, spec, cache_map):
    """Union of unslashed attesters over PendingAttestations; committee
    lookups share per-epoch CommitteeCaches."""
    from ..types import CommitteeCache

    out = set()
    for a in attestations:
        epoch = compute_epoch_at_slot(a.data.slot, preset)
        cache = cache_map.get(epoch)
        if cache is None:
            cache = CommitteeCache(state, epoch, preset, spec)
            cache_map[epoch] = cache
        committee = cache.get_beacon_committee(a.data.slot, a.data.index)
        for i, bit in zip(committee, a.aggregation_bits):
            if bit and not state.validators[i].slashed:
                out.add(i)
    return out


def _get_base_reward(state, index, total_balance_sqrt, spec):
    return (
        state.validators[index].effective_balance
        * spec.base_reward_factor
        // total_balance_sqrt
        // BASE_REWARDS_PER_EPOCH
    )


def _process_epoch_base(state, preset, spec):
    cache_map: dict = {}
    current_epoch = _current_epoch(state, preset)
    previous_epoch = _previous_epoch(state, preset)
    total_balance = _total_active_balance(state, preset, spec)

    # 1. justification & finalization
    if current_epoch > GENESIS_EPOCH + 1:
        _, prev_bal, cur_bal = _justification_target_balances(
            state, preset, spec
        )
        _weigh_justification_and_finalization(
            state, total_balance, prev_bal, cur_bal, preset
        )

    # 2. rewards & penalties
    if current_epoch > GENESIS_EPOCH:
        rewards, penalties = _attestation_deltas(
            state, preset, spec, cache_map, total_balance
        )
        apply_balance_deltas(state, rewards, penalties)

    # 3-10. registry, slashings, resets
    _process_registry_updates(state, preset, spec)
    _process_slashings(state, preset, spec, spec.proportional_slashing_multiplier)
    _process_eth1_data_reset(state, preset)
    _process_effective_balance_updates(state, spec)
    _process_slashings_reset(state, preset)
    _process_randao_mixes_reset(state, preset)
    _process_historical_roots_update(state, preset)
    _rotate_participation(state)


def attestation_component_deltas(state, preset, spec, cache_map, total_balance):
    """Phase0 reward/penalty deltas SPLIT BY COMPONENT, matching the EF
    rewards vectors' file set (cases/rewards.rs; reference
    per_epoch_processing/base/rewards_and_penalties.rs): source, target,
    head, inclusion_delay, inactivity -- each (rewards, penalties)."""
    n = len(state.validators)
    previous_epoch = _previous_epoch(state, preset)
    sqrt_total = integer_squareroot(total_balance)
    eligible = _eligible_validator_indices(state, preset)
    in_leak = _is_in_inactivity_leak(state, preset, spec)
    incr = spec.effective_balance_increment

    source_atts = _matching_source_attestations(state, previous_epoch, preset)
    target_atts = _matching_target_attestations(state, previous_epoch, preset)
    head_atts = _matching_head_attestations(state, previous_epoch, preset)

    out: dict[str, tuple[list[int], list[int]]] = {}
    for name, atts in (
        ("source", source_atts),
        ("target", target_atts),
        ("head", head_atts),
    ):
        rewards = [0] * n
        penalties = [0] * n
        attesting = _attesting_indices(state, atts, preset, spec, cache_map)
        attesting_balance = get_total_balance(state, attesting, spec)
        for i in eligible:
            base = _get_base_reward(state, i, sqrt_total, spec)
            if i in attesting:
                if in_leak:
                    rewards[i] += base
                else:
                    rewards[i] += (
                        base
                        * (attesting_balance // incr)
                        // (total_balance // incr)
                    )
            else:
                penalties[i] += base
        out[name] = (rewards, penalties)

    # inclusion delay rewards (source attesters only; no penalties)
    rewards = [0] * n
    source_attesting = _attesting_indices(
        state, source_atts, preset, spec, cache_map
    )
    best: dict[int, object] = {}
    for a in source_atts:
        epoch = compute_epoch_at_slot(a.data.slot, preset)
        cache = cache_map[epoch]
        committee = cache.get_beacon_committee(a.data.slot, a.data.index)
        for i, bit in zip(committee, a.aggregation_bits):
            if bit and i in source_attesting:
                if i not in best or a.inclusion_delay < best[i].inclusion_delay:
                    best[i] = a
    for i, a in best.items():
        base = _get_base_reward(state, i, sqrt_total, spec)
        proposer_reward = base // spec.proposer_reward_quotient
        rewards[a.proposer_index] += proposer_reward
        max_attester_reward = base - proposer_reward
        rewards[i] += max_attester_reward // a.inclusion_delay
    out["inclusion_delay"] = (rewards, [0] * n)

    # inactivity penalties (no rewards)
    penalties = [0] * n
    if in_leak:
        target_attesting = _attesting_indices(
            state, target_atts, preset, spec, cache_map
        )
        delay = _finality_delay(state, preset)
        for i in eligible:
            base = _get_base_reward(state, i, sqrt_total, spec)
            proposer_reward = base // spec.proposer_reward_quotient
            penalties[i] += BASE_REWARDS_PER_EPOCH * base - proposer_reward
            if i not in target_attesting:
                penalties[i] += (
                    state.validators[i].effective_balance
                    * delay
                    // spec.inactivity_penalty_quotient
                )
    out["inactivity"] = ([0] * n, penalties)
    return out


def _attestation_deltas(state, preset, spec, cache_map, total_balance):
    """Phase0 get_attestation_deltas: the component sum."""
    n = len(state.validators)
    rewards = [0] * n
    penalties = [0] * n
    components = attestation_component_deltas(
        state, preset, spec, cache_map, total_balance
    )
    for r, p in components.values():
        for i in range(n):
            rewards[i] += r[i]
            penalties[i] += p[i]
    return rewards, penalties


# ===========================================================================
# altair
# ===========================================================================


def _unslashed_participating_indices(state, flag_index, epoch, preset):
    if epoch == _current_epoch(state, preset):
        participation = state.current_epoch_participation
    else:
        participation = state.previous_epoch_participation
    return {
        i
        for i in get_active_validator_indices(state, epoch)
        if has_flag(participation[i], flag_index)
        and not state.validators[i].slashed
    }


def _process_epoch_altair(state, preset, spec):
    current_epoch = _current_epoch(state, preset)
    previous_epoch = _previous_epoch(state, preset)
    total_balance = _total_active_balance(state, preset, spec)

    # 1. justification & finalization from participation flags
    if current_epoch > GENESIS_EPOCH + 1:
        _, prev_bal, cur_bal = _justification_target_balances(
            state, preset, spec
        )
        _weigh_justification_and_finalization(
            state, total_balance, prev_bal, cur_bal, preset
        )

    # 2. inactivity scores
    if current_epoch > GENESIS_EPOCH:
        _process_inactivity_updates(state, preset, spec)

    # 3. rewards & penalties
    if current_epoch > GENESIS_EPOCH:
        rewards, penalties = _flag_deltas(state, preset, spec, total_balance)
        apply_balance_deltas(state, rewards, penalties)

    _process_registry_updates(state, preset, spec)
    _process_slashings(
        state,
        preset,
        spec,
        spec.proportional_slashing_multiplier_for(state.fork_name),
    )
    _process_eth1_data_reset(state, preset)
    _process_effective_balance_updates(state, spec)
    _process_slashings_reset(state, preset)
    _process_randao_mixes_reset(state, preset)
    _process_historical_roots_update(state, preset)
    _rotate_participation(state)
    _process_sync_committee_updates(state, preset, spec)


def _process_inactivity_updates(state, preset, spec):
    previous_epoch = _previous_epoch(state, preset)
    target = _unslashed_participating_indices(
        state, TIMELY_TARGET_FLAG_INDEX, previous_epoch, preset
    )
    leak = _is_in_inactivity_leak(state, preset, spec)
    scores = list(state.inactivity_scores)
    for i in _eligible_validator_indices(state, preset):
        if i in target:
            scores[i] -= min(1, scores[i])
        else:
            scores[i] += spec.inactivity_score_bias
        if not leak:
            scores[i] -= min(spec.inactivity_score_recovery_rate, scores[i])
    state.inactivity_scores = tuple(scores)


def flag_component_deltas(state, preset, spec, total_balance):
    """Altair reward/penalty deltas split by component (source, target,
    head, inactivity), matching the EF rewards vectors' altair file set."""
    n = len(state.validators)
    previous_epoch = _previous_epoch(state, preset)
    eligible = _eligible_validator_indices(state, preset)
    in_leak = _is_in_inactivity_leak(state, preset, spec)
    incr = spec.effective_balance_increment
    base_per_inc = get_base_reward_per_increment(state, preset, spec)
    active_increments = total_balance // incr

    from .participation import WEIGHT_DENOMINATOR

    out: dict[str, tuple[list[int], list[int]]] = {}
    names = {0: "source", 1: "target", 2: "head"}
    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        rewards = [0] * n
        penalties = [0] * n
        participating = _unslashed_participating_indices(
            state, flag_index, previous_epoch, preset
        )
        participating_increments = (
            get_total_balance(state, participating, spec) // incr
        )
        for i in eligible:
            base = (
                state.validators[i].effective_balance // incr * base_per_inc
            )
            if i in participating:
                if not in_leak:
                    rewards[i] += (
                        base
                        * weight
                        * participating_increments
                        // (active_increments * WEIGHT_DENOMINATOR)
                    )
            elif flag_index != TIMELY_HEAD_FLAG_INDEX:
                penalties[i] += base * weight // WEIGHT_DENOMINATOR
        out[names[flag_index]] = (rewards, penalties)

    # inactivity penalties (no rewards)
    penalties = [0] * n
    target = _unslashed_participating_indices(
        state, TIMELY_TARGET_FLAG_INDEX, previous_epoch, preset
    )
    for i in eligible:
        if i not in target:
            penalties[i] += (
                state.validators[i].effective_balance
                * state.inactivity_scores[i]
                // (
                    spec.inactivity_score_bias
                    * spec.inactivity_penalty_quotient_for(state.fork_name)
                )
            )
    out["inactivity"] = ([0] * n, penalties)
    return out


def _flag_deltas(state, preset, spec, total_balance):
    """Altair combined deltas: the component sum."""
    n = len(state.validators)
    rewards = [0] * n
    penalties = [0] * n
    for r, p in flag_component_deltas(
        state, preset, spec, total_balance
    ).values():
        for i in range(n):
            rewards[i] += r[i]
            penalties[i] += p[i]
    return rewards, penalties


def _process_sync_committee_updates(state, preset, spec):
    next_epoch = _current_epoch(state, preset) + 1
    if next_epoch % preset.epochs_per_sync_committee_period == 0:
        from ..types.sync_committee import compute_sync_committee

        state.current_sync_committee = state.next_sync_committee
        # spec get_next_sync_committee samples at current_epoch + 1
        state.next_sync_committee = compute_sync_committee(
            state, next_epoch, preset, spec
        )
