"""Per-block state transition (reference consensus/state_processing/src/
per_block_processing.rs:91 `per_block_processing`, plus the process_
operations modules). Signature handling follows the reference's
`BlockSignatureStrategy` (per_block_processing.rs:45-56): NoVerification /
VerifyIndividual / VerifyBulk / VerifyRandao -- bulk collects every set and
makes ONE backend call (the TPU batch path).
"""

from __future__ import annotations

import enum

from ..crypto.bls import verify_signature_sets
from ..types import (
    FAR_FUTURE_EPOCH,
    compute_activation_exit_epoch,
    compute_epoch_at_slot,
    get_domain,
    is_active_validator,
    is_slashable_validator,
)
from ..types.chain_spec import DOMAIN_RANDAO
from ..types.containers import BeaconBlockHeader, Validator, types_for
from ..types.helpers import (
    apply_balance_deltas,
    decrease_balance,
    get_block_root,
    get_block_root_at_slot,
    get_randao_mix,
    get_total_active_balance,
    hash32,
    increase_balance,
)
from ..types.presets import Preset
from .context import BlockProcessingError, ConsensusContext
from .participation import (
    PARTICIPATION_FLAG_WEIGHTS,
    PROPOSER_WEIGHT,
    SYNC_REWARD_WEIGHT,
    WEIGHT_DENOMINATOR,
    add_flag,
    get_attestation_participation_flag_indices,
    get_base_reward_altair,
    get_base_reward_per_increment,
    has_flag,
)
from .signature_sets import (
    attester_slashing_signature_sets,
    block_proposal_signature_set,
    deposit_signature_set,
    exit_signature_set,
    indexed_attestation_signature_set,
    proposer_slashing_signature_sets,
    randao_signature_set,
    state_pubkey_getter,
    sync_aggregate_signature_set,
)


class BlockSignatureStrategy(enum.Enum):
    NO_VERIFICATION = "no_verification"
    VERIFY_INDIVIDUAL = "verify_individual"
    VERIFY_BULK = "verify_bulk"
    VERIFY_RANDAO = "verify_randao"


class BlockProcessingSignatureError(BlockProcessingError):
    pass


def per_block_processing(
    state,
    signed_block,
    preset: Preset,
    spec,
    strategy: BlockSignatureStrategy = BlockSignatureStrategy.VERIFY_BULK,
    ctxt: ConsensusContext | None = None,
    verified_proposer_index: int | None = None,
    get_pubkey=None,
    resolve_pubkey=None,
):
    """Mutates `state` by applying `signed_block`. Signature work follows
    `strategy`; bulk mode batches all sets into one verifier call via
    BlockSignatureVerifier. `get_pubkey`/`resolve_pubkey` are the chain's
    pubkey-cache hooks: passing them keeps every set table-tagged, so the
    bulk batch gathers limb rows from the device-resident (mesh-sharded)
    table instead of host-packing -- whole-block import as one sharded
    device program."""
    ctxt = ctxt or ConsensusContext(preset, spec)

    if strategy in (
        BlockSignatureStrategy.VERIFY_BULK,
        BlockSignatureStrategy.VERIFY_INDIVIDUAL,
    ):
        from .block_signature_verifier import BlockSignatureVerifier

        verifier = BlockSignatureVerifier(
            state, preset, spec, ctxt,
            get_pubkey=get_pubkey, resolve_pubkey=resolve_pubkey,
        )
        verifier.include_all_signatures(signed_block)
        if strategy is BlockSignatureStrategy.VERIFY_BULK:
            if not verifier.verify(slot=int(signed_block.message.slot)):
                raise BlockProcessingSignatureError("bulk signature check failed")
        else:
            for s in verifier.sets:
                if not verify_signature_sets([s]):
                    raise BlockProcessingSignatureError(
                        "individual signature check failed"
                    )
    elif strategy is BlockSignatureStrategy.VERIFY_RANDAO:
        block = signed_block.message
        s = randao_signature_set(
            state,
            state_pubkey_getter(state),
            block.proposer_index,
            block.body.randao_reveal,
            preset,
            spec,
        )
        if not verify_signature_sets([s]):
            raise BlockProcessingSignatureError("randao signature check failed")

    block = signed_block.message
    if verified_proposer_index is not None:
        ctxt.proposer_index = verified_proposer_index
    process_block_header(
        state, block, preset, spec, ctxt.get_proposer_index(state)
    )
    if body_payload(block.body) is not None:
        # spec order: process_execution_payload runs right after the header
        # (if_execution_enabled); randao is checked against the PRE-randao
        # mix, hence before process_randao
        process_execution_payload(
            state, block.body, preset, spec, ctxt.notify_new_payload
        )
    process_randao(state, block.body, preset, spec)
    process_eth1_data(state, block.body.eth1_data, preset)
    process_operations(state, block.body, preset, spec, ctxt)
    if getattr(block.body, "sync_aggregate", None) is not None:
        process_sync_aggregate(
            state, block.body.sync_aggregate, preset, spec, verify=False,
            ctxt=ctxt,
        )
    return ctxt


# --- header / randao / eth1 -------------------------------------------------


def process_block_header(
    state, block, preset, spec, verified_proposer_index=None
):
    if block.slot != state.slot:
        raise BlockProcessingError("block slot != state slot")
    if block.slot <= state.latest_block_header.slot:
        raise BlockProcessingError("block not newer than latest header")
    if verified_proposer_index is None:
        from .per_slot import get_beacon_proposer_index

        verified_proposer_index = get_beacon_proposer_index(
            state, preset, spec
        )
    if block.proposer_index != verified_proposer_index:
        raise BlockProcessingError("wrong proposer index")
    if (
        bytes(block.parent_root)
        != state.latest_block_header.tree_hash_root()
    ):
        raise BlockProcessingError("parent root mismatch")
    if state.validators[block.proposer_index].slashed:
        raise BlockProcessingError("proposer is slashed")
    state.latest_block_header = BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=bytes(32),  # filled at the next slot transition
        body_root=block.body.tree_hash_root(),
    )


def process_randao(state, body, preset, spec):
    epoch = compute_epoch_at_slot(state.slot, preset)
    mix = bytes(
        a ^ b
        for a, b in zip(
            get_randao_mix(state, epoch, preset),
            hash32(bytes(body.randao_reveal)),
        )
    )
    mixes = list(state.randao_mixes)
    mixes[epoch % preset.epochs_per_historical_vector] = mix
    state.randao_mixes = tuple(mixes)


def process_eth1_data(state, eth1_data, preset: Preset):
    votes = list(state.eth1_data_votes)
    votes.append(eth1_data)
    state.eth1_data_votes = tuple(votes)
    if votes.count(eth1_data) * 2 > preset.slots_per_eth1_voting_period:
        state.eth1_data = eth1_data


# --- operations -------------------------------------------------------------


def process_operations(state, body, preset, spec, ctxt: ConsensusContext):
    expected_deposits = min(
        preset.max_deposits,
        state.eth1_data.deposit_count - state.eth1_deposit_index,
    )
    if len(body.deposits) != expected_deposits:
        raise BlockProcessingError(
            f"expected {expected_deposits} deposits, got {len(body.deposits)}"
        )
    for op in body.proposer_slashings:
        process_proposer_slashing(state, op, preset, spec, ctxt)
    for op in body.attester_slashings:
        process_attester_slashing(state, op, preset, spec, ctxt)
    for op in body.attestations:
        process_attestation(state, op, preset, spec, ctxt)
    for op in body.deposits:
        process_deposit(state, op, preset, spec, ctxt)
    for op in body.voluntary_exits:
        process_voluntary_exit(state, op, preset, spec)


def is_slashable_attestation_data(d1, d2) -> bool:
    double = d1 != d2 and d1.target.epoch == d2.target.epoch
    surround = (
        d1.source.epoch < d2.source.epoch and d2.target.epoch < d1.target.epoch
    )
    return double or surround


def slash_validator(
    state,
    index: int,
    preset,
    spec,
    whistleblower: int | None = None,
    ctxt=None,
):
    epoch = compute_epoch_at_slot(state.slot, preset)
    initiate_validator_exit(state, index, preset, spec)
    vals = list(state.validators)
    v = vals[index]
    v.slashed = True
    v.withdrawable_epoch = max(
        v.withdrawable_epoch, epoch + preset.epochs_per_slashings_vector
    )
    state.validators = tuple(vals)
    slashings = list(state.slashings)
    slashings[epoch % preset.epochs_per_slashings_vector] += v.effective_balance
    state.slashings = tuple(slashings)
    quotient = spec.min_slashing_penalty_quotient_for(state.fork_name)
    decrease_balance(state, index, v.effective_balance // quotient)

    proposer_index = (
        ctxt.get_proposer_index(state)
        if ctxt is not None
        else _proposer(state, preset, spec)
    )
    if whistleblower is None:
        whistleblower = proposer_index
    whistleblower_reward = (
        v.effective_balance // spec.whistleblower_reward_quotient
    )
    if state.fork_name == "phase0":
        proposer_reward = whistleblower_reward // spec.proposer_reward_quotient
    else:
        proposer_reward = (
            whistleblower_reward * PROPOSER_WEIGHT // WEIGHT_DENOMINATOR
        )
    increase_balance(state, proposer_index, proposer_reward)
    increase_balance(state, whistleblower, whistleblower_reward - proposer_reward)


def process_proposer_slashing(state, slashing, preset, spec, ctxt=None):
    h1 = slashing.signed_header_1.message
    h2 = slashing.signed_header_2.message
    if h1.slot != h2.slot:
        raise BlockProcessingError("proposer slashing: slots differ")
    if h1.proposer_index != h2.proposer_index:
        raise BlockProcessingError("proposer slashing: proposers differ")
    if h1 == h2:
        raise BlockProcessingError("proposer slashing: headers identical")
    proposer = state.validators[h1.proposer_index]
    if not is_slashable_validator(
        proposer, compute_epoch_at_slot(state.slot, preset)
    ):
        raise BlockProcessingError("proposer not slashable")
    slash_validator(state, h1.proposer_index, preset, spec, ctxt=ctxt)


def process_attester_slashing(state, slashing, preset, spec, ctxt):
    a1, a2 = slashing.attestation_1, slashing.attestation_2
    if not is_slashable_attestation_data(a1.data, a2.data):
        raise BlockProcessingError("attestations not slashable")
    for a in (a1, a2):
        if not _is_valid_indexed_attestation_structure(a, preset):
            raise BlockProcessingError("invalid indexed attestation")
    epoch = compute_epoch_at_slot(state.slot, preset)
    slashed_any = False
    common = set(a1.attesting_indices) & set(a2.attesting_indices)
    for index in sorted(common):
        if is_slashable_validator(state.validators[index], epoch):
            slash_validator(state, index, preset, spec, ctxt=ctxt)
            slashed_any = True
    if not slashed_any:
        raise BlockProcessingError("attester slashing slashed nobody")


def _is_valid_indexed_attestation_structure(indexed, preset) -> bool:
    idx = list(indexed.attesting_indices)
    return bool(idx) and idx == sorted(idx) and len(set(idx)) == len(idx)


def process_attestation(state, attestation, preset, spec, ctxt):
    data = attestation.data
    current_epoch = compute_epoch_at_slot(state.slot, preset)
    previous_epoch = max(current_epoch - 1, 0)
    if data.target.epoch not in (previous_epoch, current_epoch):
        raise BlockProcessingError("attestation target epoch out of range")
    if data.target.epoch != compute_epoch_at_slot(data.slot, preset):
        raise BlockProcessingError("target epoch != slot epoch")
    if not (
        data.slot + spec.min_attestation_inclusion_delay
        <= state.slot
        <= data.slot + preset.slots_per_epoch
    ):
        raise BlockProcessingError("attestation outside inclusion window")
    cache = ctxt.committee_cache(state, data.target.epoch)
    if data.index >= cache.committees_per_slot:
        raise BlockProcessingError("committee index out of range")

    indexed = ctxt.get_indexed_attestation(state, attestation)
    if not _is_valid_indexed_attestation_structure(indexed, preset):
        raise BlockProcessingError("invalid indexed attestation")

    if data.target.epoch == current_epoch:
        justified = state.current_justified_checkpoint
    else:
        justified = state.previous_justified_checkpoint
    if data.source != justified:
        raise BlockProcessingError("attestation source != justified checkpoint")

    if state.fork_name == "phase0":
        pending = types_for(preset).PendingAttestation(
            aggregation_bits=attestation.aggregation_bits,
            data=data,
            inclusion_delay=state.slot - data.slot,
            proposer_index=ctxt.get_proposer_index(state),
        )
        if data.target.epoch == current_epoch:
            state.current_epoch_attestations = (
                *state.current_epoch_attestations,
                pending,
            )
        else:
            state.previous_epoch_attestations = (
                *state.previous_epoch_attestations,
                pending,
            )
    else:
        _process_attestation_altair(state, data, indexed, preset, spec, ctxt)


def _proposer(state, preset, spec):
    from .per_slot import get_beacon_proposer_index

    return get_beacon_proposer_index(state, preset, spec)


def _process_attestation_altair(state, data, indexed, preset, spec, ctxt):
    flags = get_attestation_participation_flag_indices(
        state, data, state.slot - data.slot, preset, spec
    )
    current_epoch = compute_epoch_at_slot(state.slot, preset)
    in_current = data.target.epoch == current_epoch
    participation = list(
        state.current_epoch_participation
        if in_current
        else state.previous_epoch_participation
    )
    base_per_inc = get_base_reward_per_increment(state, preset, spec)
    proposer_reward_numerator = 0
    for index in indexed.attesting_indices:
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if flag_index in flags and not has_flag(
                participation[index], flag_index
            ):
                participation[index] = add_flag(participation[index], flag_index)
                proposer_reward_numerator += (
                    get_base_reward_altair(
                        state, index, base_per_inc, preset, spec
                    )
                    * weight
                )
    if in_current:
        state.current_epoch_participation = tuple(participation)
    else:
        state.previous_epoch_participation = tuple(participation)
    denominator = (
        (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
        * WEIGHT_DENOMINATOR
        // PROPOSER_WEIGHT
    )
    increase_balance(
        state,
        ctxt.get_proposer_index(state),
        proposer_reward_numerator // denominator,
    )


def _verify_merkle_branch(leaf, branch, depth, index, root) -> bool:
    value = leaf
    for i in range(depth):
        sibling = bytes(branch[i])
        if (index >> i) & 1:
            value = hash32(sibling + value)
        else:
            value = hash32(value + sibling)
    return value == bytes(root)


def process_deposit(state, deposit, preset, spec, ctxt=None):
    if not _verify_merkle_branch(
        deposit.data.tree_hash_root(),
        deposit.proof,
        preset.deposit_contract_tree_depth + 1,
        state.eth1_deposit_index,
        state.eth1_data.deposit_root,
    ):
        raise BlockProcessingError("bad deposit merkle proof")
    state.eth1_deposit_index += 1
    apply_deposit(state, deposit.data, preset, spec, ctxt)


def apply_deposit(state, data, preset, spec, ctxt=None):
    pubkey = bytes(data.pubkey)
    if ctxt is not None:
        index = ctxt.pubkey_to_index(state, pubkey)
    else:
        pubkeys = [bytes(v.pubkey) for v in state.validators]
        index = pubkeys.index(pubkey) if pubkey in pubkeys else None
    if index is None:
        # new validator: proof-of-possession must verify, else ignore deposit
        try:
            s = deposit_signature_set(data, spec)
        except Exception:
            return
        if not verify_signature_sets([s]):
            return
        state.validators = (
            *state.validators,
            Validator(
                pubkey=pubkey,
                withdrawal_credentials=bytes(data.withdrawal_credentials),
                effective_balance=min(
                    data.amount - data.amount % spec.effective_balance_increment,
                    spec.max_effective_balance,
                ),
                slashed=False,
                activation_eligibility_epoch=FAR_FUTURE_EPOCH,
                activation_epoch=FAR_FUTURE_EPOCH,
                exit_epoch=FAR_FUTURE_EPOCH,
                withdrawable_epoch=FAR_FUTURE_EPOCH,
            ),
        )
        state.balances = (*state.balances, data.amount)
        if state.fork_name != "phase0":
            state.previous_epoch_participation = (
                *state.previous_epoch_participation,
                0,
            )
            state.current_epoch_participation = (
                *state.current_epoch_participation,
                0,
            )
            state.inactivity_scores = (*state.inactivity_scores, 0)
    else:
        increase_balance(state, index, data.amount)


def initiate_validator_exit(state, index: int, preset, spec):
    vals = list(state.validators)
    if vals[index].exit_epoch != FAR_FUTURE_EPOCH:
        return
    exit_epochs = [
        v.exit_epoch for v in vals if v.exit_epoch != FAR_FUTURE_EPOCH
    ]
    current_epoch = compute_epoch_at_slot(state.slot, preset)
    exit_queue_epoch = max(
        exit_epochs
        + [compute_activation_exit_epoch(current_epoch, spec)]
    )
    exit_queue_churn = sum(
        1 for v in vals if v.exit_epoch == exit_queue_epoch
    )
    active = sum(
        1 for v in vals if is_active_validator(v, current_epoch)
    )
    churn_limit = max(
        spec.min_per_epoch_churn_limit, active // spec.churn_limit_quotient
    )
    if exit_queue_churn >= churn_limit:
        exit_queue_epoch += 1
    v = vals[index]
    v.exit_epoch = exit_queue_epoch
    v.withdrawable_epoch = (
        exit_queue_epoch + spec.min_validator_withdrawability_delay
    )
    state.validators = tuple(vals)


def process_voluntary_exit(state, signed_exit, preset, spec):
    exit_msg = signed_exit.message
    current_epoch = compute_epoch_at_slot(state.slot, preset)
    v = state.validators[exit_msg.validator_index]
    if not is_active_validator(v, current_epoch):
        raise BlockProcessingError("exiting validator not active")
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        raise BlockProcessingError("validator already exiting")
    if current_epoch < exit_msg.epoch:
        raise BlockProcessingError("exit epoch in the future")
    if current_epoch < v.activation_epoch + spec.shard_committee_period:
        raise BlockProcessingError("validator too young to exit")
    initiate_validator_exit(state, exit_msg.validator_index, preset, spec)


# --- sync aggregate (altair) ------------------------------------------------


def process_sync_aggregate(
    state, sync_aggregate, preset, spec, verify=True, ctxt=None
):
    if verify:
        root = get_block_root_at_slot(
            state, max(state.slot - 1, 0), preset
        )
        s = sync_aggregate_signature_set(
            state,
            None,
            sync_aggregate,
            state.slot,
            root,
            list(state.current_sync_committee.pubkeys),
            preset,
            spec,
        )
        if s is not None and not verify_signature_sets([s]):
            raise BlockProcessingSignatureError("sync aggregate signature")

    total_active_increments = (
        get_total_active_balance(state, preset, spec)
        // spec.effective_balance_increment
    )
    base_per_inc = get_base_reward_per_increment(state, preset, spec)
    total_base_rewards = base_per_inc * total_active_increments
    max_participant_rewards = (
        total_base_rewards
        * SYNC_REWARD_WEIGHT
        // WEIGHT_DENOMINATOR
        // preset.slots_per_epoch
    )
    participant_reward = max_participant_rewards // preset.sync_committee_size
    proposer_reward = (
        participant_reward
        * PROPOSER_WEIGHT
        // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
    )

    pubkey_to_index = {
        bytes(v.pubkey): i for i, v in enumerate(state.validators)
    }
    proposer = (
        ctxt.get_proposer_index(state)
        if ctxt is not None
        else _proposer(state, preset, spec)
    )
    n = len(state.validators)
    rewards = [0] * n
    penalties = [0] * n
    for bit, pk in zip(
        sync_aggregate.sync_committee_bits,
        state.current_sync_committee.pubkeys,
    ):
        index = pubkey_to_index[bytes(pk)]
        if bit:
            rewards[index] += participant_reward
            rewards[proposer] += proposer_reward
        else:
            penalties[index] += participant_reward
    apply_balance_deltas(state, rewards, penalties)


# --- execution payload (bellatrix) ------------------------------------------
# Reference: consensus/state_processing per_block_processing's
# process_execution_payload + is_merge_transition_* helpers; the engine
# round trip mirrors execution_layer/src/lib.rs notify_new_payload.


def is_merge_transition_complete(state) -> bool:
    hdr = getattr(state, "latest_execution_payload_header", None)
    if hdr is None:
        return False
    return any(bytes(hdr.block_hash))


_DEFAULT_PAYLOAD_ROOTS: dict[type, bytes] = {}


def _is_default_payload(payload) -> bool:
    cls = type(payload)
    root = _DEFAULT_PAYLOAD_ROOTS.get(cls)
    if root is None:
        root = _DEFAULT_PAYLOAD_ROOTS[cls] = cls().tree_hash_root()
    return payload.tree_hash_root() == root


def body_payload(body):
    """The body's execution payload OR payload header (blinded blocks
    carry the header only -- the reference's AbstractExecPayload seam over
    FullPayload/BlindedPayload, consensus/types/src/payload.rs)."""
    payload = getattr(body, "execution_payload", None)
    if payload is not None:
        return payload
    return getattr(body, "execution_payload_header", None)


def is_merge_transition_block(state, body) -> bool:
    payload = body_payload(body)
    if payload is None:
        return False
    return not is_merge_transition_complete(state) and not _is_default_payload(
        payload
    )


def is_execution_enabled(state, body) -> bool:
    return is_merge_transition_block(state, body) or (
        is_merge_transition_complete(state) and body_payload(body) is not None
    )


def compute_timestamp_at_slot(state, slot: int, spec) -> int:
    return state.genesis_time + slot * spec.seconds_per_slot


def payload_to_header(payload, preset: Preset):
    """ExecutionPayload -> ExecutionPayloadHeader (transactions list
    replaced by its hash tree root)."""
    from ..types import types_for

    t = types_for(preset)
    kwargs = {
        name: getattr(payload, name)
        for name, _ in payload.ssz_fields
        if name != "transactions"
    }
    tx_field = dict(payload.ssz_fields)["transactions"]
    kwargs["transactions_root"] = tx_field.hash_tree_root(payload.transactions)
    return t.ExecutionPayloadHeader(**kwargs)


def process_execution_payload(
    state, body, preset: Preset, spec, notify_new_payload=None
):
    """Spec process_execution_payload. `notify_new_payload` is the engine
    hook (payload -> bool or PayloadVerificationStatus); None skips the
    engine round trip (the NoVerification analogue used in replay)."""
    from ..types import compute_epoch_at_slot as _epoch_at
    from ..types.helpers import get_randao_mix

    payload = body_payload(body)
    blinded = not hasattr(payload, "transactions")
    if not is_execution_enabled(state, body):
        # pre-merge: payload must be the default one (tree-root compare:
        # SSZ offsets make even a default payload nonzero on the wire)
        if not _is_default_payload(payload):
            raise BlockProcessingError(
                "execution payload present before the merge transition"
            )
        return
    if is_merge_transition_complete(state):
        if bytes(payload.parent_hash) != bytes(
            state.latest_execution_payload_header.block_hash
        ):
            raise BlockProcessingError("payload parent hash mismatch")
    epoch = _epoch_at(state.slot, preset)
    if bytes(payload.prev_randao) != bytes(
        get_randao_mix(state, epoch, preset)
    ):
        raise BlockProcessingError("payload prev_randao mismatch")
    if int(payload.timestamp) != compute_timestamp_at_slot(
        state, state.slot, spec
    ):
        raise BlockProcessingError("payload timestamp mismatch")
    if blinded:
        # blinded processing: the header IS the commitment; there is no
        # payload to send to an engine (the builder reveals it post-signing)
        from ..types import types_for

        t = types_for(preset)
        state.latest_execution_payload_header = t.ExecutionPayloadHeader(
            **{name: getattr(payload, name) for name, _ in payload.ssz_fields}
        )
        return
    if notify_new_payload is not None:
        ok = notify_new_payload(payload)
        if ok is False:
            raise BlockProcessingError("execution engine rejected payload")
    state.latest_execution_payload_header = payload_to_header(payload, preset)
