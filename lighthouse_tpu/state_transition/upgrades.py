"""Fork upgrades (reference consensus/state_processing/src/upgrade.rs):
state re-shaping at fork boundaries. phase0 -> altair translates pending
attestations into participation flags (spec translate_participation)."""

from __future__ import annotations

from ..types import compute_epoch_at_slot, types_for
from ..types.containers import Fork
from ..types.presets import Preset
from .participation import (
    add_flag,
    get_attestation_participation_flag_indices,
)


_FORK_ORDER = ["phase0", "altair", "bellatrix"]


def upgrade_state_if_due(state, preset: Preset, spec):
    """Called after each slot increment; upgrades (possibly through several
    forks, e.g. a config with no separate altair epoch) when the new slot is
    an epoch boundary and the spec names a later fork for that epoch."""
    if state.slot % preset.slots_per_epoch != 0:
        return state
    epoch = compute_epoch_at_slot(state.slot, preset)
    target = spec.fork_name_at_epoch(epoch)
    while _FORK_ORDER.index(state.fork_name) < _FORK_ORDER.index(target):
        if state.fork_name == "phase0":
            state = upgrade_to_altair(state, preset, spec)
        elif state.fork_name == "altair":
            state = upgrade_to_bellatrix(state, preset, spec)
    return state


def upgrade_to_altair(pre, preset: Preset, spec):
    t = types_for(preset)
    post = t.BeaconStateAltair.default()
    for name, _ in pre.ssz_fields:
        if hasattr(post, name) and name not in (
            "previous_epoch_attestations",
            "current_epoch_attestations",
        ):
            setattr(post, name, getattr(pre, name))
    post.fork = Fork(
        previous_version=pre.fork.current_version,
        current_version=spec.altair_fork_version,
        epoch=compute_epoch_at_slot(pre.slot, preset),
    )
    zeros = tuple(0 for _ in pre.validators)
    post.previous_epoch_participation = zeros
    post.current_epoch_participation = zeros
    post.inactivity_scores = zeros

    # translate_participation: replay previous-epoch pending attestations
    part = list(zeros)
    from ..types import CommitteeCache

    caches: dict[int, CommitteeCache] = {}
    for a in pre.previous_epoch_attestations:
        data = a.data
        try:
            flags = get_attestation_participation_flag_indices(
                post, data, a.inclusion_delay, preset, spec
            )
        except ValueError:
            continue
        epoch = compute_epoch_at_slot(data.slot, preset)
        cache = caches.get(epoch)
        if cache is None:
            cache = CommitteeCache(post, epoch, preset, spec)
            caches[epoch] = cache
        committee = cache.get_beacon_committee(data.slot, data.index)
        for i, bit in zip(committee, a.aggregation_bits):
            if bit:
                for f in flags:
                    part[i] = add_flag(part[i], f)
    post.previous_epoch_participation = tuple(part)

    from ..types.sync_committee import compute_sync_committee

    epoch = compute_epoch_at_slot(post.slot, preset)
    committee = compute_sync_committee(post, epoch + 1, preset, spec)
    post.current_sync_committee = committee
    post.next_sync_committee = committee
    return post


def upgrade_to_bellatrix(pre, preset: Preset, spec):
    """altair -> bellatrix (reference upgrade.rs upgrade_to_bellatrix):
    identical field copy plus a default (pre-merge, all-zero) execution
    payload header; the merge itself happens when the first payload-bearing
    block is imported (is_merge_transition_complete flips)."""
    t = types_for(preset)
    post = t.BeaconStateBellatrix.default()
    for name, _ in pre.ssz_fields:
        if hasattr(post, name):
            setattr(post, name, getattr(pre, name))
    post.fork = Fork(
        previous_version=pre.fork.current_version,
        current_version=spec.bellatrix_fork_version,
        epoch=compute_epoch_at_slot(pre.slot, preset),
    )
    post.latest_execution_payload_header = t.ExecutionPayloadHeader()
    return post
