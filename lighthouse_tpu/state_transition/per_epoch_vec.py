"""Vectorized altair/bellatrix per-epoch processing: numpy array passes
over balances / participation / registry columns instead of per-validator
Python loops (reference consensus/state_processing/src/per_epoch_processing/
altair/*.rs computes the same quantities via its ParticipationCache; here
the cache IS the column extraction).

Bit-exactness: every arithmetic step mirrors the spec's integer semantics
(floor division of non-negative int64/uint64 quantities). The handful of
products that could overflow 64 bits in pathological states (inactivity
scores beyond 2**28, slashing totals beyond 2**57) trip a guard that
falls back to the pure-Python oracle in per_epoch.py — the oracle stays
the semantic source of truth and the differential test in
tests/test_epoch_vec.py holds the two paths equal.

Scale target (BASELINE config 4): 500k-validator epoch transition < 1 s;
the loop oracle is ~10 s there.
"""

from __future__ import annotations

import numpy as np

from ..types import FAR_FUTURE_EPOCH, GENESIS_EPOCH, compute_activation_exit_epoch
from ..types.presets import Preset
from ..utils.math import integer_squareroot
from .participation import (
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
)

_U64_FAR = np.uint64(FAR_FUTURE_EPOCH)


class VectorGuard(Exception):
    """A magnitude guard tripped: the state needs the arbitrary-precision
    oracle (per_epoch.py) for exactness."""


class _Columns:
    """One pass over the registry extracting the epoch-processing columns.

    Cached on the state instance keyed by the validators tuple's identity
    AND the preset (`state.__dict__['_lh_epoch_cols']`): epoch N+1 reuses
    epoch N's arrays — which the epoch-N writeback kept in sync — unless
    block processing replaced the registry tuple in between. The preset
    key matters when a harness swaps presets mid-process on a reused
    state object: identical tuple identity under a different preset must
    re-extract rather than serve stale column widths. clone_state is an
    SSZ round trip (fresh __dict__), so clones never alias the cache."""

    def __init__(self, state):
        vals = state.validators
        n = len(vals)
        self.n = n
        self.eff = np.fromiter(
            (v.effective_balance for v in vals), dtype=np.int64, count=n
        )
        self.slashed = np.fromiter(
            (v.slashed for v in vals), dtype=bool, count=n
        )
        self.activation = np.fromiter(
            (v.activation_epoch for v in vals), dtype=np.uint64, count=n
        )
        self.exit = np.fromiter(
            (v.exit_epoch for v in vals), dtype=np.uint64, count=n
        )
        self.withdrawable = np.fromiter(
            (v.withdrawable_epoch for v in vals), dtype=np.uint64, count=n
        )
        self.eligibility = np.fromiter(
            (v.activation_eligibility_epoch for v in vals),
            dtype=np.uint64,
            count=n,
        )

    def active_at(self, epoch: int) -> np.ndarray:
        e = np.uint64(epoch)
        return (self.activation <= e) & (e < self.exit)


def _columns_for(state, preset) -> _Columns:
    cached = state.__dict__.get("_lh_epoch_cols")
    if (
        cached is not None
        and len(cached) == 3
        and cached[0] is state.validators
        and cached[1] is preset
    ):
        return cached[2]
    return _Columns(state)


def _cached_col(state, key: str, field_value, dtype) -> np.ndarray:
    """Identity-keyed column cache for a basic-element list field."""
    cached = state.__dict__.get(key)
    if cached is not None and cached[0] is field_value:
        return cached[1]
    return np.fromiter(field_value, dtype=dtype, count=len(field_value))


def _total_with_floor(eff_sum: int, spec) -> int:
    # get_total_balance's EFFECTIVE_BALANCE_INCREMENT floor
    return max(spec.effective_balance_increment, int(eff_sum))


def process_epoch_altair_vec(state, preset: Preset, spec) -> None:
    """Drop-in replacement for per_epoch._process_epoch_altair. Raises
    VectorGuard when a magnitude guard would compromise exactness; the
    caller falls back to the oracle."""
    from .per_epoch import (
        _current_epoch,
        _previous_epoch,
        _process_eth1_data_reset,
        _process_historical_roots_update,
        _process_randao_mixes_reset,
        _process_slashings_reset,
        _process_sync_committee_updates,
        _weigh_justification_and_finalization,
    )

    current_epoch = _current_epoch(state, preset)
    previous_epoch = _previous_epoch(state, preset)
    original_validators = state.validators
    cols = _columns_for(state, preset)
    n = cols.n
    incr = spec.effective_balance_increment

    active_cur = cols.active_at(current_epoch)
    active_prev = cols.active_at(previous_epoch)
    total_balance = _total_with_floor(cols.eff[active_cur].sum(), spec)

    part_prev = _cached_col(
        state, "_lh_part_prev", state.previous_epoch_participation, np.uint8
    )
    part_cur = _cached_col(
        state, "_lh_part_cur", state.current_epoch_participation, np.uint8
    )

    # ALL magnitude guards run before any state mutation: a guard that
    # tripped mid-flight would hand the oracle a half-processed state.
    sqrt_total = integer_squareroot(total_balance)
    base_per_inc = incr * spec.base_reward_factor // sqrt_total
    active_increments = total_balance // incr
    if base_per_inc * 32 * max(PARTICIPATION_FLAG_WEIGHTS) * max(
        1, active_increments
    ) >= 2**62:
        raise VectorGuard("flag reward product near int64")
    scores0 = _cached_col(
        state, "_lh_scores", state.inactivity_scores, np.uint64
    )
    if n and int(scores0.max(initial=0)) + spec.inactivity_score_bias >= 2**28:
        raise VectorGuard("inactivity score near overflow")

    def participating(flag_index: int, epoch: int) -> np.ndarray:
        part = part_cur if epoch == current_epoch else part_prev
        active = active_cur if epoch == current_epoch else active_prev
        flag = (part & np.uint8(1 << flag_index)) != 0
        return active & flag & ~cols.slashed

    # 1. justification & finalization (the checkpoint logic itself is
    # scalar; only the participating-balance sums are the hot part)
    if current_epoch > GENESIS_EPOCH + 1:
        prev_target_bal = _total_with_floor(
            cols.eff[participating(TIMELY_TARGET_FLAG_INDEX, previous_epoch)].sum(),
            spec,
        )
        cur_target_bal = _total_with_floor(
            cols.eff[participating(TIMELY_TARGET_FLAG_INDEX, current_epoch)].sum(),
            spec,
        )
        _weigh_justification_and_finalization(
            state, total_balance, prev_target_bal, cur_target_bal, preset
        )

    # eligibility mask (spec get_eligible_validator_indices)
    eligible = active_prev | (
        cols.slashed & (np.uint64(previous_epoch + 1) < cols.withdrawable)
    )
    finality_delay = previous_epoch - state.finalized_checkpoint.epoch
    in_leak = finality_delay > spec.min_epochs_to_inactivity_penalty

    prev_target = participating(TIMELY_TARGET_FLAG_INDEX, previous_epoch)

    # 2. inactivity scores (spec process_inactivity_updates); order matters:
    # flag-delta inactivity penalties read the UPDATED scores
    scores = scores0
    if current_epoch > GENESIS_EPOCH:
        hit = eligible & prev_target
        miss = eligible & ~prev_target
        scores[hit] -= np.minimum(np.uint64(1), scores[hit])
        scores[miss] += np.uint64(spec.inactivity_score_bias)
        if not in_leak:
            scores[eligible] -= np.minimum(
                np.uint64(spec.inactivity_score_recovery_rate), scores[eligible]
            )
        new_scores = tuple(scores.tolist())
        state.inactivity_scores = new_scores
        state.__dict__["_lh_scores"] = (new_scores, scores)

    # 3. rewards & penalties (spec get_flag_index_deltas + inactivity)
    balances = _cached_col(state, "_lh_bal", state.balances, np.int64)
    if current_epoch > GENESIS_EPOCH:
        base = (cols.eff // incr) * np.int64(base_per_inc)

        rewards = np.zeros(n, dtype=np.int64)
        penalties = np.zeros(n, dtype=np.int64)
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            part = participating(flag_index, previous_epoch)
            part_increments = (
                _total_with_floor(cols.eff[part].sum(), spec) // incr
            )
            hit = eligible & part
            if not in_leak:
                rewards[hit] += (
                    base[hit]
                    * np.int64(weight)
                    * np.int64(part_increments)
                    // np.int64(active_increments * WEIGHT_DENOMINATOR)
                )
            if flag_index != TIMELY_HEAD_FLAG_INDEX:
                miss = eligible & ~part
                penalties[miss] += (
                    base[miss] * np.int64(weight) // np.int64(WEIGHT_DENOMINATOR)
                )
        # inactivity penalties read the updated scores
        miss_target = eligible & ~prev_target
        denom = (
            spec.inactivity_score_bias
            * spec.inactivity_penalty_quotient_for(state.fork_name)
        )
        penalties[miss_target] += (
            cols.eff[miss_target] * scores[miss_target].astype(np.int64)
            // np.int64(denom)
        )
        # apply_balance_deltas semantics: add rewards, clamp penalties at 0
        b = balances + rewards
        balances = np.where(penalties > b, np.int64(0), b - penalties)

    # 4. registry updates (spec process_registry_updates)
    changed = _registry_updates_vec(
        state, cols, active_cur, current_epoch, preset, spec
    )

    # 5. slashings (spec process_slashings, altair multiplier); the hits
    # are rare (slashed + exact half-vector withdrawable epoch) so the
    # penalty arithmetic runs in exact Python ints per hit
    slash_sum = sum(state.slashings)
    adjusted = min(
        slash_sum * spec.proportional_slashing_multiplier_for(state.fork_name),
        total_balance
    )
    hits = np.nonzero(
        cols.slashed
        & (
            np.uint64(current_epoch + preset.epochs_per_slashings_vector // 2)
            == cols.withdrawable
        )
    )[0]
    for i in hits.tolist():
        penalty = (
            int(cols.eff[i]) // incr * adjusted // total_balance * incr
        )
        balances[i] = 0 if penalty > balances[i] else balances[i] - penalty

    # 6-7. eth1 + effective-balance hysteresis (balances are final now)
    _process_eth1_data_reset(state, preset)
    changed |= _effective_balance_updates_vec(state, cols, balances, spec)

    new_bal = tuple(balances.tolist())
    state.balances = new_bal
    state.__dict__["_lh_bal"] = (new_bal, balances)

    # registry writeback: ONE surgical tree-cache update covering every
    # validator index any phase touched; a clean epoch keeps the original
    # tuple identity so the hash cache skips the field entirely
    if changed or state.validators is not original_validators:
        from ..ssz.cached import surgical_list_update

        final = tuple(list(state.validators))
        surgical_list_update(
            state, "validators", original_validators, final, sorted(changed)
        )
    state.__dict__["_lh_epoch_cols"] = (state.validators, preset, cols)

    # 8-10. resets, historical roots, rotation, sync committees
    _process_slashings_reset(state, preset)
    _process_randao_mixes_reset(state, preset)
    _process_historical_roots_update(state, preset)
    rotated = state.current_epoch_participation
    state.previous_epoch_participation = rotated
    new_cur = (0,) * n
    state.current_epoch_participation = new_cur
    state.__dict__["_lh_part_prev"] = (rotated, part_cur)
    state.__dict__["_lh_part_cur"] = (new_cur, np.zeros(n, dtype=np.uint8))
    _process_sync_committee_updates(state, preset, spec)


def _registry_updates_vec(
    state, cols, active_cur, current_epoch, preset, spec
) -> set[int]:
    """Spec process_registry_updates over columns. Eligibility marking and
    the activation queue are vectorized; ejections (rare) run through the
    exact initiate_validator_exit path. Element objects are mutated in
    place; the caller issues one surgical tree-cache update for the
    returned changed-index set."""
    from .per_block import initiate_validator_exit

    vals = state.validators
    changed: set[int] = set()

    newly_eligible = np.nonzero(
        (cols.eligibility == _U64_FAR)
        & (cols.eff == np.int64(spec.max_effective_balance))
    )[0]
    for i in newly_eligible.tolist():
        vals[i].activation_eligibility_epoch = current_epoch + 1
        cols.eligibility[i] = current_epoch + 1
        changed.add(i)

    ejections = np.nonzero(
        active_cur & (cols.eff <= np.int64(spec.ejection_balance))
    )[0]
    for i in ejections.tolist():
        initiate_validator_exit(state, i, preset, spec)
        v = state.validators[i]
        cols.exit[i] = v.exit_epoch
        cols.withdrawable[i] = v.withdrawable_epoch
        changed.add(i)
    vals = state.validators

    # activation queue: eligible-for-activation, FIFO by (eligibility, index)
    candidates = np.nonzero(
        (cols.eligibility <= np.uint64(state.finalized_checkpoint.epoch))
        & (cols.activation == _U64_FAR)
    )[0]
    if len(candidates):
        order = np.lexsort((candidates, cols.eligibility[candidates]))
        active_count = int(active_cur.sum())
        churn_limit = max(
            spec.min_per_epoch_churn_limit,
            active_count // spec.churn_limit_quotient,
        )
        target_epoch = compute_activation_exit_epoch(current_epoch, spec)
        for i in candidates[order[:churn_limit]].tolist():
            vals[i].activation_epoch = target_epoch
            cols.activation[i] = target_epoch
            changed.add(i)
    return changed


def _effective_balance_updates_vec(state, cols, balances, spec) -> set[int]:
    """Spec process_effective_balance_updates: hysteresis compare over the
    whole registry, object writes only for the (few) crossers."""
    incr = spec.effective_balance_increment
    hysteresis_increment = incr // spec.hysteresis_quotient
    down = hysteresis_increment * spec.hysteresis_downward_multiplier
    up = hysteresis_increment * spec.hysteresis_upward_multiplier
    crossed = np.nonzero(
        (balances + np.int64(down) < cols.eff)
        | (cols.eff + np.int64(up) < balances)
    )[0]
    vals = state.validators
    max_eff = spec.max_effective_balance
    for i in crossed.tolist():
        b = int(balances[i])
        new_eff = min(b - b % incr, max_eff)
        vals[i].effective_balance = new_eff
        cols.eff[i] = new_eff
    return set(crossed.tolist())
