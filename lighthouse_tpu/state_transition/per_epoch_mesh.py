"""Mesh-sharded altair/bellatrix per-epoch processing: the validator
columns of per_epoch_vec.py (balances, participation, inactivity scores,
registry flags) lifted onto the device mesh.

Columns shard their validator-index dimension over the `validators` mesh
axis (parallel/verify_sharded.validators_mesh -- the same physical
devices the MeshVerifier shards batches over, so a fixed-size mesh
absorbs registry growth for state processing AND signature
verification). Two `shard_map` programs do the per-validator work:

  * a REDUCE pass whose only collectives are the genuinely-global
    reductions -- the total-active-balance sum (the
    `integer_squareroot` input), the per-flag participating-balance
    sums (justification weighing + flag-reward increments), and the
    active-validator count (the activation-queue churn limit) -- each
    one int64 psum of a per-shard partial;
  * an elementwise UPDATE pass (inactivity scores, flag rewards and
    penalties, balance application) with NO collectives at all.

Both run with int64/uint64 semantics identical to the numpy path (the
passes execute under `jax.experimental.enable_x64`; floor division of
non-negative 64-bit quantities matches the spec's integer arithmetic
exactly). Rare per-validator paths -- ejections, the FIFO activation
queue, slashing hits, hysteresis crossers -- and the surgical tree-cache
writeback are SHARED with per_epoch_vec.py, so the bit-exactness
contract against the per_epoch.py oracle carries over unchanged,
including the pre-mutation VectorGuard overflow fallback
(tests/test_sharded_state.py holds mesh sizes 1/2/4 equal to the
oracle).

Shapes bucket to powers of two (floor 256) so a live node compiles a
handful of small programs per mesh, never one per registry size.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import enable_x64
from jax.sharding import NamedSharding, PartitionSpec as P

from ..types import FAR_FUTURE_EPOCH, GENESIS_EPOCH
from ..types.presets import Preset
from ..utils.math import integer_squareroot
from .participation import (
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
)
from .per_epoch_vec import (
    VectorGuard,
    _cached_col,
    _columns_for,
    _effective_balance_updates_vec,
    _registry_updates_vec,
    _total_with_floor,
)

_N_FLAGS = len(PARTICIPATION_FLAG_WEIGHTS)
_PAD_FLOOR = 256

# mesh + compiled shard_map programs, one per device set (jit itself
# re-specializes per padded shape, so shapes never key these dicts)
_MESHES: dict[tuple, object] = {}
_PROGRAMS: dict[tuple, tuple] = {}


def _mesh_for(devices):
    from ..parallel.verify_sharded import validators_mesh

    if devices is None:
        devices = jax.devices()
    key = tuple(d.id for d in devices)
    mesh = _MESHES.get(key)
    if mesh is None:
        mesh = _MESHES[key] = validators_mesh(devices)
    return mesh


def _pad_bucket(n: int, n_shards: int) -> int:
    """Power-of-two row bucket, floor 256, always divisible by the
    (power-of-two) shard count."""
    b = max(_PAD_FLOOR, n_shards)
    while b < n:
        b *= 2
    return b


def _flag_mask(part, active, slashed, flag_index: int):
    """Spec get_unslashed_participating_indices as a device mask."""
    return active & ((part & jnp.uint8(1 << flag_index)) != 0) & ~slashed


def _build_programs(mesh):
    """The two shard_map programs for `mesh` (see module docstring)."""
    from ..parallel.verify_sharded import VALIDATOR_AXIS, shard_map

    def psum_i64(x):
        return jax.lax.psum(jnp.sum(x), VALIDATOR_AXIS)

    def sums_body(eff, activation, exit_e, slashed, part_prev, part_cur, ep):
        prev_e, cur_e = ep[0], ep[1]
        active_prev = (activation <= prev_e) & (prev_e < exit_e)
        active_cur = (activation <= cur_e) & (cur_e < exit_e)
        zero = jnp.int64(0)
        out = [
            psum_i64(jnp.where(active_cur, eff, zero)),
            psum_i64(active_cur.astype(jnp.int64)),
        ]
        for f in range(_N_FLAGS):
            m = _flag_mask(part_prev, active_prev, slashed, f)
            out.append(psum_i64(jnp.where(m, eff, zero)))
        cur_target = _flag_mask(
            part_cur, active_cur, slashed, TIMELY_TARGET_FLAG_INDEX
        )
        out.append(psum_i64(jnp.where(cur_target, eff, zero)))
        return jnp.stack(out)

    def update_body(
        eff, activation, exit_e, withdrawable, slashed, part_prev,
        scores, balances, pu, pi, in_leak,
    ):
        prev_e, bias, recovery = pu[0], pu[1], pu[2]
        base_per_inc, act_incr, denom, incr = pi[0], pi[1], pi[2], pi[3]
        part_inc = pi[4 : 4 + _N_FLAGS]
        active_prev = (activation <= prev_e) & (prev_e < exit_e)
        eligible = active_prev | (
            slashed & (prev_e + jnp.uint64(1) < withdrawable)
        )
        flags = [
            _flag_mask(part_prev, active_prev, slashed, f)
            for f in range(_N_FLAGS)
        ]
        prev_target = flags[TIMELY_TARGET_FLAG_INDEX]

        # inactivity scores (spec process_inactivity_updates); the
        # inactivity penalty below reads the UPDATED scores
        one = jnp.uint64(1)
        hit = eligible & prev_target
        miss = eligible & ~prev_target
        scores = jnp.where(hit, scores - jnp.minimum(one, scores), scores)
        scores = jnp.where(miss, scores + bias, scores)
        scores = jnp.where(
            eligible & ~in_leak,
            scores - jnp.minimum(recovery, scores),
            scores,
        )

        # flag rewards/penalties (spec get_flag_index_deltas): products
        # are guarded < 2**62 BEFORE dispatch, so the masked lanes are
        # overflow-free exactly like the numpy fancy-indexed path
        base = (eff // incr) * base_per_inc
        rewards = jnp.zeros_like(eff)
        penalties = jnp.zeros_like(eff)
        wden = jnp.int64(WEIGHT_DENOMINATOR)
        zero = jnp.int64(0)
        for f, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            w = jnp.int64(weight)
            rewards = rewards + jnp.where(
                eligible & flags[f] & ~in_leak,
                base * w * part_inc[f] // (act_incr * wden),
                zero,
            )
            if f != TIMELY_HEAD_FLAG_INDEX:
                penalties = penalties + jnp.where(
                    eligible & ~flags[f], base * w // wden, zero
                )
        penalties = penalties + jnp.where(
            eligible & ~prev_target,
            eff * scores.astype(jnp.int64) // denom,
            zero,
        )
        # apply_balance_deltas semantics: add rewards, clamp at zero
        b = balances + rewards
        balances = jnp.where(penalties > b, zero, b - penalties)
        return scores, balances

    col, rep = P(VALIDATOR_AXIS), P()

    def wrap(body, n_col_args, n_rep_args, n_out_cols):
        specs = (col,) * n_col_args + (rep,) * n_rep_args
        out_specs = rep if n_out_cols == 0 else (col,) * n_out_cols
        try:
            mapped = shard_map(
                body, mesh=mesh, in_specs=specs, out_specs=out_specs,
                check_vma=False,
            )
        except TypeError:  # pre-0.6 jax spells the flag check_rep
            mapped = shard_map(
                body, mesh=mesh, in_specs=specs, out_specs=out_specs,
                check_rep=False,
            )
        return jax.jit(mapped)

    sums = wrap(sums_body, 6, 1, 0)
    update = wrap(update_body, 8, 3, 2)
    return sums, update


def _programs_for(mesh):
    key = tuple(int(d.id) for d in np.ravel(mesh.devices))
    progs = _PROGRAMS.get(key)
    if progs is None:
        progs = _PROGRAMS[key] = _build_programs(mesh)
    return progs


def _pad(arr: np.ndarray, n_pad: int, fill) -> np.ndarray:
    out = np.full((n_pad,), fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def process_epoch_altair_mesh(state, preset: Preset, spec, devices=None) -> None:
    """Drop-in replacement for process_epoch_altair_vec with the column
    passes sharded over the device mesh. Raises VectorGuard when a
    magnitude guard (or an unsupported corner: the genesis epochs) needs
    the single-device/oracle path; the caller falls back."""
    from .per_epoch import (
        _current_epoch,
        _previous_epoch,
        _process_eth1_data_reset,
        _process_historical_roots_update,
        _process_randao_mixes_reset,
        _process_slashings_reset,
        _process_sync_committee_updates,
        _weigh_justification_and_finalization,
    )

    current_epoch = _current_epoch(state, preset)
    previous_epoch = _previous_epoch(state, preset)
    if current_epoch <= GENESIS_EPOCH + 1:
        # the genesis epochs skip justification/inactivity phases; they
        # run once per chain -- not worth a second program variant
        raise VectorGuard("mesh epoch path starts past genesis+1")
    original_validators = state.validators
    cols = _columns_for(state, preset)
    n = cols.n
    incr = spec.effective_balance_increment

    part_prev = _cached_col(
        state, "_lh_part_prev", state.previous_epoch_participation, np.uint8
    )
    part_cur = _cached_col(
        state, "_lh_part_cur", state.current_epoch_participation, np.uint8
    )
    scores0 = _cached_col(
        state, "_lh_scores", state.inactivity_scores, np.uint64
    )
    balances0 = _cached_col(state, "_lh_bal", state.balances, np.int64)

    mesh = _mesh_for(devices)
    n_shards = int(np.ravel(mesh.devices).size)
    n_pad = _pad_bucket(max(n, 1), n_shards)
    sums_fn, update_fn = _programs_for(mesh)

    from ..parallel.verify_sharded import VALIDATOR_AXIS

    col_sharding = NamedSharding(mesh, P(VALIDATOR_AXIS))
    rep_sharding = NamedSharding(mesh, P())

    with enable_x64():
        def shard(arr):
            return jax.device_put(arr, col_sharding)

        def rep(arr):
            return jax.device_put(arr, rep_sharding)

        # padding rows: never active, never eligible, zero balance --
        # they vanish from every sum and the update pass is identity
        d_eff = shard(_pad(cols.eff, n_pad, 0))
        d_act = shard(_pad(cols.activation, n_pad, np.uint64(FAR_FUTURE_EPOCH)))
        d_exit = shard(_pad(cols.exit, n_pad, np.uint64(FAR_FUTURE_EPOCH)))
        d_wd = shard(_pad(cols.withdrawable, n_pad, np.uint64(0)))
        d_slashed = shard(_pad(cols.slashed, n_pad, False))
        d_part_prev = shard(_pad(part_prev, n_pad, np.uint8(0)))
        d_part_cur = shard(_pad(part_cur, n_pad, np.uint8(0)))
        d_scores = shard(_pad(scores0, n_pad, np.uint64(0)))
        d_balances = shard(_pad(balances0, n_pad, 0))

        epochs = rep(
            np.array([previous_epoch, current_epoch], dtype=np.uint64)
        )
        sums = np.asarray(
            sums_fn(
                d_eff, d_act, d_exit, d_slashed, d_part_prev, d_part_cur,
                epochs,
            )
        )

    total_eff = int(sums[0])
    active_count = int(sums[1])
    flag_sums = [int(v) for v in sums[2 : 2 + _N_FLAGS]]
    cur_target_sum = int(sums[-1])
    total_balance = _total_with_floor(total_eff, spec)

    # ALL magnitude guards run before any state mutation (the vec
    # contract): a guard that tripped mid-flight would hand the fallback
    # a half-processed state
    sqrt_total = integer_squareroot(total_balance)
    base_per_inc = incr * spec.base_reward_factor // sqrt_total
    active_increments = total_balance // incr
    if base_per_inc * 32 * max(PARTICIPATION_FLAG_WEIGHTS) * max(
        1, active_increments
    ) >= 2**62:
        raise VectorGuard("flag reward product near int64")
    if n and int(scores0.max(initial=0)) + spec.inactivity_score_bias >= 2**28:
        raise VectorGuard("inactivity score near overflow")

    # 1. justification & finalization from the psum'd balances
    prev_target_bal = _total_with_floor(
        flag_sums[TIMELY_TARGET_FLAG_INDEX], spec
    )
    cur_target_bal = _total_with_floor(cur_target_sum, spec)
    _weigh_justification_and_finalization(
        state, total_balance, prev_target_bal, cur_target_bal, preset
    )

    finality_delay = previous_epoch - state.finalized_checkpoint.epoch
    in_leak = finality_delay > spec.min_epochs_to_inactivity_penalty
    part_increments = [_total_with_floor(s, spec) // incr for s in flag_sums]
    denom = (
        spec.inactivity_score_bias
        * spec.inactivity_penalty_quotient_for(state.fork_name)
    )

    # 2-3. inactivity scores + flag rewards/penalties: ONE elementwise
    # sharded pass, no collectives
    with enable_x64():
        pu = jax.device_put(
            np.array(
                [
                    previous_epoch,
                    spec.inactivity_score_bias,
                    spec.inactivity_score_recovery_rate,
                ],
                dtype=np.uint64,
            ),
            rep_sharding,
        )
        pi = jax.device_put(
            np.array(
                [base_per_inc, active_increments, denom, incr]
                + part_increments,
                dtype=np.int64,
            ),
            rep_sharding,
        )
        leak = jax.device_put(np.bool_(in_leak), rep_sharding)
        d_new_scores, d_new_balances = update_fn(
            d_eff, d_act, d_exit, d_wd, d_slashed, d_part_prev,
            d_scores, d_balances, pu, pi, leak,
        )
        scores = np.array(d_new_scores)[:n]
        balances = np.array(d_new_balances)[:n]

    new_scores = tuple(scores.tolist())
    state.inactivity_scores = new_scores
    state.__dict__["_lh_scores"] = (new_scores, scores)

    # 4. registry updates: eligibility marking / ejections / activation
    # queue are the SAME host-side rare paths as the vec module; the
    # churn limit consumes the mesh's psum'd active count
    active_cur = cols.active_at(current_epoch)
    assert int(active_cur.sum()) == active_count
    changed = _registry_updates_vec(
        state, cols, active_cur, current_epoch, preset, spec
    )

    # 5. slashings (rare hits, exact Python ints per hit -- shared
    # semantics with per_epoch_vec)
    slash_sum = sum(state.slashings)
    adjusted = min(
        slash_sum * spec.proportional_slashing_multiplier_for(state.fork_name),
        total_balance,
    )
    hits = np.nonzero(
        cols.slashed
        & (
            np.uint64(current_epoch + preset.epochs_per_slashings_vector // 2)
            == cols.withdrawable
        )
    )[0]
    for i in hits.tolist():
        penalty = (
            int(cols.eff[i]) // incr * adjusted // total_balance * incr
        )
        balances[i] = 0 if penalty > balances[i] else balances[i] - penalty

    # 6-7. eth1 + effective-balance hysteresis (balances are final now)
    _process_eth1_data_reset(state, preset)
    changed |= _effective_balance_updates_vec(state, cols, balances, spec)

    new_bal = tuple(balances.tolist())
    state.balances = new_bal
    state.__dict__["_lh_bal"] = (new_bal, balances)

    if changed or state.validators is not original_validators:
        from ..ssz.cached import surgical_list_update

        final = tuple(list(state.validators))
        surgical_list_update(
            state, "validators", original_validators, final, sorted(changed)
        )
    state.__dict__["_lh_epoch_cols"] = (state.validators, preset, cols)

    # 8-10. resets, historical roots, rotation, sync committees
    _process_slashings_reset(state, preset)
    _process_randao_mixes_reset(state, preset)
    _process_historical_roots_update(state, preset)
    rotated = state.current_epoch_participation
    state.previous_epoch_participation = rotated
    new_cur = (0,) * n
    state.current_epoch_participation = new_cur
    state.__dict__["_lh_part_prev"] = (rotated, part_cur)
    state.__dict__["_lh_part_cur"] = (new_cur, np.zeros(n, dtype=np.uint8))
    _process_sync_committee_updates(state, preset, spec)
