"""Per-slot state advancement (reference consensus/state_processing/src/
per_slot_processing.rs:25): cache roots, bump the slot, and run epoch
processing at boundaries. Also proposer selection and state cloning."""

from __future__ import annotations

from ..types import (
    compute_epoch_at_slot,
    compute_proposer_index,
    get_active_validator_indices,
    get_seed,
)
from ..types.chain_spec import DOMAIN_BEACON_PROPOSER
from ..types.helpers import hash32
from ..types.presets import Preset
from .context import BlockProcessingError


def clone_state(state):
    """Deep copy via SSZ round trip -- guarantees no aliasing between the
    copies (the reference gets this from Rust Clone; BeaconState ssz
    encode/decode round trips are its benchmark workload,
    consensus/types/benches/benches.rs:49-176)."""
    cls = type(state)
    return cls.from_ssz_bytes(state.as_ssz_bytes())


def get_beacon_proposer_index(state, preset: Preset, spec) -> int:
    epoch = compute_epoch_at_slot(state.slot, preset)
    seed = hash32(
        get_seed(state, epoch, DOMAIN_BEACON_PROPOSER, preset, spec)
        + state.slot.to_bytes(8, "little")
    )
    indices = get_active_validator_indices(state, epoch)
    return compute_proposer_index(state, indices, seed, spec)


def process_slot(state, preset: Preset):
    """Cache state/block roots into the ring buffers (spec process_slot).

    The state root goes through the incremental tree-hash cache
    (ssz/cached.py, reference consensus/cached_tree_hash): slot-to-slot the
    state differs in a handful of fields, so the cached path re-hashes only
    dirty merkle paths instead of the whole ~100k-validator tree."""
    from ..ssz import cached_root

    previous_state_root = cached_root(state)
    roots = list(state.state_roots)
    roots[state.slot % preset.slots_per_historical_root] = previous_state_root
    state.state_roots = tuple(roots)

    if bytes(state.latest_block_header.state_root) == bytes(32):
        state.latest_block_header.state_root = previous_state_root

    block_root = state.latest_block_header.tree_hash_root()
    roots = list(state.block_roots)
    roots[state.slot % preset.slots_per_historical_root] = block_root
    state.block_roots = tuple(roots)


def process_slots(state, target_slot: int, preset: Preset, spec):
    """Advance `state` to `target_slot`, running epoch transitions at
    boundaries (spec process_slots; reference per_slot_processing)."""
    if target_slot < state.slot:
        raise BlockProcessingError(
            f"cannot rewind state from {state.slot} to {target_slot}"
        )
    from .per_epoch import process_epoch
    from .upgrades import upgrade_state_if_due

    while state.slot < target_slot:
        process_slot(state, preset)
        if (state.slot + 1) % preset.slots_per_epoch == 0:
            process_epoch(state, preset, spec)
        state.slot += 1
        state = upgrade_state_if_due(state, preset, spec)
    return state
