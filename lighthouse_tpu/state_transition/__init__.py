"""State transition (reference consensus/state_processing, SURVEY.md
section 2.2): per-slot/epoch/block processing, signature-set builders,
and the batched BlockSignatureVerifier."""

from .block_signature_verifier import BlockSignatureVerifier  # noqa: F401
from .context import BlockProcessingError, ConsensusContext  # noqa: F401
from .per_block import (  # noqa: F401
    BlockSignatureStrategy,
    per_block_processing,
    process_attestation,
    process_deposit,
)
from .per_epoch import process_epoch  # noqa: F401
from .per_slot import (  # noqa: F401
    clone_state,
    get_beacon_proposer_index,
    process_slot,
    process_slots,
)
from .genesis import (  # noqa: F401
    initialize_beacon_state_from_eth1,
    is_valid_genesis_state,
    try_genesis_from_eth1,
)
from .replay import BlockReplayer  # noqa: F401
from .upgrades import upgrade_to_altair  # noqa: F401
