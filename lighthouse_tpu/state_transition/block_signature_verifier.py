"""Block signature verifier: collect EVERY signature in a block into one
set list, verify with ONE backend call (reference consensus/
state_processing/src/per_block_processing/block_signature_verifier.rs:73,
127-138 -- its rayon map-reduce at :357-385 becomes the TPU batch kernel's
internal set-axis parallelism)."""

from __future__ import annotations

from ..crypto.bls import verify_signature_sets_async
from ..types.presets import Preset
from .context import ConsensusContext
from .signature_sets import (
    attester_slashing_signature_sets,
    block_proposal_signature_set,
    deposit_signature_set,
    exit_signature_set,
    indexed_attestation_signature_set,
    proposer_slashing_signature_sets,
    randao_signature_set,
    state_pubkey_getter,
    sync_aggregate_signature_set,
)


class BlockSignatureVerifier:
    def __init__(
        self,
        state,
        preset: Preset,
        spec,
        ctxt: ConsensusContext | None = None,
        get_pubkey=None,
        resolve_pubkey=None,
    ):
        self.state = state
        self.preset = preset
        self.spec = spec
        self.ctxt = ctxt or ConsensusContext(preset, spec)
        self.get_pubkey = get_pubkey or state_pubkey_getter(state)
        # bytes -> PublicKey for sync-committee participants; the chain
        # plugs its pubkey cache here so keys stay table-tagged
        self.resolve_pubkey = resolve_pubkey
        self.sets = []

    # include_* mirror block_signature_verifier.rs:141-340

    def include_block_proposal(self, signed_block):
        self.sets.append(
            block_proposal_signature_set(
                self.state, self.get_pubkey, signed_block, self.preset, self.spec
            )
        )

    def include_randao_reveal(self, signed_block):
        block = signed_block.message
        self.sets.append(
            randao_signature_set(
                self.state,
                self.get_pubkey,
                block.proposer_index,
                block.body.randao_reveal,
                self.preset,
                self.spec,
            )
        )

    def include_proposer_slashings(self, signed_block):
        for op in signed_block.message.body.proposer_slashings:
            self.sets.extend(
                proposer_slashing_signature_sets(
                    self.state, self.get_pubkey, op, self.preset, self.spec
                )
            )

    def include_attester_slashings(self, signed_block):
        for op in signed_block.message.body.attester_slashings:
            self.sets.extend(
                attester_slashing_signature_sets(
                    self.state, self.get_pubkey, op, self.preset, self.spec
                )
            )

    def include_attestations(self, signed_block):
        for att in signed_block.message.body.attestations:
            indexed = self.ctxt.get_indexed_attestation(self.state, att)
            self.sets.append(
                indexed_attestation_signature_set(
                    self.state, self.get_pubkey, indexed, self.preset, self.spec
                )
            )

    def include_exits(self, signed_block):
        for op in signed_block.message.body.voluntary_exits:
            self.sets.append(
                exit_signature_set(
                    self.state, self.get_pubkey, op, self.preset, self.spec
                )
            )

    def include_sync_aggregate(self, signed_block):
        body = signed_block.message.body
        sync_aggregate = getattr(body, "sync_aggregate", None)
        if sync_aggregate is None:
            return
        from ..types.helpers import get_block_root_at_slot

        block = signed_block.message
        root = bytes(block.parent_root)
        s = sync_aggregate_signature_set(
            self.state,
            self.resolve_pubkey,
            sync_aggregate,
            block.slot,
            root,
            list(self.state.current_sync_committee.pubkeys),
            self.preset,
            self.spec,
        )
        if s is not None:
            self.sets.append(s)

    def include_all_signatures(self, signed_block):
        """Everything except deposits (deposits self-certify and are
        verified during processing, as the reference does)."""
        self.include_block_proposal(signed_block)
        self.include_all_signatures_except_block_proposal(signed_block)

    def include_all_signatures_except_block_proposal(self, signed_block):
        self.include_randao_reveal(signed_block)
        self.include_proposer_slashings(signed_block)
        self.include_attester_slashings(signed_block)
        self.include_attestations(signed_block)
        self.include_exits(signed_block)
        self.include_sync_aggregate(signed_block)

    def verify(self, slot: int | None = None) -> bool:
        """One device program for the whole block's sets. Routed on the
        block lane: under continuous batching the sets merge with queued
        attestation/sync traffic at the HIGHEST priority; when the chain
        passed its pubkey-cache getter in, every set is table-tagged, so
        the batch rides the device-table gather (and the sharded mesh at
        mesh-eligible sizes) -- whole-block import as one sharded device
        program."""
        if not self.sets:
            return True
        return verify_signature_sets_async(
            self.sets, lane="block", slot=slot
        ).result()
