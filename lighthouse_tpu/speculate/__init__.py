"""Duty-driven precompute & speculative verification.

`lighthouse_tpu/speculate/` sits between the chain's epoch boundary and
the BLS pipeline:

  * :mod:`.precompute` — per-(slot, committee) aggregate pubkeys built at
    each epoch transition, keyed on the attester shuffling seed, so the
    hot attestation path skips per-set pubkey aggregation entirely (full
    participation) or pays only an O(absent) incremental correction;
  * :mod:`.scheduler` — idle-time pre-verification of the expected
    next-slot aggregates, confirmed-by-lookup on arrival.

`attach_speculation(chain, ...)` wires both into a live chain: it sets
`chain.speculation` (the hook `chain/attestation_verification.py` probes
during aggregate batch setup), subscribes to the chain's event sinks for
epoch-rollover refresh and reorg invalidation, and registers the idle
task on the BeaconProcessor. Every path is fail-open: a missing entry, a
stale shuffling key, or any speculation mismatch falls through to the
normal fully-verified path — speculation can make verification cheaper,
never weaker.
"""

from __future__ import annotations

from ..crypto.bls import SignatureSet
from ..state_transition.context import ConsensusContext
from ..types import compute_epoch_at_slot
from ..utils import metrics as M
from .precompute import CommitteePrecompute, PrecomputeEntry
from .scheduler import SpeculativeVerifier

__all__ = [
    "CommitteePrecompute",
    "PrecomputeEntry",
    "SpeculativeVerifier",
    "SpeculationSubsystem",
    "attach_speculation",
]


class SpeculationSubsystem:
    """The two halves plus their chain/processor plumbing. Construct via
    :func:`attach_speculation`."""

    def __init__(
        self,
        chain,
        processor=None,
        signature_source=None,
        queue_wait_p95_max: float = 0.05,
        device_correction: bool | None = None,
    ):
        self.chain = chain
        self.processor = processor
        self.enabled = True
        self.precompute = CommitteePrecompute(
            chain.preset, chain.spec, device_correction=device_correction
        )
        self.verifier = SpeculativeVerifier(
            chain,
            self.precompute,
            signature_source=signature_source,
            queue_wait_p95_max=queue_wait_p95_max,
        )
        self._last_refreshed_epoch: int | None = None
        # audit trail for the scenario harness: tree roots of every
        # attestation accepted by CONFIRM-BY-LOOKUP (the only speculation
        # outcome that skips re-verification). The Byzantine-VC scenarios
        # counter-assert that no byz-emitted aggregate ever lands here.
        self.confirmed_roots: list[bytes] = []

    # -- precompute refresh (epoch boundary / startup / reorg) ---------------

    def refresh(self, force: bool = False) -> int:
        """Precompute the head state's current and next epochs (the
        committees a gossip aggregate can reference under the propagation
        window). Cheap when keys are unchanged; `force` re-walks anyway."""
        chain = self.chain
        state = chain.head_state
        epoch = compute_epoch_at_slot(int(state.slot), chain.preset)
        ctxt = ConsensusContext(chain.preset, chain.spec)
        get_pubkey = chain.pubkey_cache.getter(state)
        built = 0
        for e in (epoch, epoch + 1):
            if force:
                self.precompute._drop_epoch(e, invalidated=False)
            built += self.precompute.refresh_epoch(state, e, ctxt, get_pubkey)
        self.precompute.prune(max(0, epoch - 1))
        self._last_refreshed_epoch = epoch
        return built

    # -- chain event sink ----------------------------------------------------

    def on_event(self, kind: str, payload) -> None:
        """Head events drive the lifecycle: epoch rollover refreshes the
        next epoch's committees; any head move revalidates cached
        shuffling keys against the new head state (a reorg that crossed
        an epoch boundary changes the seed and drops the entries; a
        same-shuffling reorg keeps them warm)."""
        if kind != "head":
            return
        chain = self.chain
        state = chain.head_state
        epoch = compute_epoch_at_slot(int(state.slot), chain.preset)
        stale = False
        for e in list(self.precompute._keys):
            if not self.precompute.check_epoch(state, e):
                stale = True
        if stale or epoch != self._last_refreshed_epoch:
            self.refresh()
        self.verifier.prune(int(state.slot) - 2)

    # -- idle task (BeaconProcessor seam) ------------------------------------

    def idle_task(self) -> None:
        """One speculation pass, gated on pipeline idleness; registered
        via BeaconProcessor.set_idle_task."""
        if not self.enabled:
            return
        if not self.verifier.should_run(self.processor):
            return
        self.verifier.stats["idle_runs"] += 1
        M.SPECULATE_IDLE_RUNS.inc()
        self.verifier.speculate_slot()

    # -- the verification hook (critical path) -------------------------------

    def process_indexed_set(self, state, attestation, indexed, ind_set):
        """Called by aggregate batch setup with the already-built indexed
        attestation signature set. Returns:

          * ``None`` — the exact claim was pre-verified and the arriving
            signature matches: confirmed by lookup, drop the set;
          * a replacement set whose single pubkey is the precomputed
            (full or corrected) committee aggregate — zero per-set
            aggregation for the backend, identical verdict;
          * ``ind_set`` unchanged — miss; verify on the normal path.
        """
        if not self.enabled:
            return ind_set
        data = attestation.data
        slot, index = int(data.slot), int(data.index)
        epoch = int(data.target.epoch)
        bits = tuple(bool(b) for b in attestation.aggregation_bits)
        entry = self.precompute.lookup(state, slot, index, epoch)
        if entry is None or not entry.matches(bits, indexed.attesting_indices):
            self.precompute.stats["misses"] += 1
            M.SPECULATE_PRECOMPUTE_MISSES.inc()
            return ind_set
        if self.verifier.confirm(
            ind_set.message,
            bits,
            slot,
            index,
            entry.shuffling_key,
            bytes(attestation.signature),
        ):
            self.confirmed_roots.append(bytes(attestation.tree_hash_root()))
            return None
        pk = self.precompute.aggregate_pubkey(entry, bits)
        return SignatureSet(ind_set.signature, [pk], ind_set.message)

    # -- teardown ------------------------------------------------------------

    def detach(self) -> None:
        self.enabled = False
        chain = self.chain
        if getattr(chain, "speculation", None) is self:
            chain.speculation = None
        try:
            chain.event_sinks.remove(self.on_event)
        except ValueError:
            pass
        if self.processor is not None and (
            getattr(self.processor, "idle_task", None) == self.idle_task
        ):
            self.processor.set_idle_task(None)


def attach_speculation(
    chain,
    processor=None,
    signature_source=None,
    queue_wait_p95_max: float = 0.05,
    device_correction: bool | None = None,
) -> SpeculationSubsystem:
    """Wire the speculation subsystem into `chain` (and optionally a
    BeaconProcessor for idle-time scheduling). Refreshes the precompute
    for the current/next epochs immediately (the startup contract)."""
    sub = SpeculationSubsystem(
        chain,
        processor=processor,
        signature_source=signature_source,
        queue_wait_p95_max=queue_wait_p95_max,
        device_correction=device_correction,
    )
    chain.speculation = sub
    chain.event_sinks.append(sub.on_event)
    if processor is not None and hasattr(processor, "set_idle_task"):
        processor.set_idle_task(sub.idle_task)
    sub.refresh()
    return sub
