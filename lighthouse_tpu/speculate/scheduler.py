"""Speculative next-slot pre-verification (tentpole half 2).

During idle device time the scheduler pre-verifies the EXPECTED next-slot
aggregate attestations from the duty schedule: expected message = the
current head as target/source (no-reorg assumption, exactly what
`chain.produce_attestation_data` returns for a future slot), expected
participation = the full committee. Each pre-verified (message,
participation-bits, committee, shuffling-key) is memoized together with
the VERIFIED signature bytes, so when the real aggregate arrives it is
confirmed by cache lookup instead of paying a pairing on the critical
path.

Hard soundness rule — NEVER TRUST-ON-PREDICT:

  * a memo entry is only written after a real `verify_signature_sets`
    call returned True for exactly that (message, bits, committee) claim;
  * confirmation requires the arriving signature BYTES to equal the
    pre-verified ones (BLS signing is deterministic, so the honest
    aggregate over the same signer set is unique) — any difference is a
    MISMATCH, counted and fully re-verified on the normal path;
  * a missing memo entry is a MISS: the set rides the normal batch.

The expected aggregate's signature cannot be known by a node that does
not hold the keys, so where it comes from is pluggable
(`signature_source`): the bench/test harnesses supply interop-key
signing, a staking-pool deployment would bridge its own signers, and
with no source the scheduler is a no-op (precompute still works).

Idle gating (PR-5 observability): a pass runs only when the processor's
queues are empty, nothing is deferred or in flight, and the windowed
queue-wait p95 is below `queue_wait_p95_max` — speculation must never
add latency to real work.
"""

from __future__ import annotations

from ..crypto.bls import (
    Signature,
    SignatureSet,
    verify_signature_sets_async,
)
from ..types import (
    DOMAIN_BEACON_ATTESTER,
    compute_epoch_at_slot,
)
from ..types.helpers import compute_signing_root, get_domain
from ..utils import metrics as M

_MAX_MEMO = 8192


class SpeculativeVerifier:
    def __init__(
        self,
        chain,
        precompute,
        signature_source=None,
        queue_wait_p95_max: float = 0.05,
    ):
        self.chain = chain
        self.precompute = precompute
        # signature_source(data, members, signing_root) -> bytes | None
        self.signature_source = signature_source
        self.queue_wait_p95_max = queue_wait_p95_max
        # (message, bits, slot, index, shuffling_key) -> verified sig bytes
        self._memo: dict[tuple, bytes] = {}
        self._wait_baseline = M.PROCESSOR_QUEUE_WAIT.snapshot()
        self.stats = {
            "preverified": 0,
            "confirms": 0,
            "confirm_misses": 0,
            "mismatches": 0,
            "idle_runs": 0,
        }

    # -- idle gating ---------------------------------------------------------

    def should_run(self, processor=None) -> bool:
        """Only speculate when the pipeline is genuinely idle: empty
        queues, zero in-flight verdicts, no busy workers, and the
        queue-wait p95 over the window since the last pass below the
        threshold."""
        if processor is not None:
            health = processor.health_snapshot()
            if (
                health["pending"]
                or health["deferred"]
                or health["busy_workers"]
            ):
                return False
        p95 = M.PROCESSOR_QUEUE_WAIT.quantile(0.95, since=self._wait_baseline)
        if p95 is not None and p95 > self.queue_wait_p95_max:
            # pressure in the window: skip, and restart the window so a
            # past storm doesn't gate speculation forever
            self._wait_baseline = M.PROCESSOR_QUEUE_WAIT.snapshot()
            return False
        return True

    # -- the speculation pass ------------------------------------------------

    def speculate_slot(self, slot: int | None = None) -> int:
        """Pre-verify the expected aggregates for `slot` (default: the
        slot after the chain's current one) from the duty schedule.
        Returns the number of memo entries written."""
        if self.signature_source is None:
            return 0
        chain = self.chain
        if slot is None:
            slot = int(chain.current_slot) + 1
        state = chain.head_state
        epoch = compute_epoch_at_slot(slot, chain.preset)
        entries = self.precompute._epochs.get(epoch)
        if not entries:
            return 0
        self._wait_baseline = M.PROCESSOR_QUEUE_WAIT.snapshot()
        written = 0
        for (e_slot, index), entry in sorted(entries.items()):
            if e_slot != slot:
                continue
            bits = (True,) * len(entry.members)
            key = None
            try:
                data = chain.produce_attestation_data(slot, index)
                domain = get_domain(
                    state, DOMAIN_BEACON_ATTESTER, epoch, chain.preset
                )
                root = compute_signing_root(data, domain)
                key = (
                    bytes(root),
                    bits,
                    slot,
                    index,
                    entry.shuffling_key,
                )
                if key in self._memo:
                    continue
                sig_bytes = self.signature_source(
                    data, entry.members, root
                )
            except Exception:  # noqa: BLE001 -- speculation must never
                # break the node: a failed prediction is just a future
                # confirm-miss
                continue
            if sig_bytes is None:
                continue
            # a REAL verification (device batch of one, precomputed
            # aggregate pubkey): only a True verdict is ever memoized.
            # Routed on the SPECULATIVE lane: under continuous batching
            # this work is preempted at any launch boundary where real
            # arrivals are queued (it stays queued, never dropped)
            s = SignatureSet.multiple_pubkeys(
                Signature.from_bytes(bytes(sig_bytes)), [entry.full_pk], root
            )
            if verify_signature_sets_async(
                [s], lane="speculative", slot=slot
            ).result():
                self._memo[key] = bytes(sig_bytes)
                written += 1
                self.stats["preverified"] += 1
                M.SPECULATE_PREVERIFIED.inc()
        if len(self._memo) > _MAX_MEMO:
            self.prune(slot - 2)
        return written

    # -- confirm-on-arrival (critical path) ----------------------------------

    def confirm(
        self, message, bits, slot, index, shuffling_key, signature_bytes
    ) -> bool:
        """True iff this exact claim was pre-verified: memo hit AND the
        arriving signature bytes equal the verified ones. Counts the
        outcome either way; False always means "verify normally"."""
        key = (bytes(message), tuple(bits), int(slot), int(index),
               shuffling_key)
        expected = self._memo.get(key)
        if expected is None:
            self.stats["confirm_misses"] += 1
            M.SPECULATE_CONFIRM_MISSES.inc()
            return False
        if bytes(signature_bytes) != expected:
            # same expected message but a different signature: a forgery
            # or non-canonical encoding — never trusted
            self.stats["mismatches"] += 1
            M.SPECULATE_MISMATCHES.inc()
            return False
        self.stats["confirms"] += 1
        M.SPECULATE_CONFIRMS.inc()
        return True

    def prune(self, min_slot: int) -> None:
        """Drop memo entries for slots before `min_slot` (stale
        speculations can never confirm: gossip's propagation window has
        passed)."""
        stale = [k for k in self._memo if k[2] < min_slot]
        for k in stale:
            del self._memo[k]

    def __len__(self) -> int:
        return len(self._memo)
