"""Committee aggregate-pubkey precompute (tentpole half 1).

Committees are known an epoch ahead (MIN_SEED_LOOKAHEAD): at each epoch
boundary this cache walks the committee shuffle and precomputes, per
(slot, committee_index), the aggregate pubkey point for expected
full-committee participation plus the per-member point table. Attestation
verification then skips per-set pubkey aggregation entirely when the
arriving aggregation bits are the full committee, and falls back to an
INCREMENTAL CORRECTION (cached full aggregate minus the absent members'
points) for partial participation — O(absent) point ops instead of
O(committee).

Soundness model (the "One For All" framing, PAPERS.md): the precompute
only ever substitutes a MATHEMATICALLY IDENTICAL aggregate point for the
per-set aggregation the backend would have computed — exact group
arithmetic on both paths, so accept/reject verdicts are bit-identical
and planted forgeries still fail the pairing and are attributed by
bisection (tests/test_speculation.py plants them).

Reorg safety: every entry is keyed on the epoch's attester shuffling
seed (`get_seed(state, epoch, DOMAIN_BEACON_ATTESTER)` — the shuffling
decision input; CommitteeCache derives its permutation from exactly this
value). At verification time the seed is recomputed from the batch's own
head state: a reorg that changed the shuffling yields a different seed,
the entry is invalidated and the set falls through to the normal path;
a same-shuffling reorg keeps the cache warm.
"""

from __future__ import annotations

from ..crypto.bls import PublicKey, get_backend_name
from ..crypto.bls.api import _g1_infinity
from ..types import DOMAIN_BEACON_ATTESTER, compute_start_slot_at_epoch
from ..types.helpers import get_seed
from ..utils import metrics as M

# partial-participation corrections memoized per entry (gossip re-sends
# the same bit patterns); bounded per entry, entries are epoch-scoped
_MAX_CORRECTIONS_PER_ENTRY = 64


class PrecomputeEntry:
    """One (slot, committee_index)'s precomputed aggregation state."""

    __slots__ = (
        "shuffling_key",
        "slot",
        "index",
        "members",
        "member_pks",
        "full_point",
        "full_pk",
        "corrections",
    )

    def __init__(self, shuffling_key, slot, index, members, member_pks):
        self.shuffling_key = shuffling_key
        self.slot = slot
        self.index = index
        self.members = members  # tuple, committee order
        self.member_pks = member_pks  # same order
        point = _g1_infinity()
        for pk in member_pks:
            point = point + pk.point
        self.full_point = point
        # sum of cache-validated member keys: G1 is closed under +, so
        # the aggregate inherits key_validate without paying the check
        self.full_pk = PublicKey(point, subgroup_checked=True)
        self.corrections: dict[tuple, PublicKey] = {}

    def matches(self, bits, attesting_indices) -> bool:
        """Never-trust guard: the bit-selected committee members must be
        exactly the indexed attestation's attesting indices (which
        ConsensusContext derives sorted)."""
        if len(bits) != len(self.members):
            return False
        selected = sorted(
            m for m, b in zip(self.members, bits) if b
        )
        return selected == [int(i) for i in attesting_indices]


class CommitteePrecompute:
    """Epoch-scoped map (slot, committee_index) -> PrecomputeEntry, keyed
    on the epoch's shuffling seed. `refresh_epoch` runs off the critical
    path (epoch boundary / idle time); `lookup` + `aggregate_pubkey` run
    inside batch setup and do no point arithmetic on a full-bits hit."""

    def __init__(self, preset, spec, device_correction: bool | None = None):
        self.preset = preset
        self.spec = spec
        # None -> decide per call from the backend env flag
        self.device_correction = device_correction
        self._epochs: dict[int, dict[tuple[int, int], PrecomputeEntry]] = {}
        self._keys: dict[int, bytes] = {}
        self.stats = {
            "full_hits": 0,
            "corrections": 0,
            "misses": 0,
            "invalidations": 0,
            "refreshes": 0,
        }

    def shuffling_key(self, state, epoch: int) -> bytes:
        """The attester shuffling seed — one randao-mix lookup + one hash,
        cheap enough to recompute per verification batch item."""
        return get_seed(
            state, epoch, DOMAIN_BEACON_ATTESTER, self.preset, self.spec
        )

    # -- refresh / invalidation (off the critical path) ---------------------

    def refresh_epoch(self, state, epoch: int, ctxt, get_pubkey) -> int:
        """Precompute every committee of `epoch` (members + full aggregate
        point) under the epoch's shuffling key. No-op when the key is
        unchanged (warm across same-shuffling reorgs). Returns the number
        of entries built."""
        key = self.shuffling_key(state, epoch)
        if self._keys.get(epoch) == key:
            return 0
        self._drop_epoch(epoch, invalidated=epoch in self._epochs)
        cache = ctxt.committee_cache(state, epoch)
        entries: dict[tuple[int, int], PrecomputeEntry] = {}
        start = compute_start_slot_at_epoch(epoch, self.preset)
        for slot in range(start, start + self.preset.slots_per_epoch):
            for index in range(cache.committees_per_slot):
                members = tuple(cache.get_beacon_committee(slot, index))
                if not members:
                    continue
                pks = [get_pubkey(i) for i in members]
                entries[(slot, index)] = PrecomputeEntry(
                    key, slot, index, members, pks
                )
        self._epochs[epoch] = entries
        self._keys[epoch] = key
        self.stats["refreshes"] += 1
        self._update_gauge()
        self._register_device_resident()
        return len(entries)

    def check_epoch(self, state, epoch: int) -> bool:
        """Revalidate a cached epoch against (possibly reorged) `state`:
        drops it when the shuffling seed moved. True iff still valid."""
        if epoch not in self._keys:
            return False
        if self._keys[epoch] == self.shuffling_key(state, epoch):
            return True
        self._drop_epoch(epoch, invalidated=True)
        return False

    def prune(self, min_epoch: int) -> None:
        """Forget epochs before `min_epoch` (normal aging, not counted as
        invalidation)."""
        for e in [e for e in self._epochs if e < min_epoch]:
            self._drop_epoch(e, invalidated=False)

    def _drop_epoch(self, epoch: int, invalidated: bool) -> None:
        dropped = self._epochs.pop(epoch, None)
        self._keys.pop(epoch, None)
        if dropped and invalidated:
            n = len(dropped)
            self.stats["invalidations"] += n
            M.SPECULATE_PRECOMPUTE_INVALIDATIONS.inc(n)
        if dropped:
            self._update_gauge()

    def __len__(self) -> int:
        return sum(len(v) for v in self._epochs.values())

    def _update_gauge(self) -> None:
        M.SPECULATE_PRECOMPUTE_ENTRIES.set(
            sum(len(v) for v in self._epochs.values())
        )

    def _register_device_resident(self) -> None:
        """Park the full-aggregate family device-resident next to the
        validator pubkey table (jax_tpu backend only): warms each
        synthetic key's cached limb tensor so marshalling an
        all-precomputed batch ships precomputed arrays, never converts
        coordinates on the critical path."""
        if get_backend_name() not in ("jax_tpu", "fallback"):
            return
        try:
            from ..crypto.bls.backends import jax_tpu
        except Exception:  # noqa: BLE001 -- jax genuinely unavailable:
            # the precompute stays host-only, verdicts are unchanged
            return
        jax_tpu.set_committee_aggregates(
            [
                e.full_pk
                for entries in self._epochs.values()
                for e in entries.values()
            ]
        )

    # -- critical-path lookup ----------------------------------------------

    def lookup(self, state, slot: int, index: int, epoch: int):
        """Entry for (slot, index) iff its shuffling key matches the seed
        derived from the VERIFYING state (the stale-after-reorg gate).
        None on miss; the caller counts the miss once per set."""
        entries = self._epochs.get(epoch)
        if entries is None:
            return None
        entry = entries.get((slot, index))
        if entry is None:
            return None
        if entry.shuffling_key != self.shuffling_key(state, epoch):
            # reorg moved the shuffling under us: the whole epoch is stale
            self._drop_epoch(epoch, invalidated=True)
            return None
        return entry

    def aggregate_pubkey(self, entry: PrecomputeEntry, bits) -> PublicKey:
        """The precomputed aggregate for this participation pattern.
        Caller must have checked `entry.matches(bits, ...)`. Full
        participation returns the cached full-committee key (zero point
        ops); partial returns the memoized incremental correction."""
        if all(bits):
            self.stats["full_hits"] += 1
            M.SPECULATE_PRECOMPUTE_HITS.inc()
            return entry.full_pk
        memo_key = tuple(bits)
        cached = entry.corrections.get(memo_key)
        if cached is not None:
            self.stats["corrections"] += 1
            M.SPECULATE_PRECOMPUTE_CORRECTIONS.inc()
            return cached
        absent = [pk for pk, b in zip(entry.member_pks, bits) if not b]
        point = self._corrected_point(entry, absent)
        # full - sum(absent) over validated keys stays in the subgroup
        pk = PublicKey(point, subgroup_checked=True)
        if len(entry.corrections) < _MAX_CORRECTIONS_PER_ENTRY:
            entry.corrections[memo_key] = pk
        self.stats["corrections"] += 1
        M.SPECULATE_PRECOMPUTE_CORRECTIONS.inc()
        return pk

    def _corrected_point(self, entry: PrecomputeEntry, absent):
        """full - sum(absent): host oracle arithmetic by default; the
        staged device program (jax_tpu.correct_aggregate_device) behind
        LIGHTHOUSE_TPU_SPECULATE_DEVICE computes the identical point with
        warm bucketed executables."""
        use_device = self.device_correction
        if use_device is None and get_backend_name() in (
            "jax_tpu",
            "fallback",
        ):
            try:
                from ..crypto.bls.backends import jax_tpu

                use_device = jax_tpu._speculate_device_enabled()
            except Exception:  # noqa: BLE001 -- no jax: host fallback
                use_device = False
        if use_device:
            try:
                from ..crypto.bls.backends import jax_tpu

                point = jax_tpu.correct_aggregate_device(
                    entry.full_pk, absent
                )
                if point is not None:
                    return point
            # lint: allow[broad-except] -- device-fault boundary: any
            # device/compile failure here must degrade to the host
            # oracle below, which computes the identical point (never a
            # verdict change, only a slower correction)
            except Exception:  # noqa: BLE001
                pass
        point = entry.full_point
        for pk in absent:
            point = point + (-pk.point)
        return point
