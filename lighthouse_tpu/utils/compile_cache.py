"""Persistent on-disk XLA compilation cache, generalized into the backend.

The verifier's XLA programs cost 70-360 s to compile per shape bucket
(BENCH_r04/r05), and until now only bench.py / warm_tpu.py armed JAX's
persistent compilation cache (the warm-cache trick in __graft_entry__).
This module is that trick promoted to a first-class backend facility:

  * ``arm(root)`` points ``jax_compilation_cache_dir`` at a partition
    under ``root`` -- a node passes ``<datadir>/compile_cache`` (cli.py),
    entry-point scripts pass the repo-level ``.jax_cache`` -- so compiled
    executables are paid for once per binary, not once per process.
  * Partitions are keyed on the backend platform, and CPU partitions are
    additionally fingerprinted by host CPU features: XLA:CPU's AOT loader
    aborts on entries compiled for another machine's feature set, and
    remote-TPU sessions compile CPU stubs on the REMOTE host. A different
    host or platform simply starts a fresh partition -- cross-poisoning
    is impossible by construction.
  * A sidecar ``shapes.json`` registry records every bucketed batch
    shape whose executables a process finished compiling under the
    partition: the backend LOOKS a shape up at marshal time
    (``shape_on_disk``, feeding ``tpu_compile_cache_hits_total`` for
    process-cold but disk-warm shapes) and WRITES it only after the
    shape's first dispatch has returned (``record_shape``) -- jit
    compilation is synchronous at call time, so by then the executables
    exist and are persisted. A process killed mid-compile therefore
    never registers the shape, and the next process honestly counts a
    miss.

Registry updates are atomic-rename writes; concurrent processes can lose
an update (the next completed dispatch re-records it), which only ever
under-counts hits -- never corrupts the registry or the cache.
"""

from __future__ import annotations

import hashlib
import json
import os

_ARMED_DIR: str | None = None


def host_cpu_fingerprint() -> str:
    """Stable short hash of the host's CPU feature flags (the AOT-entry
    compatibility domain of XLA:CPU executables)."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha256(line.encode()).hexdigest()[:10]
    except OSError:
        pass
    import platform as _platform

    return hashlib.sha256(_platform.processor().encode()).hexdigest()[:10]


def partition(root: str) -> str:
    """The backend-keyed cache partition under ``root`` for the platform
    the current process will compile for (resolved WITHOUT initializing
    the backend: a device query here would freeze the platform before an
    entry point's own forcing could take effect)."""
    import jax

    platform = (
        jax.config.jax_platforms
        or os.environ.get("JAX_PLATFORMS")
        or "device"
    ).split(",")[0]
    sub = f"cpu-{host_cpu_fingerprint()}" if platform == "cpu" else "tpu"
    return os.path.join(root, sub)


def arm(root: str) -> str:
    """Point JAX's persistent compilation cache at this root's partition
    and remember it for shape-registry lookups. Returns the partition
    directory. Set ``LIGHTHOUSE_TPU_COMPILE_CACHE=0`` to refuse (test
    suites, debugging)."""
    global _ARMED_DIR
    if os.environ.get("LIGHTHOUSE_TPU_COMPILE_CACHE") == "0":
        return ""
    import jax

    part = partition(root)
    os.makedirs(part, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", part)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _ARMED_DIR = part
    return part


def armed_dir() -> str | None:
    return _ARMED_DIR


def _registry_path(part: str) -> str:
    return os.path.join(part, "shapes.json")


def seen_shapes(part: str | None = None) -> set[str]:
    part = part if part is not None else _ARMED_DIR
    if not part:
        return set()
    try:
        with open(_registry_path(part)) as f:
            loaded = json.load(f)
    except (OSError, json.JSONDecodeError):
        return set()
    return set(loaded) if isinstance(loaded, list) else set()


def _shape_name(key: tuple) -> str:
    return "x".join(str(int(v)) for v in key)


def shape_on_disk(key: tuple, part: str | None = None) -> bool:
    """True when a previous process finished compiling this bucketed
    shape under the armed partition (the persistent cache holds its
    executables: a hit for a process-cold shape). False when it is new
    here or no cache is armed. Read-only."""
    part = part if part is not None else _ARMED_DIR
    if not part:
        return False
    return _shape_name(key) in seen_shapes(part)


def record_shape(key: tuple, part: str | None = None) -> None:
    """Register one bucketed shape as COMPILED under the partition. Call
    only after the shape's first dispatch has returned -- that is the
    point at which its executables exist and have been persisted, so a
    crash/timeout mid-compile never leaves a phantom registry entry."""
    part = part if part is not None else _ARMED_DIR
    if not part:
        return
    shapes = seen_shapes(part)
    name = _shape_name(key)
    if name in shapes:
        return
    shapes.add(name)
    path = _registry_path(part)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(sorted(shapes), f)
        os.replace(tmp, path)
    except OSError:
        pass  # registry is advisory telemetry; never block dispatch
