"""Prometheus-style metrics registry (reference common/lighthouse_metrics:
global registry, start_timer/stop_timer section timers used as ad-hoc
profilers throughout beacon_chain/src/metrics.rs:37-80).

Counters, gauges, and histograms with a process-global default registry;
`Histogram.time()` is the `start_timer` seat — block import is split into
named phases exactly like the reference's BLOCK_PROCESSING_* family."""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


def escape_help(text: str) -> str:
    """Prometheus exposition-format HELP escaping: backslash and newline
    only (a raw newline would split the HELP line and corrupt the whole
    scrape)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(value: str) -> str:
    """Exposition-format label-value escaping: backslash, double-quote,
    newline (an endpoint URL containing `"` must not terminate the label
    early)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class Counter:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def expose(self) -> list[str]:
        return [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} counter",
            f"{self.name} {self.value:g}",
        ]


class Gauge:
    """Thread-safe like Counter (a queue-depth gauge is written from
    every worker); `inc`/`dec` spare call sites the read-modify-write."""

    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = value

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount

    def get(self) -> float:
        with self._lock:
            return self.value

    def expose(self) -> list[str]:
        return [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} gauge",
            f"{self.name} {self.value:g}",
        ]


class Histogram:
    def __init__(self, name: str, help_: str, buckets=_DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self.bucket_counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self.sum += value
            self.count += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self.bucket_counts[i] += 1
                    return
            self.bucket_counts[-1] += 1

    @contextmanager
    def time(self):
        """The start_timer/stop_timer seat (lighthouse_metrics)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)

    def snapshot(self) -> tuple:
        """Point-in-time copy of (bucket_counts, count, sum): the delta
        base for windowed quantiles (scenario SLO checks subtract a
        start-of-run snapshot so process-global history doesn't bleed
        into the scenario's verdict)."""
        with self._lock:
            return list(self.bucket_counts), self.count, self.sum

    def quantile(self, q: float, since: tuple | None = None) -> float | None:
        """Upper-bound estimate of the q-quantile from the bucket counts
        (linear within the winning bucket's upper edge, like PromQL's
        histogram_quantile). `since` subtracts an earlier snapshot().
        None when the (windowed) histogram is empty; the overflow bucket
        reports the largest finite edge."""
        with self._lock:
            counts = list(self.bucket_counts)
        if since is not None:
            base = since[0]
            counts = [c - b for c, b in zip(counts, base)]
        total = sum(counts)
        if total <= 0:
            return None
        rank = q * total
        cum = 0
        for edge, c in zip(self.buckets, counts):
            cum += c
            if cum >= rank:
                return edge
        return self.buckets[-1]

    def expose(self) -> list[str]:
        out = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} histogram",
        ]
        cum = 0
        for b, c in zip(self.buckets, self.bucket_counts):
            cum += c
            out.append(f'{self.name}_bucket{{le="{b:g}"}} {cum}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {self.count}')
        out.append(f"{self.name}_sum {self.sum:g}")
        out.append(f"{self.name}_count {self.count}")
        return out


class LabeledGauge:
    """A one-label gauge family (`name{label="x"} v` per child): the
    minimal labels support the resilience layer needs for per-endpoint
    health scores without pulling in a full label model."""

    def __init__(self, name: str, help_: str, label: str = "endpoint"):
        self.name = name
        self.help = help_
        self.label = label
        self._children: dict[str, float] = {}
        self._lock = threading.Lock()

    def set(self, label_value: str, value: float) -> None:
        with self._lock:
            self._children[str(label_value)] = value

    def get(self, label_value: str) -> float | None:
        with self._lock:
            return self._children.get(str(label_value))

    def expose(self) -> list[str]:
        out = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} gauge",
        ]
        with self._lock:
            items = sorted(self._children.items())
        for lv, v in items:
            out.append(
                f'{self.name}{{{self.label}="{escape_label_value(lv)}"}}'
                f" {v:g}"
            )
        return out


class Registry:
    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name, help_, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help_, **kw)
            elif not isinstance(m, cls):
                # registry hygiene: one name, one family type -- silently
                # handing a Counter to a gauge() caller corrupts both
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(Gauge, name, help_)

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS):
        return self._get(Histogram, name, help_, buckets=buckets)

    def labeled_gauge(self, name: str, help_: str = "", label: str = "endpoint"):
        return self._get(LabeledGauge, name, help_, label=label)

    def expose(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


# the process-global registry (lighthouse_metrics' lazy_static globals)
REGISTRY = Registry()

# -- the beacon_chain metric family (metrics.rs:37-80) ------------------------

BLOCK_PROCESSING_TIMES = REGISTRY.histogram(
    "beacon_block_processing_seconds", "Full block import time"
)
BLOCK_SIGNATURE_TIMES = REGISTRY.histogram(
    "beacon_block_processing_signature_seconds",
    "Signature batch verification phase",
)
BLOCK_TRANSITION_TIMES = REGISTRY.histogram(
    "beacon_block_processing_state_transition_seconds",
    "per_block/per_slot state transition phase",
)
BLOCK_STATE_ROOT_TIMES = REGISTRY.histogram(
    "beacon_block_processing_state_root_seconds", "State-root computation"
)
BLOCK_FORK_CHOICE_TIMES = REGISTRY.histogram(
    "beacon_block_processing_fork_choice_seconds", "Fork-choice import + head"
)
ATTN_BATCH_SETUP_TIMES = REGISTRY.histogram(
    "beacon_attestation_batch_setup_seconds",
    "Gossip attestation batch: checks + set building",
)
ATTN_BATCH_VERIFY_TIMES = REGISTRY.histogram(
    "beacon_attestation_batch_verify_seconds",
    "Gossip attestation batch: worker-visible wait for the signature "
    "verdict (+bisection); under the async pipeline device compute "
    "overlaps the next batch's marshalling, so this is residual wait, "
    "not raw device time",
)
BLOCKS_IMPORTED = REGISTRY.counter(
    "beacon_blocks_imported_total", "Blocks successfully imported"
)
BLOCKS_REJECTED = REGISTRY.counter(
    "beacon_blocks_rejected_total", "Blocks rejected on import"
)
# NOTE: head-slot / finalized-epoch are PER-CHAIN facts; they are exposed
# by each node's /metrics endpoint from its own chain (server.py), not as
# process globals -- multiple chains share one process in the simulator.
ATTESTATIONS_PROCESSED = REGISTRY.counter(
    "beacon_attestations_processed_total", "Gossip attestations verified"
)
BLOCK_EQUIVOCATIONS = REGISTRY.counter(
    "beacon_block_equivocations_total",
    "Gossip blocks IGNOREd as a second distinct proposal from the same "
    "(slot, proposer) — handed to the slasher, never imported via gossip",
)

# -- the resilience metric family (lighthouse_tpu/resilience/) ----------------
# Retry attempts, breaker transitions, BLS backend degradation, and
# per-endpoint health scores: the observable surface of graceful
# degradation (reference: beacon_node_fallback / eth1 endpoint metrics).

RETRY_ATTEMPTS = REGISTRY.counter(
    "resilience_retry_attempts_total",
    "Operations re-attempted by a RetryPolicy",
)
BREAKER_TRANSITIONS = REGISTRY.counter(
    "resilience_breaker_transitions_total",
    "Circuit-breaker state transitions (closed/open/half-open)",
)
BLS_FALLBACK_EVENTS = REGISTRY.counter(
    "bls_backend_fallback_total",
    "Batches degraded from the primary BLS backend to the fallback",
)
BLS_USING_FALLBACK = REGISTRY.gauge(
    "bls_backend_using_fallback",
    "1 while BLS verification is degraded to the fallback backend",
)
ENDPOINT_HEALTH = REGISTRY.labeled_gauge(
    "resilience_endpoint_health_score",
    "Recent-outcome health score per tracked endpoint (0..1)",
    label="endpoint",
)

# -- the verification-pipeline metric family (crypto/bls/pipeline.py,
# parallel/verify_sharded.py, chain/attestation_verification.py) -------------
# Async pipeline depth/occupancy, device-gather hit rate, shard-mesh size,
# and bisection cost: the observable surface of the pipelined hot path.

BLS_PIPELINE_DEPTH = REGISTRY.gauge(
    "bls_pipeline_depth",
    "Configured max in-flight batches of the async verify pipeline",
)
BLS_PIPELINE_OCCUPANCY = REGISTRY.gauge(
    "bls_pipeline_occupancy",
    "Batches currently dispatched to device and not yet resolved",
)
BLS_PIPELINE_OCCUPANCY_PEAK = REGISTRY.gauge(
    "bls_pipeline_occupancy_peak",
    "High-water mark of in-flight batches since process start",
)
BLS_PIPELINE_BATCHES = REGISTRY.counter(
    "bls_pipeline_batches_total",
    "Batches submitted through verify_signature_sets_async",
)
BLS_GATHER_HITS = REGISTRY.counter(
    "bls_device_gather_batches_total",
    "Batches whose pubkeys were gathered from the device-resident table",
)
BLS_GATHER_MISSES = REGISTRY.counter(
    "bls_host_packed_batches_total",
    "Batches that fell back to per-key host limb packing",
)
BLS_SHARD_MESH_SIZE = REGISTRY.gauge(
    "bls_shard_mesh_devices",
    "Devices in the shard mesh used by the last sharded batch",
)
BLS_SHARDED_BATCHES = REGISTRY.counter(
    "bls_sharded_batches_total",
    "Batches verified across the multi-chip shard mesh",
)
BLS_MESH_SHRINKS = REGISTRY.counter(
    "bls_shard_mesh_shrinks_total",
    "Times a chip fault re-sharded a batch over the surviving devices",
)
BLS_BISECTION_CALLS = REGISTRY.counter(
    "bls_bisection_backend_calls_total",
    "Extra backend calls spent isolating invalid sets by bisection",
)
BLS_BISECTION_BAD_ITEMS = REGISTRY.counter(
    "bls_bisection_bad_items_total",
    "Invalid items isolated (and attributed) by the bisection fallback",
)

# -- the message-aggregation (mega-pairing) family (crypto/bls/aggregation.py
# + backends/jax_tpu.py dispatch): pairing cost is THE batch-verification
# latency driver, so the Miller-pair count per batch and the sets-per-pair
# ratio are the observable face of the aggregated path.

BLS_MILLER_PAIRS = REGISTRY.counter(
    "bls_miller_pairs_total",
    "Miller-loop pairs dispatched across all verification batches",
)
BLS_MILLER_PAIRS_LAST = REGISTRY.gauge(
    "bls_miller_pairs_last_batch",
    "Miller-loop pairs of the most recently dispatched batch (scales "
    "with bucketed distinct messages on the aggregated path, bucketed "
    "sets otherwise)",
)
BLS_AGGREGATION_RATIO = REGISTRY.gauge(
    "bls_aggregation_ratio",
    "Signature sets per Miller pair in the most recent batch (~1 "
    "unaggregated; ~sets/messages on the mega-pairing path)",
)
BLS_AGGREGATED_BATCHES = REGISTRY.counter(
    "bls_aggregated_batches_total",
    "Batches verified through the per-message mega-pairing path",
)
BLS_WEIGHT_REDRAWS = REGISTRY.counter(
    "bls_weight_redraws_total",
    "Random-linear-combination batch weights redrawn by the nonzero/"
    "independence guard (a zero or within-batch colliding draw would let "
    "a forged set cancel inside the combination)",
)

# -- the crash-safety metric family (store/kv.py journal, store/fsck.py) ------
# Write-ahead journal recovery outcomes and consistency-checker results:
# the observable surface of the crash-safe store (reference: leveldb
# write-batch semantics + `lighthouse db` tooling).

STORE_JOURNAL_REPLAYS = REGISTRY.counter(
    "store_journal_replays_total",
    "Committed write-ahead batches re-applied on store reopen (the crash "
    "hit mid-apply; redo)",
)
STORE_JOURNAL_ROLLBACKS = REGISTRY.counter(
    "store_journal_rollbacks_total",
    "Torn/uncommitted write-ahead batches discarded on store reopen (the "
    "crash hit the intent write; the batch never happened)",
)
STORE_FSCK_RUNS = REGISTRY.counter(
    "store_fsck_runs_total", "db fsck consistency walks"
)
STORE_FSCK_FAILURES = REGISTRY.counter(
    "store_fsck_issues_total", "Consistency violations found by db fsck"
)
# NativeStore (C++ log-structured backend) open-time recovery outcomes:
# the native twin of the python-WAL replay/rollback counters above,
# read back from the C side via kv_recovery_stats at every open.
STORE_NATIVE_REPLAYED = REGISTRY.counter(
    "store_native_replayed_batches_total",
    "Committed native-log batches re-applied during store open replay",
)
STORE_NATIVE_ROLLED_BACK = REGISTRY.counter(
    "store_native_rolled_back_batches_total",
    "Uncommitted native-log batches dropped during store open replay "
    "(the crash hit between BATCH_BEGIN and BATCH_COMMIT)",
)
STORE_NATIVE_TRUNCATED = REGISTRY.counter(
    "store_native_truncated_bytes_total",
    "Torn native-log tail bytes truncated during store open replay",
)

# -- slot-relative delay family (reference beacon_block_delay_* in
# beacon_chain/src/metrics.rs): seconds past the block's SLOT START on the
# injected slot clock at each hot-path milestone. Replayable: the clock is
# the chain's slot_clock, never the wall clock (lint rule span-wallclock).

_SLOT_DELAY_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0,
)

BLOCK_OBSERVED_DELAY = REGISTRY.histogram(
    "beacon_block_observed_delay_seconds",
    "Slot-start to gossip arrival of the block",
    buckets=_SLOT_DELAY_BUCKETS,
)
BLOCK_VERIFIED_DELAY = REGISTRY.histogram(
    "beacon_block_verified_delay_seconds",
    "Slot-start to full signature verification of the block",
    buckets=_SLOT_DELAY_BUCKETS,
)
BLOCK_IMPORTED_DELAY = REGISTRY.histogram(
    "beacon_block_imported_delay_seconds",
    "Slot-start to completed import (store + fork choice) of the block",
    buckets=_SLOT_DELAY_BUCKETS,
)
BLOCK_HEAD_DELAY = REGISTRY.histogram(
    "beacon_block_head_delay_seconds",
    "Slot-start to the block becoming the canonical head",
    buckets=_SLOT_DELAY_BUCKETS,
)


def slot_delay_seconds(slot_clock, slot: int) -> float:
    """Seconds past `slot`'s start on the INJECTED slot clock (negative
    when observed early, e.g. a locally-produced block)."""
    start = slot_clock.genesis_time + slot * slot_clock.seconds_per_slot
    return slot_clock.now() - start


def observe_slot_delay(histogram: Histogram, slot_clock, slot: int) -> None:
    """Record one slot-relative delay sample; the single seat the
    span-wallclock lint rule audits for wall-clock operands."""
    histogram.observe(slot_delay_seconds(slot_clock, slot))


# -- beacon-processor scheduling family (beacon_processor.py) ----------------

PROCESSOR_PENDING = REGISTRY.gauge(
    "beacon_processor_work_pending",
    "Work items queued across all processor lanes, not yet claimed",
)
PROCESSOR_QUEUE_WAIT = REGISTRY.histogram(
    "beacon_processor_queue_wait_seconds",
    "Enqueue-to-claim wait of the oldest item in each claimed batch "
    "(tracer clock)",
)

# -- TPU device telemetry (crypto/bls/backends/jax_tpu.py marshal/dispatch
# seam + parallel/verify_sharded.py mesh) ------------------------------------

TPU_COMPILE_CACHE_HITS = REGISTRY.counter(
    "tpu_compile_cache_hits_total",
    "Batches whose bucketed (sets, pubkeys, messages) shape was already "
    "compiled this process",
)
TPU_COMPILE_CACHE_MISSES = REGISTRY.counter(
    "tpu_compile_cache_misses_total",
    "Batches marshalled to a NEW bucketed shape (XLA compile expected)",
)
TPU_WARM_COMPILE_SECONDS = REGISTRY.labeled_gauge(
    "tpu_warm_compile_seconds",
    "Wall seconds the AOT warm pass spent compiling (or cache-loading) "
    "each shape bucket's backend executables",
    label="bucket",
)
TPU_TRANSFER_BYTES = REGISTRY.counter(
    "tpu_transfer_bytes_total",
    "Host-to-device bytes marshalled for verification batches",
)
TPU_MARSHAL_BATCH_BYTES = REGISTRY.gauge(
    "tpu_marshal_batch_bytes",
    "Host-to-device bytes of the most recent marshalled batch",
)
TPU_PUBKEY_TABLE_BYTES = REGISTRY.labeled_gauge(
    "tpu_pubkey_table_bytes",
    "Decompressed pubkey-table bytes RESIDENT PER DEVICE (label: device "
    "id). Replicated tables repeat the full size on every device; the "
    "mesh-sharded table holds ~1/N of the bucketed rows per device",
    label="device",
)
TPU_PUBKEY_GATHER_BYTES = REGISTRY.counter(
    "tpu_pubkey_gather_bytes_total",
    "Pubkey limb-row bytes pulled to the verifying chip by per-batch "
    "gathers from the (sharded) device-resident table",
)
TPU_PUBKEY_GATHER_BATCHES = REGISTRY.counter(
    "tpu_pubkey_gather_batches_total",
    "Verification batches whose pubkeys were gathered from the "
    "device-resident table by validator index",
)
MESH_CHIP_BATCH_SECONDS = REGISTRY.labeled_gauge(
    "bls_mesh_chip_last_batch_seconds",
    "Per-chip wall of the last sharded batch this chip participated in "
    "(tracer clock)",
    label="chip",
)

# -- serving tier (serving/: response cache, SSE fan-out, admission) ---------

SERVING_CACHE_HITS = REGISTRY.counter(
    "http_serving_cache_hits_total",
    "GET responses served from the anchored response cache without "
    "invoking the BeaconApi handler",
)
SERVING_CACHE_MISSES = REGISTRY.counter(
    "http_serving_cache_misses_total",
    "Cacheable GETs that had to invoke the underlying handler",
)
SERVING_CACHE_INVALIDATIONS = REGISTRY.counter(
    "http_serving_cache_invalidations_total",
    "Entries dropped because a head/finality event moved their anchor",
)
SERVING_CACHE_ENTRIES = REGISTRY.gauge(
    "http_serving_cache_entries",
    "Entries currently held by the response cache (LRU-bounded)",
)
SERVING_NOT_MODIFIED = REGISTRY.counter(
    "http_serving_not_modified_total",
    "Conditional GETs answered 304 via If-None-Match ETag revalidation",
)
SERVING_SHED_READ_ONLY = REGISTRY.counter(
    "http_serving_shed_read_only_total",
    "Read-only-lane requests shed with 503 + Retry-After under "
    "processor backpressure",
)
SERVING_SHED_DEBUG = REGISTRY.counter(
    "http_serving_shed_debug_total",
    "Debug-lane requests shed with 503 + Retry-After under processor "
    "backpressure",
)
SERVING_SSE_SUBSCRIBERS = REGISTRY.gauge(
    "http_serving_sse_subscribers",
    "Live SSE subscribers currently attached to the event broadcaster",
)
SERVING_SSE_DROPPED = REGISTRY.counter(
    "http_serving_sse_dropped_events_total",
    "Events dropped from per-subscriber ring buffers (slow consumers)",
)
SERVING_SSE_REJECTED = REGISTRY.counter(
    "http_serving_sse_rejected_total",
    "SSE subscriptions refused because the concurrent-subscriber cap "
    "was reached",
)
SERVING_EVENT_RING_DROPPED = REGISTRY.counter(
    "http_serving_event_ring_dropped_total",
    "Oldest events evicted from the bounded replay ring (api.events)",
)
SERVING_COALESCED = REGISTRY.counter(
    "http_serving_coalesced_requests_total",
    "Cache-miss GETs coalesced onto another in-flight computation of the "
    "same (route, params, anchor) key (singleflight followers)",
)

# -- speculative verification (speculate/: committee precompute + idle-time
#    next-slot pre-verification) ---------------------------------------------

SPECULATE_PRECOMPUTE_ENTRIES = REGISTRY.gauge(
    "speculate_precompute_entries",
    "Per-(slot, committee) aggregate-pubkey precompute entries currently "
    "cached (keyed on the epoch's shuffling seed)",
)
SPECULATE_PRECOMPUTE_HITS = REGISTRY.counter(
    "speculate_precompute_full_hits_total",
    "Indexed-attestation sets whose aggregation bits matched a cached "
    "full-committee aggregate exactly (zero pubkey aggregation on the "
    "critical path)",
)
SPECULATE_PRECOMPUTE_CORRECTIONS = REGISTRY.counter(
    "speculate_precompute_corrections_total",
    "Partial-participation sets served by incremental correction "
    "(cached full aggregate minus absent members)",
)
SPECULATE_PRECOMPUTE_MISSES = REGISTRY.counter(
    "speculate_precompute_misses_total",
    "Indexed-attestation sets that fell through to normal per-set pubkey "
    "aggregation (no entry, stale shuffling key, or member mismatch)",
)
SPECULATE_PRECOMPUTE_INVALIDATIONS = REGISTRY.counter(
    "speculate_precompute_invalidations_total",
    "Precompute entries dropped because a reorg changed the epoch's "
    "shuffling seed (same-shuffling reorgs keep entries)",
)
SPECULATE_PREVERIFIED = REGISTRY.counter(
    "speculate_preverified_total",
    "Expected next-slot aggregates pre-verified during idle device time "
    "and memoized for confirm-on-arrival",
)
SPECULATE_CONFIRMS = REGISTRY.counter(
    "speculate_confirm_hits_total",
    "Arriving aggregates confirmed by speculation-memo lookup instead of "
    "pairing on the critical path",
)
SPECULATE_CONFIRM_MISSES = REGISTRY.counter(
    "speculate_confirm_misses_total",
    "Arriving aggregates with no matching speculation memo (fell through "
    "to the normal verified path)",
)
SPECULATE_MISMATCHES = REGISTRY.counter(
    "speculate_mismatches_total",
    "Arriving aggregates whose memo key matched but whose signature bytes "
    "differed from the pre-verified one (never trusted; full verify)",
)
SPECULATE_IDLE_RUNS = REGISTRY.counter(
    "speculate_idle_runs_total",
    "Idle-time speculation passes actually run by the processor (gated "
    "on queue-wait p95 and in-flight depth)",
)
SPECULATE_TABLE_BYTES = REGISTRY.gauge(
    "speculate_committee_table_bytes",
    "Device-resident per-committee aggregate-pubkey table size in bytes "
    "(lives next to the validator pubkey table in the jax_tpu backend)",
)
SPECULATE_PREEMPTIONS = REGISTRY.counter(
    "speculate_preemptions_total",
    "Speculative batches withheld at a scheduler launch boundary because "
    "real (validator-lane) work was queued; withheld batches stay queued "
    "and launch at the next idle boundary, never dropped",
)

# -- continuous-batching scheduler (crypto/bls/scheduler.py): per-lane
#    deadline queues in front of the verification pipeline ------------------

BLS_SCHED_MERGES = REGISTRY.counter(
    "bls_sched_merged_launches_total",
    "Device launches that merged entries from more than one submission "
    "(the continuous-batching win: arrivals ride the next launch)",
)
BLS_SCHED_LAUNCHES = REGISTRY.counter(
    "bls_sched_launches_total",
    "Device launches admitted by the scheduler (merged or singleton)",
)
BLS_SCHED_MERGE_FALLBACKS = REGISTRY.counter(
    "bls_sched_merge_fallbacks_total",
    "Merged launches that verified False and were re-verified per entry "
    "to recover exact per-submission verdicts",
)
BLS_SCHED_PAD_SETS = REGISTRY.counter(
    "bls_sched_pad_sets_total",
    "Padding rows added to reach the nearest WARMED bucket capacity "
    "(the padding tax, numerator)",
)
BLS_SCHED_REAL_SETS = REGISTRY.counter(
    "bls_sched_real_sets_total",
    "Real signature sets admitted through the scheduler (the padding "
    "tax, denominator)",
)
BLS_SCHED_QUEUE_DEPTH = REGISTRY.labeled_gauge(
    "bls_sched_queue_depth",
    "Entries currently queued per lane, sampled at submit/launch",
    label="lane",
)
# Per-lane slot-start -> verdict latency, on the INJECTED slot clock
# (observe_slot_delay is the one sanctioned seat; lint rule
# span-wallclock). One histogram per lane so /metrics stays label-free.
BLS_SCHED_VERDICT_DELAY_BLOCK = REGISTRY.histogram(
    "bls_sched_verdict_delay_seconds_block",
    "Slot-start to verdict for block-proposal signature batches",
    buckets=_SLOT_DELAY_BUCKETS,
)
BLS_SCHED_VERDICT_DELAY_AGGREGATE = REGISTRY.histogram(
    "bls_sched_verdict_delay_seconds_aggregate",
    "Slot-start to verdict for aggregate-attestation batches",
    buckets=_SLOT_DELAY_BUCKETS,
)
BLS_SCHED_VERDICT_DELAY_UNAGGREGATED = REGISTRY.histogram(
    "bls_sched_verdict_delay_seconds_unaggregated",
    "Slot-start to verdict for unaggregated-attestation batches",
    buckets=_SLOT_DELAY_BUCKETS,
)
BLS_SCHED_VERDICT_DELAY_SYNC = REGISTRY.histogram(
    "bls_sched_verdict_delay_seconds_sync",
    "Slot-start to verdict for sync-committee message/contribution batches",
    buckets=_SLOT_DELAY_BUCKETS,
)
BLS_SCHED_VERDICT_DELAY_SPECULATIVE = REGISTRY.histogram(
    "bls_sched_verdict_delay_seconds_speculative",
    "Slot-start to verdict for speculative idle-time batches",
    buckets=_SLOT_DELAY_BUCKETS,
)
SCHEDULER_VERDICT_DELAY = {
    "block": BLS_SCHED_VERDICT_DELAY_BLOCK,
    "aggregate": BLS_SCHED_VERDICT_DELAY_AGGREGATE,
    "unaggregated": BLS_SCHED_VERDICT_DELAY_UNAGGREGATED,
    "sync": BLS_SCHED_VERDICT_DELAY_SYNC,
    "speculative": BLS_SCHED_VERDICT_DELAY_SPECULATIVE,
}

# -- the validator-monitor metric family (validator_monitor.rs) ---------------
# Families live HERE (metric-origin lint rule): the monitor references
# them, so the /metrics surface stays enumerable from this one module.

VALIDATOR_MONITOR_PROPOSALS = REGISTRY.counter(
    "validator_monitor_blocks_proposed_total",
    "Blocks proposed by monitored validators",
)
VALIDATOR_MONITOR_ATTESTATIONS = REGISTRY.counter(
    "validator_monitor_attestations_total",
    "Attestations by monitored validators seen on-chain or gossip",
)
VALIDATOR_MONITOR_INCLUSION_DELAY = REGISTRY.histogram(
    "validator_monitor_attestation_inclusion_delay_slots",
    "Slots between attestation slot and block inclusion",
    buckets=(1, 2, 3, 4, 8, 16, 32),
)
VALIDATOR_MONITOR_TARGET_MISSES = REGISTRY.counter(
    "validator_monitor_prev_epoch_target_misses_total",
    "Monitored validators that missed the target in an epoch",
)
VALIDATOR_MONITOR_HEAD_MISSES = REGISTRY.counter(
    "validator_monitor_prev_epoch_head_misses_total",
    "Monitored validators that missed the head in an epoch",
)
VALIDATOR_MONITOR_SYNC_SIGNATURES = REGISTRY.counter(
    "validator_monitor_sync_committee_messages_total",
    "Sync-committee messages by monitored validators",
)
VALIDATOR_MONITOR_SLASHED = REGISTRY.counter(
    "validator_monitor_slashings_total",
    "Slashings naming monitored validators",
)

# -- the task-executor metric family (task_executor/src/metrics.rs) -----------

EXECUTOR_TASKS_SPAWNED = REGISTRY.counter(
    "executor_tasks_spawned_total", "Tasks spawned via TaskExecutor"
)
EXECUTOR_TASK_PANICS = REGISTRY.counter(
    "executor_task_panics_total", "Tasks that died with an exception"
)
