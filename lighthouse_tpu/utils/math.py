"""Safe integer math helpers (reference consensus/safe_arith +
int_to_bytes: Python ints can't overflow, so the crate reduces to the spec
integer_squareroot and byte helpers)."""

from __future__ import annotations


import math


def integer_squareroot(n: int) -> int:
    if n < 0:
        raise ValueError("negative input")
    return math.isqrt(n)


def int_to_bytes32_le(n: int) -> bytes:
    return n.to_bytes(32, "little")


def int_to_bytes8_le(n: int) -> bytes:
    return n.to_bytes(8, "little")
