"""Deterministic span tracer: the end-to-end timing layer of the hot path.

The reference client answers "where did the slot budget go?" with a pile
of phase histograms (beacon_chain/src/metrics.rs start_timer seats) --
enough when one thread owns a block import end to end. Here a single
attestation's latency spans the gossip router, a BeaconProcessor worker,
the async BLS pipeline, and a device mesh (four threads and a chip since
the PR-3 double buffer), so the phases must be CORRELATED, not just
counted. This module is that correlation layer:

  * spans carry (trace_id, span_id, parent_id) and nest via an ambient
    per-thread stack; ``Tracer.current()`` captures the ambient context
    and ``Tracer.attach(ctx)`` re-establishes it on another thread or at
    a future's resolution -- the DeferredWork / VerifyFuture boundary
    propagation the BeaconProcessor and VerifyPipeline use;
  * time comes from an injected clock exposing ``now()`` (the slot
    clocks and resilience ``VirtualClock`` qualify) and ids from an
    injected ``random.Random(seed)``, so a seeded replay under
    ``VirtualClock`` exports a bit-identical trace (the determinism
    contract tests/test_tracing.py asserts; lint rule ``span-wallclock``
    keeps wall time out);
  * finished spans land in a bounded ring (overflow drops the OLDEST
    and counts) and export as Chrome trace-event JSON ("X" complete
    events, microsecond timestamps) -- loadable in Perfetto / chrome://
    tracing; served at /lighthouse/tracing/{status,dump} and dumped by
    ``python -m lighthouse_tpu.cli trace``;
  * under load the ring need not record every span: ``sample_rate``
    keeps 1-in-N TRACES, decided once per trace from the root span's
    trace id (a pure function of the id, so every span of a trace --
    across threads, futures, and ``attach`` boundaries -- shares the
    decision without carrying a flag). Unsampled spans still draw ids
    and clock reads, so flipping the rate never perturbs the id/clock
    stream of a seeded replay; they are simply not recorded (counted in
    ``sampled_out``). Default 1.0 (record everything);
    ``LIGHTHOUSE_TPU_TRACE_SAMPLE`` seeds the process default.

The default process tracer uses a :class:`StepClock` (each read advances
a fixed synthetic step): fully deterministic, no wall-clock read, and
still orders every event. Entry points that WANT wall-time spans (cli,
bench) inject a real clock at their injection boundary.
"""

from __future__ import annotations

import json
import random
import threading
from collections import deque
from contextlib import contextmanager

_CHROME_CAT = "lighthouse"


class StepClock:
    """Deterministic fallback clock: every ``now()`` advances a fixed
    synthetic step, so span ordering (and strictly positive durations)
    exist without a single wall-clock read."""

    def __init__(self, start: float = 0.0, step: float = 1e-6):
        self._now = float(start)
        self._step = float(step)
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            t = self._now
            self._now += self._step
            return t


class TickingClock:
    """Wraps a manually-advanced clock (resilience ``VirtualClock``),
    advancing it a fixed step per read: replays stay deterministic AND
    span durations are non-zero, without the test hand-advancing around
    every instrumented call."""

    def __init__(self, inner, step: float = 1e-6):
        self.inner = inner
        self.step = float(step)
        self._lock = threading.Lock()

    def now(self) -> float:
        # the read-advance pair is atomic (like StepClock): concurrent
        # readers must never observe the same instant
        with self._lock:
            t = self.inner.now()
            self.inner.advance(self.step)
            return t


class SpanContext:
    """The propagable half of a span: enough to parent remote children."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "start", "end", "attrs", "tid",
    )

    def __init__(self, name, trace_id, span_id, parent_id, start, tid, attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end = None
        self.tid = tid
        self.attrs = attrs

    def duration(self) -> float:
        return (self.end if self.end is not None else self.start) - self.start

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)


class Tracer:
    """Bounded, injectable span recorder. Thread-safe; the ambient span
    stack is per-thread, the finished ring and id draws share one lock."""

    def __init__(self, clock=None, rng=None, capacity: int = 4096,
                 enabled: bool = True, sample_rate: float = 1.0):
        self.clock = clock if clock is not None else StepClock()
        self.rng = rng if rng is not None else random.Random(0)
        self.capacity = int(capacity)
        self.enabled = enabled
        self.sample_rate = float(sample_rate)
        self.finished: deque[Span] = deque(maxlen=self.capacity)
        self.dropped = 0
        self.sampled_out = 0
        self._lock = threading.Lock()
        self._local = threading.local()
        # thread ident -> stable small tid, first-seen order: chrome trace
        # tids stay deterministic under seeded single-thread replays and
        # merely small under real worker pools
        self._tids: dict[int, int] = {}

    # -- ambient context ----------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = self._tids[ident] = len(self._tids)
            return tid

    def _new_id(self) -> int:
        with self._lock:
            return self.rng.getrandbits(64) or 1

    def current(self) -> SpanContext | None:
        """The ambient context on THIS thread (capture it before handing
        work to another thread/future; re-establish with ``attach``)."""
        st = self._stack()
        if not st:
            return None
        top = st[-1]
        return SpanContext(top.trace_id, top.span_id)

    @contextmanager
    def attach(self, ctx: SpanContext | None):
        """Make ``ctx`` the ambient parent on this thread: the cross-
        thread / cross-future propagation seat (DeferredWork resume,
        VerifyFuture resolution)."""
        if ctx is None or not self.enabled:
            yield
            return
        st = self._stack()
        st.append(ctx)
        try:
            yield
        finally:
            if st and st[-1] is ctx:
                st.pop()
            elif ctx in st:
                st.remove(ctx)

    # -- spans --------------------------------------------------------------

    def start_span(self, name: str, parent: SpanContext | None = None,
                   **attrs) -> Span | None:
        if not self.enabled:
            return None
        if parent is None:
            parent = self.current()
        if parent is None:
            trace_id, parent_id = self._new_id(), 0
        else:
            trace_id, parent_id = parent.trace_id, parent.span_id
        s = Span(
            name, trace_id, self._new_id(), parent_id,
            self.clock.now(), self._tid(), attrs,
        )
        self._stack().append(s)
        return s

    def end_span(self, span: Span | None) -> None:
        if span is None:
            return
        span.end = self.clock.now()
        st = self._stack()
        if st and st[-1] is span:
            st.pop()
        elif span in st:  # mis-nested end: drop it wherever it sits
            st.remove(span)
        self._record(span)

    @contextmanager
    def span(self, name: str, parent: SpanContext | None = None, **attrs):
        s = self.start_span(name, parent=parent, **attrs)
        try:
            yield s
        finally:
            self.end_span(s)

    def instant(self, name: str, parent: SpanContext | None = None,
                **attrs) -> None:
        """A zero-duration event (gossip arrival, dispatch edges)."""
        if not self.enabled:
            return
        if parent is None:
            parent = self.current()
        if parent is None:
            trace_id, parent_id = self._new_id(), 0
        else:
            trace_id, parent_id = parent.trace_id, parent.span_id
        s = Span(
            name, trace_id, self._new_id(), parent_id,
            self.clock.now(), self._tid(), attrs,
        )
        s.end = s.start
        self._record(s)

    def trace_sampled(self, trace_id: int) -> bool:
        """The per-trace sampling verdict: a pure function of the trace
        id (drawn at the ROOT span), so it is decided exactly once per
        trace and every descendant span -- on any thread, through any
        ``attach`` -- agrees without propagating a flag. Full-precision
        against the 64-bit id range: any positive rate keeps a positive
        fraction of traces (1e-6 keeps ~1-in-a-million, not zero)."""
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        return trace_id < self.sample_rate * 2.0**64

    def _record(self, span: Span) -> None:
        with self._lock:
            if not self.trace_sampled(span.trace_id):
                self.sampled_out += 1
                return
            if len(self.finished) == self.finished.maxlen:
                self.dropped += 1
            self.finished.append(span)

    # -- export -------------------------------------------------------------

    def finished_spans(self) -> list[Span]:
        """Snapshot of the finished ring (monitoring's trace-derived
        health fields and the scenario SLO checker read through this)."""
        with self._lock:
            return list(self.finished)

    def status(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "capacity": self.capacity,
                "recorded": len(self.finished),
                "dropped": self.dropped,
                "sample_rate": self.sample_rate,
                "sampled_out": self.sampled_out,
                "threads": len(self._tids),
            }

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON (Perfetto / chrome://tracing loadable):
        one "X" complete event per finished span, microsecond units,
        span/trace/parent ids in ``args`` so nesting survives the export.
        Sorted by (ts, trace_id, span_id): a replayed ring exports a
        byte-identical document regardless of resolution interleaving."""
        with self._lock:
            spans = list(self.finished)
        spans.sort(key=lambda s: (s.start, s.trace_id, s.span_id))
        events = []
        for s in spans:
            args = {str(k): v for k, v in sorted(s.attrs.items())}
            args["trace_id"] = f"{s.trace_id:016x}"
            args["span_id"] = f"{s.span_id:016x}"
            if s.parent_id:
                args["parent_id"] = f"{s.parent_id:016x}"
            events.append({
                "name": s.name,
                "cat": _CHROME_CAT,
                "ph": "X",
                "ts": round(s.start * 1e6, 3),
                "dur": round(s.duration() * 1e6, 3),
                "pid": 1,
                "tid": s.tid,
                "args": args,
            })
        return {
            "displayTimeUnit": "ms",
            "traceEvents": events,
        }

    def dump_json(self) -> str:
        return json.dumps(self.chrome_trace(), sort_keys=True)

    def reset(self) -> None:
        """Clear recorded spans + thread table; clock/rng keep their
        state (a reset mid-run must not replay old ids)."""
        with self._lock:
            self.finished.clear()
            self.dropped = 0
            self.sampled_out = 0
            self._tids.clear()


# -- module-level default (the seat instrumented code consults) --------------

_DEFAULT: Tracer | None = None


def default_tracer() -> Tracer:
    global _DEFAULT
    if _DEFAULT is None:
        import os

        try:
            rate = float(os.environ.get("LIGHTHOUSE_TPU_TRACE_SAMPLE", "1"))
        except ValueError:
            rate = 1.0
        _DEFAULT = Tracer(sample_rate=rate)
    return _DEFAULT


def configure(**kwargs) -> Tracer:
    """Replace the process tracer (tests inject clock/rng/capacity here,
    mirroring crypto.bls.pipeline.configure)."""
    global _DEFAULT
    _DEFAULT = Tracer(**kwargs)
    return _DEFAULT


# thin wrappers: instrumented call sites consult the CURRENT default at
# every call, so configure() swaps take effect mid-process
def span(name: str, parent: SpanContext | None = None, **attrs):
    return default_tracer().span(name, parent=parent, **attrs)


def instant(name: str, parent: SpanContext | None = None, **attrs) -> None:
    default_tracer().instant(name, parent=parent, **attrs)


def current() -> SpanContext | None:
    return default_tracer().current()


def attach(ctx: SpanContext | None):
    return default_tracer().attach(ctx)
