"""Task executor with shutdown broadcast (reference common/task_executor/
src/lib.rs:181-291 + environment/src/lib.rs:418-520): every service
thread spawns through one executor that tracks it, a shutdown sender any
task can trigger (fatal errors), and a blocking wait that joins all
tasks — the graceful-shutdown spine the reference builds on tokio."""

from __future__ import annotations

import threading
import traceback
from dataclasses import dataclass, field

from . import metrics


@dataclass
class ShutdownReason:
    message: str
    failure: bool = False


class TaskExecutor:
    def __init__(self, name: str = "env"):
        self.name = name
        self._threads: list[threading.Thread] = []
        self._shutdown = threading.Event()
        self._reason: ShutdownReason | None = None
        self._lock = threading.Lock()
        # families live in utils/metrics.py (metric-origin rule)
        self._tasks_total = metrics.EXECUTOR_TASKS_SPAWNED
        self._panics = metrics.EXECUTOR_TASK_PANICS

    # -- spawn (task_executor spawn / spawn_blocking) -----------------------

    def spawn(self, fn, name: str, *args, **kwargs) -> threading.Thread:
        """Run fn on a tracked daemon thread; an escaped exception triggers
        a failure shutdown (the reference logs + optionally exits)."""

        def run():
            try:
                fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 -- task boundary
                traceback.print_exc()
                self._panics.inc()
                self.shutdown(f"task {name!r} failed: {e}", failure=True)

        t = threading.Thread(target=run, name=f"{self.name}/{name}", daemon=True)
        with self._lock:
            if self._shutdown.is_set():
                raise RuntimeError("executor is shut down")
            self._threads.append(t)
        self._tasks_total.inc()
        t.start()
        return t

    def spawn_loop(self, fn, name: str, interval_s: float) -> threading.Thread:
        """Periodic task: fn() every interval until shutdown (the slot-timer
        and notifier pattern, timer/src/lib.rs:12-35)."""

        def loop():
            while not self._shutdown.wait(interval_s):
                fn()

        return self.spawn(loop, name)

    # -- shutdown broadcast --------------------------------------------------

    def shutdown(self, message: str = "requested", failure: bool = False) -> None:
        with self._lock:
            if self._reason is None:
                self._reason = ShutdownReason(message, failure)
        self._shutdown.set()

    @property
    def is_shutdown(self) -> bool:
        return self._shutdown.is_set()

    def shutdown_reason(self) -> ShutdownReason | None:
        return self._reason

    def wait_shutdown(self, timeout: float | None = None) -> bool:
        return self._shutdown.wait(timeout)

    def join_all(self, timeout: float = 5.0) -> None:
        """Join tracked tasks after shutdown (environment's block-until-
        shutdown + drain). `timeout` is a SHARED budget across all
        threads, not per-thread."""
        import time as _time

        deadline = _time.monotonic() + timeout
        for t in list(self._threads):
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                break
            t.join(remaining)
