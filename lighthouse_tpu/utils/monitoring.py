"""Remote monitoring push (reference common/monitoring_api/src/lib.rs):
periodically POST process/system/chain health JSON to a configured
endpoint (the beaconcha.in-style "remote monitoring" integration).

Payload shape mirrors the reference: a list of per-process records
`{sub_type, timestamp_s, data}` for the beacon node and/or validator
client, where `data` carries version metadata, process metrics
(cpu/memory/fds from getrusage + /proc), system metrics (load, total
memory, disk), and whatever chain gauges the caller wires in via
`data_sources` (head slot, sync state, validator count -- the fields
process_beacon_node/process_validator attach in lib.rs:218-268).

Transport is plain HTTP POST with bounded exponential-backoff retries,
failing fast on 4xx (a bad monitoring token is configuration, not an
outage) -- the same policy as the repo's JSON-RPC boundaries. The
in-process `MonitoringRig` receives pushes in tests.
"""

from __future__ import annotations

import json
import os
import resource
import shutil
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

VERSION = "lighthouse-tpu/4.0"


class MonitoringError(RuntimeError):
    pass


def process_metrics() -> dict:
    ru = resource.getrusage(resource.RUSAGE_SELF)
    try:
        fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        fds = -1
    return {
        "cpu_process_seconds_total": round(ru.ru_utime + ru.ru_stime, 3),
        "memory_process_bytes": ru.ru_maxrss * 1024,
        "process_open_fds": fds,
    }


def system_metrics() -> dict:
    try:
        load1, load5, load15 = os.getloadavg()
    except OSError:
        load1 = load5 = load15 = 0.0
    try:
        total_mem = os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError):
        total_mem = 0
    disk = shutil.disk_usage(os.getcwd())
    return {
        "cpu_cores": os.cpu_count() or 0,
        "system_load_1": round(load1, 3),
        "system_load_5": round(load5, 3),
        "system_load_15": round(load15, 3),
        "memory_total_bytes": total_mem,
        "disk_total_bytes": disk.total,
        "disk_free_bytes": disk.free,
    }


class MonitoringService:
    """Collect-and-push loop. `data_sources` maps sub_type
    ("beacon_node" / "validator") to a zero-arg callable returning that
    process's chain-level fields; system metrics ride along once."""

    def __init__(
        self,
        endpoint: str,
        data_sources: dict | None = None,
        update_period_s: float = 60.0,
        retries: int = 3,
        backoff_s: float = 0.05,
        timeout_s: float = 5.0,
        clock=time.time,
    ):
        self.endpoint = endpoint
        self.data_sources = dict(data_sources or {"beacon_node": dict})
        self.update_period_s = update_period_s
        self.retries = retries
        self.backoff_s = backoff_s
        self.timeout_s = timeout_s
        self.clock = clock
        self.stats = {"sent": 0, "failed": 0}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- payload ---------------------------------------------------------------

    def collect(self) -> list:
        now = int(self.clock())
        records = []
        for sub_type, source in self.data_sources.items():
            data = {"version": VERSION}
            data.update(process_metrics())
            try:
                data.update(source() or {})
            except Exception as e:  # noqa: BLE001 -- a sick chain still reports
                data["source_error"] = str(e)[:200]
            records.append(
                {"sub_type": "process", "process": sub_type,
                 "timestamp_s": now, "data": data}
            )
        records.append(
            {"sub_type": "system", "timestamp_s": now, "data": system_metrics()}
        )
        return records

    # -- transport -------------------------------------------------------------

    def send_once(self) -> None:
        payload = json.dumps(self.collect()).encode()
        last = None
        for attempt in range(self.retries):
            try:
                req = urllib.request.Request(
                    self.endpoint,
                    data=payload,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=self.timeout_s):
                    self.stats["sent"] += 1
                    return
            except urllib.error.HTTPError as e:
                if 400 <= e.code < 500:
                    self.stats["failed"] += 1
                    raise MonitoringError(
                        f"monitoring endpoint rejected push: HTTP {e.code}"
                    ) from None
                last = e
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                last = e
            if attempt < self.retries - 1:
                time.sleep(self.backoff_s * (2**attempt))
        self.stats["failed"] += 1
        raise MonitoringError(f"monitoring push failed after retries: {last}")

    # -- loop ------------------------------------------------------------------

    def start(self) -> "MonitoringService":
        def loop():
            while not self._stop.is_set():
                try:
                    self.send_once()
                except MonitoringError:
                    pass  # counted; the loop keeps its cadence
                self._stop.wait(self.update_period_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class MonitoringRig:
    """In-process receiver for pushes (test stand-in for the remote
    service): records bodies, can inject transient 503s or a hard 401."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.received: list = []
        self.fail_next = 0
        self.reject_all = False
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_POST(self):
                if outer.reject_all:
                    self.send_error(401)
                    return
                if outer.fail_next > 0:
                    outer.fail_next -= 1
                    self.send_error(503)
                    return
                length = int(self.headers.get("Content-Length", "0"))
                outer.received.append(json.loads(self.rfile.read(length)))
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.url = f"http://{host}:{self._server.server_address[1]}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    def start(self) -> "MonitoringRig":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def trace_health_fields(tracer=None) -> dict:
    """Trace/metrics-derived health: p95 work-span durations per
    processor lane (from the span ring) plus queue-wait and slot-delay
    p95s (from the shared registry's histograms). This is the ONE code
    path for these numbers — the remote monitoring push attaches them
    and the scenario harness's SLO checker asserts against them."""
    from . import metrics as M
    from . import tracing

    t = tracer if tracer is not None else tracing.default_tracer()
    work: dict[str, list[float]] = {}
    for s in t.finished_spans():
        if s.name.startswith("work/") and s.end is not None:
            work.setdefault(s.name[len("work/"):], []).append(s.duration())
    fields: dict = {}
    for lane, durs in sorted(work.items()):
        durs.sort()
        idx = min(len(durs) - 1, int(0.95 * len(durs)))
        fields[f"work_p95_{lane}_seconds"] = round(durs[idx], 9)
    pairs = (
        ("queue_wait", M.PROCESSOR_QUEUE_WAIT),
        ("block_observed_delay", M.BLOCK_OBSERVED_DELAY),
        ("block_imported_delay", M.BLOCK_IMPORTED_DELAY),
        ("block_head_delay", M.BLOCK_HEAD_DELAY),
    )
    for name, hist in pairs:
        v = hist.quantile(0.95)
        if v is not None:
            fields[f"{name}_p95_seconds"] = v
    return fields


def ledger_health_fields(ledger=None) -> dict:
    """Launch-ledger-derived health: merged-launch occupancy, the
    pad-waste ratio, compile tax, and withheld-speculation counts from
    the per-launch flight recorder (obs/ledger.py). Like
    trace_health_fields, this is the ONE code path — the remote
    monitoring push attaches it and the scenario harness's SLO report
    carries the same numbers."""
    from ..obs import ledger as launch_ledger

    led = ledger if ledger is not None else launch_ledger.default_ledger()
    stats = led.stats()
    fields: dict = {
        "launch_records": stats["records"],
        "launch_dropped": stats["dropped"],
        "cold_dispatches": stats["compile_tax_s"]["cold_dispatches"],
        "warm_compile_s_total": stats["compile_tax_s"]["total_s"],
        "speculative_withheld_total": stats["speculative_withheld_total"],
    }
    kind = stats.get("pad_waste_kind")
    occ = stats["occupancy"].get(kind) if kind else None
    if occ is not None:
        fields["launch_occupancy"] = occ["ratio"]
        fields["pad_waste_ratio"] = round(1.0 - occ["ratio"], 4)
    return fields


def beacon_node_source(chain, serving=None) -> dict:
    """Chain-level fields for the beacon_node record (lib.rs:218-243),
    plus the trace-derived health block (PR-5 follow-up), the
    launch-ledger health block, and — when a serving tier is wired —
    its cache/SSE/admission counters."""
    head_root, head_state = chain.head()
    fin_epoch, _ = chain.finalized_checkpoint
    health = trace_health_fields()
    health["ledger"] = ledger_health_fields()
    fields = {
        "slot": int(chain.current_slot),
        "head_slot": int(head_state.slot),
        "head_root": "0x" + bytes(head_root).hex(),
        "finalized_epoch": int(fin_epoch),
        "validator_count": len(head_state.validators),
        "is_synced": int(chain.current_slot) <= int(head_state.slot) + 1,
        "health": health,
    }
    if serving is not None:
        fields["serving"] = serving.stats()
    return fields
