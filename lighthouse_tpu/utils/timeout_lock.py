"""Timeout-guarded chain lock (reference beacon_node/beacon_chain/src/
timeout_rw_lock.rs): lock acquisition that raises after a deadline
instead of deadlocking silently, so a stuck holder surfaces as a loud
error with the slow path named.

The reference wraps parking_lot's RwLock; here a reentrant exclusive
lock is the right shape — CPython's GIL already serializes reads, the
hazards are compound read-modify-write sequences interleaving across
threads (gossip workers vs the tick loop vs HTTP handlers), and chain
entry points nest (process_block -> recompute_head)."""

from __future__ import annotations

import threading


LOCK_TIMEOUT = 30.0  # seconds; reference uses 30s for beacon-chain locks


class LockTimeoutError(RuntimeError):
    pass


class TimeoutRLock:
    """threading.RLock with a timeout-raising context manager."""

    def __init__(self, name: str = "lock", timeout: float = LOCK_TIMEOUT):
        self._lock = threading.RLock()
        self.name = name
        self.timeout = timeout

    def __enter__(self):
        if not self._lock.acquire(timeout=self.timeout):
            raise LockTimeoutError(
                f"{self.name}: lock not acquired within {self.timeout}s "
                "(holder stuck?)"
            )
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False
