"""Swap-or-not committee shuffle (spec SHUFFLE_ROUND_COUNT = 90).

Equivalent of the reference's consensus/swap_or_not_shuffle crate:
`compute_shuffled_index` (single-index, compute_shuffled_index.rs:21) and
the whole-list fast path (shuffle_list.rs:79). The list path is vectorized
with numpy -- per round, ONE set of pivot/source hashes is computed and the
swap decisions for every index are applied as array ops, the same
round-level data-parallelism the reference gets by precomputing the round's
"pivots" buffer.
"""

from __future__ import annotations

import hashlib

import numpy as np

SHUFFLE_ROUND_COUNT = 90


def _hash(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def compute_shuffled_index(
    index: int, list_size: int, seed: bytes, rounds: int = SHUFFLE_ROUND_COUNT
) -> int:
    """Forward-shuffled position of one index (spec algorithm)."""
    if not 0 <= index < list_size:
        raise ValueError("index out of range")
    for r in range(rounds):
        pivot = (
            int.from_bytes(_hash(seed + bytes([r]))[:8], "little") % list_size
        )
        flip = (pivot + list_size - index) % list_size
        position = max(index, flip)
        source = _hash(
            seed + bytes([r]) + (position // 256).to_bytes(4, "little")
        )
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) & 1:
            index = flip
    return index


def shuffle_list(
    input_list,
    seed: bytes,
    forwards: bool = False,
    rounds: int = SHUFFLE_ROUND_COUNT,
) -> list:
    """Whole-list shuffle, both directions of the reference's shuffle_list
    (shuffle_list.rs:79):

      forwards=True:   output[compute_shuffled_index(i)] == input[i]
      forwards=False:  output[i] == input[compute_shuffled_index(i)]

    The backwards direction (default) is the one committee assignment uses
    (spec compute_committee; reference committee_cache.rs calls
    shuffle_list with forwards = false)."""
    n = len(input_list)
    if n == 0:
        return []
    perm = shuffle_indices(n, seed, rounds)
    out = [None] * n
    if forwards:
        for i, p in enumerate(perm):
            out[p] = input_list[i]
    else:
        for i, p in enumerate(perm):
            out[i] = input_list[p]
    return out


def shuffle_indices(
    n: int, seed: bytes, rounds: int = SHUFFLE_ROUND_COUNT
) -> np.ndarray:
    """Vectorized: perm[i] = compute_shuffled_index(i, n, seed)."""
    idx = np.arange(n, dtype=np.int64)
    n_words = (n + 255) // 256
    for r in range(rounds):
        rb = bytes([r])
        pivot = int.from_bytes(_hash(seed + rb)[:8], "little") % n
        flip = (pivot - idx) % n
        position = np.maximum(idx, flip)
        # one 32-byte source block per 256 positions
        blocks = np.frombuffer(
            b"".join(
                _hash(seed + rb + w.to_bytes(4, "little"))
                for w in range(n_words)
            ),
            dtype=np.uint8,
        ).reshape(n_words, 32)
        byte = blocks[position // 256, (position % 256) // 8]
        bit = (byte >> (position % 8).astype(np.uint8)) & 1
        idx = np.where(bit == 1, flip, idx)
    return idx
