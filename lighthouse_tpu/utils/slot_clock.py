"""Slot clocks (reference common/slot_clock: SystemTimeSlotClock +
manual_slot_clock.rs for tests).

This module is the ONE place consensus time enters the system: chain /
fork-choice / state-transition code takes a clock (or a timestamp) as a
parameter and never reads the wall clock directly -- that invariant is
enforced by `python -m tools.lint` (rule `wallclock`).
"""
# lint: allow-file[wallclock] -- the slot clock IS the injection boundary

from __future__ import annotations

import time


class SystemSlotClock:
    def __init__(self, genesis_time: int, seconds_per_slot: int):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot

    def now(self) -> float:
        """Seconds since the unix epoch; the only wall-clock read."""
        return time.time()

    def current_slot(self) -> int:
        now = self.now()
        if now < self.genesis_time:
            return 0
        return int(now - self.genesis_time) // self.seconds_per_slot

    def seconds_into_slot(self) -> float:
        now = self.now()
        return (now - self.genesis_time) % self.seconds_per_slot


class ManualSlotClock:
    """Test clock advanced by hand (manual_slot_clock.rs)."""

    def __init__(self, genesis_time: int = 0, seconds_per_slot: int = 12):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot
        self._slot = 0

    def now(self) -> float:
        """Deterministic: the start of the manually-set slot."""
        return float(self.genesis_time + self._slot * self.seconds_per_slot)

    def current_slot(self) -> int:
        return self._slot

    def set_slot(self, slot: int) -> None:
        self._slot = slot

    def advance_slot(self) -> None:
        self._slot += 1

    def seconds_into_slot(self) -> float:
        return 0.0
