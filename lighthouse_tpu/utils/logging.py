"""Structured logging (reference common/logging + environment's slog
setup, environment/src/lib.rs:155-279): leveled key=value records to a
stream and/or file, optional JSON lines, per-service child loggers with
bound context — the slog `o!(...)` pattern."""

from __future__ import annotations

import json
import sys
import threading
import time

LEVELS = {"trace": 5, "debug": 10, "info": 20, "warn": 30, "error": 40, "crit": 50}


class Logger:
    def __init__(
        self,
        level: str = "info",
        stream=None,
        path: str | None = None,
        json_lines: bool = False,
    ):
        self.level = LEVELS[level]
        self.context: dict = {}
        # child() is the ONLY other construction path (via __new__), and
        # it shares this sink dict + lock
        self._shared = {
            "stream": stream if stream is not None else sys.stderr,
            "file": open(path, "a") if path else None,
            "json": json_lines,
            "lock": threading.Lock(),
        }

    def child(self, **context) -> "Logger":
        """Bound-context child (slog o!): service loggers carry their
        service name on every record."""
        merged = {**self.context, **context}
        out = Logger.__new__(Logger)
        out.level = self.level
        out.context = merged
        out._shared = self._shared
        return out

    def _emit(self, level: str, msg: str, kv: dict) -> None:
        if LEVELS[level] < self.level:
            return
        record = {
            # lint: allow[wallclock] -- log timestamps are wall time by
            # definition; nothing downstream consumes them
            "ts": round(time.time(), 3),
            "level": level,
            "msg": msg,
            **self.context,
            **kv,
        }
        if self._shared["json"]:
            line = json.dumps(record)
        else:
            pairs = " ".join(
                f"{k}={v}" for k, v in record.items() if k not in ("ts", "level", "msg")
            )
            line = f"{record['ts']} {level.upper():5s} {msg}" + (
                f" | {pairs}" if pairs else ""
            )
        with self._shared["lock"]:
            print(line, file=self._shared["stream"])
            if self._shared["file"] is not None:
                print(line, file=self._shared["file"])
                self._shared["file"].flush()

    def trace(self, msg, **kv):
        self._emit("trace", msg, kv)

    def debug(self, msg, **kv):
        self._emit("debug", msg, kv)

    def info(self, msg, **kv):
        self._emit("info", msg, kv)

    def warn(self, msg, **kv):
        self._emit("warn", msg, kv)

    def error(self, msg, **kv):
        self._emit("error", msg, kv)

    def crit(self, msg, **kv):
        self._emit("crit", msg, kv)
