"""Shared JSON-RPC 2.0 HTTP plumbing used by both external-chain
boundaries — the eth1 deposit provider (reference eth1/src/http.rs) and
the engine API (execution_layer/src/engine_api/http.rs): a client with
bounded exponential-backoff retries that fails FAST on HTTP 4xx (auth or
protocol misconfiguration is not a transient transport fault), and a
threaded in-process server scaffold with fault injection for rig tests.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class JsonRpcClient:
    """POSTs JSON-RPC calls to `url`. `headers_fn` is invoked per attempt
    (JWT tokens are short-lived); `error_cls` shapes raised errors so each
    boundary surfaces its own exception type."""

    def __init__(
        self,
        url: str,
        error_cls=RuntimeError,
        headers_fn=None,
        retries: int = 3,
        backoff_s: float = 0.05,
        timeout_s: float = 5.0,
    ):
        self.url = url
        self.error_cls = error_cls
        self.headers_fn = headers_fn
        self.retries = retries
        self.backoff_s = backoff_s
        self.timeout_s = timeout_s
        self._id = 0

    def call(self, method: str, params: list):
        self._id += 1
        payload = json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        ).encode()
        last = None
        for attempt in range(self.retries):
            headers = {"Content-Type": "application/json"}
            if self.headers_fn is not None:
                headers.update(self.headers_fn())
            try:
                req = urllib.request.Request(
                    self.url, data=payload, headers=headers
                )
                with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                    body = json.loads(resp.read())
                if body.get("error") is not None:
                    raise self.error_cls(str(body["error"]))
                return body["result"]
            except urllib.error.HTTPError as e:
                if 400 <= e.code < 500:
                    # 4xx is deterministic (bad auth/request): retrying
                    # cannot help and masks misconfiguration as an outage
                    raise self.error_cls(
                        f"{method} rejected: HTTP {e.code} {e.reason}"
                    ) from None
                last = e
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                last = e
            if attempt < self.retries - 1:
                time.sleep(self.backoff_s * (2**attempt))
        raise self.error_cls(f"{method} failed after retries: {last}")


class JsonRpcHttpServer:
    """Threaded JSON-RPC server over a scriptable `dispatch(method, params)`
    callable. `fail_next` injects transient 503s; `auth_fn`, when set,
    vets each request's Authorization header and 401s on rejection."""

    def __init__(self, dispatch, host: str = "127.0.0.1", port: int = 0,
                 auth_fn=None):
        self.dispatch = dispatch
        self.auth_fn = auth_fn
        self.fail_next = 0
        self.requests_seen = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_POST(self):
                outer.requests_seen += 1
                if outer.fail_next > 0:
                    outer.fail_next -= 1
                    self.send_error(503)
                    return
                if outer.auth_fn is not None and not outer.auth_fn(
                    self.headers.get("Authorization", "")
                ):
                    self.send_error(401)
                    return
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length))
                try:
                    result = outer.dispatch(req["method"], req.get("params", []))
                    body = {"jsonrpc": "2.0", "id": req.get("id"), "result": result}
                except Exception as e:  # noqa: BLE001
                    body = {
                        "jsonrpc": "2.0",
                        "id": req.get("id"),
                        "error": {"code": -32000, "message": str(e)},
                    }
                data = json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.url = f"http://{host}:{self._server.server_address[1]}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
