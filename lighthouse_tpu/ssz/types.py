"""SSZ type system: encode/decode + hash-tree-root.

Python equivalent of the reference's ssz / ssz_derive / ssz_types /
tree_hash crates (consensus/ssz/src, consensus/ssz_types/src,
consensus/tree_hash/src): `Encode`/`Decode`/`TreeHash` become methods on
type-descriptor objects; the derive macros become the `@container`
decorator over annotated dataclass-like classes.

Descriptors are singletons (`uint64`, `Bytes32`, ...) or parameterized
(`List(uint64, 1024)`), each with:
    is_fixed()  fixed_size()  encode(v)->bytes  decode(b)->v
    hash_tree_root(v)->bytes32  default()
"""

from __future__ import annotations

from .hash import (
    BYTES_PER_CHUNK,
    ZERO_HASHES,
    merkleize,
    mix_in_length,
    pack_bytes,
)

OFFSET_SIZE = 4


class SszError(ValueError):
    pass


class SszType:
    def is_fixed(self) -> bool:
        raise NotImplementedError

    def fixed_size(self) -> int:
        raise NotImplementedError

    def encode(self, value) -> bytes:
        raise NotImplementedError

    def decode(self, data: bytes):
        raise NotImplementedError

    def hash_tree_root(self, value) -> bytes:
        raise NotImplementedError

    def default(self):
        raise NotImplementedError


class _UInt(SszType):
    def __init__(self, byte_len: int):
        self.byte_len = byte_len

    def is_fixed(self):
        return True

    def fixed_size(self):
        return self.byte_len

    def encode(self, value) -> bytes:
        value = int(value)
        if not 0 <= value < (1 << (8 * self.byte_len)):
            raise SszError(
                f"uint{self.byte_len * 8}: value out of range: {value}"
            )
        return value.to_bytes(self.byte_len, "little")

    def decode(self, data: bytes) -> int:
        if len(data) != self.byte_len:
            raise SszError(f"uint{self.byte_len * 8}: bad length {len(data)}")
        return int.from_bytes(data, "little")

    def hash_tree_root(self, value) -> bytes:
        return self.encode(value).ljust(BYTES_PER_CHUNK, b"\x00")

    def default(self):
        return 0


class _Boolean(SszType):
    def is_fixed(self):
        return True

    def fixed_size(self):
        return 1

    def encode(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def decode(self, data: bytes) -> bool:
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise SszError("boolean must be 0x00 or 0x01")

    def hash_tree_root(self, value) -> bytes:
        return self.encode(value).ljust(BYTES_PER_CHUNK, b"\x00")

    def default(self):
        return False


uint8 = _UInt(1)
uint16 = _UInt(2)
uint32 = _UInt(4)
uint64 = _UInt(8)
uint128 = _UInt(16)
uint256 = _UInt(32)
boolean = _Boolean()


class ByteVector(SszType):
    """Fixed-length opaque bytes (Bytes4/20/32/48/96 spec aliases)."""

    def __init__(self, length: int):
        self.length = length

    def is_fixed(self):
        return True

    def fixed_size(self):
        return self.length

    def encode(self, value) -> bytes:
        value = bytes(value)
        if len(value) != self.length:
            raise SszError(f"ByteVector[{self.length}]: got {len(value)}")
        return value

    def decode(self, data: bytes) -> bytes:
        return self.encode(data)

    def hash_tree_root(self, value) -> bytes:
        return merkleize(pack_bytes(self.encode(value)))

    def default(self):
        return bytes(self.length)


Bytes4 = ByteVector(4)
Bytes20 = ByteVector(20)
Bytes32 = ByteVector(32)
Bytes48 = ByteVector(48)
Bytes96 = ByteVector(96)


class ByteList(SszType):
    """Variable-length opaque bytes with a max length."""

    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed(self):
        return False

    def encode(self, value) -> bytes:
        value = bytes(value)
        if len(value) > self.limit:
            raise SszError(f"ByteList[{self.limit}]: got {len(value)}")
        return value

    def decode(self, data: bytes) -> bytes:
        if len(data) > self.limit:
            raise SszError(f"ByteList[{self.limit}]: got {len(data)}")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        value = self.encode(value)
        limit_chunks = (self.limit + BYTES_PER_CHUNK - 1) // BYTES_PER_CHUNK
        return mix_in_length(
            merkleize(pack_bytes(value), limit_chunks), len(value)
        )

    def default(self):
        return b""


class Bitvector(SszType):
    """Fixed-length bit sequence; value is a tuple/list of bools."""

    def __init__(self, length: int):
        if length <= 0:
            raise SszError("Bitvector length must be positive")
        self.length = length

    def is_fixed(self):
        return True

    def fixed_size(self):
        return (self.length + 7) // 8

    def encode(self, value) -> bytes:
        bits = list(value)
        if len(bits) != self.length:
            raise SszError(f"Bitvector[{self.length}]: got {len(bits)}")
        out = bytearray(self.fixed_size())
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)

    def decode(self, data: bytes):
        if len(data) != self.fixed_size():
            raise SszError(f"Bitvector[{self.length}]: bad byte length")
        bits = [bool(data[i // 8] >> (i % 8) & 1) for i in range(self.length)]
        # excess bits in the last byte must be zero
        for i in range(self.length, len(data) * 8):
            if data[i // 8] >> (i % 8) & 1:
                raise SszError("Bitvector: non-zero padding bits")
        return tuple(bits)

    def hash_tree_root(self, value) -> bytes:
        limit = (self.length + 255) // 256
        return merkleize(pack_bytes(self.encode(value)), limit)

    def default(self):
        return tuple(False for _ in range(self.length))


class Bitlist(SszType):
    """Variable-length bit sequence with max length; delimiting-bit format."""

    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed(self):
        return False

    def encode(self, value) -> bytes:
        bits = list(value)
        if len(bits) > self.limit:
            raise SszError(f"Bitlist[{self.limit}]: got {len(bits)}")
        out = bytearray((len(bits) // 8) + 1)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        out[len(bits) // 8] |= 1 << (len(bits) % 8)  # delimiter
        return bytes(out)

    def decode(self, data: bytes):
        if not data or data[-1] == 0:
            raise SszError("Bitlist: missing delimiter bit")
        total_bits = (len(data) - 1) * 8 + data[-1].bit_length() - 1
        if total_bits > self.limit:
            raise SszError(f"Bitlist[{self.limit}]: got {total_bits}")
        return tuple(
            bool(data[i // 8] >> (i % 8) & 1) for i in range(total_bits)
        )

    def hash_tree_root(self, value) -> bytes:
        bits = list(value)
        out = bytearray((len(bits) + 7) // 8)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        limit = (self.limit + 255) // 256
        return mix_in_length(merkleize(pack_bytes(bytes(out)), limit), len(bits))

    def default(self):
        return ()


def _is_basic(t: SszType) -> bool:
    return isinstance(t, (_UInt, _Boolean))


class Vector(SszType):
    """Fixed-length homogeneous sequence."""

    def __init__(self, elem: SszType, length: int):
        if length <= 0:
            raise SszError("Vector length must be positive")
        self.elem = elem
        self.length = length

    def is_fixed(self):
        return self.elem.is_fixed()

    def fixed_size(self):
        return self.elem.fixed_size() * self.length

    def encode(self, value) -> bytes:
        items = list(value)
        if len(items) != self.length:
            raise SszError(f"Vector[{self.length}]: got {len(items)}")
        return _encode_sequence(self.elem, items)

    def decode(self, data: bytes):
        return tuple(_decode_sequence(self.elem, data, exact=self.length))

    def hash_tree_root(self, value) -> bytes:
        return _sequence_root(self.elem, list(value), limit_elems=None)

    def default(self):
        return tuple(self.elem.default() for _ in range(self.length))


class List(SszType):
    """Variable-length homogeneous sequence with max length."""

    def __init__(self, elem: SszType, limit: int):
        self.elem = elem
        self.limit = limit

    def is_fixed(self):
        return False

    def encode(self, value) -> bytes:
        items = list(value)
        if len(items) > self.limit:
            raise SszError(f"List[{self.limit}]: got {len(items)}")
        return _encode_sequence(self.elem, items)

    def decode(self, data: bytes):
        items = _decode_sequence(self.elem, data, exact=None)
        if len(items) > self.limit:
            raise SszError(f"List[{self.limit}]: got {len(items)}")
        return tuple(items)

    def hash_tree_root(self, value) -> bytes:
        items = list(value)
        root = _sequence_root(self.elem, items, limit_elems=self.limit)
        return mix_in_length(root, len(items))

    def default(self):
        return ()


def _encode_sequence(elem: SszType, items) -> bytes:
    if elem.is_fixed():
        return b"".join(elem.encode(v) for v in items)
    parts = [elem.encode(v) for v in items]
    offset = OFFSET_SIZE * len(parts)
    head = bytearray()
    for p in parts:
        head += offset.to_bytes(OFFSET_SIZE, "little")
        offset += len(p)
    return bytes(head) + b"".join(parts)


def _decode_sequence(elem: SszType, data: bytes, exact: int | None):
    if elem.is_fixed():
        size = elem.fixed_size()
        if len(data) % size:
            raise SszError("sequence length not a multiple of element size")
        n = len(data) // size
        if exact is not None and n != exact:
            raise SszError(f"expected {exact} elements, got {n}")
        return [elem.decode(data[i * size : (i + 1) * size]) for i in range(n)]
    if not data:
        if exact:
            raise SszError(f"expected {exact} elements, got 0")
        return []
    first_off = int.from_bytes(data[:OFFSET_SIZE], "little")
    if first_off % OFFSET_SIZE or first_off > len(data):
        raise SszError("bad first offset")
    n = first_off // OFFSET_SIZE
    if exact is not None and n != exact:
        raise SszError(f"expected {exact} elements, got {n}")
    offsets = [
        int.from_bytes(data[i * OFFSET_SIZE : (i + 1) * OFFSET_SIZE], "little")
        for i in range(n)
    ] + [len(data)]
    out = []
    for i in range(n):
        if offsets[i + 1] < offsets[i] or offsets[i + 1] > len(data):
            raise SszError("offsets not monotonic")
        out.append(elem.decode(data[offsets[i] : offsets[i + 1]]))
    return out


def _sequence_root(elem: SszType, items, limit_elems: int | None) -> bytes:
    if _is_basic(elem):
        data = b"".join(elem.encode(v) for v in items)
        chunks = pack_bytes(data)
        if limit_elems is not None:
            per_chunk = BYTES_PER_CHUNK // elem.fixed_size()
            limit = (limit_elems + per_chunk - 1) // per_chunk
        else:
            limit = None  # Vector: natural width
        return merkleize(chunks, limit)
    roots = [elem.hash_tree_root(v) for v in items]
    return merkleize(roots, limit_elems)


class Container(SszType):
    """Descriptor for an @container class (see below)."""

    def __init__(self, cls, fields):
        self.cls = cls
        self.fields = fields  # [(name, SszType)]

    def is_fixed(self):
        return all(t.is_fixed() for _, t in self.fields)

    def fixed_size(self):
        return sum(t.fixed_size() for _, t in self.fields)

    def encode(self, value) -> bytes:
        head = bytearray()
        tail = bytearray()
        fixed_len = sum(
            t.fixed_size() if t.is_fixed() else OFFSET_SIZE
            for _, t in self.fields
        )
        for name, t in self.fields:
            v = getattr(value, name)
            if t.is_fixed():
                head += t.encode(v)
            else:
                head += (fixed_len + len(tail)).to_bytes(OFFSET_SIZE, "little")
                tail += t.encode(v)
        return bytes(head) + bytes(tail)

    def decode(self, data: bytes):
        kwargs = {}
        pos = 0
        var_fields = []
        offsets = []
        for name, t in self.fields:
            if t.is_fixed():
                size = t.fixed_size()
                kwargs[name] = t.decode(data[pos : pos + size])
                pos += size
            else:
                if pos + OFFSET_SIZE > len(data):
                    raise SszError("container truncated")
                offsets.append(
                    int.from_bytes(data[pos : pos + OFFSET_SIZE], "little")
                )
                var_fields.append((name, t))
                pos += OFFSET_SIZE
        if var_fields:
            if offsets[0] != pos:
                raise SszError("first offset must equal fixed length")
            offsets.append(len(data))
            for i, (name, t) in enumerate(var_fields):
                if offsets[i + 1] < offsets[i] or offsets[i + 1] > len(data):
                    raise SszError("offsets not monotonic")
                kwargs[name] = t.decode(data[offsets[i] : offsets[i + 1]])
        elif pos != len(data):
            raise SszError("container trailing bytes")
        return self.cls(**kwargs)

    def hash_tree_root(self, value) -> bytes:
        roots = [t.hash_tree_root(getattr(value, name)) for name, t in self.fields]
        return merkleize(roots)

    def default(self):
        return self.cls(**{name: t.default() for name, t in self.fields})


def container(cls):
    """Class decorator: annotations of SszType descriptors -> SSZ container.

    Produces an __init__ (defaults from the descriptors), equality, repr,
    and classmethods/methods: as_ssz_bytes, from_ssz_bytes, tree_hash_root,
    ssz_type. The derive-macro equivalent of ssz_derive + tree_hash_derive.
    """
    fields = [
        (name, t) for name, t in cls.__dict__.get("__annotations__", {}).items()
    ]
    for name, t in fields:
        if not isinstance(t, SszType):
            raise TypeError(f"{cls.__name__}.{name}: not an SszType")
    desc = Container(cls, fields)

    def __init__(self, **kwargs):
        for name, t in fields:
            if name in kwargs:
                setattr(self, name, kwargs.pop(name))
            else:
                setattr(self, name, t.default())
        if kwargs:
            raise TypeError(f"unknown fields: {sorted(kwargs)}")

    def __eq__(self, other):
        if other.__class__ is not self.__class__:
            return NotImplemented
        return all(
            getattr(self, n) == getattr(other, n) for n, _ in fields
        )

    def __repr__(self):
        inner = ", ".join(f"{n}={getattr(self, n)!r}" for n, _ in fields[:4])
        more = ", …" if len(fields) > 4 else ""
        return f"{cls.__name__}({inner}{more})"

    cls.__init__ = __init__
    cls.__eq__ = __eq__
    cls.__hash__ = None
    cls.__repr__ = __repr__
    cls.ssz_type = desc
    cls.ssz_fields = fields
    cls.as_ssz_bytes = lambda self: desc.encode(self)
    cls.from_ssz_bytes = classmethod(lambda c, data: desc.decode(bytes(data)))
    cls.tree_hash_root = lambda self: desc.hash_tree_root(self)
    cls.default = classmethod(lambda c: desc.default())
    return cls
