"""General merkle single- and multi-proofs over SSZ generalized indices
(reference consensus/merkle_proof/src/lib.rs + the consensus-spec
generalized-index helpers in ssz/merkle-proofs.md).

A generalized index addresses a node in the binary merkle tree rooted at
1: node g's children are 2g and 2g+1, depth = floor(log2(g)). Single
proofs carry the sibling on each level; multiproofs carry exactly the
helper nodes not derivable from the provided leaves.

`MerkleTree` builds the full padded tree from chunks so proofs can be
GENERATED for any SSZ merkleization this repo produces (the same padding
rules as ssz/hash.py merkleize, so proven roots match tree_hash_root /
cached_root outputs).
"""

from __future__ import annotations

from .hash import ZERO_HASHES, hash_concat


class MerkleProofError(ValueError):
    pass


def generalized_index_depth(index: int) -> int:
    if index < 1:
        raise MerkleProofError("generalized index must be >= 1")
    return index.bit_length() - 1


def generalized_index_sibling(index: int) -> int:
    return index ^ 1


def generalized_index_child(index: int, right: bool) -> int:
    return 2 * index + (1 if right else 0)


def branch_indices(index: int) -> list[int]:
    """The sibling path from a node up to (not including) the root --
    the generalized indices a single proof carries, leaf-to-root order."""
    out = []
    while index > 1:
        out.append(generalized_index_sibling(index))
        index //= 2
    return out


def multiproof_helper_indices(indices: list[int]) -> list[int]:
    """get_helper_indices from the consensus spec: all nodes needed to
    reconstruct the root that are not derivable from `indices`
    themselves, sorted descending (the spec's canonical order)."""
    all_helpers: set[int] = set()
    all_path: set[int] = set()
    for index in indices:
        i = index
        while i > 1:
            all_helpers.add(generalized_index_sibling(i))
            all_path.add(i)
            i //= 2
    return sorted(
        (i for i in all_helpers if i not in all_path), reverse=True
    )


def verify_merkle_proof(
    leaf: bytes, branch: list[bytes], index: int, root: bytes
) -> bool:
    """Single proof: fold the branch from the leaf up (reference
    merkle_proof/src/lib.rs verify_merkle_proof)."""
    return calculate_merkle_root(leaf, branch, index) == bytes(root)


def calculate_merkle_root(leaf: bytes, branch: list[bytes], index: int) -> bytes:
    depth = generalized_index_depth(index)
    if len(branch) != depth:
        raise MerkleProofError(
            f"branch length {len(branch)} != index depth {depth}"
        )
    node = bytes(leaf)
    i = index
    for sibling in branch:
        if i % 2:
            node = hash_concat(bytes(sibling), node)
        else:
            node = hash_concat(node, bytes(sibling))
        i //= 2
    return node


def verify_merkle_multiproof(
    leaves: list[bytes],
    proof: list[bytes],
    indices: list[int],
    root: bytes,
) -> bool:
    """Multiproof: `proof` holds the helper nodes in
    multiproof_helper_indices(indices) order (spec
    calculate_multi_merkle_root)."""
    helper_indices = multiproof_helper_indices(indices)
    if len(proof) != len(helper_indices):
        raise MerkleProofError("proof length != helper count")
    if len(leaves) != len(indices):
        raise MerkleProofError("leaves length != indices length")
    objects = {
        **{gi: bytes(leaf) for gi, leaf in zip(indices, leaves)},
        **{gi: bytes(node) for gi, node in zip(helper_indices, proof)},
    }
    keys = sorted(objects.keys(), reverse=True)
    pos = 0
    while pos < len(keys):
        k = keys[pos]
        if (
            k in objects
            and k ^ 1 in objects
            and k // 2 not in objects
        ):
            objects[k // 2] = hash_concat(
                objects[k & ~1], objects[k | 1]
            )
            keys.append(k // 2)
        pos += 1
    if 1 not in objects:
        raise MerkleProofError("multiproof does not reach the root")
    return objects[1] == bytes(root)


class MerkleTree:
    """Full padded binary tree over leaf chunks (the shape ssz/hash.py's
    merkleize produces): proof GENERATION for anything this repo
    merkleizes. Padding leaves are zero-hash subtrees, so trees with a
    `limit` (SSZ lists) prove correctly without materializing the limit."""

    def __init__(self, chunks: list[bytes], limit: int | None = None):
        n = max(len(chunks), 1)
        width = limit if limit is not None else n
        if width < len(chunks):
            raise MerkleProofError("more chunks than the limit allows")
        self.depth = max(width - 1, 0).bit_length()
        self.chunks = [bytes(c) for c in chunks]
        # levels[0] = leaves (padded virtually); levels[d] = root level
        # stored sparsely: only nodes covering real data; zero-subtree
        # roots come from ZERO_HASHES
        self.levels: list[list[bytes]] = [list(self.chunks)]
        for d in range(self.depth):
            prev = self.levels[d]
            nxt = []
            for i in range(0, len(prev), 2):
                left = prev[i]
                right = (
                    prev[i + 1] if i + 1 < len(prev) else ZERO_HASHES[d]
                )
                nxt.append(hash_concat(left, right))
            self.levels.append(nxt)

    @property
    def root(self) -> bytes:
        if not self.levels[-1]:
            return ZERO_HASHES[self.depth]
        return self.levels[-1][0]

    def _node(self, level: int, idx: int) -> bytes:
        row = self.levels[level]
        if idx < len(row):
            return row[idx]
        return ZERO_HASHES[level]

    def generalized_index_of_chunk(self, chunk_index: int) -> int:
        return (1 << self.depth) + chunk_index

    def proof(self, chunk_index: int) -> list[bytes]:
        """Single-proof branch for a leaf, leaf-to-root order."""
        if chunk_index >= (1 << self.depth):
            raise MerkleProofError("chunk index beyond tree width")
        out = []
        idx = chunk_index
        for level in range(self.depth):
            out.append(self._node(level, idx ^ 1))
            idx //= 2
        return out

    def multiproof(self, chunk_indices: list[int]) -> list[bytes]:
        """Helper nodes for a set of leaves, in spec helper order."""
        indices = [self.generalized_index_of_chunk(c) for c in chunk_indices]
        helpers = multiproof_helper_indices(indices)
        out = []
        for gi in helpers:
            level = self.depth - generalized_index_depth(gi)
            idx = gi - (1 << generalized_index_depth(gi))
            out.append(self._node(level, idx))
        return out
