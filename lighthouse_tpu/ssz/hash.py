"""SSZ merkleization primitives (reference: consensus/tree_hash +
crypto/eth2_hashing).

SHA-256 comes from hashlib (OpenSSL's assembly paths -- the same class of
backend the reference selects at runtime in eth2_hashing/src/lib.rs:1-28).
The zero-subtree cache mirrors eth2_hashing's zero-hash feature. Host-side
by design: Merkleization of consensus objects is latency-sensitive small
work; batched Pallas SHA-256 for bulk tree rebuilds is a later optimization
stage (SURVEY.md section 7 phase 0 note).
"""

from __future__ import annotations

import hashlib

BYTES_PER_CHUNK = 32
ZERO_CHUNK = bytes(BYTES_PER_CHUNK)

MAX_TREE_DEPTH = 64

# ZERO_HASHES[i] = root of a depth-i tree of zero chunks
ZERO_HASHES: list[bytes] = [ZERO_CHUNK]
for _ in range(MAX_TREE_DEPTH):
    ZERO_HASHES.append(
        hashlib.sha256(ZERO_HASHES[-1] + ZERO_HASHES[-1]).digest()
    )


def hash_concat(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def merkleize(chunks: list[bytes], limit: int | None = None) -> bytes:
    """Root of the padded Merkle tree over 32-byte chunks.

    `limit` (chunk capacity) fixes the tree depth for list types; None
    means pad to the next power of two of len(chunks)."""
    count = len(chunks)
    if limit is not None and count > limit:
        raise ValueError(f"too many chunks: {count} > {limit}")
    width = _next_pow2(limit if limit is not None else max(count, 1))
    depth = width.bit_length() - 1

    layer = list(chunks)
    for d in range(depth):
        if len(layer) % 2 == 1:
            layer.append(ZERO_HASHES[d])
        layer = [
            hash_concat(layer[i], layer[i + 1]) for i in range(0, len(layer), 2)
        ]
        if not layer:
            layer = []
    if not layer:
        return ZERO_HASHES[depth]
    return layer[0]


def mix_in_length(root: bytes, length: int) -> bytes:
    return hash_concat(root, length.to_bytes(32, "little"))


def pack_bytes(data: bytes) -> list[bytes]:
    """Right-pad to a whole number of 32-byte chunks."""
    if not data:
        return []
    pad = (-len(data)) % BYTES_PER_CHUNK
    data = data + bytes(pad)
    return [
        data[i : i + BYTES_PER_CHUNK]
        for i in range(0, len(data), BYTES_PER_CHUNK)
    ]
