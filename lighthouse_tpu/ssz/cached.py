"""Incremental (cached) tree hashing.

Python equivalent of the reference's `consensus/cached_tree_hash` crate
(cached_tree_hash/src/lib.rs): instead of re-merkleizing every field of a
large container (the `BeaconState` hot case) on every root request, keep
the previous merkle layers per field and re-hash only the paths whose leaf
chunks changed. The reference stores one arena-backed `TreeHashCache` per
multi-leaf field (cached_tree_hash/src/v2.rs style multi-cache over
validators/balances/roots vectors); here each such field gets a
`ChunkTreeCache`, and composite list elements (validators) get a
content-keyed root memo shared process-wide so cloned states re-use work.

Safety model (why content keys, not object identity): the state-transition
code mutates element containers in place *and then replaces the outer
tuple* (e.g. per_epoch.py effective-balance updates). The outer-tuple
identity is therefore a reliable "unchanged" signal, while element
identity is not — so unchanged fields are skipped by tuple identity, and
changed composite elements are keyed by their field *contents*.
"""

from __future__ import annotations

import struct

from .hash import ZERO_HASHES, hash_concat, merkleize, mix_in_length, pack_bytes
from .types import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    SszType,
    Vector,
    _Boolean,
    _UInt,
)

BYTES_PER_CHUNK = 32


def _is_basic(t: SszType) -> bool:
    return isinstance(t, (_UInt, _Boolean))


def _ceil_log2(n: int) -> int:
    return max(n - 1, 0).bit_length()


class ChunkTreeCache:
    """Incremental merkleization of a bounded chunk list.

    Equivalent contract to `merkleize(chunks, limit)` in hash.py, but
    `update()` diffs the new chunk list against the previous one and
    re-hashes only dirty parent paths. Layers store the occupied prefix
    only; absent right siblings are the standard zero-subtree hashes.
    """

    def __init__(self, limit_chunks: int | None):
        # None = Vector semantics: width fixed by the first update.
        self.limit = limit_chunks
        self.depth = None if limit_chunks is None else _ceil_log2(limit_chunks)
        self.layers: list[list[bytes]] | None = None

    def _full_build(self, chunks: list[bytes]) -> bytes:
        depth = self.depth
        if depth is None:
            depth = _ceil_log2(max(len(chunks), 1))
            self.depth = depth
        layers = [list(chunks)]
        for d in range(depth):
            prev = layers[-1]
            nxt = []
            for i in range(0, len(prev), 2):
                right = prev[i + 1] if i + 1 < len(prev) else ZERO_HASHES[d]
                nxt.append(hash_concat(prev[i], right))
            layers.append(nxt)
        self.layers = layers
        return layers[depth][0] if layers[depth] else ZERO_HASHES[depth]

    def update(self, chunks: list[bytes]) -> bytes:
        if self.limit is not None and len(chunks) > self.limit:
            raise ValueError(f"too many chunks: {len(chunks)} > {self.limit}")
        if self.layers is None:
            return self._full_build(chunks)
        depth = self.depth
        old = self.layers[0]
        n, m = len(chunks), len(old)
        common = min(n, m)
        dirty = {i for i in range(common) if chunks[i] is not old[i] and chunks[i] != old[i]}
        dirty.update(range(common, max(n, m)))
        if not dirty:
            top = self.layers[depth]
            return top[0] if top else ZERO_HASHES[depth]
        self.layers[0] = list(chunks)
        level = {i // 2 for i in dirty}
        for d in range(depth):
            prev = self.layers[d]
            cur = self.layers[d + 1]
            width = (len(prev) + 1) // 2
            del cur[width:]
            while len(cur) < width:
                cur.append(b"")
            for i in level:
                if i < width:
                    right = (
                        prev[2 * i + 1]
                        if 2 * i + 1 < len(prev)
                        else ZERO_HASHES[d]
                    )
                    cur[i] = hash_concat(prev[2 * i], right)
            # propagate even indices >= width: that subtree vanished on a
            # shrink, so its ancestor still needs re-deriving with a zero
            # right sibling at the level where it re-enters the width
            level = {i // 2 for i in level}
        top = self.layers[depth]
        return top[0] if top else ZERO_HASHES[depth]


# Process-wide memo: root of a composite element keyed by its contents.
# Bounded; cleared wholesale when it grows past the cap (validators change
# rarely, so steady-state hit rate stays high even across clears).
_COMPOSITE_MEMO: dict = {}
_COMPOSITE_MEMO_CAP = 1 << 20


def _flat_field_names(desc: Container):
    """Field names if every field is basic or fixed bytes (content key can
    be the raw attribute tuple); None if the container nests composites."""
    names = []
    for name, t in desc.fields:
        if not (_is_basic(t) or isinstance(t, ByteVector)):
            return None
        names.append(name)
    return tuple(names)


def _composite_root(t: SszType, value) -> bytes:
    """Root of one composite list element, via the content-keyed memo."""
    if isinstance(t, Container):
        flat = t.__dict__.get("_flat_names", False)
        if flat is False:
            flat = t._flat_names = _flat_field_names(t)
        if flat is not None:
            key = (id(t), tuple(getattr(value, n) for n in flat))
        else:
            key = (id(t), t.encode(value))
        root = _COMPOSITE_MEMO.get(key)
        if root is None:
            if len(_COMPOSITE_MEMO) >= _COMPOSITE_MEMO_CAP:
                _COMPOSITE_MEMO.clear()
            root = _COMPOSITE_MEMO[key] = t.hash_tree_root(value)
        return root
    return t.hash_tree_root(value)


_UINT_PACK = {}
_UINT_FMT = {1: "B", 2: "H", 4: "I", 8: "Q"}


def _basic_chunks(elem: SszType, items) -> list[bytes]:
    """pack_bytes of the encoded items, with struct fast paths for all
    uint widths (uint8 participation lists and uint64 balances/scores are
    the 500k-element hot fields; per-element encode() calls dominate the
    steady-state root otherwise)."""
    if isinstance(elem, _UInt) and elem.byte_len in _UINT_FMT:
        if elem.byte_len == 1:
            data = bytes(items)
        else:
            n = len(items)
            key = (n, elem.byte_len)
            fmt = _UINT_PACK.get(key)
            if fmt is None:
                fmt = _UINT_PACK[key] = struct.Struct(
                    f"<{n}{_UINT_FMT[elem.byte_len]}"
                )
            data = fmt.pack(*items)
    else:
        data = b"".join(elem.encode(v) for v in items)
    return pack_bytes(data)


class _FieldCache:
    __slots__ = ("ref", "root", "tree")

    def __init__(self):
        self.ref = None
        self.root = None
        self.tree = None


class CachedRoot:
    """Incremental hash_tree_root for one container *instance*.

    One per tracked object (attach via `cached_root(obj)`); re-uses
    per-field merkle trees across calls. Correct regardless of how fields
    were mutated: unchanged-ness is decided by outer-value identity only
    where the value is immutable by construction (tuples of ints/bytes,
    bytes), and by content comparison everywhere else.
    """

    def __init__(self, desc: Container):
        self.desc = desc
        self.fields = {name: _FieldCache() for name, _ in desc.fields}

    def root(self, value) -> bytes:
        roots = [
            self._field_root(name, t, getattr(value, name))
            for name, t in self.desc.fields
        ]
        return merkleize(roots)

    def _field_root(self, name: str, t: SszType, v) -> bytes:
        fc = self.fields[name]
        if isinstance(t, (List, Vector)):
            elem = t.elem
            if _is_basic(elem) or isinstance(elem, ByteVector):
                # immutable element contents: outer-tuple identity is sound
                if fc.ref is v and fc.root is not None:
                    return fc.root
                if _is_basic(elem):
                    chunks = _basic_chunks(elem, v)
                    if isinstance(t, List):
                        per = BYTES_PER_CHUNK // elem.fixed_size()
                        limit = (t.limit + per - 1) // per
                    else:
                        limit = None
                else:
                    chunks = [elem.hash_tree_root(x) for x in v]
                    limit = t.limit if isinstance(t, List) else None
                if fc.tree is None:
                    fc.tree = ChunkTreeCache(limit)
                root = fc.tree.update(chunks)
                if isinstance(t, List):
                    root = mix_in_length(root, len(v))
                fc.ref, fc.root = v, root
                return root
            # composite elements (validators &c): content-keyed elem roots,
            # incremental tree over them. The outer-tuple identity shortcut
            # leans on the state-transition convention that in-place element
            # mutation is ALWAYS followed by re-tupling the field (every
            # mutation site in per_block/per_epoch does `vals = list(...)`,
            # mutate, `state.validators = tuple(vals)`); a same-identity
            # tuple therefore has unchanged contents.
            if fc.ref is v and fc.root is not None:
                return fc.root
            leaf_roots = [_composite_root(elem, x) for x in v]
            if fc.tree is None:
                fc.tree = ChunkTreeCache(t.limit if isinstance(t, List) else None)
            root = fc.tree.update(leaf_roots)
            if isinstance(t, List):
                root = mix_in_length(root, len(v))
            fc.ref, fc.root = v, root
            return root
        if isinstance(t, (ByteVector, ByteList, Bitvector, Bitlist)):
            if fc.ref is v and fc.root is not None:
                return fc.root  # bytes/tuple-of-bool values are immutable
            root = t.hash_tree_root(v)
            fc.ref, fc.root = v, root
            return root
        if isinstance(t, Container):
            return _composite_root(t, v)
        return t.hash_tree_root(v)  # basics: trivial


def cached_root(obj) -> bytes:
    """hash_tree_root(obj) through a per-instance incremental cache.

    The cache rides on the instance (`_lh_tree_cache`); a freshly cloned
    state pays one full build, then every subsequent call is proportional
    to what changed. Falls back to the plain root for non-@container
    values.
    """
    desc = getattr(obj, "ssz_type", None)
    if not isinstance(desc, Container):
        return obj.tree_hash_root()
    cache = obj.__dict__.get("_lh_tree_cache")
    if cache is None or cache.desc is not desc:
        cache = CachedRoot(desc)
        obj.__dict__["_lh_tree_cache"] = cache
    return cache.root(obj)


def surgical_list_update(
    obj, field_name: str, old_value, new_value, changed_indices
) -> None:
    """Install `new_value` into obj.<field_name> and update the instance
    tree cache leaf-wise: only `changed_indices` get their element roots
    recomputed (epoch processing touches a handful of 500k validators; a
    full memo pass per boundary is the dominant steady-state hash cost).

    Sound only when the cache's previous leaf layer corresponds to
    `old_value` element-for-element and `new_value` differs from it at
    exactly `changed_indices` (same length). When any precondition fails
    this degrades to plain assignment — the next cached_root recomputes
    the field in full, which is always correct."""
    setattr(obj, field_name, new_value)
    cache = obj.__dict__.get("_lh_tree_cache")
    if cache is None:
        return
    fc = cache.fields.get(field_name)
    if (
        fc is None
        or fc.tree is None
        or fc.tree.layers is None
        or fc.ref is not old_value
        or len(fc.tree.layers[0]) != len(new_value)
    ):
        if fc is not None:
            fc.ref = None  # force a full field recompute on the next root
        return
    t = next(ft for fn, ft in cache.desc.fields if fn == field_name)
    chunks = list(fc.tree.layers[0])
    for i in changed_indices:
        chunks[i] = _composite_root(t.elem, new_value[i])
    root = fc.tree.update(chunks)
    if isinstance(t, List):
        root = mix_in_length(root, len(new_value))
    fc.ref, fc.root = new_value, root


def cached_field_roots(obj) -> list[bytes]:
    """Per-field roots through the same per-instance incremental cache as
    cached_root (merkle-proof generation needs the field layer; computing
    it fresh would re-merkleize the whole state per proof)."""
    desc = getattr(obj, "ssz_type", None)
    if not isinstance(desc, Container):
        raise TypeError("cached_field_roots needs an @container instance")
    cache = obj.__dict__.get("_lh_tree_cache")
    if cache is None or cache.desc is not desc:
        cache = CachedRoot(desc)
        obj.__dict__["_lh_tree_cache"] = cache
    return [
        cache._field_root(name, t, getattr(obj, name))
        for name, t in desc.fields
    ]
