"""SSZ: SimpleSerialize encode/decode + hash-tree-root.

TPU-framework equivalent of the reference crates consensus/ssz,
consensus/ssz_derive, consensus/ssz_types, consensus/tree_hash (see
SURVEY.md section 2.2). The `@container` decorator plays the role of the
derive macros; ssz_types' FixedVector/VariableList/Bitfield map to
Vector/List/Bitvector/Bitlist descriptors.
"""

from .hash import (  # noqa: F401
    BYTES_PER_CHUNK,
    ZERO_HASHES,
    hash_concat,
    merkleize,
    mix_in_length,
    pack_bytes,
)
from .cached import (  # noqa: F401
    CachedRoot,
    ChunkTreeCache,
    cached_field_roots,
    cached_root,
)
from .types import (  # noqa: F401
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Bytes4,
    Bytes20,
    Bytes32,
    Bytes48,
    Bytes96,
    Container,
    List,
    SszError,
    SszType,
    Vector,
    boolean,
    container,
    uint8,
    uint16,
    uint32,
    uint64,
    uint128,
    uint256,
)
