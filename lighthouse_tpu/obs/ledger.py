"""Launch ledger: a deterministic per-launch flight recorder.

The span tracer (utils/tracing.py) answers "where did this attestation's
latency go?"; the metric families answer "how much of X happened?". What
neither answers is the question the hardware campaign stalls on: for
EVERY device program launch, how full was it, how much padding did the
warm-bucket contract cost, and what compile tax did its shape family
pay? Those are record-level facts -- occupancy vs pad-waste per launch
is the continuous-batching tuning knob (ROADMAP) and scattered counters
(`bls_sched_*`, `tpu_compile_cache_*`) cannot reconstruct it after the
fact.

This module is that record layer. Each seam that launches a device
program appends one :class:`LaunchRecord`:

  * ``"pipeline"`` -- a VerifyPipeline batch dispatch (crypto/bls/
    pipeline.py), real set count vs the padded capacity it was asked
    to take;
  * ``"sched"`` -- a continuous-batching merged launch (crypto/bls/
    scheduler.py), carrying the admission audit: lane mix, per-lane set
    counts, the deadline slot, and the ``speculative_withheld`` /
    ``real_queued_before`` preemption facts the launch_log used to keep
    private;
  * ``"dispatch"`` -- a jax_tpu backend dispatch (backends/jax_tpu.py),
    bucketed shape, distinct-message count, Miller-pair count, and the
    compile-cache hit/miss verdict of its shape family;
  * ``"mesh"`` -- a sharded mesh launch (parallel/verify_sharded.py),
    participating device count + the per-chip batch wall;
  * ``"warm"`` -- one warm-compile bucket (the AOT pass), its shape
    family and JIT seconds.

Records land in a bounded ring (overflow drops the OLDEST, counted),
timestamps come from the PROCESS tracer's injected clock and trace/span
ids from the ambient span context -- so a seeded scenario replay exports
a byte-identical ledger dump exactly like it exports a byte-identical
trace (``assert_bit_identical_replay`` asserts both). The only
non-deterministic fields are measured device seconds (``chip_seconds``
on the mesh path, ``compile_seconds`` on the warm pass), which never
occur in replayed scenario runs.

Derived stats are PURE functions of a record list
(:func:`stats_from_records`): occupancy per kind, pad-waste per bucket,
launches-per-slot, compile-tax seconds per shape family, per-lane
launch share. One formatter (:func:`format_report`) renders them for
``cli ledger --report``, ``tools/ledger_report.py``, and the
``/lighthouse/ledger/report`` route -- one code path, three surfaces.

Export seats mirror the tracer's: ``/lighthouse/ledger/{status,dump,
report}``, ``python -m lighthouse_tpu.cli ledger``, Chrome counter
events ("C" phase) merged into bench's ``.bench_trace.json`` so
occupancy draws as a Perfetto counter track next to the spans, and
``bench.py --latency/--profile`` JSON ``ledger`` blocks.

``LIGHTHOUSE_TPU_LEDGER=0`` disables recording (the seams early-out);
``LIGHTHOUSE_TPU_LEDGER_CAPACITY`` sizes the ring (default 4096).
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque

_CHROME_CAT = "lighthouse"

#: the seam kinds, in the order a merged launch flows through them. A
#: single scheduler launch produces one record PER seam it crosses
#: ("sched" -> "pipeline" -> "dispatch" [-> "mesh"]), so derived stats
#: always group by kind and never sum across kinds.
KINDS = ("pipeline", "sched", "dispatch", "mesh", "warm")

_FIELDS = (
    "seq", "ts", "kind", "bucket", "real_sets", "padded_sets", "entries",
    "lanes", "lane_sets", "slot", "n_messages", "miller_pairs",
    "cache_hit", "compile_seconds", "chip_seconds", "devices",
    "speculative_withheld", "real_queued_before", "trace_id", "span_id",
)


class LaunchRecord:
    """One device program launch. Fields a seam cannot know are None
    (e.g. the pipeline does not know the Miller-pair count; the mesh
    does not know the lane mix)."""

    __slots__ = _FIELDS

    def __init__(self, seq, ts, kind, **fields):
        self.seq = seq
        self.ts = ts
        self.kind = kind
        for name in _FIELDS[3:]:
            setattr(self, name, fields.pop(name, None))
        if fields:
            raise TypeError(f"unknown LaunchRecord fields: {sorted(fields)}")

    def to_dict(self) -> dict:
        d = {name: getattr(self, name) for name in _FIELDS}
        # ids render like the chrome-trace export (16-hex) so a dump
        # cross-links into a trace dump of the same run by string match
        for key in ("trace_id", "span_id"):
            if d[key] is not None:
                d[key] = f"{d[key]:016x}"
        if d["lanes"] is not None:
            d["lanes"] = list(d["lanes"])
        return d


class Ledger:
    """Bounded, drop-counted launch ring.

    ``clock`` defaults to reading the PROCESS tracer's injected clock at
    every record, so scenario/bench clock injection covers the ledger
    with no extra wiring. ``Ledger._lock`` is a LEAF lock (LOCK_ORDER):
    seams record while holding scheduler/launch locks, so nothing --
    no clock read, no tracer call, no metric -- happens inside it.
    """

    def __init__(self, clock=None, capacity: int | None = None,
                 enabled: bool = True):
        if capacity is None:
            capacity = _default_capacity()
        self._clock = clock
        self.capacity = int(capacity)
        self.enabled = enabled
        self._records: deque[LaunchRecord] = deque(maxlen=self.capacity)
        self._next_seq = 0
        self.dropped = 0
        self._lock = threading.Lock()

    def _now(self) -> float:
        if self._clock is not None:
            return self._clock.now()
        from ..utils import tracing

        return tracing.default_tracer().clock.now()

    def record(self, kind: str, **fields) -> LaunchRecord | None:
        if not self.enabled:
            return None
        if kind not in KINDS:
            raise ValueError(f"unknown ledger kind: {kind!r}")
        from ..utils import tracing

        # clock + ambient span context are read BEFORE the leaf lock:
        # the tracer has its own lock and the clocks have theirs
        ts = self._now()
        ctx = tracing.current()
        if ctx is not None:
            fields.setdefault("trace_id", ctx.trace_id)
            fields.setdefault("span_id", ctx.span_id)
        with self._lock:
            rec = LaunchRecord(self._next_seq, ts, kind, **fields)
            self._next_seq += 1
            if len(self._records) == self._records.maxlen:
                self.dropped += 1  # overflow sheds the OLDEST record
            self._records.append(rec)
            return rec

    # -- reads ---------------------------------------------------------------

    def records(self) -> list[LaunchRecord]:
        with self._lock:
            return list(self._records)

    def status(self) -> dict:
        with self._lock:
            kinds: dict[str, int] = {}
            for r in self._records:
                kinds[r.kind] = kinds.get(r.kind, 0) + 1
            return {
                "enabled": self.enabled,
                "capacity": self.capacity,
                "recorded": len(self._records),
                "dropped": self.dropped,
                "kinds": kinds,
            }

    def stats(self, window_s: float | None = None) -> dict:
        recs = self.records()
        if window_s is not None and recs:
            horizon = recs[-1].ts - float(window_s)
            recs = [r for r in recs if r.ts >= horizon]
        return stats_from_records(recs, dropped=self.dropped)

    def report_text(self) -> str:
        return format_report(self.stats())

    # -- export --------------------------------------------------------------

    def dump(self) -> dict:
        with self._lock:
            recs = list(self._records)
        recs.sort(key=lambda r: (r.ts, r.seq))
        return {
            "capacity": self.capacity,
            "dropped": self.dropped,
            "records": [r.to_dict() for r in recs],
        }

    def dump_json(self) -> str:
        """Sorted-keys JSON of the whole ring: the byte-comparable
        replay surface (`assert_bit_identical_replay`)."""
        return json.dumps(self.dump(), sort_keys=True)

    def chrome_counter_events(self) -> list[dict]:
        """Chrome trace "C" counter events, one track per kind: the
        real/pad split of every launch, mergeable into a span dump's
        `traceEvents` so Perfetto draws occupancy next to the spans."""
        events = []
        for r in sorted(self.records(), key=lambda r: (r.ts, r.seq)):
            if r.real_sets is None and r.padded_sets is None:
                continue
            real = r.real_sets or 0
            padded = r.padded_sets if r.padded_sets is not None else real
            events.append({
                "name": f"ledger/{r.kind}",
                "cat": _CHROME_CAT,
                "ph": "C",
                "ts": round(r.ts * 1e6, 3),
                "pid": 1,
                "args": {"real": real, "pad": max(0, padded - real)},
            })
        return events

    def reset(self) -> None:
        """Clear the ring; seq keeps counting (a reset mid-run must not
        replay old sequence numbers), mirroring Tracer.reset."""
        with self._lock:
            self._records.clear()
            self.dropped = 0


# -- derived stats (pure: a record list in, a stats dict out) -----------------


def _as_dict(rec) -> dict:
    return rec if isinstance(rec, dict) else rec.to_dict()


def stats_from_records(records, dropped: int = 0) -> dict:
    """Rolling-window stats over `records` (LaunchRecords or dump
    dicts). Grouped BY KIND throughout: one merged launch crosses
    several seams, so summing across kinds would double-count it."""
    recs = [_as_dict(r) for r in records]
    occupancy: dict[str, dict] = {}
    for r in recs:
        if r["real_sets"] is None and r["padded_sets"] is None:
            continue
        real = r["real_sets"] or 0
        padded = r["padded_sets"] if r["padded_sets"] is not None else real
        o = occupancy.setdefault(
            r["kind"], {"launches": 0, "real": 0, "padded": 0}
        )
        o["launches"] += 1
        o["real"] += real
        o["padded"] += padded
    for o in occupancy.values():
        o["ratio"] = round(o["real"] / o["padded"], 4) if o["padded"] else 0.0

    # pad-waste per bucket from the most upstream kind present: the
    # scheduler chose the padding, so its records are authoritative;
    # without a scheduler the backend's bucketing is the padding source
    waste_kind = next(
        (k for k in ("sched", "dispatch", "pipeline") if k in occupancy),
        None,
    )
    pad_waste: dict[str, dict] = {}
    for r in recs:
        if r["kind"] != waste_kind or r["bucket"] is None:
            continue
        real = r["real_sets"] or 0
        padded = r["padded_sets"] if r["padded_sets"] is not None else real
        b = pad_waste.setdefault(
            str(r["bucket"]), {"launches": 0, "real": 0, "padded": 0}
        )
        b["launches"] += 1
        b["real"] += real
        b["padded"] += padded
    for b in pad_waste.values():
        b["waste_ratio"] = (
            round((b["padded"] - b["real"]) / b["padded"], 4)
            if b["padded"] else 0.0
        )

    launch_kind = "sched" if "sched" in occupancy else waste_kind
    slots = sorted({
        r["slot"] for r in recs
        if r["kind"] == launch_kind and r["slot"] is not None
    })
    slot_launches = sum(
        1 for r in recs
        if r["kind"] == launch_kind and r["slot"] is not None
    )
    launches_per_slot = {
        "slots": len(slots),
        "launches": slot_launches,
        "mean": round(slot_launches / len(slots), 4) if slots else 0.0,
    }

    per_shape: dict[str, float] = {}
    for r in recs:
        if r["kind"] == "warm" and r["compile_seconds"] is not None:
            key = str(r["bucket"])
            per_shape[key] = round(
                per_shape.get(key, 0.0) + r["compile_seconds"], 6
            )
    compile_tax = {
        "per_shape_s": per_shape,
        "total_s": round(sum(per_shape.values()), 6),
        # dispatches whose shape family was COLD (an XLA compile on the
        # hot path -- the zero-JIT contract's violation counter)
        "cold_dispatches": sum(
            1 for r in recs
            if r["kind"] == "dispatch" and r["cache_hit"] is False
        ),
    }

    lane_sets: dict[str, int] = {}
    for r in recs:
        if r["kind"] == "sched" and r["lane_sets"]:
            for lane, n in r["lane_sets"].items():
                lane_sets[lane] = lane_sets.get(lane, 0) + int(n)
    total_lane = sum(lane_sets.values())
    lane_share = {
        lane: round(n / total_lane, 4)
        for lane, n in sorted(lane_sets.items())
    } if total_lane else {}

    return {
        "records": len(recs),
        "dropped": int(dropped),
        "occupancy": occupancy,
        "pad_waste_per_bucket": pad_waste,
        "pad_waste_kind": waste_kind,
        "launches_per_slot": launches_per_slot,
        "compile_tax_s": compile_tax,
        "lane_share": lane_share,
        "speculative_withheld_total": sum(
            r["speculative_withheld"] or 0
            for r in recs if r["kind"] == "sched"
        ),
    }


def format_report(stats: dict, lanes: dict | None = None) -> str:
    """The occupancy / pad-waste / compile-tax table. `lanes` is an
    optional per-lane p50/p95 block in the `bench.py --latency` shape
    ({lane: {"p50_ms": ..., "p95_ms": ...}}); one renderer serves
    `cli ledger --report`, tools/ledger_report.py, and the HTTP report
    route."""
    lines = [
        f"launch ledger: {stats['records']} records"
        f" ({stats['dropped']} dropped)",
        "",
        f"{'kind':<10}{'launches':>9}{'real':>8}{'padded':>8}{'occupancy':>11}",
    ]
    for kind in KINDS:
        o = stats["occupancy"].get(kind)
        if o is None:
            continue
        lines.append(
            f"{kind:<10}{o['launches']:>9}{o['real']:>8}"
            f"{o['padded']:>8}{o['ratio']:>11.4f}"
        )
    lines += [
        "",
        f"pad waste per bucket ({stats.get('pad_waste_kind')} launches):",
        f"{'bucket':<10}{'launches':>9}{'real':>8}{'padded':>8}{'waste':>9}",
    ]
    for bucket, b in sorted(
        stats["pad_waste_per_bucket"].items(),
        key=lambda kv: (len(kv[0]), kv[0]),
    ):
        lines.append(
            f"{bucket:<10}{b['launches']:>9}{b['real']:>8}"
            f"{b['padded']:>8}{b['waste_ratio']:>9.4f}"
        )
    lps = stats["launches_per_slot"]
    lines += [
        "",
        f"launches/slot: {lps['mean']}"
        f" ({lps['launches']} launches over {lps['slots']} slots)",
        "",
        f"compile tax: {stats['compile_tax_s']['total_s']}s warm,"
        f" {stats['compile_tax_s']['cold_dispatches']} cold dispatches",
    ]
    for shape, s in sorted(stats["compile_tax_s"]["per_shape_s"].items()):
        lines.append(f"  {shape:<16}{s:>10.4f}s")
    if stats["lane_share"]:
        lines.append("")
        lines.append("lane share (real sets per merged launch):")
        for lane, share in stats["lane_share"].items():
            lines.append(f"  {lane:<14}{share:>8.4f}")
    if stats.get("speculative_withheld_total"):
        lines.append(
            "speculation withheld at real launches: "
            f"{stats['speculative_withheld_total']}"
        )
    if lanes:
        lines += [
            "",
            "per-lane time-to-verdict:",
            f"{'lane':<14}{'p50_ms':>9}{'p95_ms':>9}",
        ]
        for lane, row in sorted(lanes.items()):
            p50 = row.get("p50_ms")
            p95 = row.get("p95_ms")
            if p50 is None and p95 is None:
                continue
            lines.append(f"{lane:<14}{p50:>9}{p95:>9}")
    return "\n".join(lines)


# -- module-level default (the seat the seams consult) ------------------------


def _default_capacity() -> int:
    try:
        return int(os.environ.get("LIGHTHOUSE_TPU_LEDGER_CAPACITY", "4096"))
    except ValueError:
        return 4096


def enabled() -> bool:
    """The ledger records unless explicitly disabled
    (`LIGHTHOUSE_TPU_LEDGER=0`); read per call so operators and tests
    flip it without reimport."""
    return os.environ.get("LIGHTHOUSE_TPU_LEDGER", "1") != "0"


_DEFAULT: Ledger | None = None


def default_ledger() -> Ledger:
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = Ledger()
    return _DEFAULT


def configure(**kwargs) -> Ledger:
    """Replace the process ledger (scenario runs / benches inject
    clock/capacity here, mirroring tracing.configure)."""
    global _DEFAULT
    _DEFAULT = Ledger(**kwargs)
    return _DEFAULT


def record(kind: str, **fields) -> None:
    """The seam entry point: append one launch record to the CURRENT
    default ledger (looked up per call, so configure() swaps apply
    mid-process); no-op when disabled."""
    if not enabled():
        return
    default_ledger().record(kind, **fields)
