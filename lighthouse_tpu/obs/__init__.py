"""Observability subsystems that sit NEXT to the span tracer: record-
level telemetry with the same determinism contract (injected clock +
rng -> byte-identical replay exports)."""

from . import ledger  # noqa: F401

__all__ = ["ledger"]
