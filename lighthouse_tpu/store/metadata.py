"""Database schema metadata + migrations (reference
beacon_node/store/src/metadata.rs CURRENT_SCHEMA_VERSION/SchemaVersion
and beacon_chain/src/schema_change.rs migrate_schema).

The on-disk schema carries a version stamp in the chain column. On open:

- a fresh database is stamped with the current version;
- an up-to-date database passes through;
- an OLDER database runs the registered per-step migrations in order.
  Each step builds its rewrite as a single op list and commits it in ONE
  atomic batch TOGETHER WITH the new version stamp (mirroring
  schema_change.rs's per-version match arms over a leveldb write-batch):
  a crash anywhere inside a step either replays the whole step from the
  write-ahead journal on reopen or rolls it back entirely — the stamp
  can never run ahead of (or lag) the rewrite it describes;
- a NEWER database refuses to open (downgrades are not supported --
  metadata.rs returns SchemaVersionError and the reference node exits).

Schema history:
  v1 -- blocks stored as raw SSZ with the fork resolved from slot order
        (the pre-multi-fork layout).
  v2 -- blocks stored fork-prefixed (`<fork>\\x00<ssz>`), letting the
        store decode any-fork blocks without a spec lookup (the current
        layout, hot_cold.py put_block).
"""

from __future__ import annotations

from .kv import Column

CURRENT_SCHEMA_VERSION = 2
SCHEMA_VERSION_KEY = b"schema_version"

_KNOWN_FORKS = (b"phase0", b"altair", b"bellatrix")


class SchemaVersionError(RuntimeError):
    pass


def get_schema_version(kv) -> int | None:
    raw = kv.get(Column.CHAIN, SCHEMA_VERSION_KEY)
    return int.from_bytes(raw, "little") if raw is not None else None


def set_schema_version(kv, version: int) -> None:
    kv.put(Column.CHAIN, SCHEMA_VERSION_KEY, version.to_bytes(8, "little"))


def _migrate_v1_to_v2(kv, preset) -> list:
    """Fork-prefix every stored block. v1 rows hold bare SSZ; phase0 is
    the only fork that ever shipped v1 databases, so the prefix is
    constant -- the rewrite is idempotent (already-prefixed rows are
    left alone, making a crashed half-migration safe to re-run).

    Returns the rewrite as batch ops; ensure_schema commits them
    atomically together with the version stamp."""
    ops = []
    for column in (Column.BLOCK, Column.FREEZER_BLOCK):
        for key in list(kv.keys(column)):
            data = kv.get(column, key)
            if data is None or data.split(b"\x00", 1)[0] in _KNOWN_FORKS:
                continue  # already v2
            ops.append(("put", column, key, b"phase0\x00" + data))
    return ops


MIGRATIONS = {
    (1, 2): _migrate_v1_to_v2,
}


def ensure_schema(kv, preset) -> list:
    """Open-time check-and-migrate. Returns the list of applied steps
    (empty for fresh/up-to-date databases)."""
    version = get_schema_version(kv)
    if version is None:
        # fresh database: stamp through the journal like every other
        # open-path write — the crash matrix tears arbitrary ops, and a
        # half-written stamp must roll back, not read as a short int
        kv.do_atomically([
            ("put", Column.CHAIN, SCHEMA_VERSION_KEY,
             CURRENT_SCHEMA_VERSION.to_bytes(8, "little")),
        ])
        return []
    if version == CURRENT_SCHEMA_VERSION:
        return []
    if version > CURRENT_SCHEMA_VERSION:
        raise SchemaVersionError(
            f"database schema v{version} is newer than this build's "
            f"v{CURRENT_SCHEMA_VERSION}; downgrades are not supported"
        )
    applied = []
    while version < CURRENT_SCHEMA_VERSION:
        step = (version, version + 1)
        migration = MIGRATIONS.get(step)
        if migration is None:
            raise SchemaVersionError(
                f"no migration registered for schema v{step[0]} -> v{step[1]}"
            )
        ops = list(migration(kv, preset))
        version += 1
        # rewrite + version stamp commit as ONE atomic batch: a crash
        # between them is impossible at the logical level, and a crash
        # inside the batch replays or rolls back on reopen
        ops.append(
            ("put", Column.CHAIN, SCHEMA_VERSION_KEY,
             version.to_bytes(8, "little"))
        )
        kv.do_atomically(ops)
        applied.append(step)
    return applied
