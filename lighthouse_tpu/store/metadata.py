"""Database schema metadata + migrations (reference
beacon_node/store/src/metadata.rs CURRENT_SCHEMA_VERSION/SchemaVersion
and beacon_chain/src/schema_change.rs migrate_schema).

The on-disk schema carries a version stamp in the chain column. On open:

- a fresh database is stamped with the current version;
- an up-to-date database passes through;
- an OLDER database runs the registered per-step migrations in order
  (each step is atomic over the keys it rewrites, mirroring
  schema_change.rs's per-version match arms);
- a NEWER database refuses to open (downgrades are not supported --
  metadata.rs returns SchemaVersionError and the reference node exits).

Schema history:
  v1 -- blocks stored as raw SSZ with the fork resolved from slot order
        (the pre-multi-fork layout).
  v2 -- blocks stored fork-prefixed (`<fork>\\x00<ssz>`), letting the
        store decode any-fork blocks without a spec lookup (the current
        layout, hot_cold.py put_block).
"""

from __future__ import annotations

from .kv import Column

CURRENT_SCHEMA_VERSION = 2
SCHEMA_VERSION_KEY = b"schema_version"

_KNOWN_FORKS = (b"phase0", b"altair", b"bellatrix")


class SchemaVersionError(RuntimeError):
    pass


def get_schema_version(kv) -> int | None:
    raw = kv.get(Column.CHAIN, SCHEMA_VERSION_KEY)
    return int.from_bytes(raw, "little") if raw is not None else None


def set_schema_version(kv, version: int) -> None:
    kv.put(Column.CHAIN, SCHEMA_VERSION_KEY, version.to_bytes(8, "little"))


def _migrate_v1_to_v2(kv, preset) -> None:
    """Fork-prefix every stored block. v1 rows hold bare SSZ; phase0 is
    the only fork that ever shipped v1 databases, so the prefix is
    constant -- the rewrite is idempotent (already-prefixed rows are
    left alone, making a crashed half-migration safe to re-run)."""
    for column in (Column.BLOCK, Column.FREEZER_BLOCK):
        ops = []
        for key in list(kv.keys(column)):
            data = kv.get(column, key)
            if data is None or data.split(b"\x00", 1)[0] in _KNOWN_FORKS:
                continue  # already v2
            ops.append(("put", column, key, b"phase0\x00" + data))
        kv.do_atomically(ops)


MIGRATIONS = {
    (1, 2): _migrate_v1_to_v2,
}


def ensure_schema(kv, preset) -> list:
    """Open-time check-and-migrate. Returns the list of applied steps
    (empty for fresh/up-to-date databases)."""
    version = get_schema_version(kv)
    if version is None:
        set_schema_version(kv, CURRENT_SCHEMA_VERSION)
        return []
    if version == CURRENT_SCHEMA_VERSION:
        return []
    if version > CURRENT_SCHEMA_VERSION:
        raise SchemaVersionError(
            f"database schema v{version} is newer than this build's "
            f"v{CURRENT_SCHEMA_VERSION}; downgrades are not supported"
        )
    applied = []
    while version < CURRENT_SCHEMA_VERSION:
        step = (version, version + 1)
        migration = MIGRATIONS.get(step)
        if migration is None:
            raise SchemaVersionError(
                f"no migration registered for schema v{step[0]} -> v{step[1]}"
            )
        migration(kv, preset)
        version += 1
        set_schema_version(kv, version)
        applied.append(step)
    return applied
