"""Store consistency checker (`python -m lighthouse_tpu.cli db fsck`;
the seat of `lighthouse db inspect`/database_manager sanity tooling).

Walks the cross-key invariants that the write-ahead journal is supposed
to preserve — the ones a torn multi-key mutation would break:

* no orphaned write-ahead journal row (open-time recovery removes it);
* the schema version stamp is present and known;
* `split_slot` agrees with the freezer: the chunked block-root vector is
  contiguous over the frozen range (no holes below the split);
* restore points exist at `slots_per_restore_point` stride below the
  `restore_points_to` high-water mark;
* frozen blocks and restore-point states actually DECODE (not just key
  contiguity): a torn or bit-rotted freezer row would otherwise surface
  only when a historical replay trips over it;
* the head pointer resolves: `head_block_root` has a post-state mapping,
  `head_state_root` matches it, and the state row (full or summary) is
  actually present;
* the finalized pointer resolves to a stored block (or the genesis
  header's post-state mapping).

Outcomes are counted in utils.metrics (`store_fsck_runs_total`,
`store_fsck_issues_total`); the CLI exits non-zero when any issue is
found.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from .hot_cold import CHUNK_SIZE, chunk_root_in_row
from .kv import JOURNAL_KEY, Column, slot_key
from .metadata import CURRENT_SCHEMA_VERSION, get_schema_version


@dataclass(frozen=True)
class FsckIssue:
    check: str
    detail: str

    def __str__(self) -> str:
        return f"[{self.check}] {self.detail}"


def run_fsck(db) -> list[FsckIssue]:
    """Check `db` (a HotColdDB); returns [] when clean."""
    from ..utils import metrics as M

    issues: list[FsckIssue] = []
    kv = db.kv

    # -- journal -------------------------------------------------------------
    if kv.get(Column.JOURNAL, JOURNAL_KEY) is not None:
        issues.append(
            FsckIssue(
                "journal",
                "orphaned write-ahead journal present (open-time recovery "
                "did not run, or a batch is mid-commit)",
            )
        )

    # -- schema --------------------------------------------------------------
    version = get_schema_version(kv)
    if version is None:
        issues.append(FsckIssue("schema", "no schema version stamp"))
    elif version != CURRENT_SCHEMA_VERSION:
        issues.append(
            FsckIssue(
                "schema",
                f"on-disk schema v{version} != current "
                f"v{CURRENT_SCHEMA_VERSION} after open",
            )
        )

    # -- split vs freezer contiguity ----------------------------------------
    # the chain's history floor: checkpoint-sync nodes hold nothing below
    # their anchor, so contiguity is only owed from there
    lo = 0
    meta = db.get_chain_item(b"oldest_block_meta")
    if meta is not None:
        lo = int.from_bytes(meta[:8], "little")
    # walk the 128-slot chunk rows directly (one get per row) instead of
    # db.cold_block_root_at_slot per slot, which would re-fetch each row
    # 128 times — on FileStore that is one file open per frozen slot
    holes = []
    split = db.split_slot
    for cindex in range(lo // CHUNK_SIZE, (split + CHUNK_SIZE - 1) // CHUNK_SIZE):
        row = kv.get(Column.FREEZER_BLOCK_ROOTS, struct.pack(">Q", cindex))
        base = cindex * CHUNK_SIZE
        for slot in range(max(lo, base), min(split, base + CHUNK_SIZE)):
            if chunk_root_in_row(row, slot) is None:
                holes.append(slot)
    if holes:
        issues.append(
            FsckIssue(
                "block-roots",
                f"{len(holes)} hole(s) in the frozen block-root vector "
                f"below split_slot {db.split_slot}, first at slot {holes[0]}",
            )
        )

    # -- restore points at stride -------------------------------------------
    marker = db.get_chain_item(b"restore_points_to")
    if marker is not None:
        upto = struct.unpack(">Q", marker)[0]
        stored_spr = db.get_chain_item(b"slots_per_restore_point")
        spr = (
            struct.unpack(">Q", stored_spr)[0]
            if stored_spr
            else db.slots_per_restore_point
        )
        missing = [
            slot
            for slot in range(lo + (-lo % spr), upto, spr)
            if kv.get(Column.FREEZER_STATE, slot_key(slot)) is None
        ]
        if missing:
            issues.append(
                FsckIssue(
                    "restore-points",
                    f"{len(missing)} restore point(s) missing below "
                    f"restore_points_to {upto} (stride {spr}), first at "
                    f"slot {missing[0]}",
                )
            )

    # -- freezer decodability -----------------------------------------------
    # key contiguity is not enough: a frozen row can exist and still be
    # garbage (torn native-log tail, bit rot). Decode every frozen block
    # and every restore-point state; the crash-recovery scenario runs this
    # after every reopen.
    bad_blocks = []
    for root in kv.keys(Column.FREEZER_BLOCK):
        try:
            blk = db._decode_stored_block(kv.get(Column.FREEZER_BLOCK, root))
            if bytes(blk.message.tree_hash_root()) != bytes(root):
                raise ValueError("stored block does not match its key root")
        except (ValueError, KeyError, IndexError, struct.error):
            bad_blocks.append(bytes(root))
    if bad_blocks:
        issues.append(
            FsckIssue(
                "freezer-decode",
                f"{len(bad_blocks)} frozen block(s) fail to decode, first "
                f"{bad_blocks[0].hex()[:12]}",
            )
        )
    bad_states = []
    for key in kv.keys(Column.FREEZER_STATE):
        try:
            db.decode_stored_state(kv.get(Column.FREEZER_STATE, key))
        except (ValueError, KeyError, IndexError, struct.error):
            bad_states.append(struct.unpack(">Q", key)[0])
    if bad_states:
        issues.append(
            FsckIssue(
                "freezer-decode",
                f"{len(bad_states)} restore-point state(s) fail to decode, "
                f"first at slot {bad_states[0]}",
            )
        )

    # -- head pointer --------------------------------------------------------
    head = db.get_chain_item(b"head_block_root")
    head_state = db.get_chain_item(b"head_state_root")
    if head is not None:
        mapped = db.get_chain_item(b"block_post_state:" + head)
        if mapped is None:
            issues.append(
                FsckIssue(
                    "head",
                    f"head_block_root {head.hex()[:12]} has no post-state "
                    "mapping",
                )
            )
        else:
            if head_state is not None and head_state != mapped:
                issues.append(
                    FsckIssue(
                        "head",
                        "head_state_root disagrees with the head block's "
                        "post-state mapping",
                    )
                )
            if (
                kv.get(Column.STATE, mapped) is None
                and kv.get(Column.STATE_SUMMARY, mapped) is None
            ):
                issues.append(
                    FsckIssue(
                        "head",
                        f"head state {mapped.hex()[:12]} is stored neither "
                        "full nor as a summary",
                    )
                )

    # -- finalized pointer ---------------------------------------------------
    fin = db.get_chain_item(b"finalized_block_root")
    if fin is not None and db.get_block_any_temperature(fin) is None:
        # the genesis "block" is a header, not a stored block: its
        # post-state mapping is the resolution path (hot_cold.get_state)
        if db.get_chain_item(b"block_post_state:" + fin) is None:
            issues.append(
                FsckIssue(
                    "finalized",
                    f"finalized_block_root {fin.hex()[:12]} resolves to no "
                    "stored block",
                )
            )

    M.STORE_FSCK_RUNS.inc()
    if issues:
        M.STORE_FSCK_FAILURES.inc(len(issues))
    return issues
