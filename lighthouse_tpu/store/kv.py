"""Key-value store abstraction (reference beacon_node/store/src/lib.rs:49,107
KeyValueStore/ItemStore traits; memory_store.rs; leveldb_store.rs).

Backends: `MemoryStore` (tests/ephemeral chains) and `FileStore` (simple
column-file persistence). A C++ embedded-store backend slots in behind the
same interface (the reference's LevelDB seat) in a later round.

Crash safety: `do_atomically` is a write-ahead journal protocol on every
backend (the reference gets the same guarantee from leveldb write-batches).
The batch is serialized — length-framed, CRC-protected — into a single
journal row FIRST; only once that intent record is durable are the ops
applied, and the journal row is deleted as the commit marker. On reopen,
`recover_journal` replays a complete journal (the crash hit mid-apply:
redo, ops are idempotent) and discards a torn one (the crash hit the
intent write itself: the batch never logically happened). Either way the
store ends in a state some crash-free execution could have produced.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from collections import OrderedDict

# guards lazy creation of per-store batch locks (see do_atomically)
_BATCH_LOCK_INIT = threading.Lock()


class Column:
    BLOCK = b"blk"
    STATE = b"ste"
    STATE_SUMMARY = b"ssu"
    CHAIN = b"chn"
    FREEZER_BLOCK = b"fbk"
    FREEZER_STATE = b"fst"
    # chunked per-slot root vectors (reference store/src/chunked_vector.rs:
    # block_roots/state_roots stored once globally in 128-entry chunk rows
    # instead of duplicated inside every frozen state)
    FREEZER_BLOCK_ROOTS = b"fbr"
    FREEZER_STATE_ROOTS = b"fsr"
    # write-ahead journal for do_atomically (one batch in flight at a time)
    JOURNAL = b"jnl"


JOURNAL_KEY = b"batch"
_JOURNAL_MAGIC = b"LHWAL1\x00"


def encode_batch(ops) -> bytes:
    """Serialize a do_atomically batch into one journal blob.

    Validates every op BEFORE any byte is framed, so a malformed batch
    raises without a journal row ever being written (mirroring
    native_kv.py's convert-before-BATCH_BEGIN care)."""
    payload = bytearray(struct.pack(">I", len(ops)))
    for op, column, key, value in ops:
        if op == "put":
            value = bytes(value)
            payload += b"P"
        elif op == "delete":
            value = b""
            payload += b"D"
        else:
            raise ValueError(f"unknown batch op {op!r}")
        column, key = bytes(column), bytes(key)
        payload += struct.pack(">I", len(column)) + column
        payload += struct.pack(">I", len(key)) + key
        payload += struct.pack(">I", len(value)) + value
    return (
        _JOURNAL_MAGIC
        + struct.pack(">II", len(payload), zlib.crc32(bytes(payload)))
        + bytes(payload)
    )


def decode_batch(blob: bytes):
    """The ops of a journal blob, or None when the blob is torn/corrupt
    (truncated write, bad checksum, bad framing) — the rollback signal."""
    hdr = len(_JOURNAL_MAGIC) + 8
    if len(blob) < hdr or not blob.startswith(_JOURNAL_MAGIC):
        return None
    length, crc = struct.unpack(">II", blob[len(_JOURNAL_MAGIC) : hdr])
    payload = blob[hdr:]
    if len(payload) != length or zlib.crc32(payload) != crc:
        return None
    try:
        (count,) = struct.unpack(">I", payload[:4])
        pos = 4
        ops = []
        for _ in range(count):
            tag = payload[pos : pos + 1]
            pos += 1
            fields = []
            for _f in range(3):
                (n,) = struct.unpack(">I", payload[pos : pos + 4])
                pos += 4
                fields.append(payload[pos : pos + n])
                pos += n
            column, key, value = fields
            if tag == b"P":
                ops.append(("put", column, key, value))
            elif tag == b"D":
                ops.append(("delete", column, key, None))
            else:
                return None
        if pos != len(payload):
            return None
        return ops
    except struct.error:
        return None


def recover_journal(kv) -> str:
    """Open-time journal recovery: "clean" (no journal), "replayed" (a
    complete intent record re-applied — the crash hit mid-apply), or
    "rolled_back" (a torn intent record discarded — the batch never
    committed). Counted in utils.metrics; idempotent under a crash during
    recovery itself (the journal row is deleted last)."""
    blob = kv.get(Column.JOURNAL, JOURNAL_KEY)
    if blob is None:
        return "clean"
    from ..utils import metrics as M

    ops = decode_batch(blob)
    if ops is None:
        kv.delete(Column.JOURNAL, JOURNAL_KEY)
        M.STORE_JOURNAL_ROLLBACKS.inc()
        return "rolled_back"
    for op, column, key, value in ops:
        if op == "put":
            kv.put(column, key, value)
        else:
            kv.delete(column, key)
    kv.delete(Column.JOURNAL, JOURNAL_KEY)
    M.STORE_JOURNAL_REPLAYS.inc()
    return "replayed"


class KeyValueStore:
    def get(self, column: bytes, key: bytes) -> bytes | None:
        raise NotImplementedError

    def put(self, column: bytes, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, column: bytes, key: bytes) -> None:
        raise NotImplementedError

    def keys(self, column: bytes):
        raise NotImplementedError

    def do_atomically(self, ops) -> None:
        """ops: [(op, column, key, value-or-None)] with op in {put, delete}.

        All-or-nothing via the write-ahead journal: intent record ->
        apply -> commit-marker delete. A crash anywhere in between is
        repaired by recover_journal on reopen (replay once the intent is
        durable, rollback when it is not). Backends with native batches
        (native_kv.py) override this.

        Batches serialize on a per-store lock: there is ONE journal row,
        so two concurrent batches (the HTTP thread's reconstruct against
        the chain thread's import) would otherwise overwrite each other's
        intent records and a crash could pass recovery as "clean" while
        one batch is torn. The lock is created lazily so subclasses need
        not call __init__."""
        ops = list(ops)
        if not ops:
            return
        lock = self.__dict__.get("_batch_lock")
        if lock is None:
            with _BATCH_LOCK_INIT:
                lock = self.__dict__.setdefault(
                    "_batch_lock", threading.Lock()
                )
        blob = encode_batch(ops)  # validates before any write
        with lock:
            self.put(Column.JOURNAL, JOURNAL_KEY, blob)
            for op, column, key, value in ops:
                if op == "put":
                    self.put(column, key, value)
                else:
                    self.delete(column, key)
            self.delete(Column.JOURNAL, JOURNAL_KEY)


class AtomicBatch:
    """Staged multi-key mutation committed through do_atomically.

    Staging (`stage` / `stage_delete` / `stage_chain_item`) performs no
    I/O; `commit()` writes the journal intent and applies everything
    all-or-nothing. This is the sanctioned shape for multi-key CHAIN
    mutations (the bare-atomic-batch lint rule flags direct sequences)."""

    def __init__(self, kv: KeyValueStore):
        self.kv = kv
        self.ops: list = []

    def stage(self, column: bytes, key: bytes, value: bytes) -> None:
        self.ops.append(("put", bytes(column), bytes(key), bytes(value)))

    def stage_delete(self, column: bytes, key: bytes) -> None:
        self.ops.append(("delete", bytes(column), bytes(key), None))

    def stage_chain_item(self, key: bytes, value: bytes) -> None:
        self.stage(Column.CHAIN, key, value)

    def __len__(self) -> int:
        return len(self.ops)

    def commit(self) -> None:
        if self.ops:
            self.kv.do_atomically(self.ops)
            self.ops = []


class MemoryStore(KeyValueStore):
    def __init__(self):
        self._data: dict[bytes, OrderedDict[bytes, bytes]] = {}

    def _col(self, column: bytes) -> OrderedDict:
        return self._data.setdefault(column, OrderedDict())

    def get(self, column, key):
        return self._col(column).get(key)

    def put(self, column, key, value):
        self._col(column)[key] = bytes(value)

    def delete(self, column, key):
        self._col(column).pop(key, None)

    def keys(self, column):
        return list(self._col(column).keys())


class FileStore(KeyValueStore):
    """One file per entry under <root>/<column>/<hexkey>. Crash-safe for
    node-restart resume; not a performance path.

    Durability: with ``durable=True`` (the default) every put fsyncs the
    tmp file before the rename and the directory entry after it, so an
    acknowledged write survives a power cut — a rename alone only orders
    the data against OTHER renames, it does not force it to disk.
    ``durable=False`` is the escape hatch for tests and throwaway dirs."""

    def __init__(self, root: str, durable: bool = True):
        self.root = root
        self.durable = durable
        os.makedirs(root, exist_ok=True)

    def _path(self, column: bytes, key: bytes) -> str:
        d = os.path.join(self.root, column.decode())
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, key.hex())

    @staticmethod
    def _fsync_dir(d: str) -> None:
        fd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def get(self, column, key):
        try:
            with open(self._path(column, key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def put(self, column, key, value):
        path = self._path(column, key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
            if self.durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if self.durable:
            self._fsync_dir(os.path.dirname(path))

    def delete(self, column, key):
        try:
            os.remove(self._path(column, key))
        except FileNotFoundError:
            return
        if self.durable:
            self._fsync_dir(os.path.dirname(self._path(column, key)))

    def keys(self, column):
        d = os.path.join(self.root, column.decode())
        if not os.path.isdir(d):
            return []
        return [bytes.fromhex(f) for f in os.listdir(d) if not f.endswith(".tmp")]


def slot_key(slot: int) -> bytes:
    return struct.pack(">Q", slot)
