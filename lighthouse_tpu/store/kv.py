"""Key-value store abstraction (reference beacon_node/store/src/lib.rs:49,107
KeyValueStore/ItemStore traits; memory_store.rs; leveldb_store.rs).

Backends: `MemoryStore` (tests/ephemeral chains) and `FileStore` (simple
column-file persistence). A C++ embedded-store backend slots in behind the
same interface (the reference's LevelDB seat) in a later round.
"""

from __future__ import annotations

import os
import struct
from collections import OrderedDict


class Column:
    BLOCK = b"blk"
    STATE = b"ste"
    STATE_SUMMARY = b"ssu"
    CHAIN = b"chn"
    FREEZER_BLOCK = b"fbk"
    FREEZER_STATE = b"fst"
    # chunked per-slot root vectors (reference store/src/chunked_vector.rs:
    # block_roots/state_roots stored once globally in 128-entry chunk rows
    # instead of duplicated inside every frozen state)
    FREEZER_BLOCK_ROOTS = b"fbr"
    FREEZER_STATE_ROOTS = b"fsr"


class KeyValueStore:
    def get(self, column: bytes, key: bytes) -> bytes | None:
        raise NotImplementedError

    def put(self, column: bytes, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, column: bytes, key: bytes) -> None:
        raise NotImplementedError

    def keys(self, column: bytes):
        raise NotImplementedError

    def do_atomically(self, ops) -> None:
        """ops: [(op, column, key, value-or-None)] with op in {put, delete}."""
        for op, column, key, value in ops:
            if op == "put":
                self.put(column, key, value)
            else:
                self.delete(column, key)


class MemoryStore(KeyValueStore):
    def __init__(self):
        self._data: dict[bytes, OrderedDict[bytes, bytes]] = {}

    def _col(self, column: bytes) -> OrderedDict:
        return self._data.setdefault(column, OrderedDict())

    def get(self, column, key):
        return self._col(column).get(key)

    def put(self, column, key, value):
        self._col(column)[key] = bytes(value)

    def delete(self, column, key):
        self._col(column).pop(key, None)

    def keys(self, column):
        return list(self._col(column).keys())


class FileStore(KeyValueStore):
    """One file per entry under <root>/<column>/<hexkey>. Crash-safe enough
    for node-restart resume; not a performance path."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, column: bytes, key: bytes) -> str:
        d = os.path.join(self.root, column.decode())
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, key.hex())

    def get(self, column, key):
        try:
            with open(self._path(column, key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def put(self, column, key, value):
        path = self._path(column, key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, path)

    def delete(self, column, key):
        try:
            os.remove(self._path(column, key))
        except FileNotFoundError:
            pass

    def keys(self, column):
        d = os.path.join(self.root, column.decode())
        if not os.path.isdir(d):
            return []
        return [bytes.fromhex(f) for f in os.listdir(d) if not f.endswith(".tmp")]


def slot_key(slot: int) -> bytes:
    return struct.pack(">Q", slot)
