"""Native embedded KV backend (the LevelDB seat, reference
beacon_node/store/src/leveldb_store.rs): a C++ log-structured store
(native/kvstore.cc) behind the same KeyValueStore interface as
MemoryStore/FileStore. ctypes binding (no pybind11 in the image); the
shared library is built on demand with g++.

Crash semantics match the reference's expectations of LevelDB:
`do_atomically` frames the ops between batch begin/commit records, and
replay drops uncommitted batches and torn tails."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .kv import KeyValueStore

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "kvstore.cc",
)
_LIB_PATH = os.path.join(os.path.dirname(_SRC), "libkvstore.so")
_BUILD_LOCK = threading.Lock()
_LIB = None


def _build_lib() -> str:
    with _BUILD_LOCK:
        if os.path.exists(_LIB_PATH) and os.path.getmtime(
            _LIB_PATH
        ) >= os.path.getmtime(_SRC):
            return _LIB_PATH
        # lint: allow[blocking-under-lock] -- the build lock exists to
        # serialize exactly this one-time g++ compile; every later call
        # takes the mtime fast path above
        subprocess.run(
            [
                "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                _SRC, "-o", _LIB_PATH,
            ],
            check=True,
            capture_output=True,
        )
        return _LIB_PATH


# key pointer MUST be c_void_p: c_char_p would NUL-truncate before
# string_at reads the full length (keys are 32-byte roots full of NULs)
_KEY_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p)


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    lib = ctypes.CDLL(_build_lib())
    lib.kv_open.restype = ctypes.c_void_p
    lib.kv_open.argtypes = [ctypes.c_char_p]
    lib.kv_close.argtypes = [ctypes.c_void_p]
    sz = ctypes.c_size_t
    buf = ctypes.c_char_p
    lib.kv_put.argtypes = [ctypes.c_void_p, buf, sz, buf, sz, buf, sz]
    lib.kv_delete.argtypes = [ctypes.c_void_p, buf, sz, buf, sz]
    lib.kv_get.restype = ctypes.c_long
    lib.kv_get.argtypes = [
        ctypes.c_void_p, buf, sz, buf, sz, ctypes.c_char_p, sz,
    ]
    lib.kv_batch_begin.argtypes = [ctypes.c_void_p]
    lib.kv_batch_put.argtypes = [ctypes.c_void_p, buf, sz, buf, sz, buf, sz]
    lib.kv_batch_delete.argtypes = [ctypes.c_void_p, buf, sz, buf, sz]
    lib.kv_batch_commit.argtypes = [ctypes.c_void_p]
    lib.kv_keys.argtypes = [ctypes.c_void_p, buf, sz, _KEY_CB, ctypes.c_void_p]
    lib.kv_compact.restype = ctypes.c_int
    lib.kv_compact.argtypes = [ctypes.c_void_p]
    lib.kv_len.restype = ctypes.c_size_t
    lib.kv_len.argtypes = [ctypes.c_void_p]
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.kv_recovery_stats.argtypes = [ctypes.c_void_p, u64p, u64p, u64p]
    _LIB = lib
    return lib


class NativeStore(KeyValueStore):
    """C++ log-structured store; one file per database."""

    def __init__(self, path: str):
        lib = _load()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._lib = lib
        self._db = lib.kv_open(path.encode())
        if not self._db:
            raise OSError(f"kv_open failed for {path}")
        self._lock = threading.Lock()
        # surface the C++ log's open-time recovery outcomes into the
        # shared metrics registry (the python-WAL counters' native twin)
        self.recovery_stats = self._read_recovery_stats()
        from ..utils import metrics as M

        M.STORE_NATIVE_REPLAYED.inc(self.recovery_stats["replayed_batches"])
        M.STORE_NATIVE_ROLLED_BACK.inc(
            self.recovery_stats["rolled_back_batches"]
        )
        M.STORE_NATIVE_TRUNCATED.inc(self.recovery_stats["truncated_bytes"])

    def _read_recovery_stats(self) -> dict:
        replayed = ctypes.c_uint64()
        rolled_back = ctypes.c_uint64()
        truncated = ctypes.c_uint64()
        self._lib.kv_recovery_stats(
            self._db,
            ctypes.byref(replayed),
            ctypes.byref(rolled_back),
            ctypes.byref(truncated),
        )
        return {
            "replayed_batches": int(replayed.value),
            "rolled_back_batches": int(rolled_back.value),
            "truncated_bytes": int(truncated.value),
        }

    def close(self) -> None:
        with self._lock:
            if self._db:
                self._lib.kv_close(self._db)
                self._db = None

    def _handle(self):
        """The live C handle; raises (instead of letting the C side
        dereference NULL -> SIGSEGV) once the store is closed."""
        if self._db is None:
            raise OSError("store is closed")
        return self._db

    def get(self, column: bytes, key: bytes) -> bytes | None:
        with self._lock:
            n = self._lib.kv_get(
                self._handle(), column, len(column), key, len(key), None, 0
            )
            if n < 0:
                return None
            out = ctypes.create_string_buffer(n)
            self._lib.kv_get(
                self._handle(), column, len(column), key, len(key), out, n
            )
            return out.raw

    def put(self, column: bytes, key: bytes, value: bytes) -> None:
        value = bytes(value)
        with self._lock:
            self._lib.kv_put(
                self._handle(), column, len(column), key, len(key), value, len(value)
            )

    def delete(self, column: bytes, key: bytes) -> None:
        with self._lock:
            self._lib.kv_delete(self._handle(), column, len(column), key, len(key))

    def keys(self, column: bytes):
        out: list[bytes] = []

        @_KEY_CB
        def cb(ptr, n, _ctx):
            out.append(ctypes.string_at(ptr, n))

        with self._lock:
            self._lib.kv_keys(self._handle(), column, len(column), cb, None)
        return out

    def do_atomically(self, ops) -> None:
        """All-or-nothing batch: one commit record, one disk barrier.

        Ops are validated/converted BEFORE the BATCH_BEGIN record is
        written: a mid-batch exception would otherwise leave an
        unterminated batch marker that replay treats as the start of an
        uncommitted region, truncating every later write on reopen."""
        converted = []
        for op, column, key, value in ops:
            if op == "put":
                converted.append((op, bytes(column), bytes(key), bytes(value)))
            elif op == "delete":
                converted.append((op, bytes(column), bytes(key), None))
            else:
                raise ValueError(f"unknown batch op {op!r}")
        with self._lock:
            self._lib.kv_batch_begin(self._handle())
            for op, column, key, value in converted:
                if op == "put":
                    self._lib.kv_batch_put(
                        self._handle(), column, len(column), key, len(key),
                        value, len(value),
                    )
                else:
                    self._lib.kv_batch_delete(
                        self._handle(), column, len(column), key, len(key)
                    )
            self._lib.kv_batch_commit(self._handle())

    def compact(self) -> None:
        with self._lock:
            rc = self._lib.kv_compact(self._handle())
            if rc == -2:
                # the log handle could not be reopened: nothing further
                # can be persisted, fail loudly rather than corrupt
                self._lib.kv_close(self._handle())
                self._db = None
                raise OSError("kv_compact lost the log handle; store closed")
            if rc != 0:
                raise OSError("kv_compact failed")

    def __len__(self) -> int:
        with self._lock:
            return self._lib.kv_len(self._handle())
