"""Hot/cold database (reference beacon_node/store/src/hot_cold_store.rs:48):
hot side stores recent blocks + periodic full states with per-block
summaries; the freezer keeps finalized history as restore points. States
between snapshots/restore points are rebuilt by block replay
(reference reconstruct.rs / BlockReplayer).
"""

from __future__ import annotations

import struct

from ..state_transition import BlockReplayer, clone_state, process_slots
from ..types import compute_epoch_at_slot, state_class_for, types_for
from ..types.presets import Preset
from .kv import Column, KeyValueStore, slot_key


class StoreError(KeyError):
    pass


class HotColdDB:
    def __init__(
        self,
        kv: KeyValueStore,
        preset: Preset,
        spec,
        slots_per_snapshot: int | None = None,
    ):
        self.kv = kv
        self.preset = preset
        self.spec = spec
        # hot snapshot cadence: every epoch by default
        self.slots_per_snapshot = slots_per_snapshot or preset.slots_per_epoch
        self.split_slot = 0  # hot/cold boundary (advances on finality)
        # schema stamp + open-time migrations (metadata.rs,
        # schema_change.rs); refuses newer-schema databases
        from .metadata import ensure_schema

        self.schema_migrations_applied = ensure_schema(kv, preset)

    # -- blocks --------------------------------------------------------------

    def put_block(self, block_root: bytes, signed_block) -> None:
        fork = type(signed_block).fork_name
        payload = fork.encode() + b"\x00" + signed_block.as_ssz_bytes()
        self.kv.put(Column.BLOCK, block_root, payload)

    def _decode_stored_block(self, data: bytes):
        fork, _, body = data.partition(b"\x00")
        t = types_for(self.preset)
        if fork == b"bellatrix_blinded":
            # payload pruned to its header (root-identical to the full
            # block; database_manager prune-payloads)
            return t.SignedBlindedBeaconBlock.from_ssz_bytes(body)
        from ..types import block_classes_for

        _, signed_cls, _ = block_classes_for(t, fork.decode())
        return signed_cls.from_ssz_bytes(body)

    def get_block(self, block_root: bytes):
        data = self.kv.get(Column.BLOCK, block_root)
        if data is None:
            return None
        return self._decode_stored_block(data)

    # -- states --------------------------------------------------------------

    def put_state(self, state_root: bytes, state) -> None:
        """Full state at snapshot cadence; otherwise a summary pointing to
        the previous snapshot (hot_cold_store.rs stores per-slot summaries
        + periodic full states the same way)."""
        if state.slot % self.slots_per_snapshot == 0:
            payload = (
                b"F" + state.fork_name.encode() + b"\x00" + state.as_ssz_bytes()
            )
            self.kv.put(Column.STATE, state_root, payload)
        else:
            # block root = header root with state_root filled (the header in
            # a post-block state still has it zeroed; the block's state_root
            # IS this state's root)
            from ..types.containers import BeaconBlockHeader

            hdr = state.latest_block_header
            block_root = BeaconBlockHeader(
                slot=hdr.slot,
                proposer_index=hdr.proposer_index,
                parent_root=hdr.parent_root,
                state_root=(
                    bytes(hdr.state_root)
                    if any(bytes(hdr.state_root))
                    else state_root
                ),
                body_root=hdr.body_root,
            ).tree_hash_root()
            summary = struct.pack(">Q", state.slot) + block_root
            self.kv.put(Column.STATE_SUMMARY, state_root, summary)
        self.kv.put(
            Column.CHAIN, b"state_at_slot:" + slot_key(state.slot), state_root
        )

    def get_full_state(self, state_root: bytes):
        data = self.kv.get(Column.STATE, state_root)
        if data is None:
            return None
        fork, _, body = data[1:].partition(b"\x00")
        t = types_for(self.preset)
        cls = state_class_for(t, fork.decode())
        return cls.from_ssz_bytes(body)

    def get_state(self, state_root: bytes, blocks_by_root=None):
        """Load a state, replaying blocks from the nearest stored snapshot
        when only a summary exists. `blocks_by_root(root)` resolves blocks
        (defaults to this store)."""
        full = self.get_full_state(state_root)
        if full is not None:
            return full
        summary = self.kv.get(Column.STATE_SUMMARY, state_root)
        if summary is None:
            raise StoreError(f"unknown state {state_root.hex()[:12]}")
        (slot,) = struct.unpack(">Q", summary[:8])
        block_root = summary[8:]
        # replay may start below the hot/cold split (a non-finalized state
        # whose snapshot ancestor was migrated): resolve blocks from either
        # temperature
        get_block = blocks_by_root or self.get_block_any_temperature

        # walk back through blocks until one whose POST-state is stored full
        chain = []
        root = block_root
        base_state = None
        while True:
            block = get_block(root)
            if block is None:
                # the genesis "block" is a header, not a stored block: its
                # post-state mapping is recorded at chain init
                mapped = self.get_chain_item(b"block_post_state:" + root)
                if mapped is not None:
                    base_state = self.get_full_state(mapped)
                    if base_state is not None:
                        break
                raise StoreError(f"missing block {root.hex()[:12]} for replay")
            post_state_root = bytes(block.message.state_root)
            base_state = self.get_full_state(post_state_root)
            if base_state is not None:
                break  # replay starts AFTER this block
            chain.append(block)
            root = bytes(block.message.parent_root)

        chain.reverse()
        replayer = BlockReplayer(base_state, self.preset, self.spec)
        replayer.apply_blocks(chain, target_slot=slot)
        return replayer.state

    # -- chain metadata ------------------------------------------------------

    def put_chain_item(self, key: bytes, value: bytes) -> None:
        self.kv.put(Column.CHAIN, key, value)

    def get_chain_item(self, key: bytes) -> bytes | None:
        return self.kv.get(Column.CHAIN, key)

    # -- freezer migration (hot_cold_store.rs:48-53 + migrate.rs) -----------

    def migrate_to_freezer(self, finalized_slot: int, canonical_roots) -> None:
        """Move finalized blocks to the freezer column and advance the
        split point; prune non-canonical hot entries older than the split.
        `canonical_roots`: {block_root} on the finalized chain."""
        for root in list(self.kv.keys(Column.BLOCK)):
            data = self.kv.get(Column.BLOCK, root)
            if data is None:
                continue
            block = self.get_block(root)
            if block.message.slot < finalized_slot:
                if root in canonical_roots:
                    self.kv.put(Column.FREEZER_BLOCK, root, data)
                self.kv.delete(Column.BLOCK, root)
        self.split_slot = finalized_slot
        self.put_chain_item(b"split_slot", struct.pack(">Q", finalized_slot))

    def get_block_any_temperature(self, block_root: bytes):
        blk = self.get_block(block_root)
        if blk is not None:
            return blk
        data = self.kv.get(Column.FREEZER_BLOCK, block_root)
        if data is None:
            return None
        return self._decode_stored_block(data)

    def prune_payloads(self, before_slot: int | None = None) -> int:
        """Replace stored full bellatrix blocks with their BLINDED form
        (payload -> header; block roots are identical by SSZ design), like
        `lighthouse db prune-payloads` (database_manager/src/lib.rs).
        Returns the number of pruned blocks."""
        from ..state_transition.per_block import payload_to_header

        t = types_for(self.preset)
        pruned = 0
        for col in (Column.BLOCK, Column.FREEZER_BLOCK):
            for root in list(self.kv.keys(col)):
                data = self.kv.get(col, root)
                if data is None or not data.startswith(b"bellatrix\x00"):
                    continue
                signed = self._decode_stored_block(data)
                blk = signed.message
                if before_slot is not None and blk.slot >= before_slot:
                    continue
                body = blk.body
                blinded_body = t.BlindedBeaconBlockBody(
                    randao_reveal=body.randao_reveal,
                    eth1_data=body.eth1_data,
                    graffiti=body.graffiti,
                    proposer_slashings=body.proposer_slashings,
                    attester_slashings=body.attester_slashings,
                    attestations=body.attestations,
                    deposits=body.deposits,
                    voluntary_exits=body.voluntary_exits,
                    sync_aggregate=body.sync_aggregate,
                    execution_payload_header=payload_to_header(
                        body.execution_payload, self.preset
                    ),
                )
                blinded = t.BlindedBeaconBlock(
                    slot=blk.slot,
                    proposer_index=blk.proposer_index,
                    parent_root=bytes(blk.parent_root),
                    state_root=bytes(blk.state_root),
                    body=blinded_body,
                )
                if blinded.tree_hash_root() != blk.tree_hash_root():
                    # never rewrite a block under a different root (a real
                    # raise, not an assert: this must survive python -O)
                    raise RuntimeError(
                        f"pruned block root diverged for {root.hex()}"
                    )
                signed_blinded = t.SignedBlindedBeaconBlock(
                    message=blinded, signature=bytes(signed.signature)
                )
                self.kv.put(
                    col,
                    root,
                    b"bellatrix_blinded\x00" + signed_blinded.as_ssz_bytes(),
                )
                pruned += 1
        return pruned
