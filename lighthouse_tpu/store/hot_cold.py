"""Hot/cold database (reference beacon_node/store/src/hot_cold_store.rs:48):
hot side stores recent blocks + periodic full states with per-block
summaries; the freezer keeps finalized history as restore points. States
between snapshots/restore points are rebuilt by block replay
(reference reconstruct.rs / BlockReplayer).
"""

from __future__ import annotations

import struct
import threading

from ..ssz import cached_root as cached_root_of
from ..state_transition import BlockReplayer, clone_state, process_slots
from ..types import compute_epoch_at_slot, state_class_for, types_for
from ..types.presets import Preset
from .kv import AtomicBatch, Column, KeyValueStore, recover_journal, slot_key


class StoreError(KeyError):
    pass


def latest_block_header_root(state, state_root: bytes) -> bytes:
    """Root of the last applied block: the state's latest header with its
    state_root filled when still zeroed (a post-block state's header has
    it zeroed until the next process_slot; the block's state_root IS that
    state's root)."""
    from ..types.containers import BeaconBlockHeader

    hdr = state.latest_block_header
    return BeaconBlockHeader(
        slot=hdr.slot,
        proposer_index=hdr.proposer_index,
        parent_root=bytes(hdr.parent_root),
        state_root=(
            bytes(hdr.state_root)
            if any(bytes(hdr.state_root))
            else state_root
        ),
        body_root=bytes(hdr.body_root),
    ).tree_hash_root()


CHUNK_SIZE = 128  # roots per freezer chunk row (chunked_vector.rs: 4K pages)


def chunk_root_in_row(row: bytes | None, slot: int) -> bytes | None:
    """Decode `slot`'s 32-byte root from its chunk row. None means absent:
    no row, a row too short to cover the slot, or the all-zero unwritten
    sentinel. The ONE place chunk framing is interpreted — _chunk_get,
    _ChunkWriter.root_at, and fsck's contiguity walk all read through it."""
    if row is None:
        return None
    offset = (slot % CHUNK_SIZE) * 32
    if len(row) < offset + 32:
        return None
    root = bytes(row[offset : offset + 32])
    return root if any(root) else None


class _ChunkWriter:
    """Buffers chunked-column writes so a migration touches each 4K chunk
    row once instead of read-modify-writing it per slot. Doubles as the
    read-through overlay for an atomic migration batch: `root_at` sees
    staged rows before they commit, so later migration phases (restore
    points) can read the root vectors the same batch is about to write."""

    def __init__(self, kv: KeyValueStore):
        self.kv = kv
        self.rows: dict[tuple[bytes, int], bytearray] = {}

    def put(self, column: bytes, slot: int, root: bytes) -> None:
        cindex = slot // CHUNK_SIZE
        key = (column, cindex)
        row = self.rows.get(key)
        if row is None:
            row = bytearray(
                self.kv.get(column, struct.pack(">Q", cindex)) or b""
            )
            self.rows[key] = row
        offset = (slot % CHUNK_SIZE) * 32
        if len(row) < offset + 32:
            row.extend(bytes(offset + 32 - len(row)))
        row[offset : offset + 32] = root

    def root_at(self, column: bytes, slot: int) -> bytes | None:
        """Staged-or-stored read of one root (the overlay view)."""
        cindex = slot // CHUNK_SIZE
        row = self.rows.get((column, cindex))
        if row is None:
            row = self.kv.get(column, struct.pack(">Q", cindex))
        elif not isinstance(row, bytes):
            row = bytes(row)
        return chunk_root_in_row(row, slot)

    def flush(self) -> None:
        for (column, cindex), row in self.rows.items():
            self.kv.put(column, struct.pack(">Q", cindex), bytes(row))
        self.rows.clear()

    def flush_into(self, batch: AtomicBatch) -> None:
        """Stage the buffered rows on `batch` instead of writing them."""
        for (column, cindex), row in self.rows.items():
            batch.stage(column, struct.pack(">Q", cindex), bytes(row))
        self.rows.clear()


class HotColdDB:
    def __init__(
        self,
        kv: KeyValueStore,
        preset: Preset,
        spec,
        slots_per_snapshot: int | None = None,
        slots_per_restore_point: int | None = None,
        migration_chunk_slots: int | None = None,
    ):
        self.kv = kv
        self.preset = preset
        self.spec = spec
        # hot snapshot cadence: every epoch by default
        self.slots_per_snapshot = slots_per_snapshot or preset.slots_per_epoch
        # freezer restore-point cadence (hot_cold_store.rs StoreConfig
        # slots_per_restore_point): full states in the cold DB at this
        # interval; states between are rebuilt by replaying <= this many
        # slots of frozen blocks
        self.slots_per_restore_point = (
            slots_per_restore_point or 4 * preset.slots_per_epoch
        )
        # hot->cold migration commits in journaled sub-batches of this
        # many slots (the long-non-finality memory bound: a multi-epoch
        # finality jump must not stage the whole range in one batch)
        self.migration_chunk_slots = migration_chunk_slots or 2 * CHUNK_SIZE
        # serializes multi-batch freezer mutations (migrate_to_freezer,
        # reconstruct_historic_states, prune_payloads) across threads:
        # kv.do_atomically makes each BATCH atomic, but the
        # restore_points_to marker is read-modify-written across a long
        # scan, and an HTTP-thread reconstruct racing a chain-thread
        # migration could commit a stale smaller marker over a fresh one
        self._mutation_lock = threading.Lock()
        # write-ahead journal recovery FIRST (an interrupted batch from
        # the previous process must replay or roll back before anything
        # reads the store), then the schema stamp + open-time migrations
        # (metadata.rs, schema_change.rs); refuses newer-schema databases
        self.journal_recovery = recover_journal(kv)
        from .metadata import ensure_schema

        self.schema_migrations_applied = ensure_schema(kv, preset)
        # hot/cold boundary (advances on finality); restored on reopen so
        # restarted nodes neither re-freeze nor clobber recorded history
        stored_split = kv.get(Column.CHAIN, b"split_slot")
        self.split_slot = (
            struct.unpack(">Q", stored_split)[0] if stored_split else 0
        )
        stored_fill = kv.get(Column.CHAIN, b"state_roots_filled_to")
        self._state_roots_filled_to = (
            struct.unpack(">Q", stored_fill)[0] if stored_fill else 0
        )

    # -- atomic batches ------------------------------------------------------

    def batch(self) -> AtomicBatch:
        """A staged multi-key mutation over this store's kv; commit()
        applies it all-or-nothing through the write-ahead journal."""
        return AtomicBatch(self.kv)

    # -- blocks --------------------------------------------------------------

    def put_block(self, block_root: bytes, signed_block, batch=None) -> None:
        fork = type(signed_block).fork_name
        payload = fork.encode() + b"\x00" + signed_block.as_ssz_bytes()
        if batch is not None:
            batch.stage(Column.BLOCK, block_root, payload)
        else:
            self.kv.put(Column.BLOCK, block_root, payload)

    def _decode_stored_block(self, data: bytes):
        fork, _, body = data.partition(b"\x00")
        t = types_for(self.preset)
        if fork == b"bellatrix_blinded":
            # payload pruned to its header (root-identical to the full
            # block; database_manager prune-payloads)
            return t.SignedBlindedBeaconBlock.from_ssz_bytes(body)
        from ..types import block_classes_for

        _, signed_cls, _ = block_classes_for(t, fork.decode())
        return signed_cls.from_ssz_bytes(body)

    def get_block(self, block_root: bytes):
        data = self.kv.get(Column.BLOCK, block_root)
        if data is None:
            return None
        return self._decode_stored_block(data)

    # -- states --------------------------------------------------------------

    def put_state(self, state_root: bytes, state, batch=None) -> None:
        """Full state at snapshot cadence; otherwise a summary pointing to
        the previous snapshot (hot_cold_store.rs stores per-slot summaries
        + periodic full states the same way). The state row and its
        slot-index row commit together: without a `batch` a private one
        is committed here, so a crash can never index an absent state."""
        sink = batch if batch is not None else self.batch()
        if state.slot % self.slots_per_snapshot == 0:
            payload = (
                b"F" + state.fork_name.encode() + b"\x00" + state.as_ssz_bytes()
            )
            sink.stage(Column.STATE, state_root, payload)
        else:
            block_root = latest_block_header_root(state, state_root)
            summary = struct.pack(">Q", state.slot) + block_root
            sink.stage(Column.STATE_SUMMARY, state_root, summary)
        sink.stage_chain_item(
            b"state_at_slot:" + slot_key(state.slot), state_root
        )
        if batch is None:
            sink.commit()

    def decode_stored_state(self, data: bytes):
        """Decode a stored full-state payload (b"F" + fork + NUL + ssz):
        the ONE place the framing is interpreted — hot snapshots, frozen
        restore points, and fsck's decodability walk all read through it.
        Raises ValueError-family errors on a torn/corrupt payload."""
        if not data or data[:1] != b"F":
            raise ValueError("not a full-state payload")
        fork, _, body = data[1:].partition(b"\x00")
        t = types_for(self.preset)
        cls = state_class_for(t, fork.decode())
        return cls.from_ssz_bytes(body)

    def get_full_state(self, state_root: bytes):
        data = self.kv.get(Column.STATE, state_root)
        if data is None:
            return None
        return self.decode_stored_state(data)

    def get_state(self, state_root: bytes, blocks_by_root=None):
        """Load a state, replaying blocks from the nearest stored snapshot
        when only a summary exists. `blocks_by_root(root)` resolves blocks
        (defaults to this store)."""
        full = self.get_full_state(state_root)
        if full is not None:
            return full
        summary = self.kv.get(Column.STATE_SUMMARY, state_root)
        if summary is None:
            raise StoreError(f"unknown state {state_root.hex()[:12]}")
        (slot,) = struct.unpack(">Q", summary[:8])
        block_root = summary[8:]
        # replay may start below the hot/cold split (a non-finalized state
        # whose snapshot ancestor was migrated): resolve blocks from either
        # temperature
        get_block = blocks_by_root or self.get_block_any_temperature

        # walk back through blocks until one whose POST-state is stored full
        chain = []
        root = block_root
        base_state = None
        while True:
            block = get_block(root)
            if block is None:
                # the genesis "block" is a header, not a stored block: its
                # post-state mapping is recorded at chain init
                mapped = self.get_chain_item(b"block_post_state:" + root)
                if mapped is not None:
                    base_state = self.get_full_state(mapped)
                    if base_state is not None:
                        break
                raise StoreError(f"missing block {root.hex()[:12]} for replay")
            post_state_root = bytes(block.message.state_root)
            base_state = self.get_full_state(post_state_root)
            if base_state is not None:
                break  # replay starts AFTER this block
            chain.append(block)
            root = bytes(block.message.parent_root)

        chain.reverse()
        replayer = BlockReplayer(base_state, self.preset, self.spec)
        replayer.apply_blocks(chain, target_slot=slot)
        return replayer.state

    # -- chain metadata ------------------------------------------------------

    def put_chain_item(self, key: bytes, value: bytes) -> None:
        self.kv.put(Column.CHAIN, key, value)

    def delete_chain_item(self, key: bytes) -> None:
        self.kv.delete(Column.CHAIN, key)

    def get_chain_item(self, key: bytes) -> bytes | None:
        return self.kv.get(Column.CHAIN, key)

    # -- freezer chunked root vectors (store/src/chunked_vector.rs) ---------
    #
    # block_roots/state_roots live ONCE in the cold DB as 128-entry chunk
    # rows keyed by chunk index, instead of duplicated in every frozen
    # state. vindex == absolute slot; cindex == slot // CHUNK_SIZE.

    def _chunk_put(self, column: bytes, slot: int, root: bytes) -> None:
        w = _ChunkWriter(self.kv)
        w.put(column, slot, root)
        w.flush()

    def _chunk_get(self, column: bytes, slot: int) -> bytes | None:
        row = self.kv.get(column, struct.pack(">Q", slot // CHUNK_SIZE))
        return chunk_root_in_row(row, slot)

    def cold_block_root_at_slot(self, slot: int) -> bytes | None:
        return self._chunk_get(Column.FREEZER_BLOCK_ROOTS, slot)

    def cold_state_root_at_slot(self, slot: int) -> bytes | None:
        return self._chunk_get(Column.FREEZER_STATE_ROOTS, slot)

    # -- freezer migration (hot_cold_store.rs:48-53 + migrate.rs) -----------

    def migrate_to_freezer(
        self,
        finalized_slot: int,
        canonical_roots,
        finalized_state=None,
        finalized_block_root: bytes | None = None,
    ) -> None:
        """Move finalized blocks to the freezer column and advance the
        split point; prune non-canonical hot entries older than the split.
        `canonical_roots`: {block_root} on the finalized chain.

        With `finalized_state` (the finalized block's post-state) the
        freezer also records the migrated range's per-slot block/state
        roots into the chunked columns and stores restore-point states at
        slots_per_restore_point cadence — historical loads then cost at
        most one restore-point read + a bounded block replay
        (hot_cold_store.rs store_cold_state/load_cold_state).

        The migration commits through the write-ahead journal in bounded
        SUB-BATCHES (the documented single-batch memory trade-off,
        resolved): block copies + hot prunes + chunked block-root rows
        per `migration_chunk_slots`-slot window in ascending slot order,
        then the state-root rows, then one batch per missing restore
        point, and FINALLY the split-slot advance (+ stride and
        finalized-checkpoint pointers) as its own batch. Each sub-batch
        is individually atomic, and the ordering keeps every inter-batch
        crash point consistent: frozen content is only ever a superset of
        what `split_slot` claims, a hot block is pruned only after its
        freezer copy and root-row committed, and a re-run resumes
        idempotently (moved blocks are no longer hot, existing chunk rows
        win over recomputation, the restore-point sweep restarts from its
        marker). Staged memory is bounded by one window of blocks or one
        full state, never by the length of a non-finality stretch."""
        with self._mutation_lock:
            old_split = self.split_slot
            # collect the hot KEYS to move/prune ONCE, sorted by slot;
            # block payloads are re-read per window at staging time so
            # peak memory really is one window, not the whole stretch
            moves = []  # canonical: (slot, root) -> freezer
            prunes = []  # non-canonical: (slot, root) -> delete only
            for root in list(self.kv.keys(Column.BLOCK)):
                data = self.kv.get(Column.BLOCK, root)
                if data is None:
                    continue
                block = self.get_block(root)
                if block.message.slot < finalized_slot:
                    if root in canonical_roots:
                        moves.append((int(block.message.slot), bytes(root)))
                    else:
                        prunes.append(
                            (int(block.message.slot), bytes(root))
                        )
            moves.sort()
            prunes.sort()
            step = max(int(self.migration_chunk_slots), 1)
            mi = pi = 0
            lo = old_split
            while lo < finalized_slot:
                hi = min(lo + step, finalized_slot)
                batch = self.batch()
                chunks = _ChunkWriter(self.kv)
                window = []
                while mi < len(moves) and moves[mi][0] < hi:
                    slot, root = moves[mi]
                    mi += 1
                    data = self.kv.get(Column.BLOCK, root)
                    if data is None:
                        continue  # vanished since the scan (re-run overlap)
                    batch.stage(Column.FREEZER_BLOCK, root, data)
                    batch.stage_delete(Column.BLOCK, root)
                    window.append((slot, root))
                while pi < len(prunes) and prunes[pi][0] < hi:
                    batch.stage_delete(Column.BLOCK, prunes[pi][1])
                    pi += 1
                self._freeze_block_roots(lo, hi, window, chunks)
                chunks.flush_into(batch)
                batch.commit()
                lo = hi
            if finalized_state is not None:
                batch = self.batch()
                chunks = _ChunkWriter(self.kv)
                filled_to = self._freeze_state_roots(
                    finalized_slot, finalized_state, chunks, batch
                )
                chunks.flush_into(batch)
                batch.commit()
                self._state_roots_filled_to = filled_to
            self._sweep_restore_points(finalized_slot)
            # the split-slot advance is the LAST batch: a crash anywhere
            # above leaves the old split naming only content that exists.
            # Values are staged only when they CHANGE — finality triggers
            # a migrate call per import, and a no-advance repeat must not
            # journal an identical marker batch every slot.
            batch = self.batch()
            markers = [
                (b"split_slot", struct.pack(">Q", finalized_slot)),
                (
                    b"slots_per_restore_point",
                    struct.pack(">Q", self.slots_per_restore_point),
                ),
            ]
            if finalized_block_root is not None:
                markers.append(
                    (b"finalized_block_root", bytes(finalized_block_root))
                )
            for key, value in markers:
                if self.get_chain_item(key) != value:
                    batch.stage_chain_item(key, value)
            batch.commit()
            # in-memory mirrors advance only AFTER the batch is durable,
            # so a commit-time crash leaves this object consistent with
            # the disk
            self.split_slot = finalized_slot

    def _freeze_block_roots(self, lo: int, hi: int, migrated, chunks) -> None:
        """Per-slot block roots for the window [lo, hi) from the migrated
        canonical blocks themselves (ring semantics: an empty slot repeats
        the previous block's root) — coverage never depends on any state's
        ring buffer, so long non-finality cannot punch holes. Rows are
        staged on the shared `chunks` overlay; the window batch flushes
        them. An EXISTING stored root wins over recomputation and becomes
        the running `prev`: a re-run over a window a crashed migration
        already committed (whose hot blocks are gone, so `migrated` no
        longer names them) must keep the recorded canonical roots instead
        of smearing a stale predecessor over them."""
        migrated.sort()
        cursor = 0
        prev = (
            chunks.root_at(Column.FREEZER_BLOCK_ROOTS, lo - 1)
            if lo
            else None
        )
        row_cache: dict[int, bytes | None] = {}

        def existing_root(slot: int) -> bytes | None:
            # one kv read per 128-slot chunk row, not one per slot
            cindex = slot // CHUNK_SIZE
            staged = chunks.rows.get((Column.FREEZER_BLOCK_ROOTS, cindex))
            if staged is not None:
                return chunk_root_in_row(bytes(staged), slot)
            if cindex not in row_cache:
                row_cache[cindex] = self.kv.get(
                    Column.FREEZER_BLOCK_ROOTS, struct.pack(">Q", cindex)
                )
            return chunk_root_in_row(row_cache[cindex], slot)

        for slot in range(lo, hi):
            while cursor < len(migrated) and migrated[cursor][0] <= slot:
                prev = migrated[cursor][1]
                cursor += 1
            stored = existing_root(slot)
            if stored is not None:
                prev = stored
                continue
            if prev is None:
                # before the first canonical block: slot 0's "block" is the
                # genesis header, recorded at chain init. Databases that
                # predate that item fall back to the backfill anchor (for
                # genesis-start chains it IS the genesis root; checkpoint
                # chains have no served history below the anchor anyway).
                prev = self.get_chain_item(
                    b"genesis_block_root"
                ) or self.get_chain_item(b"oldest_block_root")
                if prev is None:
                    continue
            chunks.put(Column.FREEZER_BLOCK_ROOTS, slot, prev)

    def _freeze_state_roots(
        self, finalized_slot: int, finalized_state, chunks, batch
    ) -> int:
        """State roots from the finalized state's ring, tracked by a
        persisted low-water mark: a finalized epoch that starts with empty
        slots leaves the tail unmaterialized this round, and the NEXT
        migration backfills it from a later ring (those state roots exist
        in any state that advanced past the gap).

        If finality ever jumps by more than the ring (non-finality longer
        than slots_per_historical_root), the stretch the ring cannot cover
        is patched from the canonical frozen blocks themselves: a block's
        state_root IS the state root at its slot. Only empty slots inside
        such a stretch stay unrecorded (their states were never part of
        any block), and the state-roots iterator raises for them.

        Stages rows on `chunks` / items on `batch`; returns the new
        low-water mark for the caller to adopt after commit."""
        ring = self.preset.slots_per_historical_root
        covered = min(finalized_slot, int(finalized_state.slot))
        lo = max(self._state_roots_filled_to, covered - ring)
        for slot in range(self._state_roots_filled_to, lo):
            root = chunks.root_at(Column.FREEZER_BLOCK_ROOTS, slot)
            if root is None:
                continue
            if slot and root == chunks.root_at(
                Column.FREEZER_BLOCK_ROOTS, slot - 1
            ):
                continue  # empty slot: no block-anchored state root
            block = self.get_block_any_temperature(root)
            if block is not None and int(block.message.slot) == slot:
                chunks.put(
                    Column.FREEZER_STATE_ROOTS,
                    slot,
                    bytes(block.message.state_root),
                )
        for slot in range(lo, covered):
            chunks.put(
                Column.FREEZER_STATE_ROOTS,
                slot,
                bytes(finalized_state.state_roots[slot % ring]),
            )
        if covered > self._state_roots_filled_to:
            batch.stage_chain_item(
                b"state_roots_filled_to", struct.pack(">Q", covered)
            )
            return covered
        return self._state_roots_filled_to

    def _store_restore_points(
        self, finalized_slot: int, chunks, batch, scan_from: int | None = None
    ) -> None:
        """Full states at restore-point cadence, loaded strictly by the
        AUTHORITATIVE root from the chunked column — never by the
        last-writer-wins state_at_slot index, which can name a
        non-canonical fork's state. Roots come through the `chunks`
        overlay (the same batch may have just staged them); the state
        payloads and the high-water marker are staged on `batch`.

        The scan starts at the earliest restore-point slot that is still
        missing (the restore_points_to marker, not the split): a slot
        skipped last round because its
        state root was in an empty-slot gap is retried once the next
        migration's ring backfill records the root. `scan_from` lets a
        caller sweeping bounded sub-ranges (http reconstruct) set the
        scan floor itself instead of rescanning from the marker every
        call — which goes quadratic when a permanently-missing state
        root pins the marker."""
        spr = self.slots_per_restore_point
        marker = 0
        stored = self.get_chain_item(b"restore_points_to")
        if stored is not None:
            marker = struct.unpack(">Q", stored)[0]
        start = marker if scan_from is None else scan_from
        all_present = True
        for slot in range(start + (-start % spr), finalized_slot, spr):
            if self.kv.get(Column.FREEZER_STATE, slot_key(slot)) is not None:
                continue
            state_root = chunks.root_at(Column.FREEZER_STATE_ROOTS, slot)
            if state_root is None:
                all_present = False
                continue
            try:
                state = self.get_state(state_root)
            except StoreError:
                all_present = False
                continue
            payload = (
                b"F" + state.fork_name.encode() + b"\x00" + state.as_ssz_bytes()
            )
            batch.stage(Column.FREEZER_STATE, slot_key(slot), payload)
        # the high-water mark means "every restore point below me exists":
        # it only advances (a bounded sweep below it must not regress it),
        # and only when this scan actually covered the ground from the
        # marker up — a sweep that began ABOVE the marker cannot vouch for
        # the gap below its floor
        if all_present and finalized_slot > marker and start <= marker:
            batch.stage_chain_item(
                b"restore_points_to", struct.pack(">Q", finalized_slot)
            )

    def _sweep_restore_points(self, upto_slot: int) -> None:
        """Store missing restore points below `upto_slot` in per-stride
        journaled batches (at most ONE rebuilt full state staged per
        commit — the migration's memory bound), starting from the
        restore_points_to marker's floor. Caller holds _mutation_lock."""
        spr = self.slots_per_restore_point
        stored = self.get_chain_item(b"restore_points_to")
        cursor = struct.unpack(">Q", stored)[0] if stored else 0
        if cursor >= upto_slot:
            return
        while True:
            upto = min(cursor + spr, upto_slot)
            batch = self.batch()
            self._store_restore_points(
                upto, _ChunkWriter(self.kv), batch, scan_from=cursor
            )
            batch.commit()
            if upto >= upto_slot:
                return
            cursor = upto

    def reconstruct_historic_states(self) -> int:
        """Fill any missing restore-point states below the split from the
        chunked columns (the reference's historic state reconstruction,
        reconstruct.rs), in bounded journaled batches: each stride
        interval commits at most one rebuilt full state plus the
        restore_points_to marker, so memory and journal size stay bounded
        however long the chain is. The sweep is idempotent — present
        points are skipped, and the marker only advances over prefixes
        verified complete — so a crash between batches resumes exactly
        where it left off. Returns the number of restore points added."""
        with self._mutation_lock:
            before = len(self.kv.keys(Column.FREEZER_STATE))
            spr = self.slots_per_restore_point
            cursor = 0
            boundary = spr
            while True:
                upto = min(boundary, self.split_slot)
                batch = self.batch()
                self._store_restore_points(
                    upto, _ChunkWriter(self.kv), batch, scan_from=cursor
                )
                batch.commit()
                cursor = upto
                if upto == self.split_slot:
                    break
                boundary += spr
            return len(self.kv.keys(Column.FREEZER_STATE)) - before

    def load_cold_state(self, slot: int):
        """Historical (pre-split) state at `slot`: nearest restore point at
        or below, then replay the frozen canonical blocks up to `slot`
        (bounded by slots_per_restore_point; reference
        hot_cold_store.rs load_cold_state_by_slot + reconstruct.rs)."""
        spr = self.slots_per_restore_point
        rp_slot = slot - slot % spr
        base = None
        while rp_slot >= 0:
            data = self.kv.get(Column.FREEZER_STATE, slot_key(rp_slot))
            if data is not None:
                base = self.decode_stored_state(data)
                break
            rp_slot -= spr
        if base is None:
            raise StoreError(f"no restore point at or below slot {slot}")
        # canonical blocks in (rp_slot, slot]: consecutive equal roots in
        # the chunked vector mean empty slots. A missing root is a REAL
        # error — silently skipping would replay a wrong chain.
        chain = []
        prev = self.cold_block_root_at_slot(rp_slot)
        if prev is None:
            raise StoreError(f"no frozen block root at restore slot {rp_slot}")
        for s in range(rp_slot + 1, slot + 1):
            r = self.cold_block_root_at_slot(s)
            if r is None:
                raise StoreError(f"no frozen block root at slot {s}")
            if r == prev:
                continue
            block = self.get_block_any_temperature(r)
            if block is None:
                raise StoreError(f"missing frozen block {r.hex()[:12]}")
            chain.append(block)
            prev = r
        replayer = BlockReplayer(base, self.preset, self.spec)
        replayer.apply_blocks(chain, target_slot=slot)
        return replayer.state

    # -- forward iterators (store/src/forwards_iter.rs) ---------------------

    def forwards_block_roots_iter(self, start_slot: int, end_slot: int, state):
        """Yield (block_root, slot) ascending over [start_slot, end_slot].
        The frozen range reads the chunked vector (FrozenForwardsIterator);
        the hot range reads `state`'s ring buffer (SimpleForwardsIterator —
        `state` must cover it, i.e. end_slot within slots_per_historical_root
        of state.slot)."""
        yield from self._forwards_iter(
            start_slot, end_slot, state, Column.FREEZER_BLOCK_ROOTS, "block_roots"
        )

    def forwards_state_roots_iter(self, start_slot: int, end_slot: int, state):
        yield from self._forwards_iter(
            start_slot, end_slot, state, Column.FREEZER_STATE_ROOTS, "state_roots"
        )

    def _forwards_iter(self, start_slot, end_slot, state, column, field):
        ring = self.preset.slots_per_historical_root
        for slot in range(start_slot, end_slot + 1):
            if slot < self.split_slot:
                root = self._chunk_get(column, slot)
                if root is None:
                    raise StoreError(f"no frozen {field} for slot {slot}")
            elif slot == state.slot:
                # the state's own slot is not in its ring buffers yet; the
                # reference computes these on demand (forwards_iter.rs)
                if field == "state_roots":
                    root = cached_root_of(state)
                else:
                    root = latest_block_header_root(
                        state, cached_root_of(state)
                    )
            elif not (state.slot - ring <= slot < state.slot):
                raise StoreError(f"slot {slot} outside hot ring")
            else:
                root = bytes(getattr(state, field)[slot % ring])
            yield root, slot

    def get_block_any_temperature(self, block_root: bytes):
        blk = self.get_block(block_root)
        if blk is not None:
            return blk
        data = self.kv.get(Column.FREEZER_BLOCK, block_root)
        if data is None:
            return None
        return self._decode_stored_block(data)

    def prune_payloads(
        self, before_slot: int | None = None, chunk_blocks: int = 128
    ) -> int:
        """Replace stored full bellatrix blocks with their BLINDED form
        (payload -> header; block roots are identical by SSZ design), like
        `lighthouse db prune-payloads` (database_manager/src/lib.rs).
        Returns the number of pruned blocks. With no explicit boundary the
        prune stops at the hot/cold split (finalized) slot — the reference
        prunes only finalized payloads, never the head's, so the node can
        still serve full blocks over req/resp and re-notify the EL.

        Commits in journaled chunks of ``chunk_blocks`` rewrites (like
        the http reconstruct sweep), so journal size and staged memory
        stay bounded however long the chain is: each individual block is
        still rewritten atomically, a crash inside a chunk recovers to
        that chunk's pre-or-post image, and a crash BETWEEN chunks leaves
        a consistent partially-pruned store the next prune resumes over
        (already-blinded blocks are skipped).

        Holds the freezer mutation lock: the prune's op list is built
        from reads of the block columns, and a concurrent migration
        committing between those reads and a chunk's commit would let
        the prune resurrect a hot row the migration just deleted."""
        with self._mutation_lock:
            return self._prune_payloads_locked(before_slot, chunk_blocks)

    def _prune_payloads_locked(
        self, before_slot: int | None, chunk_blocks: int
    ) -> int:
        from ..state_transition.per_block import payload_to_header

        if before_slot is None:
            before_slot = self.split_slot
        t = types_for(self.preset)
        pruned = 0
        batch = self.batch()
        for col in (Column.BLOCK, Column.FREEZER_BLOCK):
            for root in list(self.kv.keys(col)):
                data = self.kv.get(col, root)
                if data is None or not data.startswith(b"bellatrix\x00"):
                    continue
                signed = self._decode_stored_block(data)
                blk = signed.message
                if before_slot is not None and blk.slot >= before_slot:
                    continue
                body = blk.body
                blinded_body = t.BlindedBeaconBlockBody(
                    randao_reveal=body.randao_reveal,
                    eth1_data=body.eth1_data,
                    graffiti=body.graffiti,
                    proposer_slashings=body.proposer_slashings,
                    attester_slashings=body.attester_slashings,
                    attestations=body.attestations,
                    deposits=body.deposits,
                    voluntary_exits=body.voluntary_exits,
                    sync_aggregate=body.sync_aggregate,
                    execution_payload_header=payload_to_header(
                        body.execution_payload, self.preset
                    ),
                )
                blinded = t.BlindedBeaconBlock(
                    slot=blk.slot,
                    proposer_index=blk.proposer_index,
                    parent_root=bytes(blk.parent_root),
                    state_root=bytes(blk.state_root),
                    body=blinded_body,
                )
                if blinded.tree_hash_root() != blk.tree_hash_root():
                    # never rewrite a block under a different root (a real
                    # raise, not an assert: this must survive python -O)
                    raise RuntimeError(
                        f"pruned block root diverged for {root.hex()}"
                    )
                signed_blinded = t.SignedBlindedBeaconBlock(
                    message=blinded, signature=bytes(signed.signature)
                )
                batch.stage(
                    col,
                    root,
                    b"bellatrix_blinded\x00" + signed_blinded.as_ssz_bytes(),
                )
                pruned += 1
                if chunk_blocks and len(batch) >= chunk_blocks:
                    # per-chunk atomic commit: bounded journal, and any
                    # crash point recovers to a consistent image (no
                    # block is ever half-rewritten; a partially-pruned
                    # store is valid and resumable)
                    batch.commit()
                    batch = self.batch()
        batch.commit()
        return pruned
