"""Light-client data (reference consensus/types/src/light_client_
{bootstrap,update,finality_update,optimistic_update}.rs + the
light_client_bootstrap RPC protocol, rpc/protocol.rs:156): the objects a
light client needs to trustlessly follow the chain from a weak-
subjectivity root, built from real states with real merkle branches
(ssz/merkle_proof.py) over the altair state layout.

Spec generalized indices (light_client_update.rs:11-21): the altair
BeaconState has 24 fields -> a depth-5 field tree, so
current_sync_committee (field 22) lives at gindex 54, next_sync_committee
(field 23) at 55, and finalized_checkpoint.root at 105 (field 20's
checkpoint subtree, right child). This repo's field order matches the
spec's, which the tests pin.

NOTE: no `from __future__ import annotations` -- @container consumes
annotations as live SSZ descriptors (types/containers.py header).
"""

import functools

from ..ssz import Bytes32, Bytes96, Vector, container, uint64
from ..ssz.merkle_proof import MerkleTree, verify_merkle_proof
from ..types import types_for
from ..types.containers import BeaconBlockHeader

CURRENT_SYNC_COMMITTEE_INDEX = 54
NEXT_SYNC_COMMITTEE_INDEX = 55
FINALIZED_ROOT_INDEX = 105

CURRENT_SYNC_COMMITTEE_PROOF_LEN = 5
NEXT_SYNC_COMMITTEE_PROOF_LEN = 5
FINALIZED_ROOT_PROOF_LEN = 6


class LightClientError(ValueError):
    pass


@functools.lru_cache(maxsize=None)
def light_client_types(preset):
    t = types_for(preset)

    @container
    class LightClientBootstrap:
        header: BeaconBlockHeader.ssz_type
        current_sync_committee: t.SyncCommittee.ssz_type
        current_sync_committee_branch: Vector(
            Bytes32, CURRENT_SYNC_COMMITTEE_PROOF_LEN
        )

    @container
    class LightClientUpdate:
        attested_header: BeaconBlockHeader.ssz_type
        next_sync_committee: t.SyncCommittee.ssz_type
        next_sync_committee_branch: Vector(
            Bytes32, NEXT_SYNC_COMMITTEE_PROOF_LEN
        )
        finalized_header: BeaconBlockHeader.ssz_type
        finality_branch: Vector(Bytes32, FINALIZED_ROOT_PROOF_LEN)
        sync_aggregate: t.SyncAggregate.ssz_type
        signature_slot: uint64

    @container
    class LightClientFinalityUpdate:
        attested_header: BeaconBlockHeader.ssz_type
        finalized_header: BeaconBlockHeader.ssz_type
        finality_branch: Vector(Bytes32, FINALIZED_ROOT_PROOF_LEN)
        sync_aggregate: t.SyncAggregate.ssz_type
        signature_slot: uint64

    @container
    class LightClientOptimisticUpdate:
        attested_header: BeaconBlockHeader.ssz_type
        sync_aggregate: t.SyncAggregate.ssz_type
        signature_slot: uint64

    from types import SimpleNamespace

    return SimpleNamespace(
        LightClientBootstrap=LightClientBootstrap,
        LightClientUpdate=LightClientUpdate,
        LightClientFinalityUpdate=LightClientFinalityUpdate,
        LightClientOptimisticUpdate=LightClientOptimisticUpdate,
    )


# -- state merkle branches ----------------------------------------------------


def _field_tree(state) -> tuple[MerkleTree, dict[str, int]]:
    from ..ssz import cached_field_roots

    # the per-instance incremental cache: repeated proof generation (an
    # unauthenticated req/resp surface) must not re-merkleize the state
    roots = cached_field_roots(state)
    return MerkleTree(roots), {
        n: i for i, (n, _) in enumerate(state.ssz_fields)
    }


def sync_committee_branch(state, which: str = "current") -> list[bytes]:
    """Depth-5 branch proving (current|next)_sync_committee against the
    state root (BeaconState::compute_merkle_proof in the reference)."""
    if not hasattr(state, "current_sync_committee"):
        raise LightClientError("state predates altair")
    tree, index = _field_tree(state)
    return tree.proof(index[f"{which}_sync_committee"])


def finality_branch(state) -> list[bytes]:
    """Depth-6 branch proving finalized_checkpoint.ROOT: one step inside
    the checkpoint container (sibling = epoch leaf), then the field tree."""
    from ..ssz import uint64 as u64

    tree, index = _field_tree(state)
    epoch_leaf = u64.hash_tree_root(state.finalized_checkpoint.epoch)
    return [epoch_leaf] + tree.proof(index["finalized_checkpoint"])


def _header_for(state) -> BeaconBlockHeader:
    """latest_block_header with the state root filled (the canonical
    header a state commits to -- from_beacon_state in the reference)."""
    from ..ssz import cached_root

    hdr = state.latest_block_header
    state_root = bytes(hdr.state_root)
    if not any(state_root):
        state_root = cached_root(state)
    return BeaconBlockHeader(
        slot=hdr.slot,
        proposer_index=hdr.proposer_index,
        parent_root=bytes(hdr.parent_root),
        state_root=state_root,
        body_root=bytes(hdr.body_root),
    )


# -- server-side construction -------------------------------------------------


def light_client_bootstrap(state, preset):
    """LightClientBootstrap::from_beacon_state."""
    if not hasattr(state, "current_sync_committee"):
        raise LightClientError("state predates altair")
    lt = light_client_types(preset)
    return lt.LightClientBootstrap(
        header=_header_for(state),
        current_sync_committee=state.current_sync_committee,
        current_sync_committee_branch=sync_committee_branch(state, "current"),
    )


def light_client_finality_update(
    attested_state, finalized_header, sync_aggregate, signature_slot, preset
):
    lt = light_client_types(preset)
    return lt.LightClientFinalityUpdate(
        attested_header=_header_for(attested_state),
        finalized_header=finalized_header,
        finality_branch=finality_branch(attested_state),
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot,
    )


def light_client_optimistic_update(
    attested_state, sync_aggregate, signature_slot, preset
):
    lt = light_client_types(preset)
    return lt.LightClientOptimisticUpdate(
        attested_header=_header_for(attested_state),
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot,
    )


def light_client_update(
    attested_state, finalized_header, sync_aggregate, signature_slot, preset
):
    lt = light_client_types(preset)
    return lt.LightClientUpdate(
        attested_header=_header_for(attested_state),
        next_sync_committee=attested_state.next_sync_committee,
        next_sync_committee_branch=sync_committee_branch(
            attested_state, "next"
        ),
        finalized_header=finalized_header,
        finality_branch=finality_branch(attested_state),
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot,
    )


# -- client-side verification -------------------------------------------------


def verify_bootstrap(bootstrap, trusted_block_root: bytes) -> None:
    """The light client's install check (spec initialize_light_client_
    store): the header must BE the trusted root, and the committee must
    prove into the header's state root."""
    header_root = bootstrap.header.tree_hash_root()
    if header_root != bytes(trusted_block_root):
        raise LightClientError(
            f"bootstrap header {header_root.hex()[:12]} is not the "
            f"trusted root {bytes(trusted_block_root).hex()[:12]}"
        )
    committee_root = bootstrap.current_sync_committee.tree_hash_root()
    if not verify_merkle_proof(
        committee_root,
        [bytes(h) for h in bootstrap.current_sync_committee_branch],
        CURRENT_SYNC_COMMITTEE_INDEX,
        bytes(bootstrap.header.state_root),
    ):
        raise LightClientError("sync committee branch does not verify")


def verify_finality_branch(update) -> None:
    """The finality proof inside a (finality) update: finalized header
    root proven at gindex 105 of the ATTESTED state."""
    if not verify_merkle_proof(
        update.finalized_header.tree_hash_root(),
        [bytes(h) for h in update.finality_branch],
        FINALIZED_ROOT_INDEX,
        bytes(update.attested_header.state_root),
    ):
        raise LightClientError("finality branch does not verify")


def verify_next_committee_branch(update) -> None:
    if not verify_merkle_proof(
        update.next_sync_committee.tree_hash_root(),
        [bytes(h) for h in update.next_sync_committee_branch],
        NEXT_SYNC_COMMITTEE_INDEX,
        bytes(update.attested_header.state_root),
    ):
        raise LightClientError("next sync committee branch does not verify")


# -- update ranking (spec is_better_update) -----------------------------------


def period_slots(preset) -> int:
    """Slots per sync-committee period — the spec constant behind period
    arithmetic, window rotation, and UPDATE_TIMEOUT."""
    return preset.slots_per_epoch * preset.epochs_per_sync_committee_period


def _period_of_slot(slot: int, preset) -> int:
    return slot // period_slots(preset)


def _epoch_of(slot: int, preset) -> int:
    return slot // preset.slots_per_epoch


def is_sync_committee_update(update) -> bool:
    return any(bytes(h) != bytes(32) for h in update.next_sync_committee_branch)


def is_finality_update(update) -> bool:
    return any(bytes(h) != bytes(32) for h in update.finality_branch)


def is_better_update(new, old, preset) -> bool:
    """Spec is_better_update (altair sync protocol): full comparison
    chain — supermajority, relevant sync-committee payload, finality,
    finality-with-matching-committee, participation, attested-slot
    recency, signature-slot recency. Drives the EF update_ranking vectors
    and best_valid_update selection."""
    new_bits = list(new.sync_aggregate.sync_committee_bits)
    old_bits = list(old.sync_aggregate.sync_committee_bits)
    max_active = len(new_bits)
    new_active = sum(new_bits)
    old_active = sum(old_bits)

    new_supermajority = new_active * 3 >= max_active * 2
    old_supermajority = old_active * 3 >= max_active * 2
    if new_supermajority != old_supermajority:
        return new_supermajority
    if not new_supermajority and new_active != old_active:
        return new_active > old_active

    def relevant_committee(u) -> bool:
        return is_sync_committee_update(u) and _period_of_slot(
            int(u.attested_header.slot), preset
        ) == _period_of_slot(int(u.signature_slot), preset)

    new_rel, old_rel = relevant_committee(new), relevant_committee(old)
    if new_rel != old_rel:
        return new_rel

    new_fin, old_fin = is_finality_update(new), is_finality_update(old)
    if new_fin != old_fin:
        return new_fin

    def finality_with_committee(u, has_fin: bool) -> bool:
        return has_fin and _period_of_slot(
            int(u.finalized_header.slot), preset
        ) == _period_of_slot(int(u.attested_header.slot), preset)

    new_fwc = finality_with_committee(new, new_fin)
    old_fwc = finality_with_committee(old, old_fin)
    if new_fwc != old_fwc:
        return new_fwc

    if new_active != old_active:
        return new_active > old_active
    if int(new.attested_header.slot) != int(old.attested_header.slot):
        return int(new.attested_header.slot) < int(old.attested_header.slot)
    return int(new.signature_slot) < int(old.signature_slot)


# -- the following light client ----------------------------------------------


class LightClientStore:
    """Spec light-client store (altair sync protocol): installs from a
    trusted bootstrap, then follows updates by verifying the SYNC
    AGGREGATE SIGNATURE over the attested header (the crypto a light
    client actually trusts), the supermajority rule, the finality and
    next-committee branches, and rotating committees across periods."""

    def __init__(
        self, trusted_block_root: bytes, bootstrap, preset, spec,
        genesis_validators_root: bytes,
    ):
        verify_bootstrap(bootstrap, trusted_block_root)
        self.preset = preset
        self.spec = spec
        self.genesis_validators_root = bytes(genesis_validators_root)
        self.finalized_header = bootstrap.header
        self.optimistic_header = bootstrap.header
        self.current_sync_committee = bootstrap.current_sync_committee
        self.next_sync_committee = None
        # spec get_safety_threshold inputs: rolling max participation over
        # the current and previous half-periods — the optimistic header
        # only follows updates with MORE than half the recent max, so a
        # single captured key cannot steer it
        self.previous_max_active_participants = 0
        self.current_max_active_participants = 0
        self._participation_window = 0
        self._last_local_window: int | None = None
        # spec LightClientStore.best_valid_update: stashed for the
        # UPDATE_TIMEOUT force-update path when finality stalls
        self.best_valid_update = None
        # parsed-pubkey cache keyed by committee root: the committee is
        # fixed for a whole sync period (8192 slots on mainnet), so the
        # per-update deserialization of up to 512 keys amortizes to zero
        self._parsed_committees: dict[bytes, list] = {}

    def _period_of(self, slot: int) -> int:
        return _period_of_slot(slot, self.preset)

    def _window_of(self, slot: int) -> int:
        return (2 * slot) // max(1, period_slots(self.preset))

    def _rotate_to(self, window: int) -> None:
        if window == self._participation_window + 1:
            self.previous_max_active_participants = (
                self.current_max_active_participants
            )
            self.current_max_active_participants = 0
            self._participation_window = window
        elif window > self._participation_window + 1:
            # >=2 windows elapsed with no verified updates: both maxes are
            # stale — zero them rather than carrying an old high-water mark
            # into the threshold (it would reject a recovered-but-lower
            # participation level for an extra half-period)
            self.previous_max_active_participants = 0
            self.current_max_active_participants = 0
            self._participation_window = window

    def process_slot(self, current_slot: int) -> None:
        """Clock-driven window rotation (spec
        process_slot_for_light_client_store's UPDATE_TIMEOUT): embedders
        call this each slot so the safety threshold DECAYS when updates
        stop arriving — otherwise a stale high-water mark would reject a
        recovered-but-lower participation level indefinitely."""
        self._last_local_window = self._window_of(current_slot)
        self._rotate_to(self._last_local_window)

    def _note_participation(self, n: int, signature_slot: int) -> None:
        """Track max participation per half-period window. Update-driven
        rotation is a fallback for undriven stores, capped at the local
        window when a clock IS driven — a verified-but-future
        signature_slot must not zero the maxes early (a lone captured key
        could then steer the threshold to 0)."""
        window = self._window_of(signature_slot)
        if self._last_local_window is not None:
            window = min(window, self._last_local_window)
        self._rotate_to(window)
        self.current_max_active_participants = max(
            self.current_max_active_participants, n
        )

    def safety_threshold(self) -> int:
        return (
            max(
                self.previous_max_active_participants,
                self.current_max_active_participants,
            )
            // 2
        )

    def _verify_sync_aggregate(
        self,
        update,
        supermajority: bool = True,
        min_participants: int | None = None,
    ) -> None:
        from ..crypto.bls import (
            PublicKey,
            Signature,
            SignatureSet,
            verify_signature_sets,
        )
        from ..types.chain_spec import DOMAIN_SYNC_COMMITTEE
        from ..types.containers import SigningData
        from ..types.helpers import compute_domain, compute_epoch_at_slot

        bits = list(update.sync_aggregate.sync_committee_bits)
        n = sum(bits)
        # Supermajority gates only FINALITY application; optimistic headers
        # advance above the SAFETY THRESHOLD (spec get_safety_threshold:
        # strictly more than half the recent max participation) — liveness
        # at 34-66% participation without following a lone captured key.
        if min_participants is not None:
            minimum = min_participants
        elif supermajority:
            minimum = -(-2 * len(bits) // 3)
        else:
            minimum = max(1, self.safety_threshold() + 1)
        if n < minimum:
            raise LightClientError(
                f"insufficient sync participation {n}/{len(bits)}"
            )
        sig_slot = int(update.signature_slot)
        if sig_slot <= int(update.attested_header.slot):
            raise LightClientError("signature slot not after attested slot")
        sig_period = self._period_of(sig_slot)
        store_period = self._period_of(int(self.finalized_header.slot))
        if sig_period == store_period:
            committee = self.current_sync_committee
        elif (
            sig_period == store_period + 1
            and self.next_sync_committee is not None
        ):
            committee = self.next_sync_committee
        else:
            raise LightClientError(
                f"no committee known for period {sig_period}"
            )
        committee_root = committee.tree_hash_root()
        parsed = self._parsed_committees.get(committee_root)
        if parsed is None:
            parsed = [
                PublicKey.from_bytes(bytes(pk)) for pk in committee.pubkeys
            ]
            # cap at 2: current + next is all a store ever holds, and
            # period-boundary updates alternate between them
            if len(self._parsed_committees) >= 2:
                self._parsed_committees.pop(
                    next(iter(self._parsed_committees))
                )
            self._parsed_committees[committee_root] = parsed
        pubkeys = [pk for pk, bit in zip(parsed, bits) if bit]
        # the aggregate signs the attested header root in the slot BEFORE
        # the signature slot (spec get_sync_committee_message domain)
        epoch = compute_epoch_at_slot(max(sig_slot, 1) - 1, self.preset)
        domain = compute_domain(
            DOMAIN_SYNC_COMMITTEE,
            self.spec.fork_version_at_epoch(epoch),
            self.genesis_validators_root,
        )
        root = SigningData(
            object_root=update.attested_header.tree_hash_root(),
            domain=domain,
        ).tree_hash_root()
        ok = verify_signature_sets(
            [
                SignatureSet.multiple_pubkeys(
                    Signature.from_bytes(
                        bytes(update.sync_aggregate.sync_committee_signature)
                    ),
                    pubkeys,
                    root,
                )
            ]
        )
        if not ok:
            raise LightClientError("sync aggregate signature invalid")
        # only a VERIFIED aggregate may raise the safety-threshold inputs
        self._note_participation(n, sig_slot)

    def process_update(self, update) -> None:
        """Full LightClientUpdate: signature + finality + committee
        rotation (spec process_light_client_update, reduced to the
        immediate-apply path -- every served update carries a verified
        finality proof)."""
        self._verify_sync_aggregate(update)
        verify_finality_branch(update)
        has_next = any(bytes(h) != bytes(32) for h in update.next_sync_committee_branch)
        if has_next:
            verify_next_committee_branch(update)
        att_period = self._period_of(int(update.attested_header.slot))
        store_period = self._period_of(int(self.finalized_header.slot))
        if has_next and att_period == store_period:
            self.next_sync_committee = update.next_sync_committee
        if int(update.finalized_header.slot) > int(self.finalized_header.slot):
            new_period = self._period_of(int(update.finalized_header.slot))
            if new_period > store_period:
                if self.next_sync_committee is None:
                    raise LightClientError(
                        "cannot cross a period without the next committee"
                    )
                self.current_sync_committee = self.next_sync_committee
                self.next_sync_committee = (
                    update.next_sync_committee if has_next else None
                )
            self.finalized_header = update.finalized_header
        if int(update.attested_header.slot) > int(self.optimistic_header.slot):
            self.optimistic_header = update.attested_header

    def process_finality_update(self, update) -> None:
        """LightClientFinalityUpdate: signature + finality proof, no
        committee payload."""
        self._verify_sync_aggregate(update)
        verify_finality_branch(update)
        if int(update.finalized_header.slot) > int(self.finalized_header.slot):
            if self._period_of(
                int(update.finalized_header.slot)
            ) > self._period_of(int(self.finalized_header.slot)):
                raise LightClientError(
                    "finality update crosses a period; need a full update"
                )
            self.finalized_header = update.finalized_header
        if int(update.attested_header.slot) > int(self.optimistic_header.slot):
            self.optimistic_header = update.attested_header

    def process_optimistic_update(self, update) -> None:
        """LightClientOptimisticUpdate: signature only; advances the
        optimistic head."""
        self._verify_sync_aggregate(update, supermajority=False)
        if int(update.attested_header.slot) > int(self.optimistic_header.slot):
            self.optimistic_header = update.attested_header

    # -- spec-shaped update machinery (EF light_client/sync vectors) --------

    def _update_timeout(self) -> int:
        # spec UPDATE_TIMEOUT: one sync-committee period of slots
        return period_slots(self.preset)

    def process_spec_update(self, update, current_slot: int) -> None:
        """Full spec process_light_client_update: validate (signature,
        slot ordering, period relevance, branches), stash
        best_valid_update, advance the optimistic header past the safety
        threshold, and APPLY on supermajority+finality — the exact shape
        the EF light_client/sync vectors drive."""
        bits = list(update.sync_aggregate.sync_committee_bits)
        n_active = sum(bits)
        sig_slot = int(update.signature_slot)
        attested_slot = int(update.attested_header.slot)
        finalized_slot = int(update.finalized_header.slot)
        has_finality = is_finality_update(update)
        has_committee = is_sync_committee_update(update)
        if not (current_slot >= sig_slot):
            raise LightClientError("update signed in the future")
        # full spec slot ordering (validate_light_client_update):
        # current_slot >= sig_slot > attested_slot >= finalized_slot; the
        # attested >= finalized half rides the has_finality branch below
        if not (sig_slot > attested_slot):
            raise LightClientError("signature slot not after attested slot")
        if has_finality and attested_slot < finalized_slot:
            raise LightClientError("attested before finalized")
        if not has_finality:
            # spec validate: a non-finality update must carry the EMPTY
            # finalized header — the sync aggregate signs only the
            # attested header, so an unproven non-empty finalized_header
            # would be attacker-chosen
            empty = type(update.finalized_header).default()
            if (
                update.finalized_header.tree_hash_root()
                != empty.tree_hash_root()
            ):
                raise LightClientError(
                    "non-finality update carries a finalized header"
                )
        store_period = self._period_of(int(self.finalized_header.slot))
        sig_period = self._period_of(sig_slot)
        attested_period = self._period_of(attested_slot)
        if self.next_sync_committee is not None:
            if sig_period not in (store_period, store_period + 1):
                raise LightClientError("irrelevant signature period")
        elif sig_period != store_period:
            raise LightClientError("signature period without known committee")
        update_has_next = (
            self.next_sync_committee is None
            and has_committee
            and attested_period == store_period
        )
        if attested_slot <= int(self.finalized_header.slot) and not update_has_next:
            raise LightClientError("update does not advance the store")
        if has_finality:
            verify_finality_branch(update)
        if has_committee:
            verify_next_committee_branch(update)
        # spec validate: only MIN_SYNC_COMMITTEE_PARTICIPANTS gates here
        self._verify_sync_aggregate(update, min_participants=1)

        if self.best_valid_update is None or is_better_update(
            update, self.best_valid_update, self.preset
        ):
            self.best_valid_update = update
        if (
            n_active > self.safety_threshold()
            and attested_slot > int(self.optimistic_header.slot)
        ):
            self.optimistic_header = update.attested_header
        update_has_finalized_next = (
            update_has_next
            and has_finality
            and self._period_of(finalized_slot) == attested_period
        )
        if n_active * 3 >= len(bits) * 2 and (
            finalized_slot > int(self.finalized_header.slot)
            or update_has_finalized_next
        ):
            self._apply_spec_update(update)
            self.best_valid_update = None

    def _apply_spec_update(self, update) -> None:
        """Spec apply_light_client_update: committee rotation across the
        period boundary, then finalized/optimistic header advance."""
        store_period = self._period_of(int(self.finalized_header.slot))
        finalized_period = self._period_of(int(update.finalized_header.slot))
        if self.next_sync_committee is None:
            if finalized_period != store_period:
                raise LightClientError(
                    "cannot install next committee from another period"
                )
            # only a committee-carrying update may install: a zeroed
            # default committee would flip the None "unknown" sentinel and
            # wedge the store at the period boundary (the spec's
            # is_next_sync_committee_known compares against SyncCommittee()
            # so a zeroed install stays "unknown" there; with a None
            # sentinel the guard must live here)
            if is_sync_committee_update(update):
                self.next_sync_committee = update.next_sync_committee
        elif finalized_period == store_period + 1:
            self.current_sync_committee = self.next_sync_committee
            self.next_sync_committee = (
                update.next_sync_committee
                if is_sync_committee_update(update)
                else None
            )
            self.previous_max_active_participants = (
                self.current_max_active_participants
            )
            self.current_max_active_participants = 0
        if int(update.finalized_header.slot) > int(self.finalized_header.slot):
            self.finalized_header = update.finalized_header
            if int(self.finalized_header.slot) > int(
                self.optimistic_header.slot
            ):
                self.optimistic_header = self.finalized_header

    def force_update(self, current_slot: int) -> None:
        """Spec process_light_client_store_force_update: when finality has
        stalled for a whole UPDATE_TIMEOUT, advance from the best stashed
        update, treating its attested header as finalized."""
        if (
            current_slot
            <= int(self.finalized_header.slot) + self._update_timeout()
            or self.best_valid_update is None
        ):
            return
        best = self.best_valid_update
        if int(best.finalized_header.slot) <= int(self.finalized_header.slot):
            # promote the attested header (spec zeroes the finality proof
            # and substitutes attested_header as the new finalized header)
            lt = light_client_types(self.preset)
            best = lt.LightClientUpdate(
                attested_header=best.attested_header,
                next_sync_committee=best.next_sync_committee,
                next_sync_committee_branch=best.next_sync_committee_branch,
                finalized_header=best.attested_header,
                finality_branch=best.finality_branch,
                sync_aggregate=best.sync_aggregate,
                signature_slot=best.signature_slot,
            )
        self._apply_spec_update(best)
        self.best_valid_update = None
