"""Chain-owned validator pubkey cache: decompress each registry key ONCE
at import time, keep the decompressed keys indexed by validator index, and
expose a device-resident limb table for the TPU batch verifier.

The TPU analogue of the reference's ValidatorPubkeyCache
(beacon_node/beacon_chain/src/validator_pubkey_cache.rs:10-23,79,131):
decompression is expensive, so it happens exactly once per validator --
here the same moment also packs the key's limb tensor and (lazily) uploads
it to the device table, so steady-state batch verification ships only
validator indices host->device.

Keys handed out by this cache are tagged with `validator_index` and
`table` (= this cache); the jax_tpu backend detects fully-tagged batches
and gathers limb rows on device instead of packing host arrays. Backends
without a device path (cpu, fake) simply ignore the tags, so the cache is
backend-agnostic and never imports jax unless the device table is used.
"""

from __future__ import annotations

import functools

from ..crypto.bls import PublicKey


class PubkeyCacheError(ValueError):
    pass


@functools.lru_cache(maxsize=1 << 20)
def _validated(pubkey_bytes: bytes) -> PublicKey:
    """Decompression + subgroup check happen once per key process-wide
    (interop keys recur across every test chain)."""
    return PublicKey.from_bytes(pubkey_bytes)


def _fresh(pubkey_bytes: bytes) -> PublicKey:
    """A cache-private wrapper sharing the validated point: each chain's
    cache tags its OWN objects (index + table) without clobbering keys
    shared through the process-wide LRU."""
    src = _validated(bytes(pubkey_bytes))
    # the shared LRU key already passed from_bytes' key_validate
    return PublicKey(src.point, src.to_bytes(), subgroup_checked=True)


class ValidatorPubkeyCache:
    def __init__(self, state=None):
        self._pubkeys: list[PublicKey] = []
        self._index_by_bytes: dict[bytes, int] = {}
        self._table = None  # lazily-built jax_tpu.PubkeyTable
        if state is not None:
            self.import_new_pubkeys(state)

    def __len__(self) -> int:
        return len(self._pubkeys)

    def import_new_pubkeys(self, state) -> int:
        """Decompress + register validators added since the last import
        (mirrors import_new_pubkeys, validator_pubkey_cache.rs:79).
        Returns the number of new keys."""
        start = len(self._pubkeys)
        new = []
        for i in range(start, len(state.validators)):
            pk = _fresh(state.validators[i].pubkey)
            pk.validator_index = i
            pk.table = self
            new.append(pk)
        if not new:
            return 0
        self._pubkeys.extend(new)
        for pk in new:
            self._index_by_bytes.setdefault(pk.to_bytes(), pk.validator_index)
        if self._table is not None:
            self._table.import_new_pubkeys(new)
        return len(new)

    def get(self, index: int) -> PublicKey:
        if index >= len(self._pubkeys):
            raise PubkeyCacheError(f"unknown validator index {index}")
        return self._pubkeys[index]

    def get_index(self, pubkey_bytes: bytes):
        return self._index_by_bytes.get(bytes(pubkey_bytes))

    def resolve(self, pubkey_bytes: bytes) -> PublicKey:
        """bytes -> cached decompressed key; decompresses (untagged) only
        for keys outside the registry."""
        idx = self._index_by_bytes.get(bytes(pubkey_bytes))
        if idx is not None:
            return self._pubkeys[idx]
        return _validated(bytes(pubkey_bytes))

    def getter(self, state=None):
        """get_pubkey(validator_index) closure for the signature-set
        builders. With `state`, indices beyond the cache fall back to the
        state registry (a deposit in the block being verified may have
        appended validators the chain has not imported yet)."""

        def get_pubkey(index: int) -> PublicKey:
            if index < len(self._pubkeys):
                return self._pubkeys[index]
            if state is not None and index < len(state.validators):
                return _validated(bytes(state.validators[index].pubkey))
            raise PubkeyCacheError(f"unknown validator index {index}")

        return get_pubkey

    # --- device table (duck-typed for the jax_tpu backend) -----------------

    def device_table(self):
        """Bucketed (rows, 3, W) limb table on device; built lazily so the
        cache works without jax for cpu/fake backends."""
        if self._table is None:
            from ..crypto.bls.backends.jax_tpu import PubkeyTable

            table = PubkeyTable()
            table.import_new_pubkeys(self._pubkeys)
            self._table = table
        return self._table.device_table()

    def gather(self, indices):
        """Validator indices -> (..., 3, W) device limb rows, via the
        table's (mesh-sharded) gather path."""
        self.device_table()
        return self._table.gather(indices)
