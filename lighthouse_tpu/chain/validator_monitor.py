"""Validator monitor (reference beacon_chain/src/validator_monitor.rs,
1,690 LoC): per-registered-validator observability — block proposals,
attestation inclusions and delays, per-epoch participation summaries
(source/target/head hit or MISS, from the state's own participation
flags), sync-committee signatures, exits and slashings — surfaced as
metrics and queryable stats. Plus the block-times cache
(block_times_cache.rs): per-block observed→imported latency."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import metrics as M


@dataclass
class EpochSummary:
    """Per-epoch rollup for one monitored validator (validator_monitor.rs
    EpochSummary): what it did, and what the chain ended up recording."""

    epoch: int
    attestations_seen: int = 0
    attestation_min_delay: int | None = None
    source_hit: bool | None = None  # None until the epoch is evaluated
    target_hit: bool | None = None
    head_hit: bool | None = None
    sync_signatures: int = 0
    blocks_proposed: int = 0
    exits_observed: int = 0
    slashings_observed: int = 0


_SUMMARY_RETENTION = 8  # epochs of history per validator


@dataclass
class MonitoredValidator:
    index: int
    blocks_proposed: int = 0
    attestations_seen: int = 0
    attestation_min_delay_slots: dict[int, int] = field(default_factory=dict)
    last_attestation_slot: int | None = None
    # bounded window of recently-gossiped attestation slots (liveness
    # queries must see epoch E even after the validator attests E+1)
    recent_attestation_slots: dict[int, None] = field(default_factory=dict)
    sync_signatures: int = 0
    last_sync_signature_slot: int | None = None
    summaries: dict[int, EpochSummary] = field(default_factory=dict)

    def summary(self, epoch: int) -> EpochSummary:
        s = self.summaries.get(epoch)
        if s is None:
            s = self.summaries[epoch] = EpochSummary(epoch)
            # bounded history
            for old in sorted(self.summaries)[: -_SUMMARY_RETENTION]:
                del self.summaries[old]
        return s


@dataclass
class BlockTimes:
    slot: int
    observed_at: float | None = None
    imported_at: float | None = None

    @property
    def import_latency(self) -> float | None:
        if self.observed_at is None or self.imported_at is None:
            return None
        return self.imported_at - self.observed_at


class ValidatorMonitor:
    """Registered-validator tracking fed by the chain's import paths
    (beacon_chain calls in, exactly as the reference's monitor is driven
    from block/attestation processing)."""

    def __init__(self, auto_register: bool = False):
        self.auto_register = auto_register
        self.validators: dict[int, MonitoredValidator] = {}
        self.block_times: dict[bytes, BlockTimes] = {}
        self._last_evaluated_epoch: int | None = None
        self._retired_through: int | None = None
        # families are declared in utils/metrics.py (metric-origin rule:
        # the /metrics surface is enumerable from that one module)
        self._proposals = M.VALIDATOR_MONITOR_PROPOSALS
        self._attestations = M.VALIDATOR_MONITOR_ATTESTATIONS
        self._inclusion_delay = M.VALIDATOR_MONITOR_INCLUSION_DELAY
        self._target_misses = M.VALIDATOR_MONITOR_TARGET_MISSES
        self._head_misses = M.VALIDATOR_MONITOR_HEAD_MISSES
        self._sync_signatures = M.VALIDATOR_MONITOR_SYNC_SIGNATURES
        self._slashed = M.VALIDATOR_MONITOR_SLASHED

    def register_validator(self, index: int) -> None:
        self.validators.setdefault(index, MonitoredValidator(index))

    def _get(self, index: int) -> MonitoredValidator | None:
        v = self.validators.get(index)
        if v is None and self.auto_register:
            v = self.validators[index] = MonitoredValidator(index)
        return v

    # -- feed points (beacon_chain.rs import paths) -------------------------

    def on_block_observed(self, block_root: bytes, slot: int, now: float) -> None:
        bt = self.block_times.setdefault(bytes(block_root), BlockTimes(slot))
        if bt.observed_at is None:
            bt.observed_at = now

    def on_block_imported(
        self, block_root: bytes, block, now: float
    ) -> None:
        bt = self.block_times.setdefault(
            bytes(block_root), BlockTimes(block.slot)
        )
        bt.imported_at = now
        v = self._get(block.proposer_index)
        if v is not None:
            v.blocks_proposed += 1
            self._proposals.inc()
        # attestations included in this block credit their participants'
        # inclusion delay (validator_monitor.rs register_attestation_in_block)

    def on_attestation_included(
        self, attester_indices, data_slot: int, block_slot: int
    ) -> None:
        delay = max(block_slot - data_slot, 1)
        for idx in attester_indices:
            v = self._get(idx)
            if v is None:
                continue
            prior = v.attestation_min_delay_slots.get(data_slot)
            if prior is None or delay < prior:
                v.attestation_min_delay_slots[data_slot] = delay
                self._inclusion_delay.observe(delay)

    def on_gossip_attestation(self, attester_indices, slot: int) -> None:
        for idx in attester_indices:
            v = self._get(idx)
            if v is not None:
                v.attestations_seen += 1
                v.last_attestation_slot = slot
                v.recent_attestation_slots[slot] = None
                while len(v.recent_attestation_slots) > 128:
                    v.recent_attestation_slots.pop(
                        next(iter(v.recent_attestation_slots))
                    )
                self._attestations.inc()

    def on_sync_committee_message(self, validator_index: int, slot: int) -> None:
        v = self._get(validator_index)
        if v is not None:
            v.sync_signatures += 1
            v.last_sync_signature_slot = slot
            self._sync_signatures.inc()

    def on_exit_observed(self, validator_index: int, epoch: int) -> None:
        v = self._get(validator_index)
        if v is not None:
            v.summary(epoch).exits_observed += 1

    def on_slashing_observed(self, validator_indices, epoch: int) -> None:
        for idx in validator_indices:
            v = self._get(idx)
            if v is not None:
                v.summary(epoch).slashings_observed += 1
                self._slashed.inc()

    # -- per-epoch evaluation (validator_monitor.rs process_valid_state) ----

    def evaluate_epoch(self, state, preset) -> None:
        """At an epoch boundary, grade every monitored validator's
        PREVIOUS epoch from the state's own participation flags: did the
        chain record its source/target/head votes? Misses become counters
        a dashboard can alert on — the reference's core monitoring loop."""
        if not hasattr(state, "previous_epoch_participation"):
            return  # phase0: pending-attestation grading not surfaced
        from ..state_transition.participation import (
            TIMELY_HEAD_FLAG_INDEX,
            TIMELY_SOURCE_FLAG_INDEX,
            TIMELY_TARGET_FLAG_INDEX,
            has_flag,
        )
        from ..types import compute_epoch_at_slot, is_active_validator

        current_epoch = compute_epoch_at_slot(state.slot, preset)
        if current_epoch == 0:
            return  # no completed epoch to grade yet
        prev_epoch = current_epoch - 1
        # RE-grade on every head change while the epoch is still "previous"
        # — attestations for E-1 may land up to a full epoch late (delay
        # 2+ crosses the boundary), so summaries stay live until the epoch
        # retires. Miss COUNTERS bump only at retirement, from the final
        # summary, so late inclusions cannot overstate misses.
        if (
            self._last_evaluated_epoch is not None
            and prev_epoch > self._last_evaluated_epoch
        ):
            # a multi-epoch head jump retires EVERY epoch the watermark
            # skips over, not just the watermark itself -- intermediate
            # epochs graded on earlier head changes must still count
            # their misses (they can never be re-graded once retired)
            for epoch in range(self._last_evaluated_epoch, prev_epoch):
                self._count_retired_epoch(epoch)
        # a reorg can move the head to an EARLIER epoch; never regress the
        # watermark or a later advance would retire (and count) the same
        # epoch twice
        if (
            self._last_evaluated_epoch is None
            or prev_epoch > self._last_evaluated_epoch
        ):
            self._last_evaluated_epoch = prev_epoch
        elif prev_epoch < self._last_evaluated_epoch:
            return
        part = state.previous_epoch_participation
        for idx, v in self.validators.items():
            if idx >= len(state.validators):
                continue
            val = state.validators[idx]
            if not is_active_validator(val, prev_epoch):
                continue
            flags = part[idx]
            s = v.summary(prev_epoch)
            s.source_hit = bool(has_flag(flags, TIMELY_SOURCE_FLAG_INDEX))
            s.target_hit = bool(has_flag(flags, TIMELY_TARGET_FLAG_INDEX))
            s.head_hit = bool(has_flag(flags, TIMELY_HEAD_FLAG_INDEX))
            delays = [
                d
                for sl, d in v.attestation_min_delay_slots.items()
                if prev_epoch * preset.slots_per_epoch
                <= sl
                < (prev_epoch + 1) * preset.slots_per_epoch
            ]
            # per-epoch figures, not lifetime counters: distinct included
            # attestation slots and the best delay within THIS epoch
            s.attestations_seen = len(delays)
            s.attestation_min_delay = min(delays) if delays else None
            # prune inclusion-delay entries past the retention window so
            # per-head-change grading stays O(window), not O(uptime)
            horizon = (
                max(prev_epoch - _SUMMARY_RETENTION, 0)
                * preset.slots_per_epoch
            )
            for sl in [
                sl for sl in v.attestation_min_delay_slots if sl < horizon
            ]:
                del v.attestation_min_delay_slots[sl]

    def _count_retired_epoch(self, epoch: int) -> None:
        if self._retired_through is not None and epoch <= self._retired_through:
            return
        self._retired_through = epoch
        for v in self.validators.values():
            s = v.summaries.get(epoch)
            if s is None:
                continue
            if s.target_hit is False:
                self._target_misses.inc()
            if s.head_hit is False:
                self._head_misses.inc()

    # -- queries (the /lighthouse/ui/validator-metrics seat) ----------------

    def stats(self, index: int) -> dict | None:
        v = self.validators.get(index)
        if v is None:
            return None
        delays = v.attestation_min_delay_slots.values()
        recent = [
            {
                "epoch": s.epoch,
                "source_hit": s.source_hit,
                "target_hit": s.target_hit,
                "head_hit": s.head_hit,
                "attestation_min_delay": s.attestation_min_delay,
                "exits_observed": s.exits_observed,
                "slashings_observed": s.slashings_observed,
            }
            for _, s in sorted(v.summaries.items())
        ]
        return {
            "index": v.index,
            "blocks_proposed": v.blocks_proposed,
            "attestations_seen": v.attestations_seen,
            "attestations_included": len(v.attestation_min_delay_slots),
            "mean_inclusion_delay": (
                # lint: allow[float-consensus] -- operator-facing report,
                # never fed back into state-transition arithmetic
                sum(delays) / len(delays) if delays else None
            ),
            "last_attestation_slot": v.last_attestation_slot,
            "epoch_summaries": recent,
        }
