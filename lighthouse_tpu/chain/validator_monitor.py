"""Validator monitor (reference beacon_chain/src/validator_monitor.rs,
1,690 LoC): per-registered-validator observability — block proposals,
attestation inclusions and delays, missed duties — surfaced as metrics
and queryable stats. Plus the block-times cache
(block_times_cache.rs): per-block observed→imported latency."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils.metrics import REGISTRY


@dataclass
class MonitoredValidator:
    index: int
    blocks_proposed: int = 0
    attestations_seen: int = 0
    attestation_min_delay_slots: dict[int, int] = field(default_factory=dict)
    last_attestation_slot: int | None = None


@dataclass
class BlockTimes:
    slot: int
    observed_at: float | None = None
    imported_at: float | None = None

    @property
    def import_latency(self) -> float | None:
        if self.observed_at is None or self.imported_at is None:
            return None
        return self.imported_at - self.observed_at


class ValidatorMonitor:
    """Registered-validator tracking fed by the chain's import paths
    (beacon_chain calls in, exactly as the reference's monitor is driven
    from block/attestation processing)."""

    def __init__(self, auto_register: bool = False):
        self.auto_register = auto_register
        self.validators: dict[int, MonitoredValidator] = {}
        self.block_times: dict[bytes, BlockTimes] = {}
        self._proposals = REGISTRY.counter(
            "validator_monitor_blocks_proposed_total",
            "Blocks proposed by monitored validators",
        )
        self._attestations = REGISTRY.counter(
            "validator_monitor_attestations_total",
            "Attestations by monitored validators seen on-chain or gossip",
        )
        self._inclusion_delay = REGISTRY.histogram(
            "validator_monitor_attestation_inclusion_delay_slots",
            "Slots between attestation slot and block inclusion",
            buckets=(1, 2, 3, 4, 8, 16, 32),
        )

    def register_validator(self, index: int) -> None:
        self.validators.setdefault(index, MonitoredValidator(index))

    def _get(self, index: int) -> MonitoredValidator | None:
        v = self.validators.get(index)
        if v is None and self.auto_register:
            v = self.validators[index] = MonitoredValidator(index)
        return v

    # -- feed points (beacon_chain.rs import paths) -------------------------

    def on_block_observed(self, block_root: bytes, slot: int, now: float) -> None:
        bt = self.block_times.setdefault(bytes(block_root), BlockTimes(slot))
        if bt.observed_at is None:
            bt.observed_at = now

    def on_block_imported(
        self, block_root: bytes, block, now: float
    ) -> None:
        bt = self.block_times.setdefault(
            bytes(block_root), BlockTimes(block.slot)
        )
        bt.imported_at = now
        v = self._get(block.proposer_index)
        if v is not None:
            v.blocks_proposed += 1
            self._proposals.inc()
        # attestations included in this block credit their participants'
        # inclusion delay (validator_monitor.rs register_attestation_in_block)

    def on_attestation_included(
        self, attester_indices, data_slot: int, block_slot: int
    ) -> None:
        delay = max(block_slot - data_slot, 1)
        for idx in attester_indices:
            v = self._get(idx)
            if v is None:
                continue
            prior = v.attestation_min_delay_slots.get(data_slot)
            if prior is None or delay < prior:
                v.attestation_min_delay_slots[data_slot] = delay
                self._inclusion_delay.observe(delay)

    def on_gossip_attestation(self, attester_indices, slot: int) -> None:
        for idx in attester_indices:
            v = self._get(idx)
            if v is not None:
                v.attestations_seen += 1
                v.last_attestation_slot = slot
                self._attestations.inc()

    # -- queries (the /lighthouse/ui/validator-metrics seat) ----------------

    def stats(self, index: int) -> dict | None:
        v = self.validators.get(index)
        if v is None:
            return None
        delays = v.attestation_min_delay_slots.values()
        return {
            "index": v.index,
            "blocks_proposed": v.blocks_proposed,
            "attestations_seen": v.attestations_seen,
            "attestations_included": len(v.attestation_min_delay_slots),
            "mean_inclusion_delay": (
                sum(delays) / len(delays) if delays else None
            ),
            "last_attestation_slot": v.last_attestation_slot,
        }
