"""Block verification typestate pipeline (reference beacon_chain/src/
block_verification.rs:588-619): a block ascends through

    GossipVerifiedBlock        gossip checks + proposer signature ONLY
    SignatureVerifiedBlock     every remaining signature, ONE batch call
    (execution/import)         state transition + fork choice via
                               BeaconChain.process_block(NO_VERIFICATION)

so gossip re-publication happens after the cheap stage, the expensive
batch runs once, and the transition never re-verifies. Plus
`signature_verify_chain_segment` (block_verification.rs:525): a whole
sync segment's signatures in ONE backend call."""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto.bls import verify_signature_sets
from ..state_transition import (
    BlockProcessingError,
    BlockSignatureStrategy,
    BlockSignatureVerifier,
    clone_state,
    process_slots,
)
from ..state_transition.per_slot import get_beacon_proposer_index
from ..state_transition.signature_sets import (
    block_proposal_signature_set,
)
from .beacon_chain import BeaconChain, BlockError


class UnknownParent(BlockError):
    """Parent not known locally: the caller should trigger a block lookup
    (block_lookups/) rather than penalize the peer."""

    def __init__(self, parent_root: bytes):
        super().__init__(f"unknown parent {bytes(parent_root).hex()[:12]}")
        self.parent_root = bytes(parent_root)


class BlockAlreadyKnown(BlockError):
    """Benign duplicate (the reference's BlockIsAlreadyKnown): gossip and
    sync overlap constantly, so callers must NOT penalize the sender."""

    def __init__(self, block_root: bytes):
        super().__init__(f"block already known {bytes(block_root).hex()[:12]}")
        self.block_root = bytes(block_root)


class BlockEquivocation(BlockError):
    """A signature-valid SECOND distinct block from a (slot, proposer)
    already observed with a verified block: spec gossip validation
    IGNOREs it (no penalty — the relayer may be honest relaying a real
    equivocation), it must not import via gossip, and the caller should
    hand the header to the slasher."""

    def __init__(self, block_root: bytes):
        super().__init__(
            f"proposer equivocation {bytes(block_root).hex()[:12]}"
        )
        self.block_root = bytes(block_root)


@dataclass
class GossipVerifiedBlock:
    signed_block: object
    block_root: bytes
    # the state advanced to the block's slot, reused by the next stage
    pre_state: object

    @classmethod
    def verify(cls, chain: BeaconChain, signed_block) -> "GossipVerifiedBlock":
        """block_verification.rs:588 GossipVerifiedBlock::new: slot/parent/
        proposer checks and the proposer signature alone."""
        block = signed_block.message
        block_root = block.tree_hash_root()
        if block_root in chain._states:
            raise BlockAlreadyKnown(block_root)
        if block.slot > chain.current_slot:
            raise BlockError("block from the future")
        fin_epoch, _ = chain.finalized_checkpoint
        if block.slot <= fin_epoch * chain.preset.slots_per_epoch:
            raise BlockError("block below finalization")
        parent_root = bytes(block.parent_root)
        parent_state = chain._states.get(parent_root)
        if parent_state is None:
            raise UnknownParent(parent_root)
        state = clone_state(parent_state)
        try:
            state = process_slots(state, block.slot, chain.preset, chain.spec)
        except BlockProcessingError as e:
            raise BlockError(str(e)) from None
        expected = get_beacon_proposer_index(state, chain.preset, chain.spec)
        if block.proposer_index != expected:
            raise BlockError(
                f"wrong proposer {block.proposer_index}, expected {expected}"
            )
        from ..utils import metrics as M

        try:
            sig_set = block_proposal_signature_set(
                state,
                chain.pubkey_cache.getter(state),
                signed_block,
                chain.preset,
                chain.spec,
            )
            with M.BLOCK_SIGNATURE_TIMES.time():
                ok = verify_signature_sets([sig_set])
        except ValueError:  # undecodable signature/pubkey bytes
            ok = False
        if not ok:
            raise BlockError("invalid proposer signature")
        return cls(signed_block, block_root, state)


@dataclass
class SignatureVerifiedBlock:
    signed_block: object
    block_root: bytes
    # gossip path carries the already-advanced pre-state so the import
    # stage doesn't redo clone + process_slots; segment path leaves None
    pre_state: object = None

    @classmethod
    def from_gossip_verified(
        cls, chain: BeaconChain, gossip_verified: GossipVerifiedBlock
    ) -> "SignatureVerifiedBlock":
        """block_verification.rs:597: every signature EXCEPT the proposal
        (already checked) in one batch."""
        from ..utils import metrics as M

        state = gossip_verified.pre_state
        verifier = BlockSignatureVerifier(
            state,
            chain.preset,
            chain.spec,
            get_pubkey=chain.pubkey_cache.getter(state),
            resolve_pubkey=chain.pubkey_cache.resolve,
        )
        try:
            verifier.include_all_signatures_except_block_proposal(
                gossip_verified.signed_block
            )
            with M.BLOCK_SIGNATURE_TIMES.time():
                ok = verifier.verify()
        except ValueError:  # undecodable signature/pubkey bytes
            ok = False
        if not ok:
            raise BlockError("invalid block signatures")
        return cls(
            gossip_verified.signed_block,
            gossip_verified.block_root,
            gossip_verified.pre_state,
        )

    def import_into(self, chain: BeaconChain) -> bytes:
        """ExecutionPendingBlock seat: transition (payload round trip runs
        inside), store, fork choice — signatures are already done."""
        return chain.process_block(
            self.signed_block,
            strategy=BlockSignatureStrategy.NO_VERIFICATION,
            pre_state=self.pre_state,
        )


def process_gossip_block(
    chain: BeaconChain, signed_block, observed_producers=None
) -> bytes:
    """The full gossip pipeline in order (gossip_methods.rs:656 -> 927).

    `observed_producers` (an ObservedBlockProducers) is consulted AFTER
    every signature verifies — recording only verified blocks, exactly
    like the reference — and a signature-valid second distinct block
    from the same (slot, proposer) raises BlockEquivocation instead of
    importing."""
    from ..utils import metrics as M
    from ..utils import tracing

    with tracing.span(
        "gossip_block", slot=int(signed_block.message.slot)
    ):
        with tracing.span("block_gossip_verify"):
            gv = GossipVerifiedBlock.verify(chain, signed_block)
        with tracing.span("block_signature_verify"):
            sv = SignatureVerifiedBlock.from_gossip_verified(chain, gv)
        if observed_producers is not None:
            block = signed_block.message
            verdict = observed_producers.observe(
                block.slot, block.proposer_index, sv.block_root
            )
            if verdict == "equivocation":
                raise BlockEquivocation(sv.block_root)
        # every signature checked: the reference's beacon_block_delay_
        # gossip_verification milestone (slot clock, replayable)
        M.observe_slot_delay(
            M.BLOCK_VERIFIED_DELAY,
            chain.slot_clock,
            int(signed_block.message.slot),
        )
        return sv.import_into(chain)


def signature_verify_chain_segment(chain: BeaconChain, blocks) -> list:
    """Batch-verify the signatures of a parent-linked segment in ONE
    backend call (block_verification.rs:525
    signature_verify_chain_segment), returning SignatureVerifiedBlocks
    ready to import in order. Raises BlockError if the segment doesn't
    link or any signature fails."""
    if not blocks:
        return []
    first = blocks[0].message
    parent_state = chain._states.get(bytes(first.parent_root))
    if parent_state is None:
        raise UnknownParent(bytes(first.parent_root))
    state = clone_state(parent_state)
    verifier = None
    out = []
    prev_root = bytes(first.parent_root)
    for signed in blocks:
        block = signed.message
        if bytes(block.parent_root) != prev_root:
            raise BlockError("segment does not hash-chain")
        try:
            state = process_slots(state, block.slot, chain.preset, chain.spec)
        except BlockProcessingError as e:
            raise BlockError(str(e)) from None
        if verifier is None:
            # one verifier accumulates every block's sets; committee
            # caches come from the advancing state
            verifier = BlockSignatureVerifier(
                state,
                chain.preset,
                chain.spec,
                get_pubkey=chain.pubkey_cache.getter(state),
                resolve_pubkey=chain.pubkey_cache.resolve,
            )
        else:
            verifier.state = state
            verifier.get_pubkey = chain.pubkey_cache.getter(state)
        try:
            verifier.include_all_signatures(signed)
        except ValueError:
            raise BlockError("undecodable signature in segment") from None
        prev_root = block.tree_hash_root()
        # snapshot the advanced pre-state so import skips its own clone +
        # process_slots (same reuse as the gossip pipeline's pre_state)
        out.append(SignatureVerifiedBlock(signed, prev_root, clone_state(state)))
        # apply the block so the NEXT block's committees/proposer derive
        # from the right state (NO_VERIFICATION: sets already collected)
        from ..state_transition import per_block_processing

        try:
            per_block_processing(
                state,
                signed,
                chain.preset,
                chain.spec,
                strategy=BlockSignatureStrategy.NO_VERIFICATION,
                verified_proposer_index=block.proposer_index,
            )
        except BlockProcessingError as e:
            raise BlockError(str(e)) from None
    from ..utils import metrics as M

    with M.BLOCK_SIGNATURE_TIMES.time():
        batch_ok = verifier.verify()
    if not batch_ok:
        raise BlockError("segment signature batch failed")
    return out
