"""Gossip verification for sync-committee messages and signed
contribution-and-proofs (reference
beacon_node/beacon_chain/src/sync_committee_verification.rs:1-665), with
the repo's batch-first shape: early checks + dedup per item, then ONE
batched ASYNC signature-set dispatch (`verify_signature_sets_async`,
lane="sync") with bisection fallback -- the same submit/complete
PendingBatch structure as attestation_verification.py, so the sync lane
rides the pipeline overlap and the continuous-batching scheduler exactly
like the attestation lanes.

Also houses the naive per-subcommittee aggregation pool (the analogue of
naive_aggregation_pool.rs for sync messages) and the contribution pool
that block production draws its SyncAggregate from (op-pool's
sync_aggregate seat, operation_pool/src/sync_aggregate_id.rs).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto.bls import (
    AggregateSignature,
    Signature,
    verify_signature_sets_async,
)
from ..state_transition.context import ConsensusContext
from ..state_transition.signature_sets import (
    contribution_and_proof_signature_set,
    sync_committee_contribution_signature_set,
    sync_committee_message_set,
    sync_selection_proof_signature_set,
)
from ..types.helpers import hash32
from .attestation_verification import PendingBatch, bisect_batch_failures


class SyncCommitteeError(ValueError):
    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


@dataclass
class VerifiedSyncMessage:
    message: object
    subnet_id: int
    positions: list  # positions within the subcommittee


@dataclass
class VerifiedContribution:
    signed_contribution: object
    participant_count: int


def sync_subcommittee_pubkeys(state, preset, subcommittee_index: int):
    """The pubkeys of one subnet's slice of the CURRENT sync committee."""
    size = preset.sync_subcommittee_size
    start = subcommittee_index * size
    return list(state.current_sync_committee.pubkeys[start : start + size])


def sync_committee_positions(state, preset) -> dict[bytes, list[int]]:
    """pubkey -> committee positions, one pass over the committee (the
    per-validator lookup table duties_service/sync.rs builds per period)."""
    if not hasattr(state, "current_sync_committee"):
        raise SyncCommitteeError("head state predates altair")
    out: dict[bytes, list[int]] = {}
    for i, committee_pk in enumerate(state.current_sync_committee.pubkeys):
        out.setdefault(bytes(committee_pk), []).append(i)
    return out


def subnets_for_sync_validator(
    state, preset, validator_index: int, positions=None
):
    """subnet id -> positions-in-subcommittee for a validator (spec
    compute_subnets_for_sync_committee). Pass a `sync_committee_positions`
    table when resolving many validators to avoid rescanning the committee
    per index."""
    if positions is None:
        positions = sync_committee_positions(state, preset)
    pk = bytes(state.validators[validator_index].pubkey)
    size = preset.sync_subcommittee_size
    out: dict[int, list[int]] = {}
    for i in positions.get(pk, ()):
        out.setdefault(i // size, []).append(i % size)
    return out


def is_sync_committee_aggregator(selection_proof: bytes, preset, spec) -> bool:
    """Spec is_sync_committee_aggregator."""
    modulo = max(
        1,
        preset.sync_committee_size
        // preset.sync_committee_subnet_count
        // spec.target_aggregators_per_sync_subcommittee,
    )
    return (
        int.from_bytes(hash32(bytes(selection_proof))[:8], "little") % modulo
        == 0
    )


class ObservedSyncContributors:
    """Dedup (slot, subcommittee, validator) -- observed_attesters.rs's
    sync flavor."""

    def __init__(self, retained_slots: int = 8):
        self.retained_slots = retained_slots
        self._seen: dict[tuple, set] = {}

    def observe(self, slot: int, subnet: int, validator_index: int) -> bool:
        s = self._seen.setdefault((slot, subnet), set())
        fresh = validator_index not in s
        s.add(validator_index)
        self._prune(slot)
        return fresh

    def is_known(self, slot: int, subnet: int, validator_index: int) -> bool:
        return validator_index in self._seen.get((slot, subnet), ())

    def _prune(self, current_slot: int) -> None:
        cutoff = current_slot - self.retained_slots
        for key in [k for k in self._seen if k[0] < cutoff]:
            del self._seen[key]


class ObservedSyncAggregators(ObservedSyncContributors):
    """Dedup (slot, subcommittee, aggregator_index)."""


def _early_checks_message(chain, message, subnet_id: int):
    if message.slot != chain.current_slot and message.slot + 1 != chain.current_slot:
        raise SyncCommitteeError("message not for the current slot")
    state = chain.head_state
    if message.validator_index >= len(state.validators):
        raise SyncCommitteeError("unknown validator index")
    subnets = subnets_for_sync_validator(
        state, chain.preset, message.validator_index
    )
    if subnet_id not in subnets:
        raise SyncCommitteeError("validator not in this sync subnet")
    return subnets[subnet_id]


def submit_sync_message_batch(
    chain, items, observed_contributors, ctxt: ConsensusContext | None = None
) -> PendingBatch:
    """Phase 1 of the sync-message batch: early checks, set building,
    ONE async dispatch on the sync lane. Returns a PendingBatch whose
    ``complete()`` yields (verified, rejected) exactly like
    ``batch_verify_sync_messages``."""
    state = chain.head_state
    get_pubkey = chain.pubkey_cache.getter(state)

    survivors = []
    rejected = []
    batch_seen: set = set()
    for message, subnet_id in items:
        try:
            positions = _early_checks_message(chain, message, subnet_id)
            key = (message.slot, subnet_id, message.validator_index)
            if observed_contributors.is_known(*key) or key in batch_seen:
                raise SyncCommitteeError(
                    "validator already contributed for this slot/subnet"
                )
            batch_seen.add(key)
            s = sync_committee_message_set(
                state, get_pubkey, message, chain.preset, chain.spec
            )
            survivors.append((message, subnet_id, positions, s, key))
        except (SyncCommitteeError, ValueError) as e:
            rejected.append((message, str(e)))

    future = (
        verify_signature_sets_async(
            [s for _, _, _, s, _ in survivors],
            lane="sync",
            slot=min(int(m.slot) for m, _, _, _, _ in survivors),
        )
        if survivors
        else None
    )

    def complete():
        verified = []
        if survivors:
            if future.result():
                ok_items = survivors
            else:
                # bisection fallback: O(k log n) backend calls isolate
                # the k poisoned messages (was O(n) per-item re-verify)
                ok_items, bad_items = bisect_batch_failures(
                    survivors, lambda item: [item[3]]
                )
                for item in bad_items:
                    rejected.append((item[0], "invalid signature"))
            for message, subnet_id, positions, _, key in ok_items:
                observed_contributors.observe(*key)
                verified.append(
                    VerifiedSyncMessage(message, subnet_id, positions)
                )
        return verified, rejected

    return PendingBatch(future, complete)


def batch_verify_sync_messages(
    chain, items, observed_contributors, ctxt: ConsensusContext | None = None
):
    """[(message, subnet_id)] -> (verified: [VerifiedSyncMessage],
    rejected: [(message, reason)]). Submit + complete back-to-back (the
    synchronous entry point)."""
    return submit_sync_message_batch(
        chain, items, observed_contributors, ctxt
    ).complete()


def _early_checks_contribution(
    chain, signed, observed_aggregators, observed_contributions
):
    msg = signed.message
    contribution = msg.contribution
    if (
        contribution.slot != chain.current_slot
        and contribution.slot + 1 != chain.current_slot
    ):
        raise SyncCommitteeError("contribution not for the current slot")
    preset = chain.preset
    if contribution.subcommittee_index >= preset.sync_committee_subnet_count:
        raise SyncCommitteeError("bad subcommittee index")
    if not any(contribution.aggregation_bits):
        raise SyncCommitteeError("empty contribution")
    if not is_sync_committee_aggregator(
        msg.selection_proof, preset, chain.spec
    ):
        raise SyncCommitteeError("selection proof does not select aggregator")
    state = chain.head_state
    subnets = subnets_for_sync_validator(state, preset, msg.aggregator_index)
    if contribution.subcommittee_index not in subnets:
        raise SyncCommitteeError("aggregator not in the subcommittee")
    agg_key = (
        contribution.slot,
        int(contribution.subcommittee_index),
        int(msg.aggregator_index),
    )
    if observed_aggregators.is_known(*agg_key):
        raise SyncCommitteeError("aggregator already seen for this slot")
    root = contribution.tree_hash_root()
    if observed_contributions.is_known(contribution.slot, root):
        raise SyncCommitteeError("contribution (or superset) already known")
    return agg_key, root


def submit_contribution_batch(
    chain,
    signed_contributions,
    observed_aggregators,
    observed_contributions,
    ctxt: ConsensusContext | None = None,
) -> PendingBatch:
    """Phase 1 of the contribution-and-proof batch: early checks, three
    sets per item (selection proof, contribution-and-proof signature,
    aggregate contribution signature --
    sync_committee_verification.rs's triple), ONE async dispatch on the
    sync lane."""
    state = chain.head_state
    preset = chain.preset
    get_pubkey = chain.pubkey_cache.getter(state)

    survivors = []
    rejected = []
    batch_seen: set = set()
    for signed in signed_contributions:
        try:
            agg_key, root = _early_checks_contribution(
                chain, signed, observed_aggregators, observed_contributions
            )
            if agg_key in batch_seen:
                raise SyncCommitteeError("duplicate aggregator in batch")
            batch_seen.add(agg_key)
            contribution = signed.message.contribution
            subkeys = sync_subcommittee_pubkeys(
                state, preset, int(contribution.subcommittee_index)
            )
            sets = [
                sync_selection_proof_signature_set(
                    state, get_pubkey, signed, preset, chain.spec
                ),
                contribution_and_proof_signature_set(
                    state, get_pubkey, signed, preset, chain.spec
                ),
            ]
            agg_set = sync_committee_contribution_signature_set(
                state, signed, subkeys, preset, chain.spec,
                resolve_pubkey=chain.pubkey_cache.resolve,
            )
            if agg_set is not None:
                sets.append(agg_set)
            count = sum(contribution.aggregation_bits)
            survivors.append((signed, sets, agg_key, root, count))
        except (SyncCommitteeError, ValueError) as e:
            rejected.append((signed, str(e)))

    future = (
        verify_signature_sets_async(
            [s for _, sets, _, _, _ in survivors for s in sets],
            lane="sync",
            slot=min(
                int(signed.message.contribution.slot)
                for signed, _, _, _, _ in survivors
            ),
        )
        if survivors
        else None
    )

    def complete():
        verified = []
        if survivors:
            if future.result():
                ok_items = survivors
            else:
                ok_items, bad_items = bisect_batch_failures(
                    survivors, lambda item: item[1]
                )
                for item in bad_items:
                    rejected.append((item[0], "invalid signature"))
            for signed, _, agg_key, root, count in ok_items:
                observed_aggregators.observe(*agg_key)
                observed_contributions.observe(
                    signed.message.contribution.slot, root
                )
                verified.append(VerifiedContribution(signed, count))
        return verified, rejected

    return PendingBatch(future, complete)


def batch_verify_contributions(
    chain,
    signed_contributions,
    observed_aggregators,
    observed_contributions,
    ctxt: ConsensusContext | None = None,
):
    """[SignedContributionAndProof] -> (verified, rejected). Submit +
    complete back-to-back (the synchronous entry point; bisection on
    batch failure)."""
    return submit_contribution_batch(
        chain,
        signed_contributions,
        observed_aggregators,
        observed_contributions,
        ctxt,
    ).complete()


# --- pools -------------------------------------------------------------------


class SyncMessagePool:
    """Naive aggregation of verified sync messages into per-subcommittee
    contributions (naive_aggregation_pool.rs, sync flavor)."""

    def __init__(self, preset, retained_slots: int = 8):
        self.preset = preset
        self.retained_slots = retained_slots
        # (slot, block_root, subnet) -> {position: signature_bytes}
        self._msgs: dict[tuple, dict[int, bytes]] = {}

    def insert(self, verified: VerifiedSyncMessage) -> None:
        m = verified.message
        key = (int(m.slot), bytes(m.beacon_block_root), verified.subnet_id)
        slot_msgs = self._msgs.setdefault(key, {})
        for pos in verified.positions:
            slot_msgs.setdefault(pos, bytes(m.signature))
        self._prune(int(m.slot))

    def get_contribution(self, t, slot: int, block_root: bytes, subnet: int):
        """Build a SyncCommitteeContribution from pooled messages."""
        msgs = self._msgs.get((slot, bytes(block_root), subnet))
        if not msgs:
            return None
        bits = [False] * self.preset.sync_subcommittee_size
        sigs = []
        for pos, sig in msgs.items():
            bits[pos] = True
            sigs.append(Signature.from_bytes(sig))
        agg = AggregateSignature.aggregate(sigs)
        return t.SyncCommitteeContribution(
            slot=slot,
            beacon_block_root=bytes(block_root),
            subcommittee_index=subnet,
            aggregation_bits=tuple(bits),
            signature=agg.to_signature().to_bytes(),
        )

    def _prune(self, current_slot: int) -> None:
        cutoff = current_slot - self.retained_slots
        for key in [k for k in self._msgs if k[0] < cutoff]:
            del self._msgs[key]


class SyncContributionPool:
    """Verified contributions -> the block producer's SyncAggregate
    (operation_pool's sync-aggregate seat): per subcommittee keep the
    best (most participants) contribution, then OR the bits and aggregate
    the four signatures."""

    def __init__(self, preset, retained_slots: int = 8):
        self.preset = preset
        self.retained_slots = retained_slots
        # (slot, block_root) -> {subnet: (count, contribution)}
        self._best: dict[tuple, dict[int, tuple[int, object]]] = {}

    def insert(self, verified: VerifiedContribution) -> None:
        c = verified.signed_contribution.message.contribution
        key = (int(c.slot), bytes(c.beacon_block_root))
        per_subnet = self._best.setdefault(key, {})
        subnet = int(c.subcommittee_index)
        cur = per_subnet.get(subnet)
        if cur is None or verified.participant_count > cur[0]:
            per_subnet[subnet] = (verified.participant_count, c)
        self._prune(int(c.slot))

    def get_sync_aggregate(self, t, slot: int, block_root: bytes):
        """SyncAggregate for a block at slot+1 referencing `block_root`
        (participants signed the PREVIOUS slot's head)."""
        per_subnet = self._best.get((slot, bytes(block_root)))
        size = self.preset.sync_committee_size
        sub = self.preset.sync_subcommittee_size
        bits = [False] * size
        sigs = []
        if per_subnet:
            for subnet, (_, c) in per_subnet.items():
                for i, bit in enumerate(c.aggregation_bits):
                    if bit:
                        bits[subnet * sub + i] = True
                sigs.append(Signature.from_bytes(bytes(c.signature)))
        agg = t.SyncAggregate()
        agg.sync_committee_bits = tuple(bits)
        if sigs:
            agg.sync_committee_signature = (
                AggregateSignature.aggregate(sigs).to_signature().to_bytes()
            )
        else:
            from ..crypto.bls import INFINITY_SIGNATURE

            agg.sync_committee_signature = INFINITY_SIGNATURE
        return agg

    def _prune(self, current_slot: int) -> None:
        cutoff = current_slot - self.retained_slots
        for key in [k for k in self._best if k[0] < cutoff]:
            del self._best[key]
