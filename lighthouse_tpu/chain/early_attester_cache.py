"""Early-attester cache: attest to a just-imported block without
touching the head state (reference beacon_node/beacon_chain/src/
early_attester_cache.rs).

When a block imports, everything an attester needs for that slot --
beacon_block_root, source, target -- is precomputed from the block's
own post-state and held as one slot-keyed item. Attestation production
consults the cache first; only on a miss (older-slot requests, skipped
slots) does it derive data from the head state. This keeps the
hot per-slot attestation path free of state clones and protects the
first third of the slot from head-lock contention, which is the
reference's motivation (early_attester_cache.rs:1-18).
"""

from __future__ import annotations

from ..types import compute_epoch_at_slot, compute_start_slot_at_epoch
from ..types.containers import AttestationData, Checkpoint
from ..types.helpers import get_block_root_at_slot


class EarlyAttesterCache:
    def __init__(self):
        self._item = None
        self.stats = {"hits": 0, "misses": 0}

    def add(self, preset, block_root: bytes, block, state) -> None:
        """Record the freshly imported block (post-state in hand).
        Target: the epoch boundary root as seen from the block's own
        chain -- the block itself when it starts the epoch."""
        epoch = compute_epoch_at_slot(block.slot, preset)
        target_slot = compute_start_slot_at_epoch(epoch, preset)
        target_root = (
            get_block_root_at_slot(state, target_slot, preset)
            if target_slot < block.slot
            else bytes(block_root)
        )
        self._item = {
            "epoch": epoch,
            "slot": int(block.slot),
            "block_root": bytes(block_root),
            "source": Checkpoint(
                epoch=state.current_justified_checkpoint.epoch,
                root=bytes(state.current_justified_checkpoint.root),
            ),
            "target": Checkpoint(epoch=epoch, root=target_root),
        }

    def try_attest(self, slot: int, index: int, preset):
        """AttestationData iff the cached block IS the block of `slot`
        (the early case: attesting to the block that just arrived for
        this very slot); None otherwise."""
        item = self._item
        if (
            item is None
            or item["slot"] != slot
            or compute_epoch_at_slot(slot, preset) != item["epoch"]
        ):
            self.stats["misses"] += 1
            return None
        self.stats["hits"] += 1
        return AttestationData(
            slot=slot,
            index=index,
            beacon_block_root=item["block_root"],
            source=item["source"],
            target=item["target"],
        )
