"""BeaconChain: the core chain runtime (reference
beacon_node/beacon_chain/src/beacon_chain.rs -- process_block:2520,
canonical head recompute, per-slot tasks). Wires store, fork choice, state
transition, and the TPU signature backend behind one object.

Block verification follows the reference's typestate pipeline
(block_verification.rs:588-619): gossip checks -> batched signature
verification (BlockSignatureVerifier, ONE backend call) -> state
transition -> fork-choice import -> head update.
"""

from __future__ import annotations

from ..crypto.bls import verify_signature_sets
from ..fork_choice import ForkChoice, ForkChoiceError
from ..ssz import cached_root
from ..state_transition import (
    BlockProcessingError,
    BlockSignatureStrategy,
    ConsensusContext,
    clone_state,
    per_block_processing,
    process_slots,
)
from ..types import compute_epoch_at_slot, compute_start_slot_at_epoch
from ..types.presets import Preset
from ..store.hot_cold import HotColdDB, StoreError
from ..utils.slot_clock import ManualSlotClock
from ..utils.timeout_lock import TimeoutRLock


class BlockError(ValueError):
    pass


class BeaconChain:
    def __init__(
        self,
        store: HotColdDB,
        genesis_state,
        preset: Preset,
        spec,
        slot_clock=None,
    ):
        self.store = store
        self.preset = preset
        self.spec = spec
        self.slot_clock = slot_clock or ManualSlotClock(
            genesis_state.genesis_time, spec.seconds_per_slot
        )
        # anchor the continuous-batching scheduler's per-lane verdict-
        # delay histograms on this chain's injected clock (no-op unless
        # LIGHTHOUSE_TPU_CONT_BATCH routes lanes through the scheduler)
        from ..crypto.bls import scheduler as bls_scheduler

        bls_scheduler.set_slot_clock(self.slot_clock)

        genesis_state_root = genesis_state.tree_hash_root()
        # the canonical genesis block root: header with state_root filled,
        # exactly as the first process_slot will reference it
        from ..types.containers import BeaconBlockHeader

        hdr = genesis_state.latest_block_header
        genesis_root = BeaconBlockHeader(
            slot=hdr.slot,
            proposer_index=hdr.proposer_index,
            parent_root=hdr.parent_root,
            state_root=(
                bytes(hdr.state_root)
                if any(bytes(hdr.state_root))
                else genesis_state_root
            ),
            body_root=hdr.body_root,
        ).tree_hash_root()
        self.genesis_block_root = genesis_root

        # genesis checkpoints with zero roots alias the genesis block
        def _ckpt(cp):
            root = bytes(cp.root)
            return (cp.epoch, root if any(root) else genesis_root)

        jc = _ckpt(genesis_state.current_justified_checkpoint)
        fc = _ckpt(genesis_state.finalized_checkpoint)

        from ..fork_choice.fork_choice import _justified_balances

        self.fork_choice = ForkChoice(
            preset,
            spec,
            genesis_state.slot,
            genesis_root,
            jc,
            fc,
            state_lookup=lambda root: self._states.get(root),
        )
        self.fork_choice.justified_balances = _justified_balances(
            genesis_state, preset
        )
        # highest finalized epoch announced on event_sinks (events.rs
        # finalized_checkpoint stream); imports past this emit once
        self._finality_emitted_epoch = int(fc[0])

        # genesis/anchor init is ONE atomic batch: the state row, its
        # post-state mapping, the head pointer pair, and the anchors
        # commit together, so a crash mid-init leaves either a fresh
        # empty store or a complete chain — never a head pointing at a
        # state that was not written (write-ahead journal, store/kv.py)
        init_batch = store.batch()
        store.put_state(genesis_state_root, genesis_state, batch=init_batch)
        init_batch.stage_chain_item(
            b"block_post_state:" + genesis_root, genesis_state_root
        )
        init_batch.stage_chain_item(b"head_block_root", genesis_root)
        init_batch.stage_chain_item(b"head_state_root", genesis_state_root)
        # stable anchor for the freezer's chunked block-root fill (slot 0's
        # "block" is the genesis header, never a stored block). Keep an
        # existing anchor: a FromStore re-init passes the RESUMED head
        # state through here, which must not clobber the true genesis root.
        if store.get_chain_item(b"genesis_block_root") is None:
            init_batch.stage_chain_item(b"genesis_block_root", genesis_root)
        self.head_root = genesis_root
        self.head_state = clone_state(genesis_state)
        # bounded snapshot cache over the store (snapshot_cache.rs seat):
        # membership = every non-finalized block root; only recently-used
        # states stay materialized, misses replay from store snapshots
        from .state_cache import StateCache

        self._states = StateCache(store)
        self._states[genesis_root] = genesis_state
        # backfill anchor (historical_blocks.rs oldest_block_slot): the
        # earliest block this node holds; genesis start = nothing to fill.
        # Persisted so from_store restarts don't re-backfill known history.
        # Keep an existing anchor, like genesis_block_root above: a
        # FromStore re-init runs this with the RESUMED head state, and
        # clobbering the persisted anchor with the head (even transiently,
        # for from_store to restore in a later batch) opens a crash window
        # that durably re-anchors backfill at the head. from_anchor and
        # sync backfill advance the anchor through their own batches.
        self.oldest_block_root = genesis_root
        self.oldest_block_slot = genesis_state.slot
        self.oldest_block_parent = bytes(
            genesis_state.latest_block_header.parent_root
        )
        if store.get_chain_item(b"oldest_block_root") is None:
            init_batch.stage_chain_item(b"oldest_block_root", genesis_root)
            init_batch.stage_chain_item(
                b"oldest_block_meta",
                genesis_state.slot.to_bytes(8, "little")
                + self.oldest_block_parent,
            )
        init_batch.commit()
        # decompressed-pubkey cache + device-resident limb table
        # (validator_pubkey_cache.rs): decompress once at startup, append on
        # deposit processing; verification paths resolve keys through it
        from .pubkey_cache import ValidatorPubkeyCache

        self.pubkey_cache = ValidatorPubkeyCache(genesis_state)
        # optional engine handle (reference beacon_chain.execution_layer);
        # None = pre-merge / no EL configured
        self.execution_layer = None
        # SSE event subscribers (events.rs): fn(kind: str, payload: dict)
        self.event_sinks: list = []
        # optional per-validator observability (validator_monitor.rs)
        self.validator_monitor = None
        # attest-to-fresh-block fast path (early_attester_cache.rs)
        from .early_attester_cache import EarlyAttesterCache

        self.early_attester_cache = EarlyAttesterCache()
        # merge-transition blocks imported before their pow data was
        # available: block_root -> payload parent hash, re-checked each
        # tick (otb_verification_service.rs). PERSISTED: a pending TTD
        # re-verification must survive a restart, or the node permanently
        # follows an invalid payload subtree.
        self.optimistic_transition_blocks: dict[bytes, bytes] = {}
        self._otb_checked_slot = -1
        # timeout-guarded chain lock (timeout_rw_lock.rs seat): gossip
        # workers, the tick loop, and HTTP handlers all mutate chain
        # state; compound read-modify-write sequences must not
        # interleave, and a stuck holder raises instead of deadlocking
        self.lock = TimeoutRLock("beacon_chain")
        from ..store.kv import Column as _Col

        for key in self.store.kv.keys(_Col.CHAIN):
            if bytes(key).startswith(b"otb:"):
                parent = self.store.get_chain_item(key)
                if parent:
                    self.optimistic_transition_blocks[bytes(key)[4:]] = parent

    def emit(self, kind: str, payload: dict) -> None:
        for sink in self.event_sinks:
            sink(kind, payload)

    # -- alternative genesis resolution (client/src/config.rs:15-40) --------

    @classmethod
    def from_anchor(
        cls,
        store: HotColdDB,
        anchor_state,
        anchor_block,
        preset: Preset,
        spec,
        slot_clock=None,
    ) -> "BeaconChain":
        """Checkpoint-sync start (ClientGenesis::CheckpointSyncUrl /
        WeakSubjSszBytes, client/src/builder.rs:206-340): initialize from a
        finalized (state, block) pair instead of genesis. History below the
        anchor is absent until backfill fills it."""
        block = anchor_block.message
        block_root = block.tree_hash_root()
        state_root = cached_root(anchor_state)
        if bytes(block.state_root) != state_root:
            raise BlockError("anchor state does not match anchor block")
        chain = cls(store, anchor_state, preset, spec, slot_clock=slot_clock)
        if chain.genesis_block_root != block_root:
            raise BlockError("anchor state header does not match anchor block")
        chain.oldest_block_root = block_root
        chain.oldest_block_slot = block.slot
        chain.oldest_block_parent = bytes(block.parent_root)
        batch = store.batch()
        store.put_block(block_root, anchor_block, batch=batch)
        batch.stage_chain_item(b"oldest_block_root", block_root)
        batch.stage_chain_item(
            b"oldest_block_meta",
            block.slot.to_bytes(8, "little") + chain.oldest_block_parent,
        )
        batch.commit()
        return chain

    @classmethod
    def from_store(
        cls, store: HotColdDB, preset: Preset, spec, slot_clock=None
    ) -> "BeaconChain":
        """Node-restart resume (ClientGenesis::FromStore): reload the
        persisted chain and continue.

        Fork choice is re-anchored at the persisted FINALIZED checkpoint
        (when one resolves) and rebuilt by replaying the store's hot
        blocks above it — the seat of the reference's persisted fork
        choice. Anchoring at the raw head pointer would pin the proto
        array to whatever block happened to be head at the crash; if
        that block was a PRIVATE fork (produced and imported locally,
        killed before gossip), the node could never reorg onto the
        canonical chain its peers extended — the stuck-forever state the
        crash-recovery scenario asserts against.

        A corrupt head pointer (head_block_root that resolves to no
        stored block/state) is survivable: the node logs loudly and
        falls back to the persisted finalized checkpoint — losing the
        unfinalized tip beats refusing to start (the reference recovers
        the same way via fork_revert / the anchor on disk)."""
        from ..store.kv import Column as _Col

        head_root = store.get_chain_item(b"head_block_root")
        state_root = store.get_chain_item(b"head_state_root")
        if head_root is None or state_root is None:
            raise BlockError("store holds no persisted chain")
        # cheap head-resolvability probe (the fsck check): mapping exists
        # and the state row (full or summary) is present. The expensive
        # get_state replay of the head is NOT paid when the finalized
        # anchor is used — _replay_hot_blocks rebuilds the tip anyway.
        mapped = store.get_chain_item(b"block_post_state:" + head_root)
        head_resolvable = mapped is not None and (
            store.kv.get(_Col.STATE, mapped) is not None
            or store.kv.get(_Col.STATE_SUMMARY, mapped) is not None
        )
        if not head_resolvable:
            from ..utils.logging import Logger

            Logger(level="error").child(service="chain").crit(
                "head pointer corrupt; falling back to finalized checkpoint",
                head=head_root.hex(),
            )
        # pre-finality chains have no finalized_block_root yet: the
        # finalized checkpoint IS genesis, so anchor there
        fin_root = store.get_chain_item(
            b"finalized_block_root"
        ) or store.get_chain_item(b"genesis_block_root")
        anchor_state = None
        if fin_root is not None and (
            fin_root != head_root or not head_resolvable
        ):
            fin_state_root = store.get_chain_item(
                b"block_post_state:" + fin_root
            )
            if fin_state_root is not None:
                try:
                    anchor_state = store.get_state(fin_state_root)
                except StoreError:
                    anchor_state = None  # fall through to head anchoring
        if anchor_state is None:
            if not head_resolvable:
                raise BlockError("persisted head state missing")
            try:
                # get_state replays from the nearest stored snapshot when
                # the head landed between snapshot slots (summary entry)
                anchor_state = store.get_state(state_root)
            except StoreError as e:
                raise BlockError(
                    f"persisted head AND finalized states missing: {e}"
                ) from None
        # the persisted anchor survives __init__ untouched (its keep-existing
        # guard); only the in-memory mirror needs restoring — no store write,
        # so there is no crash window that could tear the anchor
        oldest = store.get_chain_item(b"oldest_block_root")
        meta = store.get_chain_item(b"oldest_block_meta")
        chain = cls(store, anchor_state, preset, spec, slot_clock=slot_clock)
        if oldest is not None and meta is not None:
            chain.oldest_block_root = oldest
            chain.oldest_block_slot = int.from_bytes(meta[:8], "little")
            chain.oldest_block_parent = meta[8:]
        # pass the ORIGINAL head pointer: __init__ just re-persisted the
        # anchor as the head, so the store's copy no longer names the tip
        chain._replay_hot_blocks(head_root)
        return chain

    def _replay_hot_blocks(self, persisted_head: bytes | None = None) -> None:
        """Rebuild fork choice from the store's hot blocks above the
        anchor (FromStore's persisted-fork-choice seat): every stored
        non-finalized fork re-imports in slot order, so the resumed node
        can still reorg between them once votes arrive. Signature
        re-verification is skipped — these blocks were verified before
        they were stored. Blocks that no longer attach (pruned parents,
        stale sub-finality forks) are skipped; resume must not refuse to
        start over a dangling row."""
        from ..store.kv import Column as _Col

        anchor_slot = int(self.head_state.slot)
        by_root: dict[bytes, object] = {}
        for root in self.store.kv.keys(_Col.BLOCK):
            blk = self.store.get_block(root)
            if blk is not None and int(blk.message.slot) > anchor_slot:
                by_root[bytes(root)] = blk
        # the persisted head's ancestry may dip into the FREEZER: a crash
        # between a migration's content sub-batches and its split-slot
        # marker leaves canonical blocks frozen while the stale marker
        # anchors us below them — walk the head pointer down to the
        # anchor through both temperatures so the tip still re-imports
        root = (
            persisted_head
            if persisted_head is not None
            else self.store.get_chain_item(b"head_block_root")
        )
        while root and any(root):
            r = bytes(root)
            blk = by_root.get(r) or self.store.get_block_any_temperature(r)
            if blk is None or int(blk.message.slot) <= anchor_slot:
                break
            by_root[r] = blk
            root = bytes(blk.message.parent_root)
        blocks = list(by_root.values())
        if not blocks:
            return
        blocks.sort(key=lambda b: (int(b.message.slot), b.message.tree_hash_root()))
        set_slot = getattr(self.slot_clock, "set_slot", None)
        if set_slot is not None:
            set_slot(
                max(
                    self.current_slot,
                    max(int(b.message.slot) for b in blocks),
                )
            )
        for blk in blocks:
            try:
                self.process_block(
                    blk, strategy=BlockSignatureStrategy.NO_VERIFICATION
                )
            except BlockError:
                continue

    # -- time ----------------------------------------------------------------

    @property
    def current_slot(self) -> int:
        return self.slot_clock.current_slot()

    def on_tick(self) -> None:
        with self.lock:
            self.fork_choice.on_tick(self.current_slot)
            # throttle OTB re-verification to once per slot
            # (otb_verification_service.rs polls on epoch intervals)
            slot = self.current_slot
            run_otb = slot != self._otb_checked_slot
            if run_otb:
                self._otb_checked_slot = slot
        if run_otb:
            # engine polling happens OUTSIDE the chain lock: a hung EL
            # endpoint must delay only OTB checks, not block import
            self.verify_optimistic_transition_blocks()

    def verify_optimistic_transition_blocks(self) -> None:
        """Re-check merge-transition blocks imported while their pow data
        was unavailable (otb_verification_service.rs): once the EL can
        serve the pow chain, a TTD-invalid transition block invalidates
        its payload subtree in fork choice. Engine round-trips run
        unlocked; only the fork-choice mutation takes the chain lock."""
        if self.execution_layer is None:
            return
        for root, parent_hash in list(
            self.optimistic_transition_blocks.items()
        ):
            if root not in self.fork_choice.proto.proto_array.indices:
                # pruned out of fork choice (finalized past, or already
                # discarded): nothing left to re-verify -- without this,
                # an engine with no pow surface re-polls forever
                self.optimistic_transition_blocks.pop(root, None)
                self.store.delete_chain_item(b"otb:" + root)
                continue
            verdict = self.execution_layer.validate_merge_block(
                parent_hash, self.spec
            )
            if verdict is None:
                continue  # still no pow data; keep waiting
            self.optimistic_transition_blocks.pop(root, None)
            self.store.delete_chain_item(b"otb:" + root)
            if verdict is False:
                with self.lock:
                    self.fork_choice.on_invalid_execution_payload(root)
                self.recompute_head()

    # -- block import (beacon_chain.rs:2520 process_block) ------------------

    def state_for_block_production(self, slot: int):
        with self.lock:
            state = clone_state(self.head_state)
        return process_slots(state, slot, self.preset, self.spec)

    def process_block(
        self,
        signed_block,
        strategy: BlockSignatureStrategy = BlockSignatureStrategy.VERIFY_BULK,
        pre_state=None,
    ) -> bytes:
        """Full import: signature batch -> transition -> store -> fork
        choice -> head update. Returns the block root. Phases are timed
        into the metrics registry (reference metrics.rs:37-80
        BLOCK_PROCESSING_* family)."""
        from ..utils import metrics as M
        from ..utils import tracing

        with self.lock, M.BLOCK_PROCESSING_TIMES.time(), tracing.span(
            "block_import", slot=int(signed_block.message.slot)
        ):
            try:
                block_root, fresh = self._process_block_timed(
                    signed_block, strategy, pre_state
                )
            except BlockError:
                M.BLOCKS_REJECTED.inc()
                raise
        if not fresh:
            return block_root  # duplicate: no metrics, no monitor
        M.BLOCKS_IMPORTED.inc()
        M.observe_slot_delay(
            M.BLOCK_IMPORTED_DELAY,
            self.slot_clock,
            int(signed_block.message.slot),
        )
        if self.validator_monitor is not None:
            # import time comes from the injected slot clock, so a replay
            # of the same blocks reports the same timings (wallclock rule)
            self.validator_monitor.on_block_imported(
                block_root, signed_block.message, self.slot_clock.now()
            )
        return block_root

    def _process_block_timed(self, signed_block, strategy, pre_state=None):
        from ..utils import metrics as M
        from ..utils import tracing

        self.on_tick()
        block = signed_block.message
        if block.slot > self.current_slot:
            # checked BEFORE any transition/store work: fork choice would
            # reject it anyway, but only after a partial import
            raise BlockError("block from the future")
        block_root = block.tree_hash_root()
        if block_root in self._states:
            return block_root, False  # duplicate import

        parent_root = bytes(block.parent_root)
        if parent_root not in self._states:
            # checked on BOTH paths: importing on a pre-state whose parent
            # was never imported would register a detached fork-choice root
            raise BlockError(f"unknown parent {parent_root.hex()[:12]}")
        if pre_state is not None:
            # gossip pipeline already cloned + slot-advanced the parent
            # (block_verification.rs ExecutionPendingBlock state reuse)
            state = pre_state
        else:
            parent_state = self._states[parent_root]
            state = clone_state(parent_state)
            with M.BLOCK_TRANSITION_TIMES.time():
                state = process_slots(
                    state, block.slot, self.preset, self.spec
                )
        ctxt = ConsensusContext(self.preset, self.spec)
        # merge-transition TTD validation (spec validate_merge_block via
        # the EL's pow surface): provably wrong terminal blocks are
        # rejected; unavailable pow data imports optimistically and the
        # OTB service re-checks on ticks
        otb_parent_hash = None
        if self.execution_layer is not None and hasattr(
            block.body, "execution_payload"
        ):
            from ..state_transition.per_block import is_merge_transition_block

            if is_merge_transition_block(state, block.body):
                parent_hash = bytes(block.body.execution_payload.parent_hash)
                verdict = self.execution_layer.validate_merge_block(
                    parent_hash, self.spec
                )
                if verdict is False:
                    raise BlockError(
                        "merge transition block fails TTD validation"
                    )
                if verdict is None:
                    otb_parent_hash = parent_hash
        if self.execution_layer is not None:
            # engine round trip runs INSIDE process_execution_payload (spec
            # order: after the parent-hash/randao/timestamp checks); the
            # hook records the verdict on the context for fork choice.
            def _notify(payload, _ctxt=ctxt):
                status = self.execution_layer.notify_new_payload(payload)
                _ctxt.payload_verification_status = status
                return True

            ctxt.notify_new_payload = _notify
        try:
            with M.BLOCK_TRANSITION_TIMES.time(), tracing.span(
                "block_transition"
            ):
                per_block_processing(
                    state,
                    signed_block,
                    self.preset,
                    self.spec,
                    strategy=strategy,
                    ctxt=ctxt,
                    # table-tagged keys: the bulk batch gathers limb rows
                    # from the device-resident (mesh-sharded) pubkey
                    # table, so block import is one sharded device program
                    get_pubkey=self.pubkey_cache.getter(state),
                    resolve_pubkey=self.pubkey_cache.resolve,
                )
        except BlockProcessingError as e:
            raise BlockError(str(e)) from None
        except Exception as e:
            from ..execution_layer import PayloadInvalid

            if isinstance(e, PayloadInvalid):
                raise BlockError(f"invalid execution payload: {e}") from None
            raise

        execution_status = "irrelevant"
        execution_block_hash = b""
        if ctxt.payload_verification_status is not None:
            from ..execution_layer import PayloadVerificationStatus

            execution_block_hash = bytes(
                block.body.execution_payload.block_hash
            )
            execution_status = (
                "valid"
                if ctxt.payload_verification_status
                is PayloadVerificationStatus.VERIFIED
                else "optimistic"
            )
        with M.BLOCK_STATE_ROOT_TIMES.time(), tracing.span("state_root"):
            state_root = cached_root(state)
        if bytes(block.state_root) != state_root:
            raise BlockError("block state_root mismatch")

        # deposits may have appended validators: decompress + upload the
        # new keys now (import_new_pubkeys, validator_pubkey_cache.rs:79)
        self.pubkey_cache.import_new_pubkeys(state)

        # the block row, its post-state, the post-state mapping, and any
        # OTB marker commit as ONE atomic batch: a crash mid-import can
        # never store a block whose state (or mapping) is missing
        import_batch = self.store.batch()
        self.store.put_block(block_root, signed_block, batch=import_batch)
        # drop the incremental-hash cache before retaining: stored states
        # are never re-rooted in place (later work clones them), so keeping
        # the merkle layers would ~double per-state memory for nothing
        state.__dict__.pop("_lh_tree_cache", None)
        self.store.put_state(state_root, state, batch=import_batch)
        import_batch.stage_chain_item(
            b"block_post_state:" + block_root, state_root
        )
        if otb_parent_hash is not None:
            import_batch.stage_chain_item(
                b"otb:" + block_root, otb_parent_hash
            )
        import_batch.commit()
        self._states[block_root] = state
        self.early_attester_cache.add(self.preset, block_root, block, state)
        if otb_parent_hash is not None:
            self.optimistic_transition_blocks[block_root] = otb_parent_hash

        with M.BLOCK_FORK_CHOICE_TIMES.time(), tracing.span("fork_choice"):
            self._fork_choice_import(
                signed_block, block_root, state, ctxt,
                execution_status, execution_block_hash,
            )
        self.emit(
            "block",
            {"slot": block.slot, "block": "0x" + block_root.hex()},
        )
        fin_epoch, fin_root = self.fork_choice.finalized_checkpoint
        if int(fin_epoch) > self._finality_emitted_epoch:
            self._finality_emitted_epoch = int(fin_epoch)
            self.emit(
                "finalized_checkpoint",
                {
                    "epoch": int(fin_epoch),
                    "block": "0x" + bytes(fin_root).hex(),
                },
            )
        self._prune_on_finality()
        return block_root, True

    def _fork_choice_import(
        self, signed_block, block_root, state, ctxt,
        execution_status, execution_block_hash,
    ) -> None:
        block = signed_block.message
        try:
            self.fork_choice.on_block(
                signed_block,
                block_root,
                state,
                execution_status=execution_status,
                execution_block_hash=execution_block_hash,
            )
        except ForkChoiceError as e:
            # surface fork-choice admission failures (e.g. a fork that no
            # longer descends from the finalized checkpoint — exactly what
            # a healed partition's losing side gossips) as BlockError so
            # every import caller's reject handling covers them; the block
            # row already committed, which is harmless (it is unreachable
            # from fork choice and dedup treats a retry as duplicate)
            raise BlockError(str(e)) from None
        if execution_status == "valid":
            # engine-API semantics: a VALID payload implies its ancestors'
            # payloads are valid too -- clear any stale optimistic marks
            self.fork_choice.on_valid_execution_payload(block_root)
        # fork-choice also counts the block's attestations
        for att in block.body.attestations:
            indexed = ctxt.get_indexed_attestation(state, att)
            self.fork_choice.on_attestation(
                att.data.slot,
                list(indexed.attesting_indices),
                bytes(att.data.beacon_block_root),
                from_block=True,
            )
            if self.validator_monitor is not None:
                self.validator_monitor.on_attestation_included(
                    list(indexed.attesting_indices),
                    att.data.slot,
                    block.slot,
                )
        # ... and strips equivocators' fork-choice weight (spec
        # on_attester_slashing; fork_choice.rs on_attester_slashing)
        for slashing in block.body.attester_slashings:
            self.fork_choice.on_attester_slashing(slashing)
        old_head = self.head_root
        self.recompute_head()
        if self.head_root != old_head:
            if self.head_root == block_root:
                from ..utils import metrics as M

                # the just-imported block became the canonical head: the
                # final slot-relative milestone (beacon_block_delay_head)
                M.observe_slot_delay(
                    M.BLOCK_HEAD_DELAY, self.slot_clock, int(block.slot)
                )
            head_state_root = self.store.get_chain_item(
                b"block_post_state:" + self.head_root
            )
            self.emit(
                "head",
                {
                    "slot": self.head_state.slot,
                    "block": "0x" + self.head_root.hex(),
                    "state": "0x" + (head_state_root or b"").hex(),
                },
            )

    # -- attestations (gossip path) -----------------------------------------

    def produce_attestation_data(self, slot: int, index: int):
        """AttestationData for (slot, committee index): the early-attester
        cache serves the just-imported-block case without state access
        (early_attester_cache.rs); misses derive from the head state (the
        produce_unaggregated_attestation fallback, beacon_chain.rs)."""
        data = self.early_attester_cache.try_attest(slot, index, self.preset)
        if data is not None:
            return data
        from ..types.containers import AttestationData, Checkpoint
        from ..types.helpers import get_block_root_at_slot

        head_root, state = self.head()
        epoch = compute_epoch_at_slot(slot, self.preset)
        block_root = (
            get_block_root_at_slot(state, slot, self.preset)
            if slot < state.slot
            else head_root
        )
        target_slot = compute_start_slot_at_epoch(epoch, self.preset)
        target_root = (
            get_block_root_at_slot(state, target_slot, self.preset)
            if target_slot < state.slot
            else block_root
        )
        # current-or-future epoch (a lagging head at an epoch boundary is
        # still "current"): the CURRENT justified checkpoint; only a
        # genuinely previous-epoch request uses the previous one
        source = (
            state.current_justified_checkpoint
            if epoch >= compute_epoch_at_slot(state.slot, self.preset)
            else state.previous_justified_checkpoint
        )
        return AttestationData(
            slot=slot,
            index=index,
            beacon_block_root=block_root,
            source=Checkpoint(epoch=source.epoch, root=bytes(source.root)),
            target=Checkpoint(epoch=epoch, root=target_root),
        )

    def apply_attestation(self, attestation, indexed_indices) -> None:
        """Feed a verified unaggregated/aggregate attestation into fork
        choice (verification lives in the processor/verification layer).

        Fork choice's spec recency asserts are stricter than gossip
        admission (gossip accepts anything within ATTESTATION_PROPAGATION_
        SLOT_RANGE; fork choice wants current/previous epoch only), so a
        stale-but-gossip-valid attestation is DROPPED here rather than
        propagated — the reference maps this to a non-fatal error at the
        same boundary (beacon_chain.rs apply_attestation_to_fork_choice)."""
        try:
            with self.lock:
                self.fork_choice.on_attestation(
                    attestation.data.slot,
                    indexed_indices,
                    bytes(attestation.data.beacon_block_root),
                )
        except ForkChoiceError:
            pass

    # -- head (canonical_head.rs recompute_head) ----------------------------

    def recompute_head(self) -> bytes:
        with self.lock:
            return self._recompute_head_locked()

    def _recompute_head_locked(self) -> bytes:
        head = self.fork_choice.get_head()
        if head != self.head_root:
            self.head_root = head
            # Clone: callers advance/mutate the head state (block production,
            # duty lookahead); aliasing the cached post-state would corrupt
            # the canonical chain (reference snapshots in canonical_head.rs).
            self.head_state = clone_state(self._states[head])
            # persist the head pointer PAIR atomically for FromStore
            # restart resume: a crash between the two writes would leave
            # a head block pointing at the previous head's state
            batch = self.store.batch()
            batch.stage_chain_item(b"head_block_root", head)
            state_root = self.store.get_chain_item(
                b"block_post_state:" + head
            )
            if state_root is not None:
                batch.stage_chain_item(b"head_state_root", state_root)
            batch.commit()
            if self.validator_monitor is not None:
                # per-epoch grading from the head state's participation
                # flags (validator_monitor.rs process_valid_state); the
                # monitor dedups by epoch internally
                self.validator_monitor.evaluate_epoch(
                    self.head_state, self.preset
                )
        return head

    def head(self):
        return self.head_root, self.head_state

    def state_for_block_root(self, block_root: bytes):
        """Post-state for ANY known block root: the hot cache first, then
        memoized store reconstruction -- finalized history included, which
        is what a weak-subjectivity light-client bootstrap asks for."""
        return self._states.get_any(block_root)

    # -- optimistic sync / payload invalidation (fork_revert.rs analogue) ---

    def on_invalid_payload(
        self, block_root: bytes, latest_valid_hash: bytes | None = None
    ) -> bytes:
        """The engine ruled an optimistically-imported payload INVALID
        (e.g. via a later forkchoiceUpdated): poison the subtree in fork
        choice and recompute the head away from it."""
        self.fork_choice.on_invalid_execution_payload(
            block_root, latest_valid_hash
        )
        head = self.recompute_head()
        self.emit(
            "invalid_payload",
            {"block": "0x" + bytes(block_root).hex(), "new_head": "0x" + head.hex()},
        )
        return head

    def is_optimistic(self, block_root: bytes) -> bool:
        return self.fork_choice.is_optimistic(block_root)

    @property
    def finalized_checkpoint(self):
        return self.fork_choice.finalized_checkpoint

    @property
    def justified_checkpoint(self):
        return self.fork_choice.justified_checkpoint

    # -- finality housekeeping ----------------------------------------------

    def _prune_on_finality(self) -> None:
        fin_epoch, fin_root = self.fork_choice.finalized_checkpoint
        if fin_epoch == 0 or fin_root not in self._states:
            return
        fin_slot = compute_start_slot_at_epoch(fin_epoch, self.preset)
        # canonical chain: walk head ancestry
        canonical = set()
        root = self.head_root
        while root in self._states:
            canonical.add(root)
            blk = self.store.get_block(root)
            if blk is None:
                break
            root = bytes(blk.message.parent_root)
        # drop in-memory states for pruned forks below finality
        for root in list(self._states.keys()):
            blk = self.store.get_block(root)
            if blk is None:
                continue
            if blk.message.slot < fin_slot and root != fin_root:
                del self._states[root]
        self.store.migrate_to_freezer(
            fin_slot,
            canonical,
            finalized_state=self._states.get(fin_root),
            finalized_block_root=fin_root,
        )
        self.fork_choice.proto.proto_array.maybe_prune(fin_root)
