"""Gossip attestation verification with batched TPU signature checks
(reference beacon_node/beacon_chain/src/attestation_verification.rs +
attestation_verification/batch.rs:31-222).

Pipeline per the reference's typestate flow: cheap early checks (slot
window, structure, first-seen dedup, committee lookup) run per item; all
surviving items' signature sets go to the backend in ONE
verify_signature_sets call (1 set per unaggregated attestation; 3 per
aggregate: selection proof, aggregate signature, indexed attestation);
a batch failure falls back to per-item verification so one bad item
cannot censor the rest (batch.rs:122-133).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto.bls import verify_signature_sets
from ..utils import metrics as M
from ..state_transition.context import ConsensusContext
from ..state_transition.signature_sets import (
    aggregate_and_proof_signature_set,
    indexed_attestation_signature_set,
    selection_proof_signature_set,
)
from ..types import compute_epoch_at_slot
from ..types.helpers import hash32

ATTESTATION_PROPAGATION_SLOT_RANGE = 32


class AttestationError(ValueError):
    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


@dataclass
class VerifiedUnaggregated:
    attestation: object
    indexed_indices: list
    attester_index: int
    # the full IndexedAttestation (slasher feed; the batch already built it)
    indexed: object = None


@dataclass
class VerifiedAggregate:
    signed_aggregate: object
    indexed_indices: list
    indexed: object = None


def is_aggregator(committee_len: int, selection_proof: bytes, spec) -> bool:
    """Spec is_aggregator: hash(selection_proof) picks ~TARGET_AGGREGATORS
    members per committee."""
    modulo = max(
        1, committee_len // spec.target_aggregators_per_committee
    )
    return (
        int.from_bytes(hash32(bytes(selection_proof))[:8], "little") % modulo
        == 0
    )


def _early_checks_unaggregated(chain, attestation):
    data = attestation.data
    current = chain.current_slot
    if not (
        data.slot
        <= current
        <= data.slot + ATTESTATION_PROPAGATION_SLOT_RANGE
    ):
        raise AttestationError("outside propagation slot range")
    if data.target.epoch != compute_epoch_at_slot(data.slot, chain.preset):
        raise AttestationError("target epoch does not match slot")
    bits = list(attestation.aggregation_bits)
    if sum(bits) != 1:
        raise AttestationError("not exactly one aggregation bit")
    if bytes(data.beacon_block_root) not in chain._states:
        raise AttestationError("unknown head block")
    return bits.index(True)


def _setup_unaggregated_batch(
    chain, attestations, observed_attesters, ctxt, state, get_pubkey,
    survivors, rejected, batch_seen,
):
    for att in attestations:
        try:
            pos = _early_checks_unaggregated(chain, att)
            cache = ctxt.committee_cache(state, att.data.target.epoch)
            committee = cache.get_beacon_committee(
                att.data.slot, att.data.index
            )
            if len(committee) != len(list(att.aggregation_bits)):
                raise AttestationError("bits/committee length mismatch")
            attester = committee[pos]
            # peek only: marking happens AFTER signature verification, so a
            # forged message cannot censor the real one (the reference
            # observes post-verification for the same reason)
            key = (att.data.target.epoch, attester)
            if (
                observed_attesters.is_known(*key) or key in batch_seen
            ):
                raise AttestationError("attester already seen this epoch")
            batch_seen.add(key)
            indexed = ctxt.get_indexed_attestation(state, att)
            s = indexed_attestation_signature_set(
                state, get_pubkey, indexed, chain.preset, chain.spec
            )
            survivors.append((att, s, indexed, attester))
        except (AttestationError, ValueError) as e:
            rejected.append((att, str(e)))


def batch_verify_unaggregated(
    chain, attestations, observed_attesters, ctxt: ConsensusContext | None = None
):
    """[(attestation)] -> (verified: [VerifiedUnaggregated],
    rejected: [(attestation, reason)]). ONE backend call for the batch
    (beacon_chain.rs:1696 batch_verify_unaggregated_attestations_for_gossip).
    """
    ctxt = ctxt or ConsensusContext(chain.preset, chain.spec)
    state = chain.head_state
    get_pubkey = chain.pubkey_cache.getter(state)

    survivors = []
    rejected = []
    batch_seen: set = set()
    with M.ATTN_BATCH_SETUP_TIMES.time():
        _setup_unaggregated_batch(
            chain, attestations, observed_attesters, ctxt, state,
            get_pubkey, survivors, rejected, batch_seen,
        )
    verified = []
    if survivors:
        sets = [s for _, s, _, _ in survivors]
        with M.ATTN_BATCH_VERIFY_TIMES.time():
            batch_ok = verify_signature_sets(sets)
        if batch_ok:
            ok_items = survivors
        else:
            # fallback: re-verify per item (batch.rs:122-133)
            ok_items = []
            for item in survivors:
                if verify_signature_sets([item[1]]):
                    ok_items.append(item)
                else:
                    rejected.append((item[0], "invalid signature"))
        for att, _, indexed, attester in ok_items:
            observed_attesters.observe(att.data.target.epoch, attester)
            verified.append(
                VerifiedUnaggregated(
                    att, list(indexed.attesting_indices), attester, indexed
                )
            )
        M.ATTESTATIONS_PROCESSED.inc(len(verified))
        if chain.validator_monitor is not None:
            for v in verified:
                chain.validator_monitor.on_gossip_attestation(
                    v.indexed_indices, v.attestation.data.slot
                )
    return verified, rejected


def _early_checks_aggregate(
    chain, signed_aggregate, observed_aggregates, observed_aggregators, ctxt
):
    msg = signed_aggregate.message
    data = msg.aggregate.data
    current = chain.current_slot
    if not (
        data.slot <= current <= data.slot + ATTESTATION_PROPAGATION_SLOT_RANGE
    ):
        raise AttestationError("outside propagation slot range")
    # epoch sanity BEFORE it touches any cache (an attacker-chosen epoch
    # must never drive cache pruning)
    epoch = data.target.epoch
    if epoch != compute_epoch_at_slot(data.slot, chain.preset):
        raise AttestationError("target epoch does not match slot")
    if not any(msg.aggregate.aggregation_bits):
        raise AttestationError("empty aggregation bits")
    if bytes(data.beacon_block_root) not in chain._states:
        raise AttestationError("unknown head block")
    agg_root = msg.aggregate.tree_hash_root()
    # peek only; marking happens post-verification
    if observed_aggregates.is_known(epoch, agg_root):
        raise AttestationError("aggregate already seen")
    if observed_aggregators.is_known(epoch, msg.aggregator_index):
        raise AttestationError("aggregator already seen this epoch")
    state = chain.head_state
    cache = ctxt.committee_cache(state, epoch)
    committee = cache.get_beacon_committee(data.slot, data.index)
    if msg.aggregator_index not in committee:
        raise AttestationError("aggregator not in committee")
    if not is_aggregator(
        len(committee), msg.selection_proof, chain.spec
    ):
        raise AttestationError("invalid aggregator selection")
    return agg_root


def batch_verify_aggregates(
    chain,
    signed_aggregates,
    observed_aggregates,
    observed_aggregators,
    ctxt: ConsensusContext | None = None,
):
    """Batched aggregate-and-proof verification: THREE sets per item
    (selection proof, aggregate-and-proof signature, indexed attestation;
    batch.rs:77-107), one backend call, per-item fallback."""
    ctxt = ctxt or ConsensusContext(chain.preset, chain.spec)
    state = chain.head_state
    get_pubkey = chain.pubkey_cache.getter(state)

    survivors = []
    rejected = []
    batch_seen: set = set()
    for agg in signed_aggregates:
        try:
            agg_root = _early_checks_aggregate(
                chain, agg, observed_aggregates, observed_aggregators, ctxt
            )
            epoch = agg.message.aggregate.data.target.epoch
            keys = (
                (epoch, agg_root),
                (epoch, agg.message.aggregator_index),
            )
            if any(k in batch_seen for k in keys):
                raise AttestationError("aggregate already seen")
            batch_seen.update(keys)
            indexed = ctxt.get_indexed_attestation(
                state, agg.message.aggregate
            )
            sets = [
                selection_proof_signature_set(
                    state, get_pubkey, agg, chain.preset, chain.spec
                ),
                aggregate_and_proof_signature_set(
                    state, get_pubkey, agg, chain.preset, chain.spec
                ),
                indexed_attestation_signature_set(
                    state, get_pubkey, indexed, chain.preset, chain.spec
                ),
            ]
            survivors.append((agg, sets, indexed))
        except (AttestationError, ValueError) as e:
            rejected.append((agg, str(e)))

    verified = []
    if survivors:
        all_sets = [s for _, sets, _ in survivors for s in sets]
        if verify_signature_sets(all_sets):
            ok_items = survivors
        else:
            ok_items = []
            for item in survivors:
                if verify_signature_sets(item[1]):
                    ok_items.append(item)
                else:
                    rejected.append((item[0], "invalid signature"))
        for agg, _, indexed in ok_items:
            epoch = agg.message.aggregate.data.target.epoch
            observed_aggregates.observe(
                epoch, agg.message.aggregate.tree_hash_root()
            )
            observed_aggregators.observe(epoch, agg.message.aggregator_index)
            verified.append(
                VerifiedAggregate(
                    agg, list(indexed.attesting_indices), indexed
                )
            )
    return verified, rejected
