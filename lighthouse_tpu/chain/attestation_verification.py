"""Gossip attestation verification with batched TPU signature checks
(reference beacon_node/beacon_chain/src/attestation_verification.rs +
attestation_verification/batch.rs:31-222).

Pipeline per the reference's typestate flow: cheap early checks (slot
window, structure, first-seen dedup, committee lookup) run per item; all
surviving items' signature sets go to the backend in ONE
verify_signature_sets call (1 set per unaggregated attestation; 3 per
aggregate: selection proof, aggregate signature, indexed attestation).

Two upgrades over the reference's batch.rs:

  * verification is ASYNC-first: ``submit_*_batch`` marshals and
    dispatches through ``verify_signature_sets_async`` and returns a
    :class:`PendingBatch`; the sync ``batch_verify_*`` entry points are
    submit+complete in one call, so results are identical. The
    BeaconProcessor resolves pending batches instead of blocking its
    workers (double-buffering: batch N+1 marshals while N computes).
  * a failed batch isolates its invalid sets by BISECTION -- O(k log n)
    backend calls for k bad items instead of the reference's O(n)
    per-item fallback (batch.rs:122-133) -- keeping the no-censorship
    guarantee: every valid item in a poisoned batch is still accepted.

The bisection is also the failure-attribution half of the MEGA-PAIRING
(crypto/bls/aggregation.py): the aggregated path collapses a whole
slot's attestations into ~distinct-messages Miller pairs, so a reject
names only the batch -- every sub-batch the bisection re-verifies runs
through the same aggregated backend, and the O(k log n) search pins the
k forged items exactly as it does on the per-set path
(tests/test_bls_aggregation.py plants forgeries and asserts this).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto.bls import verify_signature_sets, verify_signature_sets_async
from ..utils import metrics as M
from ..utils import tracing
from ..state_transition.context import ConsensusContext
from ..state_transition.signature_sets import (
    aggregate_and_proof_signature_set,
    indexed_attestation_signature_set,
    selection_proof_signature_set,
)
from ..types import compute_epoch_at_slot
from ..types.helpers import hash32

ATTESTATION_PROPAGATION_SLOT_RANGE = 32


class AttestationError(ValueError):
    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


@dataclass
class VerifiedUnaggregated:
    attestation: object
    indexed_indices: list
    attester_index: int
    # the full IndexedAttestation (slasher feed; the batch already built it)
    indexed: object = None


@dataclass
class VerifiedAggregate:
    signed_aggregate: object
    indexed_indices: list
    indexed: object = None


@dataclass
class PendingBatch:
    """A dispatched attestation batch: the signature verdict is in
    flight on the device; ``complete()`` resolves it, runs the bisection
    fallback if needed, and finishes post-verification observation.
    ``done()`` never blocks, so a scheduler can poll."""

    future: object
    _complete: object

    def done(self) -> bool:
        return self.future is None or self.future.done()

    def complete(self):
        return self._complete()


def bisect_batch_failures(items, sets_of, verify=None):
    """A batch containing >=1 invalid set failed as a whole: isolate the
    invalid ITEMS with O(k log n) further backend calls (k = number of
    invalid items) instead of O(n) per-item re-verification.

    Per invalid item: binary-search the smallest failing prefix
    (ceil(log2 n) calls -- batch validity of any sub-batch is itself one
    backend call), then one call certifies the remaining tail clean or
    restarts the search inside it. One bad item in a 1024-item batch
    costs ceil(log2 1024) + 1 = 11 extra calls. Returns
    (ok_items, bad_items); every call bumps BLS_BISECTION_CALLS and every
    isolated item BLS_BISECTION_BAD_ITEMS (the attribution rate of the
    mega-pairing's all-or-nothing verdict).
    """
    verify = verify or verify_signature_sets

    def check(group) -> bool:
        M.BLS_BISECTION_CALLS.inc()
        return verify([s for item in group for s in sets_of(item)])

    ok, bad = [], []
    group = list(items)
    # loop invariant: check(group) is known False (>=1 bad inside)
    while group:
        if len(group) == 1:
            bad.append(group[0])
            break
        # smallest m with first m items invalid as a sub-batch: item m-1
        # is the FIRST bad item, items 0..m-2 are certified good
        lo, hi = 0, len(group)
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if check(group[:mid]):
                lo = mid
            else:
                hi = mid
        ok.extend(group[: hi - 1])
        bad.append(group[hi - 1])
        group = group[hi:]
        if group and check(group):
            ok.extend(group)
            break
    M.BLS_BISECTION_BAD_ITEMS.inc(len(bad))
    return ok, bad


def is_aggregator(committee_len: int, selection_proof: bytes, spec) -> bool:
    """Spec is_aggregator: hash(selection_proof) picks ~TARGET_AGGREGATORS
    members per committee."""
    modulo = max(
        1, committee_len // spec.target_aggregators_per_committee
    )
    return (
        int.from_bytes(hash32(bytes(selection_proof))[:8], "little") % modulo
        == 0
    )


def _early_checks_unaggregated(chain, attestation):
    data = attestation.data
    current = chain.current_slot
    if not (
        data.slot
        <= current
        <= data.slot + ATTESTATION_PROPAGATION_SLOT_RANGE
    ):
        raise AttestationError("outside propagation slot range")
    if data.target.epoch != compute_epoch_at_slot(data.slot, chain.preset):
        raise AttestationError("target epoch does not match slot")
    bits = list(attestation.aggregation_bits)
    if sum(bits) != 1:
        raise AttestationError("not exactly one aggregation bit")
    if bytes(data.beacon_block_root) not in chain._states:
        raise AttestationError("unknown head block")
    return bits.index(True)


def _setup_unaggregated_batch(
    chain, attestations, observed_attesters, ctxt, state, get_pubkey,
    survivors, rejected, batch_seen,
):
    for att in attestations:
        try:
            pos = _early_checks_unaggregated(chain, att)
            cache = ctxt.committee_cache(state, att.data.target.epoch)
            committee = cache.get_beacon_committee(
                att.data.slot, att.data.index
            )
            if len(committee) != len(list(att.aggregation_bits)):
                raise AttestationError("bits/committee length mismatch")
            attester = committee[pos]
            # peek only: marking happens AFTER signature verification, so a
            # forged message cannot censor the real one (the reference
            # observes post-verification for the same reason)
            key = (att.data.target.epoch, attester)
            if (
                observed_attesters.is_known(*key) or key in batch_seen
            ):
                raise AttestationError("attester already seen this epoch")
            batch_seen.add(key)
            indexed = ctxt.get_indexed_attestation(state, att)
            s = indexed_attestation_signature_set(
                state, get_pubkey, indexed, chain.preset, chain.spec
            )
            survivors.append((att, s, indexed, attester))
        except (AttestationError, ValueError) as e:
            rejected.append((att, str(e)))


def submit_unaggregated_batch(
    chain, attestations, observed_attesters, ctxt: ConsensusContext | None = None
) -> PendingBatch:
    """Phase 1 of the gossip attestation batch: early checks, set
    building, and ONE async backend dispatch. Returns a PendingBatch
    whose ``complete()`` yields (verified, rejected) exactly like
    ``batch_verify_unaggregated``. Between submit and complete the
    caller is free to marshal the next batch -- the device is busy, not
    the host."""
    ctxt = ctxt or ConsensusContext(chain.preset, chain.spec)
    state = chain.head_state
    get_pubkey = chain.pubkey_cache.getter(state)

    survivors = []
    rejected = []
    batch_seen: set = set()
    with M.ATTN_BATCH_SETUP_TIMES.time(), tracing.span(
        "att_setup", n=len(attestations)
    ):
        _setup_unaggregated_batch(
            chain, attestations, observed_attesters, ctxt, state,
            get_pubkey, survivors, rejected, batch_seen,
        )
    future = (
        verify_signature_sets_async(
            [s for _, s, _, _ in survivors],
            lane="unaggregated",
            slot=min(int(att.data.slot) for att, _, _, _ in survivors),
        )
        if survivors
        else None
    )
    # the submitting span context: complete() may run on another worker
    # after a DeferredWork hand-off, but its spans stay in this trace
    submit_ctx = tracing.current()

    def complete():
        verified = []
        if survivors:
            # NOTE the metric's meaning under the async path: this times
            # the residual wait for the verdict plus any bisection -- the
            # worker-visible cost -- not raw device time, which overlaps
            # the next batch's marshalling (see utils/metrics.py help)
            with M.ATTN_BATCH_VERIFY_TIMES.time(), tracing.span(
                "att_verify_wait", parent=submit_ctx, n=len(survivors)
            ):
                batch_ok = future.result()
                if not batch_ok:
                    # bisection fallback: O(k log n) backend calls
                    # isolate the k poisoned items (vs batch.rs:122-133
                    # O(n))
                    with tracing.span("att_bisect", n=len(survivors)):
                        ok_items, bad_items = bisect_batch_failures(
                            survivors, lambda item: [item[1]]
                        )
            if batch_ok:
                ok_items = survivors
            else:
                for item in bad_items:
                    rejected.append((item[0], "invalid signature"))
            for att, _, indexed, attester in ok_items:
                if observed_attesters.observe(
                    att.data.target.epoch, attester
                ):
                    # an overlapped batch marked this attester between our
                    # submit and complete: late cross-batch dedup
                    rejected.append(
                        (att, "attester already seen this epoch")
                    )
                    continue
                verified.append(
                    VerifiedUnaggregated(
                        att, list(indexed.attesting_indices), attester,
                        indexed,
                    )
                )
            M.ATTESTATIONS_PROCESSED.inc(len(verified))
            if chain.validator_monitor is not None:
                for v in verified:
                    chain.validator_monitor.on_gossip_attestation(
                        v.indexed_indices, v.attestation.data.slot
                    )
        return verified, rejected

    return PendingBatch(future, complete)


def batch_verify_unaggregated(
    chain, attestations, observed_attesters, ctxt: ConsensusContext | None = None
):
    """[(attestation)] -> (verified: [VerifiedUnaggregated],
    rejected: [(attestation, reason)]). ONE backend call for the batch
    (beacon_chain.rs:1696 batch_verify_unaggregated_attestations_for_gossip);
    submit + complete back-to-back (the synchronous entry point).
    """
    return submit_unaggregated_batch(
        chain, attestations, observed_attesters, ctxt
    ).complete()


def _early_checks_aggregate(
    chain, signed_aggregate, observed_aggregates, observed_aggregators, ctxt
):
    msg = signed_aggregate.message
    data = msg.aggregate.data
    current = chain.current_slot
    if not (
        data.slot <= current <= data.slot + ATTESTATION_PROPAGATION_SLOT_RANGE
    ):
        raise AttestationError("outside propagation slot range")
    # epoch sanity BEFORE it touches any cache (an attacker-chosen epoch
    # must never drive cache pruning)
    epoch = data.target.epoch
    if epoch != compute_epoch_at_slot(data.slot, chain.preset):
        raise AttestationError("target epoch does not match slot")
    if not any(msg.aggregate.aggregation_bits):
        raise AttestationError("empty aggregation bits")
    if bytes(data.beacon_block_root) not in chain._states:
        raise AttestationError("unknown head block")
    agg_root = msg.aggregate.tree_hash_root()
    # peek only; marking happens post-verification
    if observed_aggregates.is_known(epoch, agg_root):
        raise AttestationError("aggregate already seen")
    if observed_aggregators.is_known(epoch, msg.aggregator_index):
        raise AttestationError("aggregator already seen this epoch")
    state = chain.head_state
    cache = ctxt.committee_cache(state, epoch)
    committee = cache.get_beacon_committee(data.slot, data.index)
    if msg.aggregator_index not in committee:
        raise AttestationError("aggregator not in committee")
    if not is_aggregator(
        len(committee), msg.selection_proof, chain.spec
    ):
        raise AttestationError("invalid aggregator selection")
    return agg_root


def _setup_aggregate_batch(
    chain, signed_aggregates, observed_aggregates, observed_aggregators,
    ctxt, state, get_pubkey, survivors, rejected, batch_seen,
):
    for agg in signed_aggregates:
        try:
            agg_root = _early_checks_aggregate(
                chain, agg, observed_aggregates, observed_aggregators, ctxt
            )
            epoch = agg.message.aggregate.data.target.epoch
            keys = (
                (epoch, agg_root),
                (epoch, agg.message.aggregator_index),
            )
            if any(k in batch_seen for k in keys):
                raise AttestationError("aggregate already seen")
            batch_seen.update(keys)
            indexed = ctxt.get_indexed_attestation(
                state, agg.message.aggregate
            )
            ind = indexed_attestation_signature_set(
                state, get_pubkey, indexed, chain.preset, chain.spec
            )
            # speculation hook (speculate/): may drop the indexed set
            # (pre-verified, confirmed by lookup) or swap in a set whose
            # single pubkey is the precomputed committee aggregate
            # (identical point => identical verdict). Miss/mismatch keeps
            # the original set — never trust-on-predict.
            speculation = getattr(chain, "speculation", None)
            if speculation is not None:
                ind = speculation.process_indexed_set(
                    state, agg.message.aggregate, indexed, ind
                )
            sets = [
                selection_proof_signature_set(
                    state, get_pubkey, agg, chain.preset, chain.spec
                ),
                aggregate_and_proof_signature_set(
                    state, get_pubkey, agg, chain.preset, chain.spec
                ),
            ]
            if ind is not None:
                sets.append(ind)
            survivors.append((agg, sets, indexed))
        except (AttestationError, ValueError) as e:
            rejected.append((agg, str(e)))


def submit_aggregate_batch(
    chain,
    signed_aggregates,
    observed_aggregates,
    observed_aggregators,
    ctxt: ConsensusContext | None = None,
) -> PendingBatch:
    """Phase 1 of the aggregate-and-proof batch: early checks, THREE
    sets per item (selection proof, aggregate-and-proof signature,
    indexed attestation; batch.rs:77-107), one async dispatch."""
    ctxt = ctxt or ConsensusContext(chain.preset, chain.spec)
    state = chain.head_state
    get_pubkey = chain.pubkey_cache.getter(state)

    survivors = []
    rejected = []
    batch_seen: set = set()
    with tracing.span("agg_setup", n=len(signed_aggregates)):
        _setup_aggregate_batch(
            chain, signed_aggregates, observed_aggregates,
            observed_aggregators, ctxt, state, get_pubkey,
            survivors, rejected, batch_seen,
        )

    future = (
        verify_signature_sets_async(
            [s for _, sets, _ in survivors for s in sets],
            lane="aggregate",
            slot=min(
                int(agg.message.aggregate.data.slot)
                for agg, _, _ in survivors
            ),
        )
        if survivors
        else None
    )
    submit_ctx = tracing.current()

    def complete():
        verified = []
        if survivors:
            with tracing.span(
                "agg_verify_wait", parent=submit_ctx, n=len(survivors)
            ):
                batch_ok = future.result()
                if not batch_ok:
                    with tracing.span("agg_bisect", n=len(survivors)):
                        ok_items, bad_items = bisect_batch_failures(
                            survivors, lambda item: item[1]
                        )
            if batch_ok:
                ok_items = survivors
            else:
                for item in bad_items:
                    rejected.append((item[0], "invalid signature"))
            for agg, _, indexed in ok_items:
                epoch = agg.message.aggregate.data.target.epoch
                already = observed_aggregates.observe(
                    epoch, agg.message.aggregate.tree_hash_root()
                )
                already |= observed_aggregators.observe(
                    epoch, agg.message.aggregator_index
                )
                if already:
                    # marked by an overlapped batch after our submit
                    rejected.append((agg, "aggregate already seen"))
                    continue
                verified.append(
                    VerifiedAggregate(
                        agg, list(indexed.attesting_indices), indexed
                    )
                )
        return verified, rejected

    return PendingBatch(future, complete)


def batch_verify_aggregates(
    chain,
    signed_aggregates,
    observed_aggregates,
    observed_aggregators,
    ctxt: ConsensusContext | None = None,
):
    """Batched aggregate-and-proof verification, submit + complete in
    one call (the synchronous entry point; bisection on batch failure)."""
    return submit_aggregate_batch(
        chain,
        signed_aggregates,
        observed_aggregates,
        observed_aggregators,
        ctxt,
    ).complete()
