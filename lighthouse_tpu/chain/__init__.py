"""Core chain runtime (reference beacon_node/beacon_chain, SURVEY.md
section 2.3): BeaconChain orchestration, head tracking, import pipeline."""

from .beacon_chain import BeaconChain, BlockError  # noqa: F401
