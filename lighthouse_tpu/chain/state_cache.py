"""Bounded hot-state cache over the store (reference beacon_chain's
snapshot cache, snapshot_cache.rs + hot_cold_store.rs:48): the chain no
longer pins a full materialized BeaconState per non-finalized block.

Dict-shaped (the chain's `_states` seat): membership tracks every
imported non-finalized block root; only the most recently used
`capacity` states stay materialized, and a miss reconstructs from the
store's snapshot + block-replay path (`HotColdDB.get_state`). At the
500k-validator scale a full state is ~100 MB -- pinning one per block
of a whole non-finality window is what this cache exists to prevent.
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class StateCacheError(KeyError):
    """A KNOWN root whose state could not be rebuilt -- store damage, not
    an unknown-parent condition; never silently mapped to None."""


class StateCache:
    def __init__(self, store, capacity: int = 16):
        self.store = store
        self.capacity = capacity
        self._roots: set[bytes] = set()  # all imported non-finalized roots
        self._hot: OrderedDict[bytes, object] = OrderedDict()
        self._cold: OrderedDict[bytes, object] = OrderedDict()
        # the API's ThreadingHTTPServer reads while imports write: the
        # plain dict this replaced was GIL-atomic per op; the LRU's
        # check-then-act sequences need a real lock
        self._lock = threading.RLock()

    # -- dict surface --------------------------------------------------------

    def __contains__(self, block_root: bytes) -> bool:
        return bytes(block_root) in self._roots

    def __len__(self) -> int:
        return len(self._roots)

    def keys(self):
        with self._lock:
            return list(self._roots)

    def hot_count(self) -> int:
        with self._lock:
            return len(self._hot)

    def __setitem__(self, block_root: bytes, state) -> None:
        root = bytes(block_root)
        with self._lock:
            self._roots.add(root)
            self._hot[root] = state
            self._hot.move_to_end(root)
            while len(self._hot) > self.capacity:
                self._hot.popitem(last=False)

    def __delitem__(self, block_root: bytes) -> None:
        root = bytes(block_root)
        with self._lock:
            self._roots.discard(root)
            self._hot.pop(root, None)

    def get(self, block_root: bytes, default=None):
        root = bytes(block_root)
        with self._lock:
            if root not in self._roots:
                return default
            state = self._hot.get(root)
            if state is not None:
                self._hot.move_to_end(root)
                return state
        # reconstruction (store replay) runs outside the lock
        try:
            state = self._reconstruct(root)
        except StateCacheError:
            with self._lock:
                if root not in self._roots:
                    return default  # pruned mid-replay: a benign race
            raise
        with self._lock:
            if root not in self._roots:
                # pruned while we were replaying: do not resurrect it
                return default
            self[root] = state
        return state

    def __getitem__(self, block_root: bytes):
        state = self.get(block_root)
        if state is None:
            raise KeyError(bytes(block_root).hex()[:12])
        return state

    def get_any(self, block_root: bytes):
        """State for a root regardless of membership: known roots via the
        hot cache, FINALIZED roots via store reconstruction memoized in a
        small cold-side LRU (repeated light-client bootstraps for the same
        deep root must not replay per request)."""
        root = bytes(block_root)
        if root in self._roots:
            state = self.get(root)
            if state is not None:
                return state
            # pruned between the membership check and the fetch: fall
            # through to store reconstruction like any finalized root
        with self._lock:
            state = self._cold.get(root)
            if state is not None:
                self._cold.move_to_end(root)
                return state
        try:
            state = self._reconstruct(root)
        except StateCacheError:
            return None
        with self._lock:
            self._cold[root] = state
            while len(self._cold) > 4:
                self._cold.popitem(last=False)
        return state

    # -- reconstruction ------------------------------------------------------

    def _reconstruct(self, block_root: bytes):
        """Cold path: resolve the block's post-state root and rebuild via
        the store's snapshot + replay machinery. A failure here is store
        damage for a root we PROMISED membership of -- raise with the
        diagnostic rather than masquerading as an unknown parent."""
        state_root = self.store.get_chain_item(
            b"block_post_state:" + block_root
        )
        if state_root is None:
            raise StateCacheError(
                f"no post-state mapping for known root "
                f"{bytes(block_root).hex()[:12]}"
            )
        try:
            return self.store.get_state(state_root)
        except KeyError as e:
            raise StateCacheError(
                f"state replay failed for {bytes(block_root).hex()[:12]}: {e}"
            ) from e
