"""EF consensus-spec-tests runner (reference testing/ef_tests/src/
handler.rs:10-41 + cases/*): walks the official vector layout

    <root>/tests/<config>/<fork>/<runner>/<handler>/<suite>/<case>/

and executes each case against this framework's state transition, SSZ,
and BLS backends. The official vectors are a multi-GB download
(reference Makefile:176-182 make-ef-tests); point LIGHTHOUSE_TPU_EF_TESTS
at an extracted tree to run them. The same machinery executes the
in-repo synthesized mini-tree (tests/test_ef_vectors.py), so the walker,
ssz_snappy loading, and case semantics stay exercised offline.

Implemented runners (cases/{operations,epoch_processing,sanity,bls,
genesis_initialization,genesis_validity,shuffling,fork,ssz_static,
fork_choice}.rs):

  operations/{attestation,attester_slashing,proposer_slashing,
              voluntary_exit,deposit,sync_aggregate}
  epoch_processing/* (full epoch transition per handler)
  sanity/{slots,blocks}
  bls/{verify,aggregate_verify,fast_aggregate_verify,batch_verify}
  genesis/{initialization,validity}
  shuffling/core
  fork/fork (phase0->altair, altair->bellatrix upgrades)
  ssz_static/<Type> (round-trip + tree-hash root)
  fork_choice/* (scripted tick/block/attestation/slashing steps + checks)
"""

from __future__ import annotations

import os

import yaml

from .crypto import bls
from .network.snappy import decompress
from .state_transition import (
    BlockProcessingError,
    BlockSignatureStrategy,
    per_block_processing,
    process_epoch,
    process_slots,
)
from .state_transition.context import ConsensusContext
from .state_transition.per_block import (
    process_attestation,
    process_attester_slashing,
    process_deposit,
    process_proposer_slashing,
    process_sync_aggregate,
    process_voluntary_exit,
)
from .types import ChainSpec, state_class_for, types_for
from .types.presets import MAINNET, MINIMAL


class CaseResult:
    def __init__(self, path: str, ok: bool, message: str = ""):
        self.path = path
        self.ok = ok
        self.message = message

    def __repr__(self):
        return f"{'ok ' if self.ok else 'FAIL'} {self.path} {self.message}"


def _load(case_dir: str, name: str) -> bytes | None:
    p = os.path.join(case_dir, name)
    if not os.path.exists(p):
        return None
    with open(p, "rb") as f:
        return decompress(f.read())


def _load_yaml(case_dir: str, name: str):
    p = os.path.join(case_dir, name)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return yaml.safe_load(f)


def _spec_for(config: str, fork: str) -> tuple:
    """The OFFICIAL config's spec (minimal/mainnet constants -- the
    vectors were generated under them; interop constants would fail
    domain- and period-dependent cases), with the target fork active from
    genesis (handler.rs fork_from_env runs each fork's vectors that
    way)."""
    preset = MINIMAL if config == "minimal" else MAINNET
    spec = ChainSpec.minimal() if config == "minimal" else ChainSpec.mainnet()
    spec.altair_fork_epoch = 0 if fork in ("altair", "bellatrix") else None
    spec.bellatrix_fork_epoch = 0 if fork == "bellatrix" else None
    return preset, spec


_OPERATION_FILES = {
    "attestation": ("attestation.ssz_snappy", "Attestation", process_attestation),
    "attester_slashing": (
        "attester_slashing.ssz_snappy",
        "AttesterSlashing",
        process_attester_slashing,
    ),
    "proposer_slashing": (
        "proposer_slashing.ssz_snappy",
        "ProposerSlashing",
        process_proposer_slashing,
    ),
    "voluntary_exit": (
        "voluntary_exit.ssz_snappy",
        "SignedVoluntaryExit",
        process_voluntary_exit,
    ),
    "deposit": ("deposit.ssz_snappy", "Deposit", process_deposit),
    "sync_aggregate": (
        "sync_aggregate.ssz_snappy",
        "SyncAggregate",
        process_sync_aggregate,
    ),
}


def _run_execution_payload_case(case_dir, config, fork) -> CaseResult:
    """operations/execution_payload (cases/operations.rs:249-310): the
    payload applies iff the engine verdict in execution.yaml says the
    payload is executable AND the consensus checks pass."""
    from types import SimpleNamespace

    from .state_transition.per_block import process_execution_payload

    if fork in ("phase0", "altair"):
        return CaseResult(case_dir, True, "pre-bellatrix (skipped)")
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state_cls = state_class_for(t, fork)
    pre = state_cls.from_ssz_bytes(_load(case_dir, "pre.ssz_snappy"))
    payload = t.ExecutionPayload.from_ssz_bytes(
        _load(case_dir, "execution_payload.ssz_snappy")
    )
    meta = _load_yaml(case_dir, "execution.yaml") or {}
    execution_valid = bool(meta.get("execution_valid", False))
    post_raw = _load(case_dir, "post.ssz_snappy")
    body = SimpleNamespace(execution_payload=payload)
    error = None
    try:
        if not execution_valid:
            raise BlockProcessingError("execution engine rejected payload")
        process_execution_payload(pre, body, preset, spec)
        applied = True
    except (BlockProcessingError, IndexError, ValueError) as e:
        applied = False
        error = str(e)
    if post_raw is None:
        if applied:
            return CaseResult(case_dir, False, "invalid payload accepted")
        return CaseResult(case_dir, True)
    if not applied:
        return CaseResult(case_dir, False, f"valid payload rejected: {error}")
    if pre.tree_hash_root() != state_cls.from_ssz_bytes(post_raw).tree_hash_root():
        return CaseResult(case_dir, False, "post-state root mismatch")
    return CaseResult(case_dir, True)


def _run_operation_case(case_dir, handler, config, fork) -> CaseResult:
    if handler == "execution_payload":
        return _run_execution_payload_case(case_dir, config, fork)
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state_cls = state_class_for(t, fork)
    fname, type_name, process = _OPERATION_FILES[handler]
    pre = state_cls.from_ssz_bytes(_load(case_dir, "pre.ssz_snappy"))
    op_raw = _load(case_dir, fname)
    from .types.containers import (
        Deposit,
        ProposerSlashing,
        SignedVoluntaryExit,
    )

    op_cls = {
        "Attestation": t.Attestation,
        "AttesterSlashing": t.AttesterSlashing,
        "ProposerSlashing": ProposerSlashing,
        "SignedVoluntaryExit": SignedVoluntaryExit,
        "Deposit": Deposit,
        "SyncAggregate": t.SyncAggregate,
    }[type_name]
    op = op_cls.from_ssz_bytes(op_raw)
    post_raw = _load(case_dir, "post.ssz_snappy")
    ctxt = ConsensusContext(preset, spec)
    try:
        if handler == "voluntary_exit":
            process(pre, op, preset, spec)
        else:
            process(pre, op, preset, spec, ctxt=ctxt)
        applied = True
    except (BlockProcessingError, IndexError, ValueError) as e:
        applied = False
        error = str(e)
    if post_raw is None:
        if applied:
            return CaseResult(case_dir, False, "invalid op was accepted")
        return CaseResult(case_dir, True)
    if not applied:
        return CaseResult(case_dir, False, f"valid op rejected: {error}")
    if pre.tree_hash_root() != state_cls.from_ssz_bytes(post_raw).tree_hash_root():
        return CaseResult(case_dir, False, "post-state root mismatch")
    return CaseResult(case_dir, True)


def _run_sanity_case(case_dir, handler, config, fork) -> CaseResult:
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state_cls = state_class_for(t, fork)
    pre = state_cls.from_ssz_bytes(_load(case_dir, "pre.ssz_snappy"))
    post_raw = _load(case_dir, "post.ssz_snappy")
    try:
        if handler == "slots":
            n = _load_yaml(case_dir, "slots.yaml")
            pre = process_slots(pre, pre.slot + int(n), preset, spec)
        else:  # blocks
            meta = _load_yaml(case_dir, "meta.yaml") or {}
            from .types import block_classes_for

            _, signed_cls, _ = block_classes_for(t, fork)
            for i in range(int(meta.get("blocks_count", 0))):
                raw = _load(case_dir, f"blocks_{i}.ssz_snappy")
                signed = signed_cls.from_ssz_bytes(raw)
                pre = process_slots(pre, signed.message.slot, preset, spec)
                per_block_processing(
                    pre,
                    signed,
                    preset,
                    spec,
                    strategy=BlockSignatureStrategy.VERIFY_BULK,
                )
        applied = True
    except (BlockProcessingError, ValueError) as e:
        applied = False
        error = str(e)
    if post_raw is None:
        return (
            CaseResult(case_dir, True)
            if not applied
            else CaseResult(case_dir, False, "invalid sanity case accepted")
        )
    if not applied:
        return CaseResult(case_dir, False, f"valid case rejected: {error}")
    if pre.tree_hash_root() != state_cls.from_ssz_bytes(post_raw).tree_hash_root():
        return CaseResult(case_dir, False, "post-state root mismatch")
    return CaseResult(case_dir, True)


def _run_epoch_case(case_dir, handler, config, fork) -> CaseResult:
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state_cls = state_class_for(t, fork)
    pre = state_cls.from_ssz_bytes(_load(case_dir, "pre.ssz_snappy"))
    post_raw = _load(case_dir, "post.ssz_snappy")
    try:
        # the official vectors' post-states reflect ONLY the named
        # sub-transition (epoch_processing.rs EpochTransition impls), so
        # run exactly that step, not the full transition
        from .state_transition.per_epoch import run_epoch_sub_transition

        run_epoch_sub_transition(pre, handler, preset, spec)
        applied = True
    except (BlockProcessingError, ValueError) as e:
        applied, error = False, str(e)
    if post_raw is None:
        return (
            CaseResult(case_dir, True)
            if not applied
            else CaseResult(case_dir, False, "invalid epoch case accepted")
        )
    if not applied:
        return CaseResult(case_dir, False, f"valid case rejected: {error}")
    if pre.tree_hash_root() != state_cls.from_ssz_bytes(post_raw).tree_hash_root():
        return CaseResult(case_dir, False, "post-state root mismatch")
    return CaseResult(case_dir, True)


def _run_bls_case(case_dir, handler, config, fork) -> CaseResult:
    data = _load_yaml(case_dir, "data.yaml")
    if data is None:
        return CaseResult(case_dir, False, "missing data.yaml")
    inp, expected = data["input"], data["output"]

    def _b(h):
        return bytes.fromhex(str(h).removeprefix("0x"))

    try:
        if handler == "verify":
            pk = bls.PublicKey.from_bytes(_b(inp["pubkey"]))
            sig = bls.Signature.from_bytes(_b(inp["signature"]))
            got = bls.verify(sig, [pk], _b(inp["message"]))
        elif handler == "fast_aggregate_verify":
            pks = [bls.PublicKey.from_bytes(_b(p)) for p in inp["pubkeys"]]
            sig = bls.Signature.from_bytes(_b(inp["signature"]))
            got = bls.verify(sig, pks, _b(inp["message"]))
        elif handler == "aggregate_verify":
            pks = [bls.PublicKey.from_bytes(_b(p)) for p in inp["pubkeys"]]
            sig = bls.Signature.from_bytes(_b(inp["signature"]))
            got = bls.aggregate_verify(
                sig, pks, [_b(m) for m in inp["messages"]]
            )
        elif handler == "batch_verify":
            sets = []
            for pk_h, m_h, sig_h in zip(
                inp["pubkeys"], inp["messages"], inp["signatures"]
            ):
                pk = bls.PublicKey.from_bytes(_b(pk_h))
                sig = bls.Signature.from_bytes(_b(sig_h))
                sets.append(bls.SignatureSet.single_pubkey(sig, pk, _b(m_h)))
            got = bls.verify_signature_sets(sets, seed=1)
        else:
            return CaseResult(case_dir, False, f"unknown bls handler {handler}")
    except (bls.BlsError, ValueError):
        got = False  # undecodable inputs are failing verifications
    if bool(got) != bool(expected):
        return CaseResult(case_dir, False, f"got {got}, expected {expected}")
    return CaseResult(case_dir, True)


def _run_genesis_case(case_dir, handler, config, fork) -> CaseResult:
    """genesis/{initialization,validity} (cases/genesis_initialization.rs,
    genesis_validity.rs)."""
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state_cls = state_class_for(t, fork)
    from .state_transition.genesis import (
        initialize_beacon_state_from_eth1,
        is_valid_genesis_state,
    )

    if handler == "validity":
        genesis = state_cls.from_ssz_bytes(_load(case_dir, "genesis.ssz_snappy"))
        want = bool(_load_yaml(case_dir, "is_valid.yaml"))
        got = is_valid_genesis_state(genesis, preset, spec)
        if got != want:
            return CaseResult(case_dir, False, f"validity {got} != {want}")
        return CaseResult(case_dir, True)

    if handler != "initialization":
        return CaseResult(case_dir, False, f"unknown genesis handler {handler}")
    eth1 = _load_yaml(case_dir, "eth1.yaml")
    meta = _load_yaml(case_dir, "meta.yaml") or {}
    from .types.containers import Deposit

    deposits = [
        Deposit.from_ssz_bytes(_load(case_dir, f"deposits_{i}.ssz_snappy"))
        for i in range(int(meta.get("deposits_count", 0)))
    ]
    header = None
    if meta.get("execution_payload_header"):
        raw = _load(case_dir, "execution_payload_header.ssz_snappy")
        header = t.ExecutionPayloadHeader.from_ssz_bytes(raw)
    block_hash = bytes.fromhex(str(eth1["eth1_block_hash"]).removeprefix("0x"))
    state = initialize_beacon_state_from_eth1(
        block_hash,
        int(eth1["eth1_timestamp"]),
        deposits,
        preset,
        spec,
        execution_payload_header=header,
    )
    want = state_cls.from_ssz_bytes(_load(case_dir, "state.ssz_snappy"))
    if state.tree_hash_root() != want.tree_hash_root():
        return CaseResult(case_dir, False, "genesis state root mismatch")
    return CaseResult(case_dir, True)


def _run_shuffling_case(case_dir, handler, config, fork) -> CaseResult:
    """shuffling/core (cases/shuffling.rs): both compute_shuffled_index
    and the whole-list fast path must reproduce the mapping, under the
    config's round count (mainnet 90 / minimal 10)."""
    from .utils.shuffle import compute_shuffled_index, shuffle_list

    _, spec = _spec_for(config, fork)
    rounds = spec.shuffle_round_count
    data = _load_yaml(case_dir, "mapping.yaml")
    count = int(data["count"])
    mapping = [int(x) for x in data["mapping"]]
    if count == 0:
        return CaseResult(case_dir, mapping == [])
    seed = bytes.fromhex(str(data["seed"]).removeprefix("0x"))
    got = [compute_shuffled_index(i, count, seed, rounds) for i in range(count)]
    if got != mapping:
        return CaseResult(case_dir, False, "compute_shuffled_index mismatch")
    # the vector's mapping[i] is shuffled(i); shuffle_list's backwards
    # direction reproduces exactly that on the identity list
    got_list = shuffle_list(list(range(count)), seed, forwards=False, rounds=rounds)
    if got_list != mapping:
        return CaseResult(case_dir, False, "shuffle_list mismatch")
    return CaseResult(case_dir, True)


def _run_fork_case(case_dir, handler, config, fork) -> CaseResult:
    """fork/fork (cases/fork.rs): upgrade the previous fork's pre-state."""
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    from .state_transition.upgrades import upgrade_to_altair, upgrade_to_bellatrix

    prev = {"altair": "phase0", "bellatrix": "altair"}.get(fork)
    if prev is None:
        return CaseResult(case_dir, False, f"fork test for {fork}")
    pre_cls = state_class_for(t, prev)
    post_cls = state_class_for(t, fork)
    pre = pre_cls.from_ssz_bytes(_load(case_dir, "pre.ssz_snappy"))
    upgraded = (
        upgrade_to_altair(pre, preset, spec)
        if fork == "altair"
        else upgrade_to_bellatrix(pre, preset, spec)
    )
    want = post_cls.from_ssz_bytes(_load(case_dir, "post.ssz_snappy"))
    if upgraded.tree_hash_root() != want.tree_hash_root():
        return CaseResult(case_dir, False, "fork post-state root mismatch")
    return CaseResult(case_dir, True)


def _ssz_static_class(name: str, t, fork: str):
    """Type-name -> class under the given preset/fork, or None if the
    container is not part of this framework's surface."""
    from .types import block_classes_for
    from .types import containers as C

    if name == "BeaconState":
        return state_class_for(t, fork)
    if name in ("BeaconBlock", "SignedBeaconBlock", "BeaconBlockBody"):
        block_cls, signed_cls, body_cls = block_classes_for(t, fork)
        return {
            "BeaconBlock": block_cls,
            "SignedBeaconBlock": signed_cls,
            "BeaconBlockBody": body_cls,
        }[name]
    if fork == "bellatrix" and name == "ExecutionPayload":
        return t.ExecutionPayload
    if fork == "bellatrix" and name == "ExecutionPayloadHeader":
        return t.ExecutionPayloadHeader
    fork_aware = {
        "Attestation": t.Attestation,
        "AttesterSlashing": t.AttesterSlashing,
        "IndexedAttestation": t.IndexedAttestation,
        "PendingAttestation": getattr(t, "PendingAttestation", None),
        "HistoricalBatch": getattr(t, "HistoricalBatch", None),
        "SyncAggregate": getattr(t, "SyncAggregate", None) if fork != "phase0" else None,
        "SyncCommittee": getattr(t, "SyncCommittee", None) if fork != "phase0" else None,
    }
    if name in fork_aware:
        return fork_aware[name]
    return getattr(C, name, None)


def _run_ssz_static_case(case_dir, handler, config, fork) -> CaseResult:
    """ssz_static/<Type> (cases/ssz_static.rs): decode -> re-encode must
    round-trip and the tree-hash root must match roots.yaml."""
    preset, _ = _spec_for(config, fork)
    t = types_for(preset)
    cls = _ssz_static_class(handler, t, fork)
    if cls is None:
        return CaseResult(case_dir, True, "type not in surface (skipped)")
    raw = _load(case_dir, "serialized.ssz_snappy")
    roots = _load_yaml(case_dir, "roots.yaml")
    try:
        value = cls.from_ssz_bytes(raw)
    except Exception as e:  # noqa: BLE001
        return CaseResult(case_dir, False, f"decode failed: {e}")
    if value.as_ssz_bytes() != raw:
        return CaseResult(case_dir, False, "re-encode mismatch")
    want_root = bytes.fromhex(str(roots["root"]).removeprefix("0x"))
    if value.tree_hash_root() != want_root:
        return CaseResult(case_dir, False, "tree-hash root mismatch")
    return CaseResult(case_dir, True)


def _run_fork_choice_case(case_dir, handler, config, fork) -> CaseResult:
    """fork_choice/* scripted steps (cases/fork_choice.rs): anchor state +
    block, then tick / block / attestation / attester_slashing steps with
    interleaved head & checkpoint checks. Ticks are ABSOLUTE seconds
    (slot = (tick - genesis_time) // seconds_per_slot, set_tick at
    fork_choice.rs:366)."""
    from .fork_choice import ForkChoice
    from .state_transition import clone_state
    from .state_transition.context import ConsensusContext
    from .types import block_classes_for, compute_epoch_at_slot

    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state_cls = state_class_for(t, fork)
    block_cls, signed_cls, _ = block_classes_for(t, fork)
    anchor_state = state_cls.from_ssz_bytes(
        _load(case_dir, "anchor_state.ssz_snappy")
    )
    anchor_block = block_cls.from_ssz_bytes(
        _load(case_dir, "anchor_block.ssz_snappy")
    )
    anchor_root = anchor_block.tree_hash_root()
    states = {anchor_root: anchor_state}
    anchor_epoch = compute_epoch_at_slot(anchor_state.slot, preset)
    anchor_cp = (anchor_epoch, anchor_root)
    fc = ForkChoice(
        preset,
        spec,
        genesis_slot=anchor_block.slot,
        genesis_root=anchor_root,
        justified_checkpoint=anchor_cp,
        finalized_checkpoint=anchor_cp,
        state_lookup=lambda root: states.get(root),
    )
    genesis_time = anchor_state.genesis_time
    time_now = genesis_time + anchor_state.slot * spec.seconds_per_slot

    def att_indices(att):
        """Indexed attestation via the attested block's state advanced to
        the attestation slot (committees are epoch+seed functions of it)."""
        base = states.get(bytes(att.data.beacon_block_root))
        if base is None:
            raise ValueError("attestation for unknown block")
        st = base
        if st.slot < att.data.slot:
            st = process_slots(clone_state(st), att.data.slot, preset, spec)
        ctxt = ConsensusContext(preset, spec)
        return list(ctxt.get_indexed_attestation(st, att).attesting_indices)

    steps = _load_yaml(case_dir, "steps.yaml") or []
    for step in steps:
        if "tick" in step:
            time_now = int(step["tick"])
            fc.on_tick_time(time_now, genesis_time)
        elif "block" in step:
            raw = _load(case_dir, f"{step['block']}.ssz_snappy")
            signed = signed_cls.from_ssz_bytes(raw)
            block = signed.message
            expected_valid = bool(step.get("valid", True))
            try:
                parent = states.get(bytes(block.parent_root))
                if parent is None:
                    raise ValueError("unknown parent")
                st = process_slots(
                    clone_state(parent), block.slot, preset, spec
                )
                ctxt = ConsensusContext(preset, spec)
                per_block_processing(
                    st,
                    signed,
                    preset,
                    spec,
                    strategy=BlockSignatureStrategy.VERIFY_BULK,
                    ctxt=ctxt,
                )
                root = block.tree_hash_root()
                fc.on_block(signed, root, st)
                states[root] = st
                # spec on_block: the block's attestations and slashings
                # feed the store too (is_from_block semantics)
                for att in block.body.attestations:
                    fc.on_attestation(
                        att.data.slot,
                        att_indices(att),
                        bytes(att.data.beacon_block_root),
                        from_block=True,
                    )
                for sl in block.body.attester_slashings:
                    fc.on_attester_slashing(sl)
                applied = True
            except (BlockProcessingError, ValueError, KeyError):
                applied = False
            if applied != expected_valid:
                return CaseResult(
                    case_dir,
                    False,
                    f"block {step['block']}: applied={applied} "
                    f"expected valid={expected_valid}",
                )
        elif "attestation" in step:
            raw = _load(case_dir, f"{step['attestation']}.ssz_snappy")
            att = t.Attestation.from_ssz_bytes(raw)
            expected_valid = bool(step.get("valid", True))
            try:
                fc.on_attestation(
                    att.data.slot,
                    att_indices(att),
                    bytes(att.data.beacon_block_root),
                )
                applied = True
            except (ValueError, KeyError):
                applied = False
            if applied != expected_valid:
                return CaseResult(
                    case_dir,
                    False,
                    f"attestation {step['attestation']}: applied={applied} "
                    f"expected valid={expected_valid}",
                )
        elif "attester_slashing" in step:
            raw = _load(case_dir, f"{step['attester_slashing']}.ssz_snappy")
            sl = t.AttesterSlashing.from_ssz_bytes(raw)
            expected_valid = bool(step.get("valid", True))
            try:
                fc.on_attester_slashing(sl)
                applied = True
            except (ValueError, KeyError):
                applied = False
            if applied != expected_valid:
                return CaseResult(
                    case_dir,
                    False,
                    f"attester_slashing: applied={applied} "
                    f"expected valid={expected_valid}",
                )
        elif "checks" in step:
            checks = step["checks"]
            if "head" in checks:
                head = fc.get_head()
                want = bytes.fromhex(
                    str(checks["head"]["root"]).removeprefix("0x")
                )
                if head != want:
                    return CaseResult(
                        case_dir,
                        False,
                        f"head {head.hex()} != {want.hex()}",
                    )
                idx = fc.proto.proto_array.indices[head]
                if fc.proto.proto_array.nodes[idx].slot != int(
                    checks["head"]["slot"]
                ):
                    return CaseResult(case_dir, False, "head slot mismatch")
            for key, attr in (
                ("justified_checkpoint", fc.justified_checkpoint),
                ("finalized_checkpoint", fc.finalized_checkpoint),
                ("u_justified_checkpoint", fc.unrealized_justified_checkpoint),
                ("u_finalized_checkpoint", fc.unrealized_finalized_checkpoint),
            ):
                if key in checks and checks[key] is not None:
                    want_cp = (
                        int(checks[key]["epoch"]),
                        bytes.fromhex(
                            str(checks[key]["root"]).removeprefix("0x")
                        ),
                    )
                    if attr != want_cp:
                        return CaseResult(
                            case_dir, False, f"{key} {attr} != {want_cp}"
                        )
            if "proposer_boost_root" in checks:
                got = fc.proto.proposer_boost_root or bytes(32)
                want = bytes.fromhex(
                    str(checks["proposer_boost_root"]).removeprefix("0x")
                )
                if got != want:
                    return CaseResult(
                        case_dir, False, "proposer_boost_root mismatch"
                    )
            if "time" in checks and checks["time"] is not None:
                if time_now != int(checks["time"]):
                    return CaseResult(case_dir, False, "time mismatch")
            if "genesis_time" in checks and checks["genesis_time"] is not None:
                if genesis_time != int(checks["genesis_time"]):
                    return CaseResult(case_dir, False, "genesis_time mismatch")
    return CaseResult(case_dir, True)


def _deltas_container():
    from .ssz import List, container, uint64

    # built via type(): this module uses `from __future__ import
    # annotations`, which would turn class-body annotations into strings
    # the @container decorator cannot evaluate
    cls = type(
        "Deltas",
        (),
        {
            "__annotations__": {
                "rewards": List(uint64, 1 << 40),
                "penalties": List(uint64, 1 << 40),
            }
        },
    )
    return container(cls)


def _run_rewards_case(case_dir, handler, config, fork) -> CaseResult:
    """rewards/{basic,leak,random} (cases/rewards.rs): per-component
    reward/penalty deltas against the pre-state."""
    from .state_transition.per_epoch import (
        _total_active_balance,
        attestation_component_deltas,
        flag_component_deltas,
    )

    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state = state_class_for(t, fork).from_ssz_bytes(
        _load(case_dir, "pre.ssz_snappy")
    )
    total = _total_active_balance(state, preset, spec)
    if fork == "phase0":
        comps = attestation_component_deltas(state, preset, spec, {}, total)
    else:
        comps = flag_component_deltas(state, preset, spec, total)
    files = {
        "source_deltas": "source",
        "target_deltas": "target",
        "head_deltas": "head",
        "inclusion_delay_deltas": "inclusion_delay",
        "inactivity_penalty_deltas": "inactivity",
    }
    Deltas = _deltas_container()
    for fname, comp in files.items():
        raw = _load(case_dir, f"{fname}.ssz_snappy")
        if raw is None:
            continue  # inclusion_delay is phase0-only
        if comp not in comps:
            return CaseResult(case_dir, False, f"unexpected {fname}")
        want = Deltas.from_ssz_bytes(raw)
        got_r, got_p = comps[comp]
        if list(want.rewards) != got_r or list(want.penalties) != got_p:
            return CaseResult(case_dir, False, f"{fname} mismatch")
    return CaseResult(case_dir, True)


def _run_transition_case(case_dir, handler, config, fork) -> CaseResult:
    """transition/core (cases/transition.rs): apply blocks across a fork
    boundary; pre-fork blocks decode under the previous fork, the rest
    under the target fork, upgrades happen inside process_slots."""
    from .state_transition import clone_state
    from .types import block_classes_for

    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    meta = _load_yaml(case_dir, "meta.yaml")
    fork_epoch = int(meta["fork_epoch"])
    prev = {"altair": "phase0", "bellatrix": "altair"}.get(fork)
    if prev is None:
        return CaseResult(case_dir, False, f"transition to {fork}")
    # the pre-fork phase runs under the PREVIOUS fork's rules until
    # fork_epoch; rebuild the spec with the real schedule
    if fork == "altair":
        spec.altair_fork_epoch = fork_epoch
        spec.bellatrix_fork_epoch = None
    else:
        spec.altair_fork_epoch = 0
        spec.bellatrix_fork_epoch = fork_epoch
    pre_cls = state_class_for(t, prev)
    _, signed_prev, _ = block_classes_for(t, prev)
    _, signed_post, _ = block_classes_for(t, fork)
    state = pre_cls.from_ssz_bytes(_load(case_dir, "pre.ssz_snappy"))
    fork_block = meta.get("fork_block")
    fork_block = -1 if fork_block is None else int(fork_block)
    try:
        for i in range(int(meta["blocks_count"])):
            raw = _load(case_dir, f"blocks_{i}.ssz_snappy")
            cls = signed_prev if i <= fork_block else signed_post
            signed = cls.from_ssz_bytes(raw)
            state = process_slots(state, signed.message.slot, preset, spec)
            per_block_processing(
                state,
                signed,
                preset,
                spec,
                strategy=BlockSignatureStrategy.VERIFY_BULK,
            )
        applied = True
    except (BlockProcessingError, ValueError) as e:
        applied, error = False, str(e)
    post_raw = _load(case_dir, "post.ssz_snappy")
    if not applied:
        return CaseResult(case_dir, False, f"valid transition rejected: {error}")
    want = state_class_for(t, fork).from_ssz_bytes(post_raw)
    if state.tree_hash_root() != want.tree_hash_root():
        return CaseResult(case_dir, False, "transition post-state mismatch")
    return CaseResult(case_dir, True)


def _mk_container(name: str, fields: dict):
    """Container class via type(): this module's `from __future__ import
    annotations` would stringify class-body annotations."""
    from .ssz import container

    return container(type(name, (), {"__annotations__": dict(fields)}))


import functools


@functools.lru_cache(maxsize=1)
def _ssz_generic_test_types():
    """The spec's ssz_generic test containers (cases/ssz_generic.rs),
    built once."""
    from .ssz import (
        Bitlist,
        Bitvector,
        List,
        Vector,
        uint8,
        uint16,
        uint32,
        uint64,
    )

    single = _mk_container("SingleFieldTestStruct", {"A": uint8})
    small = _mk_container("SmallTestStruct", {"A": uint16, "B": uint16})
    fixed = _mk_container(
        "FixedTestStruct", {"A": uint8, "B": uint64, "C": uint32}
    )
    var = _mk_container(
        "VarTestStruct",
        {"A": uint16, "B": List(uint16, 1024), "C": uint8},
    )
    cplx = _mk_container(
        "ComplexTestStruct",
        {
            "A": uint16,
            "B": List(uint16, 128),
            "C": uint8,
            "D": List(uint8, 256),
            "E": var.ssz_type,
            "F": Vector(fixed.ssz_type, 4),
            "G": Vector(var.ssz_type, 2),
        },
    )
    bits = _mk_container(
        "BitsStruct",
        {
            "A": Bitlist(5),
            "B": Bitvector(2),
            "C": Bitvector(1),
            "D": Bitlist(6),
            "E": Bitvector(8),
        },
    )
    return {
        "SingleFieldTestStruct": single,
        "SmallTestStruct": small,
        "FixedTestStruct": fixed,
        "VarTestStruct": var,
        "ComplexTestStruct": cplx,
        "BitsStruct": bits,
    }


def _ssz_generic_type(handler: str, case: str):
    """Resolve the SSZ type descriptor a case name encodes, or None if
    out of surface."""
    from .ssz import (
        Bitlist,
        Bitvector,
        Vector,
        boolean,
        uint8,
        uint16,
        uint32,
        uint64,
        uint128,
        uint256,
    )

    uints = {
        "8": uint8,
        "16": uint16,
        "32": uint32,
        "64": uint64,
        "128": uint128,
        "256": uint256,
    }
    elems = {"bool": boolean, **{f"uint{k}": v for k, v in uints.items()}}
    parts = case.split("_")
    if handler == "boolean":
        return boolean
    if handler == "uints":
        return uints.get(parts[1])
    if handler == "basic_vector" and len(parts) >= 3:
        elem = elems.get(parts[1])
        try:
            length = int(parts[2])
        except ValueError:
            return None
        if elem is None or length == 0:
            return None
        return Vector(elem, length)
    if handler == "bitvector" and len(parts) >= 2:
        try:
            return Bitvector(int(parts[1]))
        except ValueError:
            return None
    if handler == "bitlist":
        try:
            limit = int(parts[1])
        except (ValueError, IndexError):
            limit = 2048  # e.g. bitlist_no_delimiter_*: decode must fail
        return Bitlist(limit)
    if handler == "containers":
        cls = _ssz_generic_test_types().get(parts[0])
        return None if cls is None else cls.ssz_type
    return None


def _run_ssz_generic_case(case_dir, handler, config, fork) -> CaseResult:
    """ssz_generic/<handler>/{valid,invalid} (cases/ssz_generic.rs):
    valid cases must round-trip and match the meta root; invalid
    serializations must FAIL to decode."""
    suite = os.path.basename(os.path.dirname(case_dir))
    case = os.path.basename(case_dir)
    ssz_type = _ssz_generic_type(handler, case)
    if ssz_type is None:
        return CaseResult(case_dir, True, "type not in surface (skipped)")
    raw = _load(case_dir, "serialized.ssz_snappy")
    if suite == "invalid":
        try:
            ssz_type.decode(raw)
        except Exception:  # noqa: BLE001 -- any decode failure is a pass
            return CaseResult(case_dir, True)
        return CaseResult(case_dir, False, "invalid bytes decoded")
    meta = _load_yaml(case_dir, "meta.yaml") or {}
    try:
        value = ssz_type.decode(raw)
    except Exception as e:  # noqa: BLE001
        return CaseResult(case_dir, False, f"valid case failed decode: {e}")
    if ssz_type.encode(value) != raw:
        return CaseResult(case_dir, False, "re-encode mismatch")
    want_root = meta.get("root")
    if want_root is not None:
        got = ssz_type.hash_tree_root(value)
        if got != bytes.fromhex(str(want_root).removeprefix("0x")):
            return CaseResult(case_dir, False, "root mismatch")
    if handler in ("uints", "boolean"):
        want_value = _load_yaml(case_dir, "value.yaml")
        if want_value is not None and int(value) != int(want_value):
            return CaseResult(case_dir, False, "value mismatch")
    return CaseResult(case_dir, True)


def _run_merkle_proof_case(case_dir, handler, config, fork) -> CaseResult:
    """light_client/single_merkle_proof (cases/merkle_proof_validity.rs):
    the state must PRODUCE the vector's branch for the generalized index,
    and the branch must verify against the state root."""
    from .ssz.merkle_proof import (
        MerkleTree,
        generalized_index_depth,
        verify_merkle_proof,
    )

    if handler not in ("single_merkle_proof", "single_proof"):
        return CaseResult(case_dir, True, "handler not in surface (skipped)")
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state = state_class_for(t, fork).from_ssz_bytes(
        _load(case_dir, "object.ssz_snappy")
        or _load(case_dir, "state.ssz_snappy")
    )
    proof = _load_yaml(case_dir, "proof.yaml")
    leaf = bytes.fromhex(str(proof["leaf"]).removeprefix("0x"))
    gi = int(proof["leaf_index"])
    branch = [
        bytes.fromhex(str(b).removeprefix("0x")) for b in proof["branch"]
    ]
    root = state.tree_hash_root()
    if not verify_merkle_proof(leaf, branch, gi, root):
        return CaseResult(case_dir, False, "branch does not verify")
    # regenerate: the vectors' indices live at the container-field level
    # (e.g. altair current_sync_committee = gi 54); deeper paths would
    # need recursive descent, which no current vector uses
    fields = state.ssz_fields
    depth = generalized_index_depth(gi)
    field_level = max(len(fields) - 1, 0).bit_length()
    if depth == field_level:
        field_idx = gi - (1 << depth)
        if field_idx >= len(fields):
            return CaseResult(case_dir, False, "index beyond field count")
        roots = [
            ftype.hash_tree_root(getattr(state, name))
            for name, ftype in fields
        ]
        tree = MerkleTree(roots)
        if roots[field_idx] != leaf:
            return CaseResult(case_dir, False, "leaf is not the field root")
        if tree.proof(field_idx) != branch:
            return CaseResult(case_dir, False, "generated branch mismatch")
    return CaseResult(case_dir, True)


def _run_update_ranking_case(case_dir, handler, config, fork) -> CaseResult:
    """light_client/update_ranking: the vector's updates are ordered from
    highest to lowest precedence; every later update must NOT rank better
    than an earlier one (spec is_better_update)."""
    from .chain.light_client import is_better_update, light_client_types

    preset, _ = _spec_for(config, fork)
    lt = light_client_types(preset)
    meta = _load_yaml(case_dir, "meta.yaml") or {}
    count = int(meta.get("updates_count", 0))
    updates = [
        lt.LightClientUpdate.from_ssz_bytes(
            _load(case_dir, f"updates_{i}.ssz_snappy")
        )
        for i in range(count)
    ]
    for i in range(len(updates) - 1):
        if is_better_update(updates[i + 1], updates[i], preset):
            return CaseResult(
                case_dir, False, f"update {i + 1} ranks above update {i}"
            )
        if not is_better_update(updates[i], updates[i + 1], preset):
            return CaseResult(
                case_dir, False, f"update {i} does not outrank {i + 1}"
            )
    return CaseResult(case_dir, True)


def _run_light_client_sync_case(case_dir, handler, config, fork) -> CaseResult:
    """light_client/sync: scripted steps driving a spec store —
    process_update / force_update with finalized/optimistic header
    checks after each step."""
    from .chain.light_client import LightClientStore, light_client_types

    preset, spec = _spec_for(config, fork)
    lt = light_client_types(preset)
    meta = _load_yaml(case_dir, "meta.yaml") or {}
    trusted = bytes.fromhex(
        str(meta["trusted_block_root"]).removeprefix("0x")
    )
    gvr = bytes.fromhex(
        str(meta["genesis_validators_root"]).removeprefix("0x")
    )
    bootstrap = lt.LightClientBootstrap.from_ssz_bytes(
        _load(case_dir, "bootstrap.ssz_snappy")
    )
    store = LightClientStore(trusted, bootstrap, preset, spec, gvr)
    steps = _load_yaml(case_dir, "steps.yaml") or []

    def _check(checks) -> str | None:
        for name, want in (checks or {}).items():
            header = getattr(store, name, None)
            if header is None:
                return f"unknown check target {name}"
            if int(header.slot) != int(want["slot"]):
                return f"{name} slot {header.slot} != {want['slot']}"
            want_root = want.get("beacon_root", want.get("root"))
            if want_root is not None and header.tree_hash_root() != (
                bytes.fromhex(str(want_root).removeprefix("0x"))
            ):
                return f"{name} root mismatch"
        return None

    for step in steps:
        if "process_update" in step:
            p = step["process_update"]
            update = lt.LightClientUpdate.from_ssz_bytes(
                _load(case_dir, f"{p['update']}.ssz_snappy")
            )
            store.process_spec_update(update, int(p["current_slot"]))
            err = _check(p.get("checks"))
        elif "force_update" in step:
            p = step["force_update"]
            store.force_update(int(p["current_slot"]))
            err = _check(p.get("checks"))
        else:
            # an unsupported step kind ends the case as an explicit SKIP —
            # continuing would run later checks against missed state, and
            # a bare pass would be a false green in a conformance runner
            kind = next(iter(step), "?")
            return CaseResult(
                case_dir, True, f"skipped at unsupported step {kind!r}"
            )
        if err:
            return CaseResult(case_dir, False, err)
    return CaseResult(case_dir, True)


def _run_light_client_case(case_dir, handler, config, fork) -> CaseResult:
    if handler == "update_ranking":
        return _run_update_ranking_case(case_dir, handler, config, fork)
    if handler == "sync":
        return _run_light_client_sync_case(case_dir, handler, config, fork)
    return _run_merkle_proof_case(case_dir, handler, config, fork)


_RUNNERS = {
    "operations": _run_operation_case,
    "sanity": _run_sanity_case,
    "random": _run_sanity_case,
    "epoch_processing": _run_epoch_case,
    "bls": _run_bls_case,
    "genesis": _run_genesis_case,
    "shuffling": _run_shuffling_case,
    "fork": _run_fork_case,
    "ssz_static": _run_ssz_static_case,
    "fork_choice": _run_fork_choice_case,
    "transition": _run_transition_case,
    "rewards": _run_rewards_case,
    "light_client": _run_light_client_case,
    "merkle": _run_merkle_proof_case,
    "merkle_proof": _run_merkle_proof_case,
    "ssz_generic": _run_ssz_generic_case,
}


def run_tree(root: str, configs=("general", "minimal", "mainnet")) -> list[CaseResult]:
    """Walk <root>/tests/... and run every recognized case (the Handler
    walk, handler.rs:37-70). Unrecognized runners are skipped silently --
    the official tree carries many runner kinds."""
    results = []
    tests = os.path.join(root, "tests")
    for config in configs:
        cfg_dir = os.path.join(tests, config)
        if not os.path.isdir(cfg_dir):
            continue
        for fork in sorted(os.listdir(cfg_dir)):
            if fork not in ("phase0", "altair", "bellatrix"):
                continue
            fork_dir = os.path.join(cfg_dir, fork)
            for runner in sorted(os.listdir(fork_dir)):
                run_case = _RUNNERS.get(runner)
                if run_case is None:
                    continue
                runner_dir = os.path.join(fork_dir, runner)
                for handler in sorted(os.listdir(runner_dir)):
                    handler_dir = os.path.join(runner_dir, handler)
                    for suite in sorted(os.listdir(handler_dir)):
                        suite_dir = os.path.join(handler_dir, suite)
                        for case in sorted(os.listdir(suite_dir)):
                            case_dir = os.path.join(suite_dir, case)
                            if not os.path.isdir(case_dir):
                                continue
                            try:
                                results.append(
                                    run_case(case_dir, handler, config, fork)
                                )
                            except Exception as e:  # noqa: BLE001
                                results.append(
                                    CaseResult(case_dir, False, f"crash: {e}")
                                )
    return results
