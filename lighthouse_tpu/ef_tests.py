"""EF consensus-spec-tests runner (reference testing/ef_tests/src/
handler.rs:10-41 + cases/*): walks the official vector layout

    <root>/tests/<config>/<fork>/<runner>/<handler>/<suite>/<case>/

and executes each case against this framework's state transition, SSZ,
and BLS backends. The official vectors are a multi-GB download
(reference Makefile:176-182 make-ef-tests); point LIGHTHOUSE_TPU_EF_TESTS
at an extracted tree to run them. The same machinery executes the
in-repo synthesized mini-tree (tests/test_ef_vectors.py), so the walker,
ssz_snappy loading, and case semantics stay exercised offline.

Implemented runners (cases/{operations,epoch_processing,sanity,bls}.rs):

  operations/{attestation,attester_slashing,proposer_slashing,
              voluntary_exit,deposit,sync_aggregate}
  epoch_processing/* (full epoch transition per handler)
  sanity/{slots,blocks}
  bls/{verify,aggregate_verify,fast_aggregate_verify,batch_verify}
"""

from __future__ import annotations

import os

import yaml

from .crypto import bls
from .network.snappy import decompress
from .state_transition import (
    BlockProcessingError,
    BlockSignatureStrategy,
    per_block_processing,
    process_epoch,
    process_slots,
)
from .state_transition.context import ConsensusContext
from .state_transition.per_block import (
    process_attestation,
    process_attester_slashing,
    process_deposit,
    process_proposer_slashing,
    process_sync_aggregate,
    process_voluntary_exit,
)
from .types import ChainSpec, state_class_for, types_for
from .types.presets import MAINNET, MINIMAL


class CaseResult:
    def __init__(self, path: str, ok: bool, message: str = ""):
        self.path = path
        self.ok = ok
        self.message = message

    def __repr__(self):
        return f"{'ok ' if self.ok else 'FAIL'} {self.path} {self.message}"


def _load(case_dir: str, name: str) -> bytes | None:
    p = os.path.join(case_dir, name)
    if not os.path.exists(p):
        return None
    with open(p, "rb") as f:
        return decompress(f.read())


def _load_yaml(case_dir: str, name: str):
    p = os.path.join(case_dir, name)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return yaml.safe_load(f)


def _spec_for(config: str, fork: str) -> tuple:
    """The OFFICIAL config's spec (minimal/mainnet constants -- the
    vectors were generated under them; interop constants would fail
    domain- and period-dependent cases), with the target fork active from
    genesis (handler.rs fork_from_env runs each fork's vectors that
    way)."""
    preset = MINIMAL if config == "minimal" else MAINNET
    spec = ChainSpec.minimal() if config == "minimal" else ChainSpec.mainnet()
    spec.altair_fork_epoch = 0 if fork in ("altair", "bellatrix") else None
    spec.bellatrix_fork_epoch = 0 if fork == "bellatrix" else None
    return preset, spec


_OPERATION_FILES = {
    "attestation": ("attestation.ssz_snappy", "Attestation", process_attestation),
    "attester_slashing": (
        "attester_slashing.ssz_snappy",
        "AttesterSlashing",
        process_attester_slashing,
    ),
    "proposer_slashing": (
        "proposer_slashing.ssz_snappy",
        "ProposerSlashing",
        process_proposer_slashing,
    ),
    "voluntary_exit": (
        "voluntary_exit.ssz_snappy",
        "SignedVoluntaryExit",
        process_voluntary_exit,
    ),
    "deposit": ("deposit.ssz_snappy", "Deposit", process_deposit),
    "sync_aggregate": (
        "sync_aggregate.ssz_snappy",
        "SyncAggregate",
        process_sync_aggregate,
    ),
}


def _run_operation_case(case_dir, handler, config, fork) -> CaseResult:
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state_cls = state_class_for(t, fork)
    fname, type_name, process = _OPERATION_FILES[handler]
    pre = state_cls.from_ssz_bytes(_load(case_dir, "pre.ssz_snappy"))
    op_raw = _load(case_dir, fname)
    from .types.containers import (
        Deposit,
        ProposerSlashing,
        SignedVoluntaryExit,
    )

    op_cls = {
        "Attestation": t.Attestation,
        "AttesterSlashing": t.AttesterSlashing,
        "ProposerSlashing": ProposerSlashing,
        "SignedVoluntaryExit": SignedVoluntaryExit,
        "Deposit": Deposit,
        "SyncAggregate": t.SyncAggregate,
    }[type_name]
    op = op_cls.from_ssz_bytes(op_raw)
    post_raw = _load(case_dir, "post.ssz_snappy")
    ctxt = ConsensusContext(preset, spec)
    try:
        if handler == "voluntary_exit":
            process(pre, op, preset, spec)
        else:
            process(pre, op, preset, spec, ctxt=ctxt)
        applied = True
    except (BlockProcessingError, IndexError, ValueError) as e:
        applied = False
        error = str(e)
    if post_raw is None:
        if applied:
            return CaseResult(case_dir, False, "invalid op was accepted")
        return CaseResult(case_dir, True)
    if not applied:
        return CaseResult(case_dir, False, f"valid op rejected: {error}")
    if pre.tree_hash_root() != state_cls.from_ssz_bytes(post_raw).tree_hash_root():
        return CaseResult(case_dir, False, "post-state root mismatch")
    return CaseResult(case_dir, True)


def _run_sanity_case(case_dir, handler, config, fork) -> CaseResult:
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state_cls = state_class_for(t, fork)
    pre = state_cls.from_ssz_bytes(_load(case_dir, "pre.ssz_snappy"))
    post_raw = _load(case_dir, "post.ssz_snappy")
    try:
        if handler == "slots":
            n = _load_yaml(case_dir, "slots.yaml")
            pre = process_slots(pre, pre.slot + int(n), preset, spec)
        else:  # blocks
            meta = _load_yaml(case_dir, "meta.yaml") or {}
            from .types import block_classes_for

            _, signed_cls, _ = block_classes_for(t, fork)
            for i in range(int(meta.get("blocks_count", 0))):
                raw = _load(case_dir, f"blocks_{i}.ssz_snappy")
                signed = signed_cls.from_ssz_bytes(raw)
                pre = process_slots(pre, signed.message.slot, preset, spec)
                per_block_processing(
                    pre,
                    signed,
                    preset,
                    spec,
                    strategy=BlockSignatureStrategy.VERIFY_BULK,
                )
        applied = True
    except (BlockProcessingError, ValueError) as e:
        applied = False
        error = str(e)
    if post_raw is None:
        return (
            CaseResult(case_dir, True)
            if not applied
            else CaseResult(case_dir, False, "invalid sanity case accepted")
        )
    if not applied:
        return CaseResult(case_dir, False, f"valid case rejected: {error}")
    if pre.tree_hash_root() != state_cls.from_ssz_bytes(post_raw).tree_hash_root():
        return CaseResult(case_dir, False, "post-state root mismatch")
    return CaseResult(case_dir, True)


def _run_epoch_case(case_dir, handler, config, fork) -> CaseResult:
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state_cls = state_class_for(t, fork)
    pre = state_cls.from_ssz_bytes(_load(case_dir, "pre.ssz_snappy"))
    post_raw = _load(case_dir, "post.ssz_snappy")
    try:
        # the repo runs the FULL epoch transition (sub-transition isolation
        # is a test-granularity nicety, not a consensus behavior)
        process_epoch(pre, preset, spec)
        applied = True
    except (BlockProcessingError, ValueError) as e:
        applied, error = False, str(e)
    if post_raw is None:
        return (
            CaseResult(case_dir, True)
            if not applied
            else CaseResult(case_dir, False, "invalid epoch case accepted")
        )
    if not applied:
        return CaseResult(case_dir, False, f"valid case rejected: {error}")
    if pre.tree_hash_root() != state_cls.from_ssz_bytes(post_raw).tree_hash_root():
        return CaseResult(case_dir, False, "post-state root mismatch")
    return CaseResult(case_dir, True)


def _run_bls_case(case_dir, handler, config, fork) -> CaseResult:
    data = _load_yaml(case_dir, "data.yaml")
    if data is None:
        return CaseResult(case_dir, False, "missing data.yaml")
    inp, expected = data["input"], data["output"]

    def _b(h):
        return bytes.fromhex(str(h).removeprefix("0x"))

    try:
        if handler == "verify":
            pk = bls.PublicKey.from_bytes(_b(inp["pubkey"]))
            sig = bls.Signature.from_bytes(_b(inp["signature"]))
            got = bls.verify(sig, [pk], _b(inp["message"]))
        elif handler == "fast_aggregate_verify":
            pks = [bls.PublicKey.from_bytes(_b(p)) for p in inp["pubkeys"]]
            sig = bls.Signature.from_bytes(_b(inp["signature"]))
            got = bls.verify(sig, pks, _b(inp["message"]))
        elif handler == "aggregate_verify":
            pks = [bls.PublicKey.from_bytes(_b(p)) for p in inp["pubkeys"]]
            sig = bls.Signature.from_bytes(_b(inp["signature"]))
            got = bls.aggregate_verify(
                sig, pks, [_b(m) for m in inp["messages"]]
            )
        elif handler == "batch_verify":
            sets = []
            for pk_h, m_h, sig_h in zip(
                inp["pubkeys"], inp["messages"], inp["signatures"]
            ):
                pk = bls.PublicKey.from_bytes(_b(pk_h))
                sig = bls.Signature.from_bytes(_b(sig_h))
                sets.append(bls.SignatureSet.single_pubkey(sig, pk, _b(m_h)))
            got = bls.verify_signature_sets(sets, seed=1)
        else:
            return CaseResult(case_dir, False, f"unknown bls handler {handler}")
    except (bls.BlsError, ValueError):
        got = False  # undecodable inputs are failing verifications
    if bool(got) != bool(expected):
        return CaseResult(case_dir, False, f"got {got}, expected {expected}")
    return CaseResult(case_dir, True)


def _run_genesis_case(case_dir, handler, config, fork) -> CaseResult:
    """genesis/{initialization,validity} (cases/genesis_initialization.rs,
    genesis_validity.rs)."""
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    state_cls = state_class_for(t, fork)
    from .state_transition.genesis import (
        initialize_beacon_state_from_eth1,
        is_valid_genesis_state,
    )

    if handler == "validity":
        genesis = state_cls.from_ssz_bytes(_load(case_dir, "genesis.ssz_snappy"))
        want = bool(_load_yaml(case_dir, "is_valid.yaml"))
        got = is_valid_genesis_state(genesis, preset, spec)
        if got != want:
            return CaseResult(case_dir, False, f"validity {got} != {want}")
        return CaseResult(case_dir, True)

    if handler != "initialization":
        return CaseResult(case_dir, False, f"unknown genesis handler {handler}")
    eth1 = _load_yaml(case_dir, "eth1.yaml")
    meta = _load_yaml(case_dir, "meta.yaml") or {}
    from .types.containers import Deposit

    deposits = [
        Deposit.from_ssz_bytes(_load(case_dir, f"deposits_{i}.ssz_snappy"))
        for i in range(int(meta.get("deposits_count", 0)))
    ]
    header = None
    if meta.get("execution_payload_header"):
        raw = _load(case_dir, "execution_payload_header.ssz_snappy")
        header = t.ExecutionPayloadHeader.from_ssz_bytes(raw)
    block_hash = bytes.fromhex(str(eth1["eth1_block_hash"]).removeprefix("0x"))
    state = initialize_beacon_state_from_eth1(
        block_hash,
        int(eth1["eth1_timestamp"]),
        deposits,
        preset,
        spec,
        execution_payload_header=header,
    )
    want = state_cls.from_ssz_bytes(_load(case_dir, "state.ssz_snappy"))
    if state.tree_hash_root() != want.tree_hash_root():
        return CaseResult(case_dir, False, "genesis state root mismatch")
    return CaseResult(case_dir, True)


def _run_shuffling_case(case_dir, handler, config, fork) -> CaseResult:
    """shuffling/core (cases/shuffling.rs): both compute_shuffled_index
    and the whole-list fast path must reproduce the mapping, under the
    config's round count (mainnet 90 / minimal 10)."""
    from .utils.shuffle import compute_shuffled_index, shuffle_list

    _, spec = _spec_for(config, fork)
    rounds = spec.shuffle_round_count
    data = _load_yaml(case_dir, "mapping.yaml")
    count = int(data["count"])
    mapping = [int(x) for x in data["mapping"]]
    if count == 0:
        return CaseResult(case_dir, mapping == [])
    seed = bytes.fromhex(str(data["seed"]).removeprefix("0x"))
    got = [compute_shuffled_index(i, count, seed, rounds) for i in range(count)]
    if got != mapping:
        return CaseResult(case_dir, False, "compute_shuffled_index mismatch")
    # the vector's mapping[i] is shuffled(i); shuffle_list's backwards
    # direction reproduces exactly that on the identity list
    got_list = shuffle_list(list(range(count)), seed, forwards=False, rounds=rounds)
    if got_list != mapping:
        return CaseResult(case_dir, False, "shuffle_list mismatch")
    return CaseResult(case_dir, True)


def _run_fork_case(case_dir, handler, config, fork) -> CaseResult:
    """fork/fork (cases/fork.rs): upgrade the previous fork's pre-state."""
    preset, spec = _spec_for(config, fork)
    t = types_for(preset)
    from .state_transition.upgrades import upgrade_to_altair, upgrade_to_bellatrix

    prev = {"altair": "phase0", "bellatrix": "altair"}.get(fork)
    if prev is None:
        return CaseResult(case_dir, False, f"fork test for {fork}")
    pre_cls = state_class_for(t, prev)
    post_cls = state_class_for(t, fork)
    pre = pre_cls.from_ssz_bytes(_load(case_dir, "pre.ssz_snappy"))
    upgraded = (
        upgrade_to_altair(pre, preset, spec)
        if fork == "altair"
        else upgrade_to_bellatrix(pre, preset, spec)
    )
    want = post_cls.from_ssz_bytes(_load(case_dir, "post.ssz_snappy"))
    if upgraded.tree_hash_root() != want.tree_hash_root():
        return CaseResult(case_dir, False, "fork post-state root mismatch")
    return CaseResult(case_dir, True)


def _ssz_static_class(name: str, t, fork: str):
    """Type-name -> class under the given preset/fork, or None if the
    container is not part of this framework's surface."""
    from .types import block_classes_for
    from .types import containers as C

    if name == "BeaconState":
        return state_class_for(t, fork)
    if name in ("BeaconBlock", "SignedBeaconBlock", "BeaconBlockBody"):
        block_cls, signed_cls, body_cls = block_classes_for(t, fork)
        return {
            "BeaconBlock": block_cls,
            "SignedBeaconBlock": signed_cls,
            "BeaconBlockBody": body_cls,
        }[name]
    if fork == "bellatrix" and name == "ExecutionPayload":
        return t.ExecutionPayload
    if fork == "bellatrix" and name == "ExecutionPayloadHeader":
        return t.ExecutionPayloadHeader
    fork_aware = {
        "Attestation": t.Attestation,
        "AttesterSlashing": t.AttesterSlashing,
        "IndexedAttestation": t.IndexedAttestation,
        "PendingAttestation": getattr(t, "PendingAttestation", None),
        "HistoricalBatch": getattr(t, "HistoricalBatch", None),
        "SyncAggregate": getattr(t, "SyncAggregate", None) if fork != "phase0" else None,
        "SyncCommittee": getattr(t, "SyncCommittee", None) if fork != "phase0" else None,
    }
    if name in fork_aware:
        return fork_aware[name]
    return getattr(C, name, None)


def _run_ssz_static_case(case_dir, handler, config, fork) -> CaseResult:
    """ssz_static/<Type> (cases/ssz_static.rs): decode -> re-encode must
    round-trip and the tree-hash root must match roots.yaml."""
    preset, _ = _spec_for(config, fork)
    t = types_for(preset)
    cls = _ssz_static_class(handler, t, fork)
    if cls is None:
        return CaseResult(case_dir, True, "type not in surface (skipped)")
    raw = _load(case_dir, "serialized.ssz_snappy")
    roots = _load_yaml(case_dir, "roots.yaml")
    try:
        value = cls.from_ssz_bytes(raw)
    except Exception as e:  # noqa: BLE001
        return CaseResult(case_dir, False, f"decode failed: {e}")
    if value.as_ssz_bytes() != raw:
        return CaseResult(case_dir, False, "re-encode mismatch")
    want_root = bytes.fromhex(str(roots["root"]).removeprefix("0x"))
    if value.tree_hash_root() != want_root:
        return CaseResult(case_dir, False, "tree-hash root mismatch")
    return CaseResult(case_dir, True)


_RUNNERS = {
    "operations": _run_operation_case,
    "sanity": _run_sanity_case,
    "epoch_processing": _run_epoch_case,
    "bls": _run_bls_case,
    "genesis": _run_genesis_case,
    "shuffling": _run_shuffling_case,
    "fork": _run_fork_case,
    "ssz_static": _run_ssz_static_case,
}


def run_tree(root: str, configs=("general", "minimal", "mainnet")) -> list[CaseResult]:
    """Walk <root>/tests/... and run every recognized case (the Handler
    walk, handler.rs:37-70). Unrecognized runners are skipped silently --
    the official tree carries many runner kinds."""
    results = []
    tests = os.path.join(root, "tests")
    for config in configs:
        cfg_dir = os.path.join(tests, config)
        if not os.path.isdir(cfg_dir):
            continue
        for fork in sorted(os.listdir(cfg_dir)):
            if fork not in ("phase0", "altair", "bellatrix"):
                continue
            fork_dir = os.path.join(cfg_dir, fork)
            for runner in sorted(os.listdir(fork_dir)):
                run_case = _RUNNERS.get(runner)
                if run_case is None:
                    continue
                runner_dir = os.path.join(fork_dir, runner)
                for handler in sorted(os.listdir(runner_dir)):
                    handler_dir = os.path.join(runner_dir, handler)
                    for suite in sorted(os.listdir(handler_dir)):
                        suite_dir = os.path.join(handler_dir, suite)
                        for case in sorted(os.listdir(suite_dir)):
                            case_dir = os.path.join(suite_dir, case)
                            if not os.path.isdir(case_dir):
                                continue
                            try:
                                results.append(
                                    run_case(case_dir, handler, config, fork)
                                )
                            except Exception as e:  # noqa: BLE001
                                results.append(
                                    CaseResult(case_dir, False, f"crash: {e}")
                                )
    return results
