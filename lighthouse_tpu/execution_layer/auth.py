"""Engine-API JWT auth (reference execution_layer/src/engine_api/auth.rs):
every request to the authenticated engine port carries a short-lived HS256
JWT whose `iat` must be within ±60 s of the server clock, signed with the
32-byte shared secret from the jwt-secret file.

Implemented on stdlib hmac/hashlib/base64 (no external JWT dependency).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time

JWT_SECRET_LEN = 32
# auth.rs: DEFAULT_VALIDITY window for iat drift
JWT_IAT_WINDOW_S = 60


class JwtError(ValueError):
    pass


def _b64url(data: bytes) -> bytes:
    return base64.urlsafe_b64encode(data).rstrip(b"=")


def _b64url_decode(data: bytes) -> bytes:
    return base64.urlsafe_b64decode(data + b"=" * (-len(data) % 4))


class JwtKey:
    """Validated 32-byte HS256 key (auth.rs JwtKey::from_slice)."""

    def __init__(self, secret: bytes):
        if len(secret) != JWT_SECRET_LEN:
            raise JwtError(f"jwt secret must be {JWT_SECRET_LEN} bytes")
        self.secret = bytes(secret)

    @classmethod
    def from_hex(cls, text: str) -> "JwtKey":
        h = text.strip()
        if h.startswith("0x"):
            h = h[2:]
        try:
            return cls(bytes.fromhex(h))
        except ValueError as e:
            raise JwtError(f"bad jwt secret hex: {e}") from None

    @classmethod
    def from_file(cls, path: str) -> "JwtKey":
        with open(path) as f:
            return cls.from_hex(f.read())

    @classmethod
    def random(cls) -> "JwtKey":
        return cls(os.urandom(JWT_SECRET_LEN))

    def to_hex(self) -> str:
        return "0x" + self.secret.hex()


def generate_token(key: JwtKey, now: float | None = None) -> str:
    """Fresh token with an `iat` claim (auth.rs Auth::generate_token)."""
    header = _b64url(json.dumps({"typ": "JWT", "alg": "HS256"}).encode())
    claims = _b64url(
        # lint: allow[wallclock] -- JWT iat is wall time by protocol; the
        # `now` parameter is the injected/testable path
        json.dumps({"iat": int(now if now is not None else time.time())}).encode()
    )
    signing_input = header + b"." + claims
    sig = hmac.new(key.secret, signing_input, hashlib.sha256).digest()
    return (signing_input + b"." + _b64url(sig)).decode()


def validate_token(key: JwtKey, token: str, now: float | None = None) -> dict:
    """Server-side check: signature + iat drift window. Returns the claims
    (the in-process engine rig uses this exactly as geth's auth layer
    would)."""
    parts = token.encode().split(b".")
    if len(parts) != 3:
        raise JwtError("malformed token")
    header_b, claims_b, sig_b = parts
    expected = hmac.new(
        key.secret, header_b + b"." + claims_b, hashlib.sha256
    ).digest()
    if not hmac.compare_digest(expected, _b64url_decode(sig_b)):
        raise JwtError("bad signature")
    try:
        header = json.loads(_b64url_decode(header_b))
        claims = json.loads(_b64url_decode(claims_b))
    except (ValueError, UnicodeDecodeError) as e:
        raise JwtError(f"undecodable token: {e}") from None
    if header.get("alg") != "HS256":
        raise JwtError(f"unsupported alg {header.get('alg')!r}")
    iat = claims.get("iat")
    if not isinstance(iat, int):
        raise JwtError("missing iat claim")
    # lint: allow[wallclock] -- iat drift check against real time, as geth does
    t = now if now is not None else time.time()
    if abs(t - iat) > JWT_IAT_WINDOW_S:
        raise JwtError("stale token (iat outside the validity window)")
    return claims
