"""ExecutionLayer facade (reference execution_layer/src/lib.rs): the
consensus side's handle on an execution engine. Verb-level API used by the
chain:

  * notify_new_payload(payload) -> PayloadVerificationStatus -- wraps
    engine_newPayload and interprets PayloadStatusV1 the way
    payload_status.rs does (SYNCING/ACCEPTED => optimistic import).
  * notify_forkchoice_updated(head/safe/finalized hash, attrs) -- drives
    the EL's head and optionally starts payload building.
  * get_payload(parent_hash, timestamp, prev_randao, fee_recipient) --
    the production path: fcU with attributes then engine_getPayload.
"""

from __future__ import annotations

import enum

from ..resilience.primitives import RetryExhausted, RetryPolicy
from .engine_api import (
    EngineApiError,
    ForkchoiceState,
    PayloadAttributes,
    PayloadStatusV1Status,
)

# engine faults worth re-attempting: the API's own error shape plus
# transport errors (ConnectionError covers injected FaultPlan faults,
# TimeoutError/OSError cover sockets and injected hangs). EngineApiError
# is deliberately included even though it also covers semantic JSON-RPC
# rejections: the HTTP transport (http_engine.py/utils/jsonrpc.py)
# surfaces exhausted transport retries AS EngineApiError, and the
# reference treats an erroring engine like a syncing one (optimistic
# posture) rather than trusting it to distinguish its own failures.
TRANSIENT_ENGINE_ERRORS = (EngineApiError, ConnectionError, OSError)


class PayloadVerificationStatus(str, enum.Enum):
    """What block import learns about a payload (reference
    fork_choice PayloadVerificationStatus / payload_status.rs)."""

    VERIFIED = "verified"
    OPTIMISTIC = "optimistic"
    IRRELEVANT = "irrelevant"  # pre-merge blocks / default payloads


class PayloadInvalid(ValueError):
    def __init__(self, msg: str, latest_valid_hash: bytes | None = None):
        super().__init__(msg)
        self.latest_valid_hash = latest_valid_hash


class ExecutionLayer:
    def __init__(
        self,
        engine,
        suggested_fee_recipient: bytes = b"\x00" * 20,
        pre_merge_parent_hash: bytes | None = None,
        retry_policy: RetryPolicy | None = None,
        syncing_retry_attempts: int = 0,
    ):
        self.engine = engine
        self.suggested_fee_recipient = suggested_fee_recipient
        # resilience (opt-in, injected): with a RetryPolicy, transient
        # engine faults retry with backoff; an engine still unreachable
        # after the budget degrades newPayload to OPTIMISTIC (the
        # reference's optimistic-sync posture toward an offline engine)
        # while fcU/getPayload fail loudly. `syncing_retry_attempts`
        # additionally re-polls a SYNCING newPayload before settling for
        # the optimistic import.
        self.retry_policy = retry_policy
        self.syncing_retry_attempts = syncing_retry_attempts
        # the EL block to build the transition payload on before the merge
        # completes (terminal block seat); in-process mocks default to their
        # own genesis, remote engines must be told explicitly
        self.pre_merge_parent_hash = (
            pre_merge_parent_hash
            if pre_merge_parent_hash is not None
            else getattr(engine, "genesis_hash", None)
        )
        # per-proposer fee recipients pushed by VCs (reference
        # execution_layer proposer_preparation_data, fed by the VC's
        # preparation_service.rs prepare_beacon_proposer calls)
        self.proposer_preparations: dict[int, bytes] = {}

    def update_proposer_preparation(
        self, validator_index: int, fee_recipient: bytes
    ) -> None:
        self.proposer_preparations[validator_index] = bytes(fee_recipient)

    def fee_recipient_for(self, validator_index: int | None) -> bytes:
        if validator_index is None:
            return self.suggested_fee_recipient
        return self.proposer_preparations.get(
            validator_index, self.suggested_fee_recipient
        )

    def get_pow_block(self, block_hash: bytes):
        """(parent_hash, total_difficulty) of a pre-merge EL block, or
        None when the engine does not know it (still syncing) or has no
        pow surface (reference engines.rs get_pow_block via
        eth_getBlockByHash)."""
        getter = getattr(self.engine, "get_pow_block", None)
        if getter is None:
            return None
        return getter(block_hash)

    def validate_merge_block(self, payload_parent_hash: bytes, spec):
        """Spec validate_merge_block: the transition payload's parent pow
        block must cross the TTD while ITS parent is still under it.
        Returns True (valid), False (provably invalid), or None (pow data
        unavailable: import optimistically, re-check later -- the
        reference's otb_verification_service seat)."""
        if any(spec.terminal_block_hash):
            # terminal-block-hash override networks: the designated block
            # IS the terminal block; the TTD comparison is skipped
            return bytes(payload_parent_hash) == bytes(
                spec.terminal_block_hash
            )
        pow_block = self.get_pow_block(payload_parent_hash)
        if pow_block is None:
            return None
        parent_hash, ttd = pow_block
        if ttd < spec.terminal_total_difficulty:
            return False
        pow_parent = self.get_pow_block(parent_hash)
        if pow_parent is None:
            return None
        return pow_parent[1] < spec.terminal_total_difficulty

    def _engine_call(self, fn):
        """One engine round trip under the injected retry policy (none
        configured -> single attempt, errors propagate as before)."""
        if self.retry_policy is None:
            return fn()
        return self.retry_policy.call(fn, retry_on=TRANSIENT_ENGINE_ERRORS)

    # -- verification path (block import) -----------------------------------

    def notify_new_payload(self, payload) -> PayloadVerificationStatus:
        # the block-hash check runs LOCALLY before any engine round trip
        # (block_hash.rs via block_verification.rs): a payload whose header
        # doesn't keccak to its claimed hash is invalid no matter what a
        # (possibly lying) engine says, and never reaches the wire
        from .block_hash import verify_payload_block_hash

        try:
            verify_payload_block_hash(payload)
        except ValueError as e:
            raise PayloadInvalid(str(e)) from None
        syncing_budget = self.syncing_retry_attempts
        while True:
            try:
                status = self._engine_call(
                    lambda: self.engine.new_payload(payload)
                )
            except RetryExhausted:
                # the engine stayed unreachable through the retry budget:
                # treat it like a SYNCING engine and import optimistically
                # (payload_status.rs posture; fork choice re-checks later)
                return PayloadVerificationStatus.OPTIMISTIC
            s = status.status
            if s == PayloadStatusV1Status.VALID:
                return PayloadVerificationStatus.VERIFIED
            if s in (
                PayloadStatusV1Status.SYNCING,
                PayloadStatusV1Status.ACCEPTED,
            ):
                if s == PayloadStatusV1Status.SYNCING and syncing_budget > 0:
                    # re-poll a syncing engine before settling for the
                    # optimistic import -- it may catch up within the
                    # backoff window
                    syncing_budget -= 1
                    if self.retry_policy is not None:
                        self.retry_policy.pause(
                            self.syncing_retry_attempts - syncing_budget - 1
                        )
                    continue
                return PayloadVerificationStatus.OPTIMISTIC
            raise PayloadInvalid(
                f"execution payload invalid: {s.value}"
                + (
                    f" ({status.validation_error})"
                    if status.validation_error
                    else ""
                ),
                status.latest_valid_hash,
            )

    def notify_forkchoice_updated(
        self,
        head_block_hash: bytes,
        finalized_block_hash: bytes = b"\x00" * 32,
        safe_block_hash: bytes | None = None,
        attributes: PayloadAttributes | None = None,
    ):
        state = ForkchoiceState(
            head_block_hash=head_block_hash,
            safe_block_hash=(
                head_block_hash if safe_block_hash is None else safe_block_hash
            ),
            finalized_block_hash=finalized_block_hash,
        )
        resp = self._engine_call(
            lambda: self.engine.forkchoice_updated(state, attributes)
        )
        if resp.payload_status.status == PayloadStatusV1Status.INVALID:
            raise PayloadInvalid(
                "forkchoiceUpdated: head payload invalid",
                resp.payload_status.latest_valid_hash,
            )
        return resp

    # -- production path -----------------------------------------------------

    def build_payload_for_block(self, state, slot: int, proposer: int, preset, spec):
        """Execution payload for a block being produced on `state` at
        `slot` (the shared produce path of harness and BN block
        production): parent selection across the merge transition,
        spec-derived timestamp/randao, and the proposer's prepared fee
        recipient."""
        from ..state_transition.per_block import (
            compute_timestamp_at_slot,
            is_merge_transition_complete,
        )
        from ..types.helpers import get_randao_mix
        from ..types import compute_epoch_at_slot

        if is_merge_transition_complete(state):
            parent_hash = bytes(state.latest_execution_payload_header.block_hash)
        else:
            # merge transition: build on the configured terminal EL block
            if self.pre_merge_parent_hash is None:
                raise EngineApiError(
                    "pre-merge payload requested with no terminal parent configured"
                )
            parent_hash = self.pre_merge_parent_hash
        epoch = compute_epoch_at_slot(slot, preset)
        return self.get_payload(
            parent_hash,
            compute_timestamp_at_slot(state, slot, spec),
            bytes(get_randao_mix(state, epoch, preset)),
            fee_recipient=self.fee_recipient_for(proposer),
        )

    def get_payload(
        self,
        parent_hash: bytes,
        timestamp: int,
        prev_randao: bytes,
        fee_recipient: bytes | None = None,
    ):
        attrs = PayloadAttributes(
            timestamp=timestamp,
            prev_randao=prev_randao,
            suggested_fee_recipient=(
                fee_recipient or self.suggested_fee_recipient
            ),
        )
        resp = self.notify_forkchoice_updated(
            parent_hash, attributes=attrs
        )
        if resp.payload_id is None:
            raise EngineApiError("engine did not start payload build")
        # block production must fail loudly: retries smooth transient
        # faults, but an exhausted budget propagates (no silent degrade)
        return self._engine_call(
            lambda: self.engine.get_payload(resp.payload_id)
        )
