"""Execution-payload block-hash verification (reference
execution_layer/src/block_hash.rs + consensus/types/src/
execution_block_header.rs): re-derive keccak256(rlp(execution header))
from the payload a proposer shipped and compare it to the claimed
block_hash — the check that stops a lying execution engine or proposer
from smuggling a mislabeled payload through optimistic import.

The bellatrix execution header is the pre-withdrawals 15-field layout:
transactions_root is the ordered MPT root over the raw transaction bytes
(block_hash.rs calculate_transactions_root), ommers_hash is the constant
keccak(rlp([])), difficulty 0 and an all-zero 8-byte nonce post-merge.
"""

from __future__ import annotations

from .keccak import keccak256
from .rlp import encode_bytes, encode_int, encode_list, ordered_trie_root

# keccak256(rlp([])): ommers hash of every post-merge block
EMPTY_OMMERS_HASH = bytes.fromhex(
    "1dcc4de8dec75d7aab85b567b6ccd41ad312451b948a7413f0a142fd40d49347"
)
POST_MERGE_NONCE = b"\x00" * 8


def calculate_transactions_root(transactions) -> bytes:
    return ordered_trie_root([bytes(tx) for tx in transactions])


def calculate_execution_block_hash(payload) -> bytes:
    """keccak256 of the RLP execution header reconstructed from an
    ExecutionPayload (block_hash.rs calculate_execution_block_hash)."""
    fields = [
        encode_bytes(bytes(payload.parent_hash)),
        encode_bytes(EMPTY_OMMERS_HASH),
        encode_bytes(bytes(payload.fee_recipient)),
        encode_bytes(bytes(payload.state_root)),
        encode_bytes(calculate_transactions_root(payload.transactions)),
        encode_bytes(bytes(payload.receipts_root)),
        encode_bytes(bytes(payload.logs_bloom)),
        encode_int(0),  # difficulty: always 0 post-merge
        encode_int(int(payload.block_number)),
        encode_int(int(payload.gas_limit)),
        encode_int(int(payload.gas_used)),
        encode_int(int(payload.timestamp)),
        encode_bytes(bytes(payload.extra_data)),
        encode_bytes(bytes(payload.prev_randao)),  # mix_hash seat
        encode_bytes(POST_MERGE_NONCE),
        encode_int(int(payload.base_fee_per_gas)),
    ]
    return keccak256(encode_list(fields))


def verify_payload_block_hash(payload) -> None:
    """Raise ValueError on mismatch (the reference converts this into a
    block-verification failure before any engine round trip)."""
    computed = calculate_execution_block_hash(payload)
    claimed = bytes(payload.block_hash)
    if computed != claimed:
        raise ValueError(
            f"payload block_hash mismatch: claimed {claimed.hex()[:16]}, "
            f"header hashes to {computed.hex()[:16]}"
        )
