"""Keccak-256 (the pre-NIST-padding Keccak used by Ethereum), implemented
from the published Keccak-f[1600] specification. The reference reaches this
through its `keccak-hash` dependency (execution_layer/src/block_hash.rs,
types/src/execution_block_header.rs); Python's hashlib has no keccak (only
NIST SHA-3, whose domain padding differs), so the permutation lives here.

Pure Python is fine for the use cases: execution-header hashing and MPT
roots over transaction lists — a few dozen permutations per block.
"""

from __future__ import annotations

_ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# rotation offsets r[x][y]
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_MASK = (1 << 64) - 1


def _rotl(v: int, n: int) -> int:
    return ((v << n) | (v >> (64 - n))) & _MASK


def _keccak_f(a: list[list[int]]) -> None:
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rotl(a[x][y], _ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y])
        # iota
        a[0][0] ^= rc


def _sponge_256(data: bytes, domain: int) -> bytes:
    """1088-bit-rate sponge with a parametric padding domain byte:
    0x01 = original Keccak (Ethereum), 0x06 = NIST SHA3. The SHA3 variant
    exists so tests can differentially anchor the permutation against an
    independent SHA3-256 implementation (hashlib/cryptography) -- the two
    differ ONLY in this byte."""
    rate = 136
    pad_len = rate - (len(data) % rate)
    if pad_len == 1:
        padded = data + bytes([domain | 0x80])  # both pad bits in one byte
    else:
        padded = data + bytes([domain]) + b"\x00" * (pad_len - 2) + b"\x80"
    a = [[0] * 5 for _ in range(5)]
    for block_start in range(0, len(padded), rate):
        block = padded[block_start : block_start + rate]
        for i in range(rate // 8):
            lane = int.from_bytes(block[8 * i : 8 * i + 8], "little")
            a[i % 5][i // 5] ^= lane
        _keccak_f(a)
    out = b""
    for i in range(4):  # 32 bytes = 4 lanes
        out += a[i % 5][i // 5].to_bytes(8, "little")
    return out


def keccak256(data: bytes) -> bytes:
    return _sponge_256(data, 0x01)


def sha3_256(data: bytes) -> bytes:
    """NIST SHA3-256 through the same sponge (differential-test hook)."""
    return _sponge_256(data, 0x06)
