"""Execution-layer interface: engine API types, the ExecutionLayer facade,
and the in-process mock engine (reference beacon_node/execution_layer)."""

from .engine_api import (
    EngineApiError,
    ExecutionEngine,
    ForkchoiceState,
    ForkchoiceUpdatedResponse,
    PayloadAttributes,
    PayloadStatusV1,
    PayloadStatusV1Status,
)
from .execution_layer import (
    ExecutionLayer,
    PayloadInvalid,
    PayloadVerificationStatus,
)
from .mock_engine import MockExecutionEngine

__all__ = [
    "EngineApiError",
    "ExecutionEngine",
    "ExecutionLayer",
    "ForkchoiceState",
    "ForkchoiceUpdatedResponse",
    "MockExecutionEngine",
    "PayloadAttributes",
    "PayloadInvalid",
    "PayloadStatusV1",
    "PayloadStatusV1Status",
    "PayloadVerificationStatus",
]
