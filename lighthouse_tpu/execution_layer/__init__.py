"""Execution-layer interface: engine API types, the ExecutionLayer facade,
the JWT-authenticated HTTP transport, payload block-hash verification, and
the in-process mock engine (reference beacon_node/execution_layer)."""

from .auth import JwtError, JwtKey, generate_token, validate_token
from .builder import (
    BuilderError,
    BuilderHttpClient,
    BuilderHttpServer,
    MockBuilder,
    NoBidAvailable,
    make_validator_registration,
    unblind_signed_block,
    verify_bid,
)
from .block_hash import (
    calculate_execution_block_hash,
    calculate_transactions_root,
    verify_payload_block_hash,
)
from .engine_api import (
    EngineApiError,
    ExecutionEngine,
    ForkchoiceState,
    ForkchoiceUpdatedResponse,
    PayloadAttributes,
    PayloadStatusV1,
    PayloadStatusV1Status,
)
from .execution_layer import (
    ExecutionLayer,
    PayloadInvalid,
    PayloadVerificationStatus,
)
from .http_engine import EngineRpcServer, HttpJsonRpcEngine
from .mock_engine import MockExecutionEngine

__all__ = [
    "BuilderError",
    "BuilderHttpClient",
    "BuilderHttpServer",
    "EngineApiError",
    "EngineRpcServer",
    "MockBuilder",
    "NoBidAvailable",
    "make_validator_registration",
    "unblind_signed_block",
    "verify_bid",
    "ExecutionEngine",
    "ExecutionLayer",
    "ForkchoiceState",
    "ForkchoiceUpdatedResponse",
    "HttpJsonRpcEngine",
    "JwtError",
    "JwtKey",
    "MockExecutionEngine",
    "PayloadAttributes",
    "PayloadInvalid",
    "PayloadStatusV1",
    "PayloadStatusV1Status",
    "PayloadVerificationStatus",
    "calculate_execution_block_hash",
    "calculate_transactions_root",
    "generate_token",
    "validate_token",
    "verify_payload_block_hash",
]
