"""Engine-API surface (reference beacon_node/execution_layer/src/
engine_api/mod.rs + json_structures.rs): the verb set a consensus client
speaks to an execution engine, with payload-status semantics from
engine_api/payload_status.rs.

The transport here is an in-process call interface; the wire JSON-RPC
framing lives in `http_jsonrpc.py` style adapters (and the test double,
MockExecutionEngine, implements the same protocol the way the reference's
mock server does, execution_layer/src/test_utils/mock_execution_layer.rs).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class PayloadStatusV1Status(str, enum.Enum):
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"
    INVALID_BLOCK_HASH = "INVALID_BLOCK_HASH"


@dataclass
class PayloadStatusV1:
    status: PayloadStatusV1Status
    latest_valid_hash: bytes | None = None
    validation_error: str | None = None


@dataclass
class ForkchoiceState:
    head_block_hash: bytes = b"\x00" * 32
    safe_block_hash: bytes = b"\x00" * 32
    finalized_block_hash: bytes = b"\x00" * 32


@dataclass
class PayloadAttributes:
    timestamp: int = 0
    prev_randao: bytes = b"\x00" * 32
    suggested_fee_recipient: bytes = b"\x00" * 20


@dataclass
class ForkchoiceUpdatedResponse:
    payload_status: PayloadStatusV1
    payload_id: bytes | None = None


class EngineApiError(RuntimeError):
    pass


class ExecutionEngine:
    """Protocol: what an engine implementation must provide."""

    def new_payload(self, payload) -> PayloadStatusV1:  # engine_newPayloadV1
        raise NotImplementedError

    def forkchoice_updated(
        self,
        state: ForkchoiceState,
        attributes: PayloadAttributes | None = None,
    ) -> ForkchoiceUpdatedResponse:  # engine_forkchoiceUpdatedV1
        raise NotImplementedError

    def get_payload(self, payload_id: bytes):  # engine_getPayloadV1
        raise NotImplementedError
