"""In-process mock execution engine + execution block generator
(reference execution_layer/src/test_utils/{mock_execution_layer.rs,
execution_block_generator.rs}): a fake EL chain that makes full block
production/import testable without an external process.

Supports fault injection the way the reference's payload-invalidation
tests do (beacon_chain/tests/payload_invalidation.rs): specific block
hashes can be pre-marked INVALID (or the next N new_payload calls forced
SYNCING), so optimistic-import and invalidation paths are exercisable.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from .engine_api import (
    EngineApiError,
    ExecutionEngine,
    ForkchoiceState,
    ForkchoiceUpdatedResponse,
    PayloadAttributes,
    PayloadStatusV1,
    PayloadStatusV1Status,
)


def _hash(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


@dataclass
class MockBlock:
    block_hash: bytes
    parent_hash: bytes
    block_number: int
    timestamp: int
    prev_randao: bytes


class MockExecutionEngine(ExecutionEngine):
    def __init__(self, types, terminal_block_hash: bytes | None = None):
        self.t = types
        genesis_hash = _hash(b"mock-el-genesis")
        self.blocks: dict[bytes, MockBlock] = {
            genesis_hash: MockBlock(genesis_hash, b"\x00" * 32, 0, 0, b"\x00" * 32)
        }
        self.genesis_hash = genesis_hash
        self.head_hash = genesis_hash
        self.finalized_hash = b"\x00" * 32
        self._payloads: dict[bytes, object] = {}
        self._next_payload_id = 1
        # fault injection
        self.invalid_hashes: set[bytes] = set()
        self.force_syncing: int = 0
        self.new_payload_log: list[bytes] = []
        # pow (pre-merge) chain: block_hash -> (parent_hash,
        # total_difficulty), the eth_getBlockByHash surface merge-block
        # TTD validation reads (reference engines.rs get_pow_block)
        self.pow_blocks: dict[bytes, tuple[bytes, int]] = {}

    # -- fault injection hooks ----------------------------------------------

    def mark_invalid(self, block_hash: bytes) -> None:
        self.invalid_hashes.add(bytes(block_hash))

    def add_pow_block(
        self, block_hash: bytes, parent_hash: bytes, total_difficulty: int
    ) -> None:
        self.pow_blocks[bytes(block_hash)] = (
            bytes(parent_hash),
            int(total_difficulty),
        )

    def get_pow_block(self, block_hash: bytes):
        """(parent_hash, total_difficulty) or None if unknown."""
        return self.pow_blocks.get(bytes(block_hash))

    # -- engine API ----------------------------------------------------------

    def new_payload(self, payload) -> PayloadStatusV1:
        self.new_payload_log.append(bytes(payload.block_hash))
        if self.force_syncing > 0:
            self.force_syncing -= 1
            return PayloadStatusV1(PayloadStatusV1Status.SYNCING)
        block_hash = bytes(payload.block_hash)
        parent = bytes(payload.parent_hash)
        if block_hash in self.invalid_hashes:
            return PayloadStatusV1(
                PayloadStatusV1Status.INVALID,
                latest_valid_hash=self._latest_valid(parent),
                validation_error="injected invalid payload",
            )
        want = self.compute_block_hash(payload)
        if want != block_hash:
            return PayloadStatusV1(
                PayloadStatusV1Status.INVALID_BLOCK_HASH,
                validation_error="block hash mismatch",
            )
        if parent not in self.blocks:
            return PayloadStatusV1(PayloadStatusV1Status.SYNCING)
        self.blocks[block_hash] = MockBlock(
            block_hash,
            parent,
            int(payload.block_number),
            int(payload.timestamp),
            bytes(payload.prev_randao),
        )
        return PayloadStatusV1(
            PayloadStatusV1Status.VALID, latest_valid_hash=block_hash
        )

    def forkchoice_updated(
        self,
        state: ForkchoiceState,
        attributes: PayloadAttributes | None = None,
    ) -> ForkchoiceUpdatedResponse:
        head = bytes(state.head_block_hash)
        if head in self.invalid_hashes:
            return ForkchoiceUpdatedResponse(
                PayloadStatusV1(
                    PayloadStatusV1Status.INVALID,
                    latest_valid_hash=self.genesis_hash,
                )
            )
        syncing = head != b"\x00" * 32 and head not in self.blocks
        if not syncing:
            self.head_hash = head
            self.finalized_hash = bytes(state.finalized_block_hash)
        payload_id = None
        if attributes is not None:
            # Mock leniency: build even on an unknown (optimistically
            # imported) head so production on optimistic chains is testable
            # -- a real engine would return SYNCING with a null payloadId.
            payload_id = self._next_payload_id.to_bytes(8, "big")
            self._next_payload_id += 1
            self._payloads[payload_id] = self._build_payload(head, attributes)
        status = (
            PayloadStatusV1Status.SYNCING
            if syncing
            else PayloadStatusV1Status.VALID
        )
        return ForkchoiceUpdatedResponse(
            PayloadStatusV1(
                status, latest_valid_hash=None if syncing else (head or None)
            ),
            payload_id,
        )

    def get_payload(self, payload_id: bytes):
        payload = self._payloads.get(bytes(payload_id))
        if payload is None:
            raise EngineApiError(f"unknown payload id {payload_id.hex()}")
        return payload

    # -- internals -----------------------------------------------------------

    @property
    def payload_cls(self):
        return self.t.ExecutionPayload

    def compute_block_hash(self, payload) -> bytes:
        """REAL keccak-over-RLP-header hash (block_hash.rs), exactly what
        the beacon node's verify_payload_block_hash recomputes -- the mock
        chain is indistinguishable from a hash-honest engine (the
        reference's execution_block_generator does the same)."""
        from .block_hash import calculate_execution_block_hash

        return calculate_execution_block_hash(payload)

    def _build_payload(self, parent_hash: bytes, attrs: PayloadAttributes):
        parent = self.blocks.get(parent_hash)
        number = (parent.block_number + 1) if parent else 1
        p = self.t.ExecutionPayload(
            parent_hash=parent_hash,
            fee_recipient=attrs.suggested_fee_recipient,
            prev_randao=attrs.prev_randao,
            block_number=number,
            gas_limit=30_000_000,
            gas_used=21_000,
            timestamp=attrs.timestamp,
            base_fee_per_gas=7,
        )
        p.block_hash = self.compute_block_hash(p)
        return p

    def _latest_valid(self, parent: bytes) -> bytes:
        h = parent
        while h in self.invalid_hashes:
            blk = self.blocks.get(h)
            if blk is None:
                return self.genesis_hash
            h = blk.parent_hash
        return h if h in self.blocks or h == b"\x00" * 32 else self.genesis_hash
