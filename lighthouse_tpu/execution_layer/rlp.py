"""RLP encoding + the ordered Merkle-Patricia-Trie root, from the Ethereum
yellow-paper definitions. The reference reaches these through its
`triehash`/`rlp` crates (execution_layer/src/block_hash.rs:
calculate_transactions_root); here they exist to hash execution headers
and transaction lists for payload block-hash verification.

Only encoding is needed (we never decode engine data structurally), and
only the ordered trie (keys = rlp(index)) used for transactions/receipts
roots.
"""

from __future__ import annotations

from .keccak import keccak256


def encode_bytes(data: bytes) -> bytes:
    if len(data) == 1 and data[0] < 0x80:
        return data
    return _len_prefix(len(data), 0x80) + data


def encode_int(n: int) -> bytes:
    """Integers are big-endian with no leading zeros; zero is empty."""
    if n == 0:
        return encode_bytes(b"")
    return encode_bytes(n.to_bytes((n.bit_length() + 7) // 8, "big"))


def encode_list(items: list[bytes]) -> bytes:
    """`items` are already-encoded RLP payloads."""
    body = b"".join(items)
    return _len_prefix(len(body), 0xC0) + body


def _len_prefix(length: int, offset: int) -> bytes:
    if length < 56:
        return bytes([offset + length])
    ll = length.to_bytes((length.bit_length() + 7) // 8, "big")
    return bytes([offset + 55 + len(ll)]) + ll


# --- ordered Merkle-Patricia trie root --------------------------------------
# Keys are rlp(index) for index in 0..n; values are the raw byte strings.
# Node model per the yellow paper appendix D: leaf/extension nodes with
# hex-prefix-encoded paths, 17-ary branch nodes; nodes under 32 bytes embed
# in their parent, otherwise the parent stores keccak256(rlp(node)).

EMPTY_TRIE_ROOT = keccak256(encode_bytes(b""))


def _nibbles(key: bytes) -> list[int]:
    out = []
    for b in key:
        out.append(b >> 4)
        out.append(b & 0x0F)
    return out


def _hex_prefix(nibbles: list[int], leaf: bool) -> bytes:
    flag = 2 if leaf else 0
    if len(nibbles) % 2:
        packed = [((flag + 1) << 4) + nibbles[0]]
        rest = nibbles[1:]
    else:
        packed = [flag << 4]
        rest = nibbles
    for i in range(0, len(rest), 2):
        packed.append((rest[i] << 4) + rest[i + 1])
    return bytes(packed)


def _node_ref(encoded: bytes) -> bytes:
    """Sub-32-byte nodes embed verbatim; larger ones hash (yellow paper c)."""
    if len(encoded) < 32:
        return encoded
    return encode_bytes(keccak256(encoded))


def _encode_node(items: list[tuple[list[int], bytes]]) -> bytes:
    """RLP encoding of the trie node covering `items` (suffix-nibbles,
    value), which must be non-empty and prefix-free (true for rlp(index)
    keys)."""
    if len(items) == 1:
        path, value = items[0]
        return encode_list(
            [encode_bytes(_hex_prefix(path, True)), encode_bytes(value)]
        )
    # shared prefix -> extension node
    first = items[0][0]
    prefix_len = 0
    while all(
        len(path) > prefix_len and path[prefix_len] == first[prefix_len]
        for path, _ in items
    ):
        prefix_len += 1
    if prefix_len:
        child = _encode_node(
            [(path[prefix_len:], v) for path, v in items]
        )
        return encode_list(
            [
                encode_bytes(_hex_prefix(first[:prefix_len], False)),
                _node_ref(child),
            ]
        )
    # branch node
    slots: list[list] = [[] for _ in range(16)]
    branch_value = b""
    for path, v in items:
        if not path:
            branch_value = v
        else:
            slots[path[0]].append((path[1:], v))
    encoded_slots = []
    for bucket in slots:
        if not bucket:
            encoded_slots.append(encode_bytes(b""))
        else:
            encoded_slots.append(_node_ref(_encode_node(bucket)))
    encoded_slots.append(encode_bytes(branch_value))
    return encode_list(encoded_slots)


def ordered_trie_root(values: list[bytes]) -> bytes:
    """Root of the trie mapping rlp(i) -> values[i] (the
    transactions/receipts root construction)."""
    if not values:
        return EMPTY_TRIE_ROOT
    items = [(_nibbles(encode_int(i)), v) for i, v in enumerate(values)]
    return keccak256(_encode_node(items))
