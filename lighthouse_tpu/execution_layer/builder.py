"""mev-boost builder flow (reference builder_client/src/lib.rs + the
builder paths in beacon_node/execution_layer/src/lib.rs, mocked by
test_utils/mock_builder.rs):

  1. the VC's preparation service registers validators with the builder
     (SignedValidatorRegistration, application-builder domain),
  2. block production asks the builder for a header-only bid
     (get_header -> SignedBuilderBid), builds and signs a BLINDED block,
  3. submitting the signed blinded block makes the builder reveal the
     full ExecutionPayload, which unblinds into the publishable block.

Transport is the builder REST surface (builder-specs paths) with SSZ
request/response bodies (the spec's application/octet-stream encoding),
served in-process by `BuilderHttpServer` over a real socket.
"""

from __future__ import annotations

import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..types import compute_domain, compute_signing_root, types_for
from ..types.chain_spec import DOMAIN_APPLICATION_BUILDER
from ..types.containers import (
    SignedValidatorRegistration,
    ValidatorRegistrationV1,
)

REGISTRATION_SSZ_LEN = 180  # fixed-size SignedValidatorRegistration


class BuilderError(RuntimeError):
    pass


class NoBidAvailable(BuilderError):
    """Builder has no bid for this slot/parent (HTTP 204)."""


def builder_signing_root(message, spec) -> bytes:
    """Application-builder domain: genesis fork version, EMPTY
    genesis_validators_root (builder-specs; reference signing logic in
    validator_store.rs sign_validator_registration)."""
    domain = compute_domain(
        DOMAIN_APPLICATION_BUILDER, spec.genesis_fork_version, bytes(32)
    )
    return compute_signing_root(message, domain)


def header_from_payload(payload, preset):
    """ExecutionPayload -> consensus ExecutionPayloadHeader (SSZ
    transactions root, NOT the MPT root -- consensus-layer semantics)."""
    from ..state_transition.per_block import payload_to_header

    return payload_to_header(payload, preset)


def unblind_signed_block(signed_blinded, payload, preset):
    """SignedBlindedBeaconBlock + revealed payload -> full
    SignedBeaconBlock. Raises BuilderError if the payload does not match
    the header the proposer committed to (a lying builder)."""
    t = types_for(preset)
    blinded = signed_blinded.message
    committed_root = blinded.body.execution_payload_header.tree_hash_root()
    revealed_root = header_from_payload(payload, preset).tree_hash_root()
    if committed_root != revealed_root:
        raise BuilderError("revealed payload does not match the signed header")
    body = blinded.body
    full_body = t.BeaconBlockBodyBellatrix(
        randao_reveal=body.randao_reveal,
        eth1_data=body.eth1_data,
        graffiti=body.graffiti,
        proposer_slashings=body.proposer_slashings,
        attester_slashings=body.attester_slashings,
        attestations=body.attestations,
        deposits=body.deposits,
        voluntary_exits=body.voluntary_exits,
        sync_aggregate=body.sync_aggregate,
        execution_payload=payload,
    )
    full = t.BeaconBlockBellatrix(
        slot=blinded.slot,
        proposer_index=blinded.proposer_index,
        parent_root=blinded.parent_root,
        state_root=blinded.state_root,
        body=full_body,
    )
    # the unblinded block must hash to the very root the proposer signed
    if full.tree_hash_root() != blinded.tree_hash_root():
        raise BuilderError("unblinded block root diverges from signed root")
    return t.SignedBeaconBlockBellatrix(
        message=full, signature=bytes(signed_blinded.signature)
    )


# --- the builder itself (mock; reference test_utils/mock_builder.rs) --------


class MockBuilder:
    """An in-process block builder over an ExecutionLayer: serves signed
    bids for its payloads and reveals them on submission. Fault knobs:

      * `refuse_reveal`  -- accept the signed blinded block, never reveal
                            (the classic builder griefing case)
      * `corrupt_header` -- bid a header that doesn't match the payload
      * `no_bid`         -- decline to bid entirely
    """

    def __init__(self, execution_layer, preset, spec, secret_key=None, chain=None):
        from ..crypto.bls import SecretKey

        self.el = execution_layer
        self.preset = preset
        self.spec = spec
        # the chain the builder watches (mock_builder.rs holds a BN handle):
        # payload attributes must match what process_execution_payload will
        # check -- state-derived timestamp and randao mix
        self.chain = chain
        self.sk = secret_key or SecretKey(0x42B1DE5)
        self.pubkey = self.sk.public_key()
        self.t = types_for(preset)
        self.registrations: dict[bytes, object] = {}  # pubkey -> registration
        self._payloads: dict[bytes, object] = {}  # header root -> payload
        self.refuse_reveal = False
        self.corrupt_header = False
        self.no_bid = False
        self.bid_value = 10**18  # wei

    # -- builder-specs verbs -------------------------------------------------

    def register_validators(self, registrations) -> None:
        """POST /eth/v1/builder/validators (signature checking mirrors the
        reference mock: structural + known-pubkey only)."""
        for signed in registrations:
            self.registrations[bytes(signed.message.pubkey)] = signed

    def get_header(self, slot: int, parent_hash: bytes, pubkey: bytes):
        """GET /eth/v1/builder/header/{slot}/{parent_hash}/{pubkey} ->
        SignedBuilderBid. The proposer must be registered (fee recipient
        comes from its registration)."""
        if self.no_bid:
            raise NoBidAvailable("builder declined to bid")
        reg = self.registrations.get(bytes(pubkey))
        if reg is None:
            raise NoBidAvailable("proposer not registered with this builder")
        payload = self.el.get_payload(
            bytes(parent_hash),
            self._timestamp_for(slot),
            self._randao_for(slot),
            fee_recipient=bytes(reg.message.fee_recipient),
        )
        header = header_from_payload(payload, self.preset)
        if self.corrupt_header:
            header.gas_used = int(header.gas_used) + 1
        self._payloads[header.tree_hash_root()] = payload
        bid = self.t.BuilderBid(
            header=header, value=self.bid_value, pubkey=self.pubkey.to_bytes()
        )
        sig = self.sk.sign(builder_signing_root(bid, self.spec))
        return self.t.SignedBuilderBid(message=bid, signature=sig.to_bytes())

    def submit_blinded_block(self, signed_blinded):
        """POST /eth/v1/builder/blinded_blocks -> the full payload."""
        if self.refuse_reveal:
            raise BuilderError("builder refused to reveal the payload")
        root = signed_blinded.message.body.execution_payload_header.tree_hash_root()
        payload = self._payloads.get(root)
        if payload is None:
            raise BuilderError("unknown header: builder never bid this block")
        return payload

    # payload attributes derived from the watched chain's state, exactly
    # as process_execution_payload will check them
    def _timestamp_for(self, slot: int) -> int:
        if self.chain is not None:
            state = self.chain.head_state
            return int(state.genesis_time) + slot * self.spec.seconds_per_slot
        return slot * self.spec.seconds_per_slot

    def _randao_for(self, slot: int) -> bytes:
        if self.chain is not None:
            from ..types import compute_epoch_at_slot
            from ..types.helpers import get_randao_mix

            state = self.chain.state_for_block_production(slot)
            return bytes(
                get_randao_mix(
                    state, compute_epoch_at_slot(slot, self.preset), self.preset
                )
            )
        return slot.to_bytes(32, "little")


class BuilderHttpServer:
    """The mock builder behind the builder-specs REST paths with SSZ
    bodies, over a real socket."""

    def __init__(self, builder: MockBuilder, host="127.0.0.1", port=0):
        self.builder = builder
        self.fail_next = 0
        outer = self
        t = builder.t

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code: int, body: bytes = b""):
                self.send_response(code)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_GET(self):
                if outer.fail_next > 0:
                    outer.fail_next -= 1
                    self.send_error(503)
                    return
                parts = self.path.strip("/").split("/")
                # eth/v1/builder/header/{slot}/{parent_hash}/{pubkey}
                if len(parts) == 7 and parts[:4] == ["eth", "v1", "builder", "header"]:
                    try:
                        slot = int(parts[4])
                        parent = bytes.fromhex(parts[5].removeprefix("0x"))
                        pubkey = bytes.fromhex(parts[6].removeprefix("0x"))
                        bid = outer.builder.get_header(slot, parent, pubkey)
                    except NoBidAvailable:
                        self._reply(204)
                        return
                    except Exception:  # noqa: BLE001
                        self.send_error(400)
                        return
                    self._reply(200, bid.as_ssz_bytes())
                    return
                self.send_error(404)

            def do_POST(self):
                if outer.fail_next > 0:
                    outer.fail_next -= 1
                    self.send_error(503)
                    return
                length = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(length)
                path = self.path.rstrip("/")
                try:
                    if path.endswith("/eth/v1/builder/validators"):
                        if len(body) % REGISTRATION_SSZ_LEN:
                            self.send_error(400)
                            return
                        regs = [
                            SignedValidatorRegistration.from_ssz_bytes(
                                body[i : i + REGISTRATION_SSZ_LEN]
                            )
                            for i in range(0, len(body), REGISTRATION_SSZ_LEN)
                        ]
                        outer.builder.register_validators(regs)
                        self._reply(200)
                        return
                    if path.endswith("/eth/v1/builder/blinded_blocks"):
                        signed = t.SignedBlindedBeaconBlock.from_ssz_bytes(body)
                        payload = outer.builder.submit_blinded_block(signed)
                        self._reply(200, payload.as_ssz_bytes())
                        return
                except BuilderError:
                    self.send_error(502)
                    return
                except Exception:  # noqa: BLE001
                    self.send_error(400)
                    return
                self.send_error(404)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.url = f"http://{host}:{self._server.server_address[1]}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class BuilderHttpClient:
    """The BN's builder handle (builder_client/src/lib.rs): REST verbs
    with SSZ bodies, bounded timeout, 204 -> NoBidAvailable."""

    def __init__(
        self,
        url: str,
        preset,
        timeout_s: float = 5.0,
        trusted_pubkey: bytes | None = None,
    ):
        self.url = url.rstrip("/")
        self.preset = preset
        self.t = types_for(preset)
        self.timeout_s = timeout_s
        # the configured builder's BLS identity (verify_bid pins bids to it)
        self.trusted_pubkey = (
            bytes(trusted_pubkey) if trusted_pubkey is not None else None
        )

    def _request(self, method: str, path: str, body: bytes | None = None):
        req = urllib.request.Request(
            self.url + path,
            data=body,
            method=method,
            headers={"Content-Type": "application/octet-stream"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            raise BuilderError(f"builder {path}: HTTP {e.code}") from None
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise BuilderError(f"builder {path}: {e}") from None

    def register_validators(self, registrations) -> None:
        body = b"".join(r.as_ssz_bytes() for r in registrations)
        self._request("POST", "/eth/v1/builder/validators", body)

    def get_header(self, slot: int, parent_hash: bytes, pubkey: bytes):
        status, body = self._request(
            "GET",
            f"/eth/v1/builder/header/{slot}/0x{bytes(parent_hash).hex()}"
            f"/0x{bytes(pubkey).hex()}",
        )
        if status == 204:
            raise NoBidAvailable("no bid for this slot")
        return self.t.SignedBuilderBid.from_ssz_bytes(body)

    def submit_blinded_block(self, signed_blinded):
        _, body = self._request(
            "POST",
            "/eth/v1/builder/blinded_blocks",
            signed_blinded.as_ssz_bytes(),
        )
        return self.t.ExecutionPayload.from_ssz_bytes(body)


def verify_bid(
    signed_bid,
    spec,
    expected_parent_hash: bytes,
    trusted_pubkey: bytes | None = None,
) -> None:
    """The BN-side bid checks (execution_layer/src/lib.rs builder path):
    the bid's header must build on the right parent and the signature must
    verify. `trusted_pubkey` pins the CONFIGURED builder identity -- a bid
    self-signed under an attacker's fresh key must not pass, or a relay
    can burn the proposer's slot with a header nobody will reveal."""
    from ..crypto.bls import PublicKey, Signature, verify_signature_sets
    from ..crypto.bls.api import SignatureSet

    bid = signed_bid.message
    if bytes(bid.header.parent_hash) != bytes(expected_parent_hash):
        raise BuilderError("bid builds on the wrong parent")
    if trusted_pubkey is not None and bytes(bid.pubkey) != bytes(trusted_pubkey):
        raise BuilderError("bid signed by an unexpected builder key")
    root = builder_signing_root(bid, spec)
    pk = PublicKey.from_bytes(bytes(bid.pubkey))
    sig = Signature.from_bytes(bytes(signed_bid.signature))
    if not verify_signature_sets([SignatureSet.single_pubkey(sig, pk, root)]):
        raise BuilderError("bad builder bid signature")


def make_validator_registration(
    secret_key, fee_recipient: bytes, gas_limit: int, timestamp: int, spec
):
    """Build + sign a registration (the VC preparation-service flow,
    validator_client/src/preparation_service.rs)."""
    msg = ValidatorRegistrationV1(
        fee_recipient=bytes(fee_recipient),
        gas_limit=gas_limit,
        timestamp=timestamp,
        pubkey=secret_key.public_key().to_bytes(),
    )
    sig = secret_key.sign(builder_signing_root(msg, spec))
    return SignedValidatorRegistration(message=msg, signature=sig.to_bytes())
