"""Engine-API JSON-RPC over HTTP with JWT auth (reference
execution_layer/src/engine_api/http.rs + auth.rs): the transport between
the beacon node and its execution engine.

Mirrors the eth1 boundary's client/rig split (eth1/jsonrpc.py): a real
HTTP client speaking the engine dialect, and an in-process
`EngineRpcServer` that fronts any in-process `ExecutionEngine` (usually
the fault-injecting mock) behind an actual socket with real JWT
validation — so transport, auth, serialization, and retry paths are all
exercised without an external geth.

Wire encoding follows engine_api/json_structures.rs: QUANTITY fields are
minimal 0x-hex strings, DATA fields 0x-prefixed even-length hex.
"""

from __future__ import annotations

from ..utils.jsonrpc import JsonRpcClient, JsonRpcHttpServer
from .auth import JwtError, JwtKey, generate_token, validate_token
from .engine_api import (
    EngineApiError,
    ExecutionEngine,
    ForkchoiceState,
    ForkchoiceUpdatedResponse,
    PayloadAttributes,
    PayloadStatusV1,
    PayloadStatusV1Status,
)


def _q(n: int) -> str:  # QUANTITY
    return hex(int(n))


def _d(b: bytes) -> str:  # DATA
    return "0x" + bytes(b).hex()


def _un_q(s: str) -> int:
    return int(s, 16)


def _un_d(s: str) -> bytes:
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


def payload_to_json(payload) -> dict:
    return {
        "parentHash": _d(payload.parent_hash),
        "feeRecipient": _d(payload.fee_recipient),
        "stateRoot": _d(payload.state_root),
        "receiptsRoot": _d(payload.receipts_root),
        "logsBloom": _d(payload.logs_bloom),
        "prevRandao": _d(payload.prev_randao),
        "blockNumber": _q(payload.block_number),
        "gasLimit": _q(payload.gas_limit),
        "gasUsed": _q(payload.gas_used),
        "timestamp": _q(payload.timestamp),
        "extraData": _d(payload.extra_data),
        "baseFeePerGas": _q(payload.base_fee_per_gas),
        "blockHash": _d(payload.block_hash),
        "transactions": [_d(tx) for tx in payload.transactions],
    }


def payload_from_json(obj: dict, payload_cls):
    return payload_cls(
        parent_hash=_un_d(obj["parentHash"]),
        fee_recipient=_un_d(obj["feeRecipient"]),
        state_root=_un_d(obj["stateRoot"]),
        receipts_root=_un_d(obj["receiptsRoot"]),
        logs_bloom=_un_d(obj["logsBloom"]),
        prev_randao=_un_d(obj["prevRandao"]),
        block_number=_un_q(obj["blockNumber"]),
        gas_limit=_un_q(obj["gasLimit"]),
        gas_used=_un_q(obj["gasUsed"]),
        timestamp=_un_q(obj["timestamp"]),
        extra_data=_un_d(obj["extraData"]),
        base_fee_per_gas=_un_q(obj["baseFeePerGas"]),
        block_hash=_un_d(obj["blockHash"]),
        transactions=[_un_d(tx) for tx in obj["transactions"]],
    )


def _status_to_json(status: PayloadStatusV1) -> dict:
    return {
        "status": status.status.value,
        "latestValidHash": (
            _d(status.latest_valid_hash)
            if status.latest_valid_hash is not None
            else None
        ),
        "validationError": status.validation_error,
    }


def _status_from_json(obj: dict) -> PayloadStatusV1:
    lvh = obj.get("latestValidHash")
    return PayloadStatusV1(
        status=PayloadStatusV1Status(obj["status"]),
        latest_valid_hash=_un_d(lvh) if lvh else None,
        validation_error=obj.get("validationError"),
    )


class HttpJsonRpcEngine(ExecutionEngine):
    """The beacon node's engine handle over a real socket (http.rs
    HttpJsonRpc): JWT header per request, bounded retries on transport
    errors, JSON-RPC error surfacing as EngineApiError."""

    def __init__(
        self,
        url: str,
        jwt_key: JwtKey,
        payload_cls,
        retries: int = 3,
        backoff_s: float = 0.05,
        timeout_s: float = 5.0,
    ):
        self.url = url
        self.jwt_key = jwt_key
        self.payload_cls = payload_cls
        self._rpc = JsonRpcClient(
            url,
            error_cls=EngineApiError,
            # fresh token each attempt: the iat window is short
            headers_fn=lambda: {
                "Authorization": f"Bearer {generate_token(self.jwt_key)}"
            },
            retries=retries,
            backoff_s=backoff_s,
            timeout_s=timeout_s,
        )

    def _call(self, method: str, params: list):
        return self._rpc.call(method, params)

    # -- ExecutionEngine protocol -------------------------------------------

    def new_payload(self, payload) -> PayloadStatusV1:
        result = self._call("engine_newPayloadV1", [payload_to_json(payload)])
        return _status_from_json(result)

    def forkchoice_updated(
        self,
        state: ForkchoiceState,
        attributes: PayloadAttributes | None = None,
    ) -> ForkchoiceUpdatedResponse:
        fc = {
            "headBlockHash": _d(state.head_block_hash),
            "safeBlockHash": _d(state.safe_block_hash),
            "finalizedBlockHash": _d(state.finalized_block_hash),
        }
        attrs = None
        if attributes is not None:
            attrs = {
                "timestamp": _q(attributes.timestamp),
                "prevRandao": _d(attributes.prev_randao),
                "suggestedFeeRecipient": _d(attributes.suggested_fee_recipient),
            }
        result = self._call("engine_forkchoiceUpdatedV1", [fc, attrs])
        pid = result.get("payloadId")
        return ForkchoiceUpdatedResponse(
            payload_status=_status_from_json(result["payloadStatus"]),
            payload_id=_un_d(pid) if pid else None,
        )

    def get_payload(self, payload_id: bytes):
        result = self._call("engine_getPayloadV1", [_d(payload_id)])
        return payload_from_json(result, self.payload_cls)


class EngineRpcServer:
    """An in-process engine behind a real authenticated socket (the
    reference's test_utils/mock_execution_layer.rs seat, with auth.rs
    validation live). `fail_next` injects transient 503s; `reject_auth`
    forces 401s to exercise the client's error surface."""

    def __init__(self, engine, jwt_key: JwtKey, host="127.0.0.1", port=0):
        self.engine = engine
        self.jwt_key = jwt_key
        self.reject_auth = False

        def check_auth(header: str) -> bool:
            if self.reject_auth or not header.startswith("Bearer "):
                return False
            try:
                validate_token(self.jwt_key, header[len("Bearer ") :])
                return True
            except JwtError:
                return False

        self._http = JsonRpcHttpServer(
            self._dispatch, host=host, port=port, auth_fn=check_auth
        )
        self.url = self._http.url

    @property
    def fail_next(self) -> int:
        return self._http.fail_next

    @fail_next.setter
    def fail_next(self, n: int) -> None:
        self._http.fail_next = n

    @property
    def requests_seen(self) -> int:
        return self._http.requests_seen

    def start(self):
        self._http.start()
        return self

    def stop(self):
        self._http.stop()

    def _dispatch(self, method: str, params: list):
        if method == "engine_newPayloadV1":
            payload = payload_from_json(params[0], self.engine.payload_cls)
            return _status_to_json(self.engine.new_payload(payload))
        if method == "engine_forkchoiceUpdatedV1":
            fc_json, attrs_json = params[0], params[1]
            state = ForkchoiceState(
                head_block_hash=_un_d(fc_json["headBlockHash"]),
                safe_block_hash=_un_d(fc_json["safeBlockHash"]),
                finalized_block_hash=_un_d(fc_json["finalizedBlockHash"]),
            )
            attrs = None
            if attrs_json is not None:
                attrs = PayloadAttributes(
                    timestamp=_un_q(attrs_json["timestamp"]),
                    prev_randao=_un_d(attrs_json["prevRandao"]),
                    suggested_fee_recipient=_un_d(
                        attrs_json["suggestedFeeRecipient"]
                    ),
                )
            resp = self.engine.forkchoice_updated(state, attrs)
            return {
                "payloadStatus": _status_to_json(resp.payload_status),
                "payloadId": _d(resp.payload_id) if resp.payload_id else None,
            }
        if method == "engine_getPayloadV1":
            payload = self.engine.get_payload(_un_d(params[0]))
            return payload_to_json(payload)
        raise ValueError(f"unknown method {method}")
