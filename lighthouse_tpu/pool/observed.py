"""Observed-gossip dedup caches (reference beacon_node/beacon_chain/src/
observed_{attesters,aggregates,block_producers,operations}.rs): the
first-seen filters that gate gossip propagation and protect the
verification pipeline from duplicates."""

from __future__ import annotations


class ObservedAttesters:
    """Per-epoch set of validator indices that have published an
    unaggregated attestation (observed_attesters.rs AutoPruningContainer)."""

    def __init__(self, retained_epochs: int = 2):
        self.retained = retained_epochs
        self._epochs: dict[int, set[int]] = {}

    def observe(self, epoch: int, validator_index: int) -> bool:
        """Returns True if ALREADY seen (caller should drop the item)."""
        seen = self._epochs.setdefault(epoch, set())
        if validator_index in seen:
            return True
        seen.add(validator_index)
        self._prune(epoch)
        return False

    def is_known(self, epoch: int, validator_index: int) -> bool:
        return validator_index in self._epochs.get(epoch, ())

    def _prune(self, current_epoch: int) -> None:
        low = current_epoch - self.retained
        for e in [e for e in self._epochs if e < low]:
            del self._epochs[e]


class ObservedAggregators(ObservedAttesters):
    """Same structure for (epoch, aggregator_index) pairs."""


class ObservedAggregates:
    """Seen aggregate-attestation roots per epoch
    (observed_aggregates.rs)."""

    def __init__(self, retained_epochs: int = 2):
        self.retained = retained_epochs
        self._epochs: dict[int, set[bytes]] = {}

    def observe(self, epoch: int, item_root: bytes) -> bool:
        seen = self._epochs.setdefault(epoch, set())
        if item_root in seen:
            return True
        seen.add(item_root)
        low = epoch - self.retained
        for e in [e for e in self._epochs if e < low]:
            del self._epochs[e]
        return False

    def is_known(self, epoch: int, item_root: bytes) -> bool:
        return item_root in self._epochs.get(epoch, ())


class ObservedBlockProducers:
    """(slot, proposer) pairs already seen on gossip
    (observed_block_producers.rs); a second distinct block from the same
    proposer at the same slot is a slashable equivocation signal."""

    def __init__(self, retained_slots: int = 64):
        self.retained = retained_slots
        self._slots: dict[int, dict[int, bytes]] = {}

    def observe(self, slot: int, proposer: int, block_root: bytes):
        """Returns 'duplicate' | 'equivocation' | None (first sighting).
        Callers must only RECORD verified blocks (observe after the
        proposer signature checks out): recording an unverified first
        sighting would let a forged block suppress the real proposal."""
        by_proposer = self._slots.setdefault(slot, {})
        prev = by_proposer.get(proposer)
        if prev is not None:
            return "duplicate" if prev == block_root else "equivocation"
        by_proposer[proposer] = block_root
        low = slot - self.retained
        for s in [s for s in self._slots if s < low]:
            del self._slots[s]
        return None

    def known_root(self, slot: int, proposer: int) -> bytes | None:
        """Read-only probe: the VERIFIED root already recorded for
        (slot, proposer), or None. The gossip ingress uses it for cheap
        exact-duplicate shedding without recording anything."""
        return self._slots.get(slot, {}).get(proposer)


class ObservedOperations:
    """Dedup for exits/slashings by offending validator index
    (observed_operations.rs)."""

    def __init__(self):
        self._seen: set[tuple[str, int]] = set()

    def observe(self, kind: str, validator_index: int) -> bool:
        key = (kind, validator_index)
        if key in self._seen:
            return True
        self._seen.add(key)
        return False
