"""Pools & dedup caches (reference beacon_node/operation_pool +
beacon_chain's naive_aggregation_pool and observed_* caches, SURVEY.md
sections 2.3)."""

from .max_cover import max_cover  # noqa: F401
from .naive_aggregation import NaiveAggregationPool  # noqa: F401
from .observed import (  # noqa: F401
    ObservedAggregates,
    ObservedAggregators,
    ObservedAttesters,
    ObservedBlockProducers,
    ObservedOperations,
)
from .operation_pool import OperationPool  # noqa: F401
