"""Operation pool (reference beacon_node/operation_pool/src/lib.rs:189,239,
357 + attestation_storage.rs): holds pre-verified attestations, slashings,
and exits; packs blocks with greedy max-cover over unattested committee
positions."""

from __future__ import annotations

from ..crypto.bls import AggregateSignature, Signature
from ..types import CommitteeCache, compute_epoch_at_slot
from ..types.presets import Preset
from .max_cover import max_cover


class OperationPool:
    def __init__(self, preset: Preset, spec):
        self.preset = preset
        self.spec = spec
        # compact split storage: (data_root) -> {"data", variants:
        # [(bits_tuple, sig_bytes)]} (attestation_storage.rs splits
        # data from aggregation the same way)
        self._attestations: dict[bytes, dict] = {}
        self._proposer_slashings: dict[int, object] = {}
        self._attester_slashings: list = []
        self._voluntary_exits: dict[int, object] = {}
        # set by load() when a persisted blob only partially decoded
        self.persist_load_error: str | None = None

    # -- attestations (lib.rs:189 insert_attestation) -----------------------

    def insert_attestation(self, attestation) -> None:
        root = attestation.data.tree_hash_root()
        entry = self._attestations.setdefault(
            root, {"data": attestation.data, "variants": []}
        )
        bits = tuple(attestation.aggregation_bits)
        for have_bits, have_sig in entry["variants"]:
            if all(h or not b for h, b in zip(have_bits, bits)):
                return  # subset of an existing aggregate
        entry["variants"].append(
            (bits, bytes(attestation.signature))
        )

    def num_attestations(self) -> int:
        return sum(len(e["variants"]) for e in self._attestations.values())

    # -- block packing (lib.rs:239 get_attestations + max_cover) ------------

    def get_attestations(self, state, ctxt_cache: dict | None = None):
        """Pick up to MAX_ATTESTATIONS aggregates maximizing new attester
        coverage for the current/previous epoch of `state`."""
        t_epoch_ok = (
            compute_epoch_at_slot(state.slot, self.preset),
            max(compute_epoch_at_slot(state.slot, self.preset) - 1, 0),
        )
        caches: dict[int, CommitteeCache] = ctxt_cache or {}

        candidates = []
        for entry in self._attestations.values():
            data = entry["data"]
            if data.target.epoch not in t_epoch_ok:
                continue
            if not (
                data.slot + self.spec.min_attestation_inclusion_delay
                <= state.slot
                <= data.slot + self.preset.slots_per_epoch
            ):
                continue
            epoch = data.target.epoch
            cache = caches.get(epoch)
            if cache is None:
                cache = CommitteeCache(state, epoch, self.preset, self.spec)
                caches[epoch] = cache
            try:
                committee = cache.get_beacon_committee(data.slot, data.index)
            except ValueError:
                continue
            for bits, sig in entry["variants"]:
                if len(bits) != len(committee):
                    continue
                cover = {
                    v: 1 for v, b in zip(committee, bits) if b
                }
                candidates.append(((data, bits, sig), cover))

        chosen = max_cover(
            candidates,
            covering=lambda c: c[1],
            weight=None,
            limit=self.preset.max_attestations,
        )
        from ..types import types_for

        t = types_for(self.preset)
        return [
            t.Attestation(
                aggregation_bits=bits, data=data, signature=sig
            )
            for (data, bits, sig), _ in chosen
        ]

    # -- slashings & exits (lib.rs:357 get_slashings_and_exits) -------------

    def insert_proposer_slashing(self, slashing) -> None:
        index = slashing.signed_header_1.message.proposer_index
        self._proposer_slashings.setdefault(index, slashing)

    def insert_attester_slashing(self, slashing) -> None:
        self._attester_slashings.append(slashing)

    def insert_voluntary_exit(self, exit_op) -> None:
        self._voluntary_exits.setdefault(
            exit_op.message.validator_index, exit_op
        )

    def get_slashings_and_exits(self, state):
        epoch = compute_epoch_at_slot(state.slot, self.preset)

        def slashable(index):
            from ..types import is_slashable_validator

            return index < len(state.validators) and is_slashable_validator(
                state.validators[index], epoch
            )

        proposer = [
            s
            for i, s in self._proposer_slashings.items()
            if slashable(i)
        ][: self.preset.max_proposer_slashings]
        attester = [
            s
            for s in self._attester_slashings
            if any(
                slashable(i)
                for i in set(s.attestation_1.attesting_indices)
                & set(s.attestation_2.attesting_indices)
            )
        ][: self.preset.max_attester_slashings]

        def exitable(op):
            from ..types import FAR_FUTURE_EPOCH, is_active_validator

            i = op.message.validator_index
            if i >= len(state.validators):
                return False
            v = state.validators[i]
            return (
                is_active_validator(v, epoch)
                and v.exit_epoch == FAR_FUTURE_EPOCH
                and op.message.epoch <= epoch
                # process_voluntary_exit's age gate: packing a too-young
                # exit would invalidate the produced block
                and epoch >= v.activation_epoch + self.spec.shard_committee_period
            )

        exits = [e for e in self._voluntary_exits.values() if exitable(e)][
            : self.preset.max_voluntary_exits
        ]
        return proposer, attester, exits

    # -- persistence (operation_pool/src/persistence.rs) --------------------
    #
    # The pool survives restarts: every held operation serializes as its
    # SSZ container into one length-framed blob under the CHAIN column.
    # Reload replays each item through the normal insert path, so dedup /
    # subset rules apply identically to restored state.

    _PERSIST_KEY = b"op_pool_v1"

    def persist(self, store) -> None:
        import struct as _s

        from ..types.containers import types_for

        t = types_for(self.preset)
        sections: list[list[bytes]] = [[], [], [], []]
        for entry in self._attestations.values():
            for bits, sig in entry["variants"]:
                att = t.Attestation(
                    aggregation_bits=list(bits),
                    data=entry["data"],
                    signature=sig,
                )
                sections[0].append(att.as_ssz_bytes())
        for s in self._proposer_slashings.values():
            sections[1].append(s.as_ssz_bytes())
        for s in self._attester_slashings:
            sections[2].append(s.as_ssz_bytes())
        for e in self._voluntary_exits.values():
            sections[3].append(e.as_ssz_bytes())
        out = bytearray()
        for items in sections:
            out += _s.pack(">I", len(items))
            for blob in items:
                out += _s.pack(">I", len(blob)) + blob
        # the blob rewrite commits through the write-ahead journal: a
        # crash mid-write must leave the OLD blob or the NEW one, never a
        # torn prefix (load() tolerates truncation, but best-effort decode
        # of a torn blob silently drops operations; the journal's intent
        # record makes the rewrite all-or-nothing on every backend)
        batch = store.batch()
        batch.stage_chain_item(self._PERSIST_KEY, bytes(out))
        batch.commit()

    @classmethod
    def load(cls, store, preset: Preset, spec, log=None) -> "OperationPool":
        import struct as _s

        from ..types.containers import types_for

        pool = cls(preset, spec)
        blob = store.get_chain_item(cls._PERSIST_KEY)
        if not blob:
            return pool
        from ..types.containers import ProposerSlashing, SignedVoluntaryExit

        t = types_for(preset)
        decoders = [
            (t.Attestation, pool.insert_attestation),
            (ProposerSlashing, pool.insert_proposer_slashing),
            (t.AttesterSlashing, pool.insert_attester_slashing),
            (SignedVoluntaryExit, pool.insert_voluntary_exit),
        ]
        try:
            off = 0
            for cls_, insert in decoders:
                (count,) = _s.unpack_from(">I", blob, off)
                off += 4
                for _ in range(count):
                    (ln,) = _s.unpack_from(">I", blob, off)
                    off += 4
                    insert(cls_.from_ssz_bytes(blob[off : off + ln]))
                    off += ln
        except (ValueError, IndexError, _s.error) as e:
            # persistence is best-effort BOTH ways: a corrupt/truncated
            # blob (crash mid-write, SszError/struct.error) must not
            # crash-loop node startup; restart with whatever decoded and
            # surface the partial load for the operator
            pool.persist_load_error = f"{type(e).__name__}: {e}"
            if log is None:
                # fallback stderr sink; callers with a configured logger
                # (level / json / file) should pass it in
                from ..utils.logging import Logger

                log = Logger()
            log.warn(
                "op-pool persisted blob only partially decoded",
                error=pool.persist_load_error,
            )
        return pool

    # -- pruning (lib.rs prune_* on finalization) ---------------------------

    def prune(self, state) -> None:
        epoch = compute_epoch_at_slot(state.slot, self.preset)
        for root in [
            r
            for r, e in self._attestations.items()
            if e["data"].target.epoch + 1 < epoch
        ]:
            del self._attestations[root]
        for i in [
            i
            for i, v in enumerate(state.validators)
            if v.slashed and i in self._proposer_slashings
        ]:
            del self._proposer_slashings[i]
        self._voluntary_exits = {
            i: e
            for i, e in self._voluntary_exits.items()
            if i < len(state.validators)
            and state.validators[i].exit_epoch == 2**64 - 1
        }
