"""Naive aggregation pool (reference beacon_node/beacon_chain/src/
naive_aggregation_pool.rs): accumulates unaggregated attestations into
per-(slot, data-root) aggregates so the node can serve aggregation duties
and pack blocks even before committee aggregators publish."""

from __future__ import annotations

from ..crypto.bls import AggregateSignature, Signature


class NaiveAggregationPool:
    def __init__(self, retained_slots: int = 32):
        self.retained = retained_slots
        # (slot, data_root) -> {"data": AttestationData, "bits": list[bool],
        #                        "sig": AggregateSignature}
        self._map: dict[tuple[int, bytes], dict] = {}

    def insert(self, attestation) -> bool:
        """Insert an UNAGGREGATED attestation (exactly one bit set).
        Returns True if it contributed new participation."""
        bits = list(attestation.aggregation_bits)
        if sum(bits) != 1:
            raise ValueError("naive pool accepts single-bit attestations only")
        key = (attestation.data.slot, attestation.data.tree_hash_root())
        entry = self._map.get(key)
        if entry is None:
            self._map[key] = {
                "data": attestation.data,
                "bits": bits,
                "sig": AggregateSignature.aggregate(
                    [Signature.from_bytes(bytes(attestation.signature))]
                ),
            }
            self._prune(attestation.data.slot)
            return True
        idx = bits.index(True)
        if len(entry["bits"]) != len(bits):
            raise ValueError("aggregation bit length mismatch")
        if entry["bits"][idx]:
            return False  # already have this attester
        entry["bits"][idx] = True
        entry["sig"].add_assign(
            Signature.from_bytes(bytes(attestation.signature))
        )
        return True

    def get(self, data) -> dict | None:
        return self._map.get((data.slot, data.tree_hash_root()))

    def get_aggregate(self, t, data):
        """Best aggregate for AttestationData as a typed Attestation."""
        entry = self.get(data)
        if entry is None:
            return None
        return t.Attestation(
            aggregation_bits=tuple(entry["bits"]),
            data=entry["data"],
            signature=entry["sig"].to_bytes(),
        )

    def _prune(self, current_slot: int) -> None:
        low = current_slot - self.retained
        for key in [k for k in self._map if k[0] < low]:
            del self._map[key]
