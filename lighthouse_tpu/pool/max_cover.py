"""Greedy maximum-coverage (reference beacon_node/operation_pool/src/
max_cover.rs:11-31): pick k items maximizing covered weight, re-scoring
remaining items against the running cover each round."""

from __future__ import annotations


def max_cover(items, covering, weight, limit: int):
    """items: candidates; covering(item) -> {element: weight}; `weight` is
    kept for API parity (scores derive from covering); returns chosen items
    in selection order."""
    remaining = [(item, dict(covering(item))) for item in items]
    chosen = []
    covered: set = set()
    for _ in range(limit):
        best = None
        best_score = 0
        for i, (item, cover) in enumerate(remaining):
            score = sum(w for e, w in cover.items() if e not in covered)
            if score > best_score:
                best = i
                best_score = score
        if best is None:
            break
        item, cover = remaining.pop(best)
        chosen.append(item)
        covered.update(cover.keys())
    return chosen
