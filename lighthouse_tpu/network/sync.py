"""Sync algorithms over the req/resp protocols (reference
beacon_node/network/src/sync/: manager.rs dispatch, range_sync/ batched
forward sync with peer pools and retries, backfill_sync/ reverse fill
from a checkpoint anchor, block_lookups/ unknown-parent chasing).

The transport is whatever the node's bus speaks (in-process bus or the
socket-backed wire stack); the algorithms only use STATUS /
BLOCKS_BY_RANGE / BLOCKS_BY_ROOT requests plus the node's peer-score
table, mirroring how the reference's SyncManager drives
lighthouse_network through NetworkService messages."""

from __future__ import annotations

from ..chain.beacon_chain import BlockError

BATCH_SIZE = 32  # reference range_sync EPOCHS_PER_BATCH * slots (minimal)
MAX_BATCH_RETRIES = 3  # batch.rs MAX_BATCH_DOWNLOAD_ATTEMPTS
MAX_PARENT_DEPTH = 16  # block_lookups PARENT_DEPTH_TOLERANCE


class SyncManager:
    def __init__(
        self,
        node,
        batch_size: int = BATCH_SIZE,
        max_batch_retries: int = MAX_BATCH_RETRIES,
    ):
        self.node = node
        self.batch_size = batch_size
        self.max_batch_retries = max_batch_retries

    # -- peer pool -----------------------------------------------------------

    def _candidate_peers(self) -> list[str]:
        node = self.node
        peers = node.bus.peers_on(node._topic_block)
        return [
            p for p in peers if p != node.peer_id and not node.is_banned(p)
        ]

    def peer_status(self, peer: str) -> dict | None:
        from .node import STATUS_PROTOCOL

        try:
            return self.node.bus.request(
                self.node.peer_id, peer, STATUS_PROTOCOL, {}
            )
        except (ConnectionError, OSError):
            self.node.penalize(peer, -1)
            return None

    def _ranked_ahead(self) -> list[tuple[str, dict]]:
        """Peers whose head is ahead of ours, best head first
        (peer_manager's sync-committee peer selection seat)."""
        our_slot = self.node.chain.head_state.slot
        out = []
        for p in self._candidate_peers():
            status = self.peer_status(p)
            if status is not None and status["head_slot"] > our_slot:
                out.append((p, status))
        out.sort(key=lambda t: t[1]["head_slot"], reverse=True)
        return out

    # -- forward range sync (range_sync/chain.rs) ---------------------------

    def _request_batch(self, start_slot: int, count: int, peers: list[str]):
        """Try each peer in order until one returns a batch; penalize
        transport failures (which consume retry budget — an empty answer
        is a legitimate "I don't have that range" and does not).
        Returns (blocks, peer) or (None, None)."""
        from .node import BLOCKS_BY_RANGE

        failures = 0
        for peer in peers:
            if failures >= self.max_batch_retries:
                break
            try:
                blocks = self.node.bus.request(
                    self.node.peer_id,
                    peer,
                    BLOCKS_BY_RANGE,
                    {"start_slot": start_slot, "count": count},
                )
            except (ConnectionError, OSError):
                self.node.penalize(peer, -1)
                failures += 1
                continue
            if blocks:
                return blocks, peer
        return None, None

    def _import_batch(self, blocks) -> tuple[int, bool]:
        """Import a batch: segment-batched signature verification (ONE
        backend call for the whole parent-linked run,
        block_verification.rs:525) with a per-block fallback for segments
        that don't link cleanly. Returns (imported, progressed)."""
        from ..chain.block_verification import (
            signature_verify_chain_segment,
        )
        from ..state_transition import BlockSignatureStrategy

        chain = self.node.chain
        imported = 0
        # manual clocks (tests) advance with the sync frontier; a system
        # clock is already at wall time and has no set_slot
        set_slot = getattr(chain.slot_clock, "set_slot", None)
        if set_slot is not None and blocks:
            set_slot(
                max(chain.current_slot, max(b.message.slot for b in blocks))
            )
        try:
            verified = signature_verify_chain_segment(chain, list(blocks))
        except BlockError:
            verified = None
        if verified is not None:
            for sv in verified:
                try:
                    sv.import_into(chain)  # reuses the advanced pre-state
                    imported += 1
                except BlockError:
                    # every later block descends from this one; continuing
                    # with pre-states would install detached roots
                    break
            return imported, imported > 0
        for blk in blocks:  # fallback: per-block full verification
            try:
                chain.process_block(blk)
                imported += 1
            except BlockError:
                continue
        return imported, imported > 0

    def range_sync(self) -> int:
        """Catch the chain up to the best peers' head in batches; returns
        blocks imported. Peers are statused once per ranking round (the
        reference re-ranks only on batch failure, range_sync/chain.rs) and
        failed batches rotate to the next-best peer."""
        chain = self.node.chain
        imported = 0
        while True:
            ranked = self._ranked_ahead()
            if not ranked:
                break
            peers = [p for p, _ in ranked]
            target = ranked[0][1]["head_slot"]
            while chain.head_state.slot < target:
                start = chain.head_state.slot + 1
                blocks, peer = self._request_batch(
                    start, self.batch_size, peers
                )
                if blocks is None:
                    return imported
                got, progressed = self._import_batch(blocks)
                imported += got
                if not progressed:
                    # the batch may be an honest peer's FORK: its blocks
                    # descend from an ancestor we don't hold (a healed
                    # partition's other side). Chase the missing parent
                    # chain by root first (block_lookups) and retry; only
                    # a batch that STILL doesn't apply is penalized —
                    # banning honest fork-peers here is a liveness bug
                    # (the heal would never converge).
                    first_parent = bytes(blocks[0].message.parent_root)
                    if first_parent not in chain._states and self.lookup_block(
                        first_parent
                    ):
                        got, progressed = self._import_batch(blocks)
                        imported += got
                if not progressed:
                    # peer served a batch we can't use (bad chain / gap):
                    # penalize and re-rank — repeated offenders get banned
                    self.node.penalize(peer)
                    break
            # outer loop re-ranks: catches peers that advanced meanwhile;
            # terminates when no peer is ahead (or offenders are banned)
        return imported

    def sync_from(self, peer: str) -> int:
        """Single-peer forward sync (the old NetworkNode.sync_with)."""
        chain = self.node.chain
        status = self.peer_status(peer)
        if status is None:
            return 0
        imported = 0
        while chain.head_state.slot < status["head_slot"]:
            blocks, _ = self._request_batch(
                chain.head_state.slot + 1, self.batch_size, [peer]
            )
            if blocks is None:
                break
            got, progressed = self._import_batch(blocks)
            imported += got
            if not progressed:
                break
        return imported

    # -- backfill sync (backfill_sync/mod.rs) -------------------------------

    def backfill_sync(self) -> int:
        """Fill history below the anchor down to genesis: request ranges
        ending at the anchor, verify the hash chain links into the anchor's
        parent_root, and store the blocks without replaying them
        (historical_blocks.rs import_historical_block_batch)."""
        chain = self.node.chain
        stored = 0
        while chain.oldest_block_slot > 0 and any(chain.oldest_block_parent):
            start = max(0, chain.oldest_block_slot - self.batch_size)
            count = chain.oldest_block_slot - start
            blocks, peer = self._request_batch(
                start, count, self._candidate_peers()
            )
            if blocks is None:
                break
            # ascending batch must hash-chain and link into the anchor
            ok = True
            for a, b in zip(blocks, blocks[1:]):
                if bytes(b.message.parent_root) != a.message.tree_hash_root():
                    ok = False
                    break
            if ok and blocks[-1].message.tree_hash_root() != bytes(
                chain.oldest_block_parent
            ):
                ok = False
            if not ok:
                self.node.penalize(peer)
                continue
            # blocks + the advanced anchor commit atomically: a crash
            # between them would otherwise leave an anchor claiming
            # history the store does not hold (or vice versa)
            batch = chain.store.batch()
            for blk in blocks:
                chain.store.put_block(
                    blk.message.tree_hash_root(), blk, batch=batch
                )
                stored += 1
            first = blocks[0].message
            anchor_root = first.tree_hash_root()
            anchor_parent = bytes(first.parent_root)
            batch.stage_chain_item(b"oldest_block_root", anchor_root)
            batch.stage_chain_item(
                b"oldest_block_meta",
                first.slot.to_bytes(8, "little") + anchor_parent,
            )
            batch.commit()
            # in-memory mirrors advance only AFTER the batch is durable
            # (migrate_to_freezer's idiom): a failed commit must not leave
            # the running node claiming history the store does not hold
            chain.oldest_block_root = anchor_root
            chain.oldest_block_slot = first.slot
            chain.oldest_block_parent = anchor_parent
        return stored

    # -- unknown-block lookups (block_lookups/mod.rs) -----------------------

    def lookup_block(self, block_root: bytes) -> bool:
        """Fetch a block by root and import it, chasing unknown parents up
        to MAX_PARENT_DEPTH (the reference's parent-lookup chain)."""
        from .node import BLOCKS_BY_ROOT

        chain = self.node.chain
        to_import = []
        root = bytes(block_root)
        for _ in range(MAX_PARENT_DEPTH):
            if root in chain._states:
                break  # found the attachment point
            found = None
            for peer in self._candidate_peers():
                try:
                    blocks = self.node.bus.request(
                        self.node.peer_id,
                        peer,
                        BLOCKS_BY_ROOT,
                        {"roots": [root]},
                    )
                except (ConnectionError, OSError):
                    self.node.penalize(peer, -1)
                    continue
                if blocks and blocks[0].message.tree_hash_root() == root:
                    # a peer substituting a different (even valid) block
                    # must not satisfy the lookup
                    found = blocks[0]
                    break
                if blocks:
                    self.node.penalize(peer)
            if found is None:
                return False
            to_import.append(found)
            root = bytes(found.message.parent_root)
        else:
            return False  # parent chain too deep
        set_slot = getattr(chain.slot_clock, "set_slot", None)
        for blk in reversed(to_import):
            try:
                if set_slot is not None:
                    set_slot(max(chain.current_slot, blk.message.slot))
                chain.process_block(blk)
            except BlockError:
                return False
        return True
