"""Gossipsub behavioral peer scoring (reference beacon_node/
lighthouse_network/src/service/gossipsub_scoring_parameters.rs +
gossipsub's peer_score.rs): per-peer, per-topic counters combined into
one score that gates mesh membership and message acceptance.

Components (the reference's P-weights, reduced to the counters this wire
stack can observe):
  P1  time in mesh        — small positive, capped
  P2  first deliveries    — positive, decaying, capped (rewards peers
                            that deliver NEW messages fast)
  P3  mesh delivery deficit — squared penalty when a MESH peer delivers
                            fewer messages than the topic's floor
  P4  invalid messages    — squared penalty, heavy (application
                            validation failures reported by the node)
  P7  behaviour penalty   — squared penalty (protocol misbehaviour:
                            graft floods etc.)

Decay is applied lazily from timestamps: no heartbeat thread. Scores
below `graylist_threshold` drop the peer's frames at the door; below
`prune_threshold` the peer is evicted from topic meshes."""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class TopicParams:
    topic_weight: float = 1.0
    time_in_mesh_weight: float = 0.033
    time_in_mesh_quantum_s: float = 12.0
    time_in_mesh_cap: float = 300.0
    first_deliveries_weight: float = 1.0
    first_deliveries_decay_s: float = 60.0
    first_deliveries_cap: float = 100.0
    mesh_deliveries_weight: float = -1.0
    mesh_deliveries_floor: float = 4.0
    mesh_deliveries_decay_s: float = 60.0
    mesh_deliveries_activation_s: float = 12.0
    invalid_weight: float = -20.0
    invalid_decay_s: float = 600.0


@dataclass
class _TopicStats:
    mesh_since: float | None = None
    first_deliveries: float = 0.0
    mesh_deliveries: float = 0.0
    invalid: float = 0.0
    last_decay: float = field(default_factory=time.monotonic)


@dataclass
class _PeerStats:
    topics: dict[str, _TopicStats] = field(default_factory=dict)
    behaviour_penalty: float = 0.0
    last_decay: float = field(default_factory=time.monotonic)


BEHAVIOUR_DECAY_S = 600.0


class PeerScorer:
    """Score bookkeeping. Internally locked: events arrive from bus
    reader threads, sync workers, and gossip validators concurrently."""

    def __init__(
        self,
        params: TopicParams | None = None,
        gossip_threshold: float = -10.0,
        prune_threshold: float = -40.0,
        graylist_threshold: float = -80.0,
    ):
        import threading

        self.params = params or TopicParams()
        self.gossip_threshold = gossip_threshold
        self.prune_threshold = prune_threshold
        self.graylist_threshold = graylist_threshold
        self._peers: dict[str, _PeerStats] = {}
        # per-topic last delivery from ANYONE: a quiet topic is the
        # topic's lull, not every mesh peer's fault — P3 deficits only
        # apply while the topic is demonstrably active
        self._topic_last_delivery: dict[str, float] = {}
        self._lock = threading.RLock()

    # -- event feeds ---------------------------------------------------------

    def _peer(self, peer_id: str) -> _PeerStats:
        p = self._peers.get(peer_id)
        if p is None:
            p = self._peers[peer_id] = _PeerStats()
        return p

    def _topic(self, peer_id: str, topic: str) -> _TopicStats:
        p = self._peer(peer_id)
        t = p.topics.get(topic)
        if t is None:
            t = p.topics[topic] = _TopicStats()
        return t

    def on_graft(self, peer_id: str, topic: str) -> None:
        with self._lock:
            t = self._topic(peer_id, topic)
            if t.mesh_since is None:
                t.mesh_since = time.monotonic()

    def on_prune(self, peer_id: str, topic: str) -> None:
        with self._lock:
            t = self._topic(peer_id, topic)
            t.mesh_since = None
            t.mesh_deliveries = 0.0

    def on_deliver(self, peer_id: str, topic: str, first: bool) -> None:
        with self._lock:
            self._topic_last_delivery[topic] = time.monotonic()
            t = self._topic(peer_id, topic)
            self._decay_topic(t)
            if first:
                t.first_deliveries = min(
                    t.first_deliveries + 1.0,
                    self.params.first_deliveries_cap,
                )
            if t.mesh_since is not None:
                t.mesh_deliveries += 1.0

    def on_invalid(self, peer_id: str, topic: str = "") -> None:
        with self._lock:
            t = self._topic(peer_id, topic)
            self._decay_topic(t)
            t.invalid += 1.0

    def on_behaviour_penalty(self, peer_id: str, amount: float = 1.0) -> None:
        with self._lock:
            p = self._peer(peer_id)
            self._decay_behaviour(p)
            p.behaviour_penalty += amount

    def forget(self, peer_id: str) -> None:
        """Disconnected peers release their stats (bounded memory)."""
        with self._lock:
            self._peers.pop(peer_id, None)

    # -- decay (lazy; exponential with per-component half-life) -------------

    @staticmethod
    def _decay(value: float, elapsed: float, half_life: float) -> float:
        if value == 0.0 or elapsed <= 0.0:
            return value
        return value * (0.5 ** (elapsed / half_life))

    def _decay_topic(self, t: _TopicStats) -> None:
        now = time.monotonic()
        dt = now - t.last_decay
        t.last_decay = now
        t.first_deliveries = self._decay(
            t.first_deliveries, dt, self.params.first_deliveries_decay_s
        )
        t.mesh_deliveries = self._decay(
            t.mesh_deliveries, dt, self.params.mesh_deliveries_decay_s
        )
        t.invalid = self._decay(t.invalid, dt, self.params.invalid_decay_s)

    def _decay_behaviour(self, p: _PeerStats) -> None:
        now = time.monotonic()
        p.behaviour_penalty = self._decay(
            p.behaviour_penalty, now - p.last_decay, BEHAVIOUR_DECAY_S
        )
        p.last_decay = now

    # -- the score -----------------------------------------------------------

    def score(self, peer_id: str) -> float:
        with self._lock:
            p = self._peers.get(peer_id)
            if p is None:
                return 0.0
            self._decay_behaviour(p)
            pr = self.params
            now = time.monotonic()
            total = 0.0
            for topic, t in p.topics.items():
                self._decay_topic(t)
                s = 0.0
                if t.mesh_since is not None:
                    in_mesh = now - t.mesh_since
                    s += pr.time_in_mesh_weight * min(
                        in_mesh / pr.time_in_mesh_quantum_s,
                        pr.time_in_mesh_cap,
                    )
                    # P3: an established mesh peer must pull its weight —
                    # but only while the TOPIC is demonstrably active
                    last = self._topic_last_delivery.get(topic)
                    topic_active = (
                        last is not None
                        and now - last < pr.mesh_deliveries_decay_s
                    )
                    if (
                        topic_active
                        and in_mesh > pr.mesh_deliveries_activation_s
                    ):
                        deficit = max(
                            pr.mesh_deliveries_floor - t.mesh_deliveries, 0.0
                        )
                        s += pr.mesh_deliveries_weight * deficit * deficit
                s += pr.first_deliveries_weight * t.first_deliveries
                s += pr.invalid_weight * t.invalid * t.invalid
                total += pr.topic_weight * s
            total += -1.0 * p.behaviour_penalty * p.behaviour_penalty
            return total

    def graylisted(self, peer_id: str) -> bool:
        return self.score(peer_id) < self.graylist_threshold

    def should_prune(self, peer_id: str) -> bool:
        return self.score(peer_id) < self.prune_threshold
