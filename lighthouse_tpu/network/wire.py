"""Socket-backed network stack (the wire seat of reference
beacon_node/lighthouse_network: service/mod.rs swarm, rpc/codec/
ssz_snappy.rs framing, types/pubsub.rs gossip en/decode, discovery/).

`WireBus` exposes the same Router-facing API as the in-process
`MessageBus` (subscribe / register_rpc / publish / request / peers_on),
so a `NetworkNode` runs unchanged over real TCP sockets:

- every payload crosses the wire as **SSZ + snappy** (snappy.py), with
  gossip topics in the reference's fork-digest namespacing and req/resp
  responses in varint-length-prefixed chunks (ssz_snappy.rs framing);
- gossip rides a degree-bounded MESH per topic (gossipsub's eager-push
  mesh, behaviour.rs/mesh maintenance) with a seen-cache: each node
  relays to at most MESH_DEGREE mesh peers instead of flooding every
  subscriber (peer scoring stays in NetworkNode's score table);
- connections are PERSISTENT: one long-lived outbound socket per peer,
  reused for every gossip push and req/resp exchange (the reference's
  noise/yamux stream seat), redialed once on failure;
- req/resp is token-bucket rate-limited PER PEER on the server side
  (reference rpc/rate_limiter.rs): an over-quota requester gets an
  error chunk, not service;
- `Bootnode` is a registry server standing in for discv5: peers
  REGISTER their (peer_id, host, port) and LIST others (discovery/'s
  ENR directory role; the UDP DHT itself is out of scope).

NOTE: no `from __future__ import annotations` — the @container wire types
below need live annotations (see types/containers.py header)."""

import hashlib
import json
import random
import socket
import socketserver
import struct
import threading
import time
from collections import OrderedDict

from ..ssz import Bytes4, Bytes32, List, container, uint64
from ..types import decode_block_any_fork, types_for
from .snappy import compress, decompress

FRAME_HELLO = 0
FRAME_GOSSIP = 1
FRAME_REQ = 2
FRAME_RESP = 3
FRAME_GRAFT = 4
FRAME_PRUNE = 5

SEEN_CACHE_SIZE = 4096
# gossipsub mesh degree (the reference's D; config.rs mesh_n)
MESH_DEGREE = 4


class TokenBucket:
    """Per-peer request quota (reference rpc/rate_limiter.rs): `burst`
    tokens, refilled at `rate_per_s`."""

    def __init__(self, burst: float, rate_per_s: float):
        self.capacity = float(burst)
        self.rate = float(rate_per_s)
        self.tokens = float(burst)
        self.stamp = time.monotonic()

    def allow(self, cost: float = 1.0) -> bool:
        now = time.monotonic()
        self.tokens = min(
            self.capacity, self.tokens + (now - self.stamp) * self.rate
        )
        self.stamp = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False


class PlainChannel:
    """Unencrypted frame channel over a raw socket -- the same interface
    SecureSocket (secure.py) exposes, so every wire path talks to ONE
    channel abstraction and encryption is purely a handshake choice."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.peer_pubkey = None

    def send_frame(self, ftype: int, body: bytes) -> None:
        _send_frame(self.sock, ftype, body)

    def recv_frame(self):
        return _recv_frame(self.sock)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class _PeerConn:
    """One persistent outbound channel to a peer, serialized by a lock;
    redials (and re-handshakes, in secure mode) once when the cached
    connection has died. `wrap` upgrades a fresh socket to a channel."""

    def __init__(self, host: str, port: int, wrap=PlainChannel):
        self.host = host
        self.port = port
        self.wrap = wrap
        self.lock = threading.Lock()
        self._chan = None

    def _dial(self):
        s = socket.create_connection((self.host, self.port), timeout=10)
        s.settimeout(10)
        try:
            return self.wrap(s)
        except OSError:
            s.close()
            raise

    def _get(self):
        if self._chan is None:
            self._chan = self._dial()
        return self._chan

    def _drop(self) -> None:
        if self._chan is not None:
            self._chan.close()
            self._chan = None

    def send(self, ftype: int, body: bytes) -> None:
        """Fire-and-forget frame (gossip push)."""
        with self.lock:
            for attempt in (0, 1):
                try:
                    # lint: allow[blocking-under-lock] -- this per-peer
                    # lock EXISTS to serialize one socket; dialing and
                    # framing under it is the design, and it guards no
                    # other state
                    self._get().send_frame(ftype, body)
                    return
                except OSError:
                    self._drop()
                    if attempt:
                        raise

    def exchange(self, ftype: int, body: bytes):
        """Frame out, response frame back on the same stream. The redial
        retry covers ONLY a failed send on a stale cached socket (nothing
        was delivered); once the request is on the wire, a receive failure
        raises -- re-sending would execute the rpc twice and burn a second
        rate-limit token."""
        with self.lock:
            for attempt in (0, 1):
                try:
                    # lint: allow[blocking-under-lock] -- same as send():
                    # the lock serializes exactly this socket
                    chan = self._get()
                    chan.send_frame(ftype, body)
                except OSError:
                    self._drop()
                    if attempt:
                        raise
                    continue
                try:
                    rtype, resp = chan.recv_frame()
                    if rtype is None:
                        raise OSError("peer closed mid-exchange")
                    return rtype, resp
                except OSError:
                    self._drop()
                    raise

    def close(self) -> None:
        with self.lock:
            self._drop()


# NOTE: no `from __future__ annotations` interplay — these descriptors are
# evaluated eagerly by @container via the module-level calls below.
def _make_wire_types():
    @container
    class StatusMessage:
        fork_digest: Bytes4
        finalized_root: Bytes32
        finalized_epoch: uint64
        head_root: Bytes32
        head_slot: uint64

    @container
    class BlocksByRangeRequest:
        start_slot: uint64
        count: uint64
        step: uint64

    @container
    class BlocksByRootRequest:
        roots: List(Bytes32, 1024)

    return StatusMessage, BlocksByRangeRequest, BlocksByRootRequest


StatusMessage, BlocksByRangeRequest, BlocksByRootRequest = _make_wire_types()


def _ssz_snappy(obj) -> bytes:
    return compress(obj.as_ssz_bytes())


def _chunks_encode(parts: list[bytes]) -> bytes:
    out = bytearray()
    for p in parts:
        out += struct.pack(">I", len(p)) + p
    return bytes(out)


def _chunks_decode(data: bytes) -> list[bytes]:
    out = []
    pos = 0
    while pos < len(data):
        (n,) = struct.unpack_from(">I", data, pos)
        pos += 4
        out.append(data[pos : pos + n])
        pos += n
    return out


class WireCodec:
    """ssz_snappy payload codecs per gossip kind and req/resp protocol
    (reference types/pubsub.rs PubsubMessage + rpc/codec)."""

    def __init__(self, preset):
        self.preset = preset
        self.t = types_for(preset)

    # -- gossip ---------------------------------------------------------------

    def _gossip_kind(self, topic: str) -> str:
        # /eth2/<digest>/<kind>[_<subnet>]/ssz_snappy
        kind = topic.split("/")[3]
        for prefix in (
            "beacon_attestation",
            "sync_committee_contribution_and_proof",
            "sync_committee",
        ):
            if kind.startswith(prefix):
                return prefix
        return kind

    def encode_gossip(self, topic: str, payload) -> bytes:
        return _ssz_snappy(payload)

    def decode_gossip(self, topic: str, data: bytes):
        raw = decompress(data)
        kind = self._gossip_kind(topic)
        t = self.t
        if kind == "beacon_block":
            return decode_block_any_fork(raw, self.preset)
        if kind == "beacon_aggregate_and_proof":
            return t.SignedAggregateAndProof.from_ssz_bytes(raw)
        if kind == "beacon_attestation":
            return t.Attestation.from_ssz_bytes(raw)
        if kind == "sync_committee_contribution_and_proof":
            return t.SignedContributionAndProof.from_ssz_bytes(raw)
        if kind == "sync_committee":
            from ..types.containers import SyncCommitteeMessage

            return SyncCommitteeMessage.from_ssz_bytes(raw)
        # operation gossip (types/topics.rs pubsub kinds): the scenario
        # wire fabric routes EVERY node topic over sockets, so the codec
        # must cover the op lanes the in-process bus carried for free
        if kind == "proposer_slashing":
            from ..types.containers import ProposerSlashing

            return ProposerSlashing.from_ssz_bytes(raw)
        if kind == "attester_slashing":
            return t.AttesterSlashing.from_ssz_bytes(raw)
        if kind == "voluntary_exit":
            from ..types.containers import SignedVoluntaryExit

            return SignedVoluntaryExit.from_ssz_bytes(raw)
        raise ValueError(f"unknown gossip kind in topic {topic}")

    # -- req/resp -------------------------------------------------------------

    def encode_request(self, protocol: str, payload) -> bytes:
        if "fabric_gossip" in protocol:
            # scenario-fabric delivery: a gossip message pushed as a
            # SYNCHRONOUS req/resp exchange (topic-prefixed ssz_snappy)
            # so the sender observes completion — the determinism seam
            # that lets wire-transport scenarios replay bit-identically
            topic = payload["topic"].encode()
            return struct.pack(">H", len(topic)) + topic + payload["data"]
        if "status" in protocol:
            return b""  # our Router's status handler takes no input
        if "by_range" in protocol:
            return _ssz_snappy(
                BlocksByRangeRequest(
                    start_slot=payload["start_slot"],
                    count=payload["count"],
                    step=1,
                )
            )
        if "by_root" in protocol:
            return _ssz_snappy(
                BlocksByRootRequest(
                    roots=tuple(bytes(r) for r in payload["roots"])
                )
            )
        if "light_client_bootstrap" in protocol:
            return compress(bytes(payload["root"]))
        raise ValueError(f"unknown protocol {protocol}")

    def decode_request(self, protocol: str, data: bytes):
        if "fabric_gossip" in protocol:
            (tlen,) = struct.unpack_from(">H", data, 0)
            topic = data[2 : 2 + tlen].decode()
            return {
                "topic": topic,
                "payload": self.decode_gossip(topic, data[2 + tlen :]),
            }
        if "status" in protocol:
            return {}
        if "by_range" in protocol:
            req = BlocksByRangeRequest.from_ssz_bytes(decompress(data))
            return {"start_slot": req.start_slot, "count": req.count}
        if "by_root" in protocol:
            req = BlocksByRootRequest.from_ssz_bytes(decompress(data))
            return {"roots": [bytes(r) for r in req.roots]}
        if "light_client_bootstrap" in protocol:
            return {"root": decompress(data)}
        raise ValueError(f"unknown protocol {protocol}")

    def encode_response(self, protocol: str, result) -> bytes:
        if "fabric_gossip" in protocol:
            return b""  # delivery ack carries no body
        if "status" in protocol:
            msg = StatusMessage(
                fork_digest=bytes(result["fork_digest"]),
                finalized_root=bytes(result["finalized_root"]),
                finalized_epoch=result["finalized_epoch"],
                head_root=bytes(result["head_root"]),
                head_slot=result["head_slot"],
            )
            return _chunks_encode([_ssz_snappy(msg)])
        if "light_client_bootstrap" in protocol:
            return _chunks_encode([_ssz_snappy(result)])
        # block streams: one ssz_snappy chunk per block (ssz_snappy.rs)
        return _chunks_encode([_ssz_snappy(b) for b in result])

    def decode_response(self, protocol: str, data: bytes):
        if "fabric_gossip" in protocol:
            return None
        chunks = _chunks_decode(data)
        if "status" in protocol:
            msg = StatusMessage.from_ssz_bytes(decompress(chunks[0]))
            return {
                "fork_digest": bytes(msg.fork_digest),
                "finalized_root": bytes(msg.finalized_root),
                "finalized_epoch": msg.finalized_epoch,
                "head_root": bytes(msg.head_root),
                "head_slot": msg.head_slot,
            }
        if "light_client_bootstrap" in protocol:
            from ..chain.light_client import light_client_types

            lt = light_client_types(self.preset)
            return lt.LightClientBootstrap.from_ssz_bytes(
                decompress(chunks[0])
            )
        return [
            decode_block_any_fork(decompress(c), self.preset) for c in chunks
        ]


# -- framing ------------------------------------------------------------------


def _send_frame(sock: socket.socket, ftype: int, body: bytes) -> None:
    sock.sendall(struct.pack(">IB", len(body) + 1, ftype) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket):
    head = _recv_exact(sock, 5)
    if head is None:
        return None, None
    length, ftype = struct.unpack(">IB", head[:4] + head[4:5])
    body = _recv_exact(sock, length - 1) if length > 1 else b""
    if body is None:
        return None, None  # truncated body == dead peer, same as EOF
    return ftype, body


# -- discovery registry (the discv5 seat) -------------------------------------


def _register_signing_root(
    peer_id: str, host: str, port: int, seq: int
) -> bytes:
    # seq gives the proof freshness (the ENR seq-number seat): a replayed
    # old registration cannot revert a peer's entry to a stale address
    return hashlib.sha256(
        b"lighthouse-tpu-bootnode-register\x00"
        + peer_id.encode()
        + b"\x00"
        + host.encode()
        + b"\x00"
        + int(port).to_bytes(4, "big")
        + int(seq).to_bytes(8, "big")
    ).digest()


def _sign_register_proof(
    identity_sk, peer_id: str, host: str, port: int, seq: int
) -> str:
    return identity_sk.sign(
        _register_signing_root(peer_id, host, port, seq)
    ).to_bytes().hex()


def _verify_register_proof(
    pk_bytes: bytes,
    sig_bytes: bytes,
    peer_id: str,
    host: str,
    port: int,
    seq: int,
) -> bool:
    """Pinned to the CPU oracle like ENR verification (discovery.py):
    identity registrations are control plane, never routed through the
    ambient batch backend (which may be `fake` under test)."""
    from ..crypto import bls
    from ..crypto.bls.backends import cpu as cpu_bls

    try:
        pk = bls.PublicKey.from_bytes(pk_bytes)
        sig = bls.Signature.from_bytes(sig_bytes)
        return cpu_bls.verify_signature_sets(
            [
                bls.SignatureSet.single_pubkey(
                    sig, pk, _register_signing_root(peer_id, host, port, seq)
                )
            ]
        )
    except (TypeError, ValueError, IndexError, AttributeError, OverflowError):
        # remote-controlled input: malformed key/signature material
        # (BlsError is a ValueError), non-string peer_id/host
        # (AttributeError/TypeError), out-of-range port (OverflowError)
        # == invalid registration, never a crashed handler thread
        return False


class Bootnode:
    """Peer directory over TCP: REGISTER/LIST json frames (reference
    boot_node/ + discovery/enr.rs directory role)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        outer = self
        self._peers: dict[str, dict] = {}
        self._lock = threading.Lock()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                ftype, body = _recv_frame(self.request)
                if ftype is None:
                    return
                msg = json.loads(body)
                if msg.get("op") == "register":
                    reply = outer._register(msg)
                else:  # list
                    with outer._lock:
                        reply = {"peers": list(outer._peers.values())}
                _send_frame(
                    self.request, FRAME_HELLO, json.dumps(reply).encode()
                )

        self._server = socketserver.ThreadingTCPServer(
            (host, port), Handler, bind_and_activate=True
        )
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    def _register(self, msg: dict) -> dict:
        """Identity-carrying registrations must PROVE key possession (a
        BLS signature over the registration transcript) and may not rebind
        a peer_id already registered under a different key -- otherwise the
        listing dialers pin from (the ENR seat) lets an attacker bind a
        victim's peer_id to its own key (review finding)."""
        pk_hex = msg.get("identity_pk")
        entry = {
            "peer_id": msg["peer_id"],
            "host": msg["host"],
            "port": msg["port"],
            "identity_pk": None,
            "seq": 0,
        }
        if pk_hex is not None:
            try:
                pk_bytes = bytes.fromhex(str(pk_hex))
                sig_bytes = bytes.fromhex(str(msg["register_proof"]))
                seq = int(msg["seq"])
            except (KeyError, ValueError, TypeError):
                return {"ok": False, "error": "malformed identity proof"}
            if not _verify_register_proof(
                pk_bytes,
                sig_bytes,
                msg["peer_id"],
                msg["host"],
                msg["port"],
                seq,
            ):
                return {"ok": False, "error": "bad identity proof"}
            entry["identity_pk"] = pk_hex
            entry["seq"] = seq
        with self._lock:
            prev = self._peers.get(msg["peer_id"])
            if prev is not None and prev.get("identity_pk") not in (
                None,
                pk_hex,
            ):
                # first-come binding: a different key cannot take the id
                return {"ok": False, "error": "peer id bound to another key"}
            if prev is not None and prev.get("identity_pk") is not None:
                if pk_hex is None:
                    # an unauthenticated re-register may not strip a binding
                    return {"ok": False, "error": "peer id requires identity"}
                if entry["seq"] <= prev.get("seq", 0):
                    # replayed/stale proof may not revert the entry
                    return {"ok": False, "error": "stale registration seq"}
            self._peers[msg["peer_id"]] = entry
        return {"ok": True}

    def start(self) -> "Bootnode":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    @staticmethod
    def rpc(host: str, port: int, msg: dict) -> dict:
        with socket.create_connection((host, port), timeout=5) as s:
            _send_frame(s, FRAME_HELLO, json.dumps(msg).encode())
            _, body = _recv_frame(s)
            return json.loads(body)


# -- the per-node transport ---------------------------------------------------


class WireBus:
    """Per-node socket transport with the MessageBus API. One instance
    per node (unlike the shared in-process MessageBus); `listen()` then
    `bootstrap()`/`connect_to()` wire it into the network."""

    def __init__(
        self,
        preset,
        host: str = "127.0.0.1",
        mesh_degree: int = MESH_DEGREE,
        req_burst: float = 16.0,
        req_rate_per_s: float = 8.0,
        secure: bool = False,
        identity_sk=None,
        authenticate: bool = False,
        rng: random.Random | None = None,
    ):
        self.codec = WireCodec(preset)
        self.host = host
        # mesh-maintenance randomness (lint rule `nondeterminism`): tests
        # inject an rng for exact replay; otherwise derive from the node
        # identity so DISTINCT nodes make independent shuffle/sample
        # choices (a shared fixed seed would correlate gossip topology
        # across the whole network) while a fixed identity still replays
        if rng is not None:
            self.rng = rng
        elif identity_sk is not None:
            digest = hashlib.sha256(
                b"wirebus-mesh-rng" + identity_sk.to_bytes()
            ).digest()
            self.rng = random.Random(int.from_bytes(digest[:8], "big"))
        else:
            self.rng = random.Random()  # OS entropy, as before
        # transport security (the noise seat, secure.py): with secure=True
        # every connection -- inbound and outbound -- runs the DH handshake
        # and all frames are encrypted+MACed; authenticate adds BLS
        # transcript signatures binding the connection to identity keys
        self.secure = secure
        self.identity_sk = identity_sk
        self.authenticate = authenticate
        self.peer_id: str | None = None
        self.port: int | None = None
        self._subs: dict[str, object] = {}  # topic -> handler
        self._rpc: dict[str, object] = {}  # protocol -> handler
        # peer_id -> {"host", "port", "topics": set}
        self._peers: dict[str, dict] = {}
        self._conns: dict[str, _PeerConn] = {}  # persistent outbound
        self.mesh_degree = mesh_degree
        self._mesh: dict[str, set] = {}  # topic -> mesh peer ids
        # peers that PRUNEd our graft, per topic: excluded from re-grafts
        self._pruned_by: dict[str, set] = {}
        self.req_burst = req_burst
        self.req_rate_per_s = req_rate_per_s
        self._seen: OrderedDict[bytes, bool] = OrderedDict()
        # gossipsub behavioral scoring (gossipsub_scoring_parameters.rs):
        # relayer-keyed; graylisted peers' gossip drops at the door and
        # negative-score mesh peers are pruned during relay
        from .peer_score import PeerScorer

        self.scorer = PeerScorer()
        # relay-path score snapshot: peer_id -> (score, stamp). Scoring a
        # peer takes the scorer lock and lazily decays every topic, so
        # the relay loop must not do it per subscriber per message under
        # the bus lock; scores are recomputed at most once per TTL and
        # always OUTSIDE the bus lock (penalties surface one TTL late at
        # worst, which mesh behavior tolerates)
        self.score_ttl_s = 1.0
        self._score_cache: dict[str, tuple[float, float]] = {}
        self._lock = threading.Lock()
        self._server = None
        # observability for mesh/limiter tests
        self.stats = {"gossip_frames_sent": 0, "requests_rejected": 0}

    # -- MessageBus API ------------------------------------------------------

    def subscribe(self, peer_id: str, topic: str, handler) -> None:
        self._subs[topic] = handler

    def unsubscribe(self, peer_id: str, topic: str) -> None:
        self._subs.pop(topic, None)

    def register_rpc(self, peer_id: str, protocol: str, handler) -> None:
        self._rpc[protocol] = handler

    def peers_on(self, topic: str) -> list[str]:
        with self._lock:
            return [
                pid
                for pid, info in self._peers.items()
                if topic in info["topics"]
            ] + ([self.peer_id] if topic in self._subs else [])

    def publish(self, source_peer: str, topic: str, payload) -> int:
        data = self.codec.encode_gossip(topic, payload)
        msg_id = self._msg_id(topic, data)
        self._mark_seen(msg_id)
        return self._gossip_send(topic, data, exclude=None)

    def request(self, from_peer: str, to_peer: str, protocol: str, payload):
        conn = self._conn_for(to_peer)
        if conn is None:
            raise ConnectionError(f"unknown peer {to_peer}")
        body = (
            struct.pack(">H", len(protocol))
            + protocol.encode()
            + struct.pack(">H", len(self.peer_id))
            + self.peer_id.encode()
            + self.codec.encode_request(protocol, payload)
        )
        try:
            ftype, resp = conn.exchange(FRAME_REQ, body)
        except OSError as e:
            raise ConnectionError(f"peer {to_peer} unreachable: {e}") from None
        if ftype != FRAME_RESP or resp is None:
            raise ConnectionError(f"peer {to_peer} sent no response")
        if resp[:1] == b"\x01":
            raise ConnectionError(
                f"peer {to_peer} error: {resp[1:].decode(errors='replace')}"
            )
        return self.codec.decode_response(protocol, resp[1:])

    # -- lifecycle -----------------------------------------------------------

    def _wrap_client(self, sock, expect_pubkey=None):
        if not self.secure:
            return PlainChannel(sock)
        from .secure import handshake_initiator

        return handshake_initiator(
            sock,
            self.identity_sk,
            expect_pubkey=expect_pubkey,
            authenticate=self.authenticate,
        )

    def _wrap_server(self, sock):
        if not self.secure:
            return PlainChannel(sock)
        from .secure import handshake_responder

        return handshake_responder(
            sock, self.identity_sk, authenticate=self.authenticate
        )

    def listen(self, peer_id: str, port: int = 0) -> int:
        self.peer_id = peer_id
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    chan = outer._wrap_server(self.request)
                except OSError:
                    return  # failed/mismatched handshake: drop the dial
                # the quota is keyed to the CONNECTION, not a requester id
                # copied from the request body -- ids are free to rotate,
                # re-dialing costs the flooder a handshake per bucket
                bucket = TokenBucket(outer.req_burst, outer.req_rate_per_s)
                while True:
                    try:
                        ftype, body = chan.recv_frame()
                    except OSError:
                        return  # MAC/sequence failure: kill the stream
                    if ftype is None:
                        return
                    outer._handle_frame(chan, ftype, body, bucket)

        self._server = socketserver.ThreadingTCPServer(
            (self.host, port), Handler, bind_and_activate=True
        )
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, daemon=True
        ).start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            conn.close()

    def connect_to(
        self, host: str, port: int, expect_pubkey: bytes | None = None
    ) -> str | None:
        """Dial a peer: HELLO exchange records each other's listen
        address + topic interests (the identify/handshake seat).

        With authenticate=True the transcript signature is verified
        against `expect_pubkey` when the caller knows it (bootnode
        listing / discovery ENR); otherwise the key the peer presents is
        PINNED trust-on-first-use, so every later re-dial to this peer id
        (the persistent _conn_for connections) rejects an impostor."""
        hello = {
            "peer_id": self.peer_id,
            "host": self.host,
            "port": self.port,
            "topics": sorted(self._subs),
        }
        try:
            with socket.create_connection((host, port), timeout=10) as s:
                chan = self._wrap_client(s, expect_pubkey)
                chan.send_frame(FRAME_HELLO, json.dumps(hello).encode())
                ftype, body = chan.recv_frame()
        except OSError as e:
            raise ConnectionError(f"dial {host}:{port} failed: {e}") from None
        if ftype != FRAME_HELLO:
            return None
        peer = json.loads(body)
        # only a key the handshake PROVED may pin -- never one claimed in
        # the reply body, and never the caller's unverified expectation
        # (with authenticate off, expect_pubkey was not checked by anyone)
        peer.pop("identity_pk", None)
        proved = getattr(chan, "peer_pubkey", None)
        if proved is not None:
            peer["identity_pk"] = bytes(proved).hex()
        self._record_peer(peer)
        return peer["peer_id"]

    def bootstrap(self, bootnode: Bootnode | tuple) -> int:
        """Register with the bootnode and dial every listed peer."""
        host, port = (
            (bootnode.host, bootnode.port)
            if isinstance(bootnode, Bootnode)
            else bootnode
        )
        register = {
            "op": "register",
            "peer_id": self.peer_id,
            "host": self.host,
            "port": self.port,
        }
        if self.authenticate and self.identity_sk is not None:
            seq = time.time_ns()
            register["identity_pk"] = (
                self.identity_sk.public_key().to_bytes().hex()
            )
            register["seq"] = seq
            register["register_proof"] = _sign_register_proof(
                self.identity_sk, self.peer_id, self.host, self.port, seq
            )
        Bootnode.rpc(host, port, register)
        listed = Bootnode.rpc(host, port, {"op": "list"})["peers"]
        connected = 0
        for p in listed:
            if p["peer_id"] == self.peer_id:
                continue
            try:
                # inside the try: a poisoned registration (malformed hex,
                # wrong type) must skip THIS peer, not abort the bootstrap
                pk_hex = p.get("identity_pk")
                expect = bytes.fromhex(pk_hex) if pk_hex else None
                if self.connect_to(p["host"], p["port"], expect_pubkey=expect):
                    connected += 1
            except (ConnectionError, ValueError, TypeError):
                continue
        return connected

    # -- internals -----------------------------------------------------------

    def _record_peer(self, peer: dict) -> None:
        with self._lock:
            prev = self._peers.get(peer["peer_id"], {})
            prev_pin = prev.get("identity_pk")
            new_pin = peer.get("identity_pk")
            if prev_pin and new_pin and new_pin != prev_pin:
                # a DIFFERENT proved key claiming an already-pinned peer id
                # is a hijack attempt: adopting it (key OR address) would
                # redirect the persistent dials to the newcomer. Drop the
                # record; the legitimate peer keeps its pin and address.
                return
            self._peers[peer["peer_id"]] = {
                "host": peer["host"],
                "port": peer["port"],
                # an existing pin survives re-records (HELLO refreshes
                # carry no identity; they must not unpin a peer)
                "identity_pk": new_pin or prev_pin,
                "topics": set(peer.get("topics", ())),
            }
            # mesh maintenance: a new subscriber can graft into any topic
            # mesh that is below degree; grafts are SYMMETRIC (gossipsub
            # GRAFT control) so the mesh union is an undirected connected
            # graph, not a one-way star
            graft_topics = []
            for topic in peer.get("topics", ()):
                # a topology change invalidates stale prune verdicts
                self._pruned_by.get(topic, set()).discard(peer["peer_id"])
                mesh = self._mesh.setdefault(topic, set())
                if (
                    peer["peer_id"] not in mesh
                    and len(mesh) < self.mesh_degree
                ):
                    mesh.add(peer["peer_id"])
                    self.scorer.on_graft(peer["peer_id"], topic)
                    graft_topics.append(topic)
        for topic in graft_topics:
            self._send_graft(peer["peer_id"], topic)

    def _send_graft(self, peer_id: str, topic: str) -> None:
        conn = self._conn_for(peer_id)
        if conn is None:
            return
        try:
            conn.send(
                FRAME_GRAFT,
                json.dumps(
                    {"peer_id": self.peer_id, "topic": topic}
                ).encode(),
            )
        except OSError:
            pass

    def _conn_for(self, peer_id: str) -> "_PeerConn | None":
        with self._lock:
            info = self._peers.get(peer_id)
            if info is None:
                return None
            conn = self._conns.get(peer_id)
            if conn is None:
                pk_hex = info.get("identity_pk")
                expect = bytes.fromhex(pk_hex) if pk_hex else None
                conn = self._conns[peer_id] = _PeerConn(
                    info["host"],
                    info["port"],
                    wrap=lambda s, e=expect: self._wrap_client(s, e),
                )
            return conn

    def _drop_peer(self, peer_id: str) -> None:
        self.scorer.forget(peer_id)
        self._score_cache.pop(peer_id, None)
        with self._lock:
            self._peers.pop(peer_id, None)
            conn = self._conns.pop(peer_id, None)
            for mesh in self._mesh.values():
                mesh.discard(peer_id)
        if conn is not None:
            conn.close()
        # backfill meshes from remaining subscribers -- symmetrically
        # (send GRAFT) and never toward a peer that PRUNEd us
        grafts = []
        with self._lock:
            for topic, mesh in self._mesh.items():
                if len(mesh) < self.mesh_degree:
                    candidates = [
                        pid
                        for pid, info in self._peers.items()
                        if topic in info["topics"]
                        and pid not in mesh
                        and pid not in self._pruned_by.get(topic, ())
                    ]
                    self.rng.shuffle(candidates)
                    for pid in candidates[: self.mesh_degree - len(mesh)]:
                        mesh.add(pid)
                        grafts.append((pid, topic))
        for pid, topic in grafts:
            self._send_graft(pid, topic)

    def _msg_id(self, topic: str, data: bytes) -> bytes:
        return hashlib.sha256(topic.encode() + data).digest()[:20]

    def _mark_seen(self, msg_id: bytes) -> bool:
        """True if newly seen."""
        with self._lock:
            if msg_id in self._seen:
                return False
            self._seen[msg_id] = True
            while len(self._seen) > SEEN_CACHE_SIZE:
                self._seen.popitem(last=False)
            return True

    def _cached_scores(self, peer_ids) -> dict[str, float]:
        """Fresh-enough scores for `peer_ids`, recomputed at most once
        per `score_ttl_s` per peer. MUST be called outside the bus lock:
        a cache miss takes the scorer lock and runs lazy decay over the
        peer's topics."""
        now = time.monotonic()
        out = {}
        for pid in peer_ids:
            hit = self._score_cache.get(pid)
            if hit is None or now - hit[1] >= self.score_ttl_s:
                hit = (self.scorer.score(pid), now)
                self._score_cache[pid] = hit
            out[pid] = hit[0]
        if len(self._score_cache) > 4 * max(len(out), 64):
            # forget snapshot entries for long-gone peers
            stale = [
                p
                for p, (_, stamp) in list(self._score_cache.items())
                if now - stamp >= self.score_ttl_s
            ]
            for p in stale:
                self._score_cache.pop(p, None)
        return out

    def _gossip_send(self, topic: str, data: bytes, exclude: str | None) -> int:
        """Eager-push to the topic MESH over persistent connections (the
        gossipsub relay; flood only if the mesh is empty but subscribers
        exist, which covers bootstrap races)."""
        body = (
            struct.pack(">H", len(topic))
            + topic.encode()
            + struct.pack(">H", len(self.peer_id))
            + self.peer_id.encode()
            + data
        )
        # snapshot scores OUTSIDE the bus lock (relay cost was
        # O(subscribers x their topics) per message under BOTH locks)
        with self._lock:
            candidates = set(self._mesh.get(topic, ())) | {
                pid
                for pid, info in self._peers.items()
                if topic in info["topics"]
            }
        scores = self._cached_scores(candidates)
        with self._lock:
            mesh = set(self._mesh.get(topic, ()))
            # behavioral eviction: peers scored below the prune threshold
            # leave the mesh (and get a PRUNE) before this relay
            evict = {
                p
                for p in mesh
                if scores.get(p, 0.0) < self.scorer.prune_threshold
            }
            if evict:
                self._mesh[topic] = mesh - evict
                mesh -= evict
                for p in evict:
                    self.scorer.on_prune(p, topic)
            subscribers = {
                pid
                for pid, info in self._peers.items()
                if topic in info["topics"]
                # gossip_threshold: stop relaying TO low-score peers
                and scores.get(pid, 0.0) >= self.scorer.gossip_threshold
            }
            # backfill the mesh after eviction (every other removal path
            # re-grafts; eviction must not strand the mesh below degree)
            backfill = []
            if evict and len(mesh) < self.mesh_degree:
                candidates = [
                    pid
                    for pid in subscribers
                    if pid not in mesh
                    and pid not in evict
                    and pid not in self._pruned_by.get(topic, set())
                ]
                backfill = candidates[: self.mesh_degree - len(mesh)]
                self._mesh[topic].update(backfill)
                mesh.update(backfill)
                for pid in backfill:
                    self.scorer.on_graft(pid, topic)
        for pid in backfill:
            self._send_graft(pid, topic)
        # symmetric PRUNE (outside the lock: network sends): the evicted
        # peer must drop US from its mesh too or it keeps pushing to us
        for p in evict:
            conn = self._conn_for(p)
            if conn is not None:
                try:
                    conn.send(
                        FRAME_PRUNE,
                        json.dumps(
                            {"peer_id": self.peer_id, "topic": topic, "px": []}
                        ).encode(),
                    )
                except OSError:
                    pass
        subscribers.discard(exclude)
        # exclude FIRST: a mesh shrunk to exactly the upstream sender must
        # fall back to the other known subscribers, not relay to nobody
        targets = (mesh & subscribers) or subscribers
        sent = 0
        for pid in targets:
            conn = self._conn_for(pid)
            if conn is None:
                continue
            try:
                conn.send(FRAME_GOSSIP, body)
                sent += 1
                self.stats["gossip_frames_sent"] += 1
            except OSError:
                self._drop_peer(pid)
        return sent

    def _handle_frame(self, chan, ftype: int, body: bytes, bucket=None) -> None:
        if ftype == FRAME_HELLO:
            peer = json.loads(body)
            # inbound side: pin the identity the dialer PROVED during the
            # handshake (chan.peer_pubkey), never one it merely claims
            proved = getattr(chan, "peer_pubkey", None)
            if proved is not None:
                peer["identity_pk"] = bytes(proved).hex()
            else:
                peer.pop("identity_pk", None)
            self._record_peer(peer)
            reply = {
                "peer_id": self.peer_id,
                "host": self.host,
                "port": self.port,
                "topics": sorted(self._subs),
            }
            chan.send_frame(FRAME_HELLO, json.dumps(reply).encode())
            return
        if ftype == FRAME_GRAFT:
            msg = json.loads(body)
            topic = msg["topic"]
            refuse = False
            with self._lock:
                if msg["peer_id"] in self._peers:
                    # a graft IS a subscription signal: without recording
                    # it, the `mesh & subscribers` send filter would
                    # silently starve the grafted peer
                    self._peers[msg["peer_id"]]["topics"].add(topic)
                    mesh = self._mesh.setdefault(topic, set())
                    if msg["peer_id"] in mesh:
                        pass
                    elif self.scorer.should_prune(msg["peer_id"]):
                        # an evicted peer cannot graft straight back in:
                        # behavioral eviction must outlast a re-GRAFT
                        refuse = True
                        self.scorer.on_behaviour_penalty(
                            msg["peer_id"], 0.5
                        )
                    elif len(mesh) < 2 * self.mesh_degree:
                        # accept grafts up to 2x degree (gossipsub D_high)
                        mesh.add(msg["peer_id"])
                        self.scorer.on_graft(msg["peer_id"], topic)
                    else:
                        refuse = True
                        # repeated grafts into a saturated mesh are the
                        # gossipsub behaviour-penalty case (P7)
                        self.scorer.on_behaviour_penalty(msg["peer_id"], 0.5)
            if refuse:
                # full mesh: PRUNE so the grafter re-grafts elsewhere,
                # carrying peer-exchange suggestions (gossipsub PX) so a
                # late joiner facing saturated meshes still finds a seat
                with self._lock:
                    px = self.rng.sample(
                        sorted(self._mesh.get(topic, ())),
                        k=min(2, len(self._mesh.get(topic, ()))),
                    )
                conn = self._conn_for(msg["peer_id"])
                if conn is not None:
                    try:
                        conn.send(
                            FRAME_PRUNE,
                            json.dumps(
                                {
                                    "peer_id": self.peer_id,
                                    "topic": topic,
                                    "px": px,
                                }
                            ).encode(),
                        )
                    except OSError:
                        pass
            return
        if ftype == FRAME_PRUNE:
            msg = json.loads(body)
            topic = msg["topic"]
            with self._lock:
                self._mesh.get(topic, set()).discard(msg["peer_id"])
                self._pruned_by.setdefault(topic, set()).add(msg["peer_id"])
                mesh = self._mesh.setdefault(topic, set())
                # PX suggestions first (they have capacity signals), then
                # any other known subscriber we have not been pruned by
                candidates = [
                    pid
                    for pid in msg.get("px", ())
                    if pid in self._peers
                    and pid != self.peer_id
                    and pid not in mesh
                ]
                others = [
                    pid
                    for pid, info in self._peers.items()
                    if topic in info["topics"]
                    and pid not in mesh
                    and pid not in self._pruned_by[topic]
                    and pid not in candidates
                ]
                self.rng.shuffle(others)
                candidates.extend(others)
                chosen = candidates[: max(self.mesh_degree - len(mesh), 1)]
                mesh.update(chosen)
            for pid in chosen:
                self._send_graft(pid, topic)
            return
        if ftype == FRAME_GOSSIP:
            (tlen,) = struct.unpack_from(">H", body, 0)
            topic = body[2 : 2 + tlen].decode()
            pos = 2 + tlen
            (plen,) = struct.unpack_from(">H", body, pos)
            source = body[pos + 2 : pos + 2 + plen].decode()
            data = body[pos + 2 + plen :]
            with self._lock:
                if self.scorer.graylisted(source):
                    self.stats["gossip_graylisted"] = (
                        self.stats.get("gossip_graylisted", 0) + 1
                    )
                    return
            first = self._mark_seen(self._msg_id(topic, data))
            with self._lock:
                self.scorer.on_deliver(source, topic, first)
            if not first:
                return
            handler = self._subs.get(topic)
            if handler is not None:
                payload = self.codec.decode_gossip(topic, data)
                handler(payload, source)
            # relay onward through the mesh, not back to the sender
            self._gossip_send(topic, data, exclude=source)
            return
        if ftype == FRAME_REQ:
            (plen,) = struct.unpack_from(">H", body, 0)
            protocol = body[2 : 2 + plen].decode()
            pos = 2 + plen
            (rlen,) = struct.unpack_from(">H", body, pos)
            requester = body[pos + 2 : pos + 2 + rlen].decode()
            data = body[pos + 2 + rlen :]
            # per-connection token bucket (rpc/rate_limiter.rs):
            # over-quota requesters get an error chunk, not service
            if bucket is not None and not bucket.allow():
                self.stats["requests_rejected"] += 1
                chan.send_frame(FRAME_RESP, b"\x01rate limited")
                return
            handler = self._rpc.get(protocol)
            if handler is None:
                chan.send_frame(FRAME_RESP, b"\x01unknown protocol")
                return
            try:
                payload = self.codec.decode_request(protocol, data)
                result = handler(payload, requester or "remote")
                chan.send_frame(
                    FRAME_RESP,
                    b"\x00" + self.codec.encode_response(protocol, result),
                )
            # lint: allow[broad-except] -- RPC dispatch boundary: the
            # handler is arbitrary application code and a remote request
            # must never kill the connection thread; the failure is
            # counted and returned to the requester, not dropped
            except Exception as e:  # noqa: BLE001
                self.stats["rpc_handler_errors"] = (
                    self.stats.get("rpc_handler_errors", 0) + 1
                )
                chan.send_frame(
                    FRAME_RESP, b"\x01" + str(e).encode()[:512]
                )
            return
