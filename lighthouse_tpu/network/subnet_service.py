"""Attestation subnet service: which of the 64 attestation subnets a
node listens on, and when (reference beacon_node/network/src/
subnet_service/attestation_subnets.rs).

Two subscription classes, as in the reference:

- **long-lived**: every node camps on `subnets_per_node` subnets chosen
  deterministically from its node id and the current subscription
  period (EPOCHS_PER_SUBNET_SUBSCRIPTION epochs long), and advertises
  them in its ENR attnets bits -- that is what makes subnet topics
  discoverable (`subnet_predicate.rs` peers-for-subnet dials filter on
  these bits);
- **short-lived duty subscriptions**: an attester duty at (slot,
  committee) subscribes its subnet one slot ahead and drops it when the
  slot passes (the reference subscribes `ADVANCE_SUBSCRIBE_TIME` early
  and unsubscribes at slot end).

The service is clock-driven by `on_slot` and talks to the outside
through callbacks (bus subscribe/unsubscribe + ENR update), so it runs
unchanged over the in-process bus, the TCP wire stack, and in tests.
"""

from __future__ import annotations

import hashlib


def compute_subnet_for_attestation(
    committees_per_slot: int, slot: int, committee_index: int, preset, spec
) -> int:
    """The spec's compute_subnet_for_attestation (validator guide):
    committees are striped across subnets within an epoch."""
    slots_since_epoch_start = slot % preset.slots_per_epoch
    committees_since_epoch_start = committees_per_slot * slots_since_epoch_start
    return (
        committees_since_epoch_start + committee_index
    ) % spec.attestation_subnet_count


def compute_subscribed_subnets(
    node_id: bytes, epoch: int, spec, subnets_per_node: int = 2,
    epochs_per_subscription: int = 256,
) -> list:
    """Deterministic long-lived subnets for (node_id, period) -- the
    discv5-advertised camping spots. Stable within a period, rotating
    across periods, spread by hashing (the reference's
    compute_subscribed_subnets shape over its node-id prefix)."""
    period = epoch // epochs_per_subscription
    out = []
    i = 0
    while len(out) < min(subnets_per_node, spec.attestation_subnet_count):
        digest = hashlib.sha256(
            node_id + period.to_bytes(8, "little") + i.to_bytes(8, "little")
        ).digest()
        subnet = int.from_bytes(digest[:8], "little") % (
            spec.attestation_subnet_count
        )
        if subnet not in out:
            out.append(subnet)
        i += 1
    return out


class AttestationSubnetService:
    def __init__(
        self,
        node_id: bytes,
        preset,
        spec,
        subscribe_cb,
        unsubscribe_cb,
        enr_update_cb=None,
        subnets_per_node: int = 2,
        epochs_per_subscription: int = 256,
    ):
        self.node_id = bytes(node_id)
        self.preset = preset
        self.spec = spec
        self._subscribe = subscribe_cb
        self._unsubscribe = unsubscribe_cb
        self._enr_update = enr_update_cb
        self.subnets_per_node = subnets_per_node
        self.epochs_per_subscription = epochs_per_subscription
        self._long_lived: set[int] = set()
        self._duty: dict[int, int] = {}  # subnet -> last duty slot
        self._active: set[int] = set()
        self.stats = {"subscribes": 0, "unsubscribes": 0, "enr_updates": 0}

    # -- queries ---------------------------------------------------------------

    @property
    def long_lived(self) -> set:
        return set(self._long_lived)

    def set_enr_update_cb(self, cb) -> None:
        """Late-wire the ENR advertisement seam (a discovery service
        attached after construction) and advertise the current set."""
        self._enr_update = cb
        if cb is not None and self._long_lived:
            cb(sorted(self._long_lived))
            self.stats["enr_updates"] += 1

    def active_subnets(self) -> set:
        return set(self._active)

    def is_subscribed(self, subnet: int) -> bool:
        return subnet in self._active

    # -- drivers ---------------------------------------------------------------

    def on_slot(self, slot: int) -> None:
        """Rotate long-lived subnets on period boundaries; expire duty
        subscriptions whose slot has passed."""
        epoch = slot // self.preset.slots_per_epoch
        wanted = set(
            compute_subscribed_subnets(
                self.node_id,
                epoch,
                self.spec,
                self.subnets_per_node,
                self.epochs_per_subscription,
            )
        )
        if wanted != self._long_lived:
            self._long_lived = wanted
            if self._enr_update is not None:
                self._enr_update(sorted(wanted))
                self.stats["enr_updates"] += 1
        for subnet, duty_slot in list(self._duty.items()):
            if duty_slot < slot:
                del self._duty[subnet]
        self._reconcile()

    def subscribe_for_duty(
        self, duty_slot: int, committees_per_slot: int, committee_index: int
    ) -> int:
        """An attester/aggregator duty at (slot, committee): hold the
        subnet until the duty slot passes. Returns the subnet id."""
        subnet = compute_subnet_for_attestation(
            committees_per_slot,
            duty_slot,
            committee_index,
            self.preset,
            self.spec,
        )
        self._duty[subnet] = max(self._duty.get(subnet, 0), duty_slot)
        self._reconcile()
        return subnet

    def _reconcile(self) -> None:
        wanted = self._long_lived | set(self._duty)
        for subnet in sorted(wanted - self._active):
            self._subscribe(subnet)
            self.stats["subscribes"] += 1
        for subnet in sorted(self._active - wanted):
            self._unsubscribe(subnet)
            self.stats["unsubscribes"] += 1
        self._active = wanted
