"""Network node: Router + gossip methods + sync over the message bus
(reference beacon_node/network/src/router/mod.rs:206 handle_gossip,
worker/gossip_methods.rs, sync/manager.rs + range_sync, and
lighthouse_network's peer manager scoring, peer_manager/peerdb/score.rs).

One NetworkNode owns a BeaconChain, pools, observed caches, a
BeaconProcessor, and a peer score table; it subscribes to the gossip
topics and serves the req/resp protocols."""

from __future__ import annotations

from ..chain.attestation_verification import (
    submit_aggregate_batch,
    submit_unaggregated_batch,
)
from ..chain.beacon_chain import BeaconChain, BlockError
from ..pool import (
    NaiveAggregationPool,
    ObservedAggregates,
    ObservedAggregators,
    ObservedAttesters,
    ObservedBlockProducers,
    OperationPool,
)
from ..processor import BeaconProcessor, DeferredWork
from ..types import compute_epoch_at_slot, compute_fork_digest
from ..utils import metrics as M
from ..utils import tracing
from .message_bus import MessageBus, topic_name
from ..chain.sync_committee_verification import (
    ObservedSyncAggregators,
    ObservedSyncContributors,
    SyncContributionPool,
    SyncMessagePool,
    submit_contribution_batch,
    submit_sync_message_batch,
)

GOSSIP_PENALTY = -10
BAN_THRESHOLD = -50

STATUS_PROTOCOL = "/eth2/beacon_chain/req/status/1"
BLOCKS_BY_RANGE = "/eth2/beacon_chain/req/beacon_blocks_by_range/1"
BLOCKS_BY_ROOT = "/eth2/beacon_chain/req/beacon_blocks_by_root/1"
LIGHT_CLIENT_BOOTSTRAP = "/eth2/beacon_chain/req/light_client_bootstrap/1"


class NetworkNode:
    def __init__(
        self,
        peer_id: str,
        chain: BeaconChain,
        bus: MessageBus,
        subscribe_all_subnets: bool = True,
        op_pool=None,
        log=None,
    ):
        self.peer_id = peer_id
        self.chain = chain
        self.bus = bus
        # shared with the API node when the CLI wires one in; loads any
        # persisted operations either way (persistence.rs)
        self.op_pool = op_pool or OperationPool.load(
            chain.store, chain.preset, chain.spec, log=log
        )
        self.naive_pool = NaiveAggregationPool()
        self.observed_attesters = ObservedAttesters()
        self.observed_aggregates = ObservedAggregates()
        self.observed_aggregators = ObservedAggregators()
        self.observed_block_producers = ObservedBlockProducers()
        self.observed_sync_contributors = ObservedSyncContributors()
        self.observed_sync_aggregators = ObservedSyncAggregators()
        self.observed_contributions = ObservedAggregates()
        self.sync_message_pool = SyncMessagePool(chain.preset)
        self.sync_contribution_pool = SyncContributionPool(chain.preset)
        self.peer_scores: dict[str, int] = {}
        self.processor = BeaconProcessor(
            handlers={
                "gossip_block": self._work_block,
                "gossip_aggregate": self._work_aggregates,
                "gossip_attestation": self._work_attestations,
                "gossip_sync_message": self._work_sync_messages,
                "gossip_sync_contribution": self._work_sync_contributions,
            }
        )

        state = chain.head_state
        self.fork_digest = compute_fork_digest(
            bytes(state.fork.current_version),
            bytes(state.genesis_validators_root),
        )
        self._topic_block = topic_name("beacon_block", self.fork_digest)
        self._topic_aggregate = topic_name(
            "beacon_aggregate_and_proof", self.fork_digest
        )
        bus.subscribe(peer_id, self._topic_block, self._on_gossip_block)
        bus.subscribe(peer_id, self._topic_aggregate, self._on_gossip_aggregate)
        # attestation subnets: production nodes run the subnet service
        # (long-lived camping + duty subscriptions,
        # subnet_service/attestation_subnets.rs); simulators subscribe to
        # all 64 (the reference's --subscribe-all-subnets flag)
        self.subnet_service = None
        self.discovery = None
        if subscribe_all_subnets:
            for subnet in range(chain.spec.attestation_subnet_count):
                bus.subscribe(
                    peer_id,
                    topic_name("beacon_attestation", self.fork_digest, subnet),
                    self._on_gossip_attestation,
                )
        else:
            import hashlib

            from .subnet_service import AttestationSubnetService

            self.subnet_service = AttestationSubnetService(
                hashlib.sha256(peer_id.encode()).digest(),
                chain.preset,
                chain.spec,
                subscribe_cb=lambda subnet: bus.subscribe(
                    peer_id,
                    topic_name("beacon_attestation", self.fork_digest, subnet),
                    self._on_gossip_attestation,
                ),
                unsubscribe_cb=lambda subnet: bus.unsubscribe(
                    peer_id,
                    topic_name("beacon_attestation", self.fork_digest, subnet),
                ),
                enr_update_cb=None,
            )
            self.subnet_service.on_slot(chain.head_state.slot)
        self._topic_contribution = topic_name(
            "sync_committee_contribution_and_proof", self.fork_digest
        )
        bus.subscribe(
            peer_id, self._topic_contribution, self._on_gossip_contribution
        )
        # operation gossip topics (types/topics.rs: ProposerSlashing /
        # AttesterSlashing / VoluntaryExit pubsub kinds)
        self._topic_proposer_slashing = topic_name(
            "proposer_slashing", self.fork_digest
        )
        self._topic_attester_slashing = topic_name(
            "attester_slashing", self.fork_digest
        )
        self._topic_voluntary_exit = topic_name(
            "voluntary_exit", self.fork_digest
        )
        bus.subscribe(
            peer_id,
            self._topic_proposer_slashing,
            self._on_gossip_proposer_slashing,
        )
        bus.subscribe(
            peer_id,
            self._topic_attester_slashing,
            self._on_gossip_attester_slashing,
        )
        bus.subscribe(
            peer_id, self._topic_voluntary_exit, self._on_gossip_voluntary_exit
        )
        # per-epoch committees_per_slot memo for subnet computation
        self._committees_per_slot: dict[int, int] = {}
        # dedup for op gossip (observed_operations.rs): insertion-ordered
        # so the oldest half can be shed at the cap (the reference prunes
        # at finalization; a lifetime-unbounded set is a slow leak)
        self._seen_ops: dict[bytes, None] = {}
        self._seen_ops_cap = 8192
        # optional slasher (slasher/service/src/lib.rs); attach_slasher wires it
        self.slasher_service = None
        # gossip that outran its prerequisites waits here
        # (work_reprocessing_queue.rs). Deadlines ride the chain's SLOT
        # clock, not the wall clock, so the one-slot maturity window
        # advances with simulated time exactly as with real time.
        from ..processor.reprocess import ReprocessQueue
        from ..utils.timeout_lock import TimeoutRLock

        # serializes the four BATCH gossip lanes against each other (the
        # chain has its own lock; op/naive/sync pools and the observed-*
        # dedup caches are mutated inside batch_verify_* itself, so the
        # verify call cannot run outside the guard without splitting
        # dedup from verification). Block import — the long pole — runs
        # OUTSIDE this lock and overlaps every batch lane.
        self.pools_lock = TimeoutRLock("gossip_pools")

        sps = chain.spec.seconds_per_slot
        self.reprocess = ReprocessQueue(
            delay_s=float(sps),
            clock=lambda: chain.slot_clock.current_slot() * float(sps),
        )
        for subnet in range(chain.preset.sync_committee_subnet_count):
            bus.subscribe(
                peer_id,
                topic_name("sync_committee", self.fork_digest, subnet),
                self._make_sync_subnet_handler(subnet),
            )
        bus.register_rpc(peer_id, STATUS_PROTOCOL, self._rpc_status)
        bus.register_rpc(peer_id, BLOCKS_BY_RANGE, self._rpc_blocks_by_range)
        bus.register_rpc(peer_id, BLOCKS_BY_ROOT, self._rpc_blocks_by_root)
        bus.register_rpc(
            peer_id, LIGHT_CLIENT_BOOTSTRAP, self._rpc_light_client_bootstrap
        )

        from .sync import SyncManager

        self.sync_manager = SyncManager(self)

    # -- scoring (peerdb/score.rs) ------------------------------------------

    def penalize(self, peer: str, amount: int = GOSSIP_PENALTY) -> None:
        with self.pools_lock:
            self.peer_scores[peer] = self.peer_scores.get(peer, 0) + amount
        # feed the wire-level behavioral scorer too, severity-mapped:
        # full gossip penalties are P4 invalid-message events; mild -1
        # nudges (RPC timeouts, empty responses) are only a small P7
        # behaviour penalty — an honest-but-slow peer must not graylist
        scorer = getattr(self.bus, "scorer", None)
        if scorer is not None and peer:
            if amount <= GOSSIP_PENALTY:
                scorer.on_invalid(peer)
            elif amount < 0:
                scorer.on_behaviour_penalty(peer, 0.2)

    def is_banned(self, peer: str) -> bool:
        return self.peer_scores.get(peer, 0) <= BAN_THRESHOLD

    # -- gossip ingress (router -> processor queues) ------------------------

    def _on_gossip_block(self, signed_block, source: str) -> None:
        if self.is_banned(source):
            return
        block = signed_block.message
        # read-only exact-duplicate shedding against VERIFIED sightings;
        # recording happens post-signature-verification in the worker
        # (process_gossip_block), so an unverified forged block can never
        # suppress the real proposal (observe-after-verification pattern)
        known = self.observed_block_producers.known_root(
            block.slot, block.proposer_index
        )
        if known is not None and known == block.tree_hash_root():
            return
        # the trace's first event + the slot-relative observation delay
        # (reference beacon_block_delay_gossip): both ride injected clocks
        tracing.instant("gossip_block_rx", slot=int(block.slot))
        M.observe_slot_delay(
            M.BLOCK_OBSERVED_DELAY, self.chain.slot_clock, int(block.slot)
        )
        self.processor.submit("gossip_block", (signed_block, source))

    def _on_gossip_aggregate(self, signed_aggregate, source: str) -> None:
        if not self.is_banned(source):
            tracing.instant(
                "gossip_aggregate_rx",
                slot=int(signed_aggregate.message.aggregate.data.slot),
            )
            self.processor.submit("gossip_aggregate", (signed_aggregate, source))

    def _on_gossip_attestation(self, attestation, source: str) -> None:
        if not self.is_banned(source):
            tracing.instant(
                "gossip_attestation_rx", slot=int(attestation.data.slot)
            )
            self.processor.submit("gossip_attestation", (attestation, source))

    def _make_sync_subnet_handler(self, subnet: int):
        def handler(message, source: str) -> None:
            if not self.is_banned(source):
                self.processor.submit(
                    "gossip_sync_message", (message, subnet, source)
                )

        return handler

    def _on_gossip_contribution(self, signed_contribution, source: str) -> None:
        if not self.is_banned(source):
            self.processor.submit(
                "gossip_sync_contribution", (signed_contribution, source)
            )

    # -- slasher (slasher/service/src/lib.rs) -------------------------------

    def attach_slasher(self, slasher) -> None:
        """Run a slasher on this node: verified gossip feeds it, and its
        detections are pooled for block inclusion + broadcast on the
        slashing topics."""
        from ..slasher import SlasherService

        def broadcast(kind, op):
            topic = (
                self._topic_attester_slashing
                if kind == "attester_slashing"
                else self._topic_proposer_slashing
            )
            self._mark_op_seen(op.tree_hash_root())  # don't re-import our own
            self.bus.publish(self.peer_id, topic, op)

        self.slasher_service = SlasherService(
            slasher,
            self.op_pool,
            broadcast,
            fork_choice=self.chain.fork_choice,
        )

    def attach_discovery(self, disc) -> None:
        """Wire a DiscoveryService: subnet-service rotations advertise
        their long-lived subnets in the node's ENR attnets bits
        (discovery/enr.rs update flow)."""
        self.discovery = disc
        if self.subnet_service is not None:
            self.subnet_service.set_enr_update_cb(
                lambda subnets: disc.update_local_enr(attnets=subnets)
            )

    def on_slot(self) -> None:
        """Per-slot housekeeping (the reference's per-12s slasher batch)."""
        if self.slasher_service is not None:
            self.slasher_service.update()
        if self.subnet_service is not None:
            self.subnet_service.on_slot(self.chain.current_slot)
        # timed second chance for gossip still waiting on a block
        with self.pools_lock:
            due = list(self.reprocess.poll())
        for queue, item in due:
            self.processor.submit(queue, item)

    def _flush_reprocess(self, block_root: bytes) -> None:
        """A block imported: release gossip that was waiting for it."""
        with self.pools_lock:
            released = list(self.reprocess.on_block_imported(block_root))
        for queue, item in released:
            self.processor.submit(queue, item)

    # -- operation gossip (verify_operation.rs + observed_operations.rs) ---

    def _mark_op_seen(self, root: bytes) -> None:
        self._seen_ops[root] = None
        if len(self._seen_ops) > self._seen_ops_cap:
            for old in list(self._seen_ops)[: self._seen_ops_cap // 2]:
                del self._seen_ops[old]

    def _op_fresh(self, op) -> bool:
        root = op.tree_hash_root()
        if root in self._seen_ops:
            return False
        self._mark_op_seen(root)
        return True

    def _handle_op_gossip(self, op, source: str, validate, insert) -> None:
        """Shared op-gossip flow: dedup AFTER validation (the repo's
        observe-after-verification pattern -- a transiently-unverifiable op
        must be retryable on re-gossip), and distinguish ignore (our view
        is behind: no penalty) from reject (provably bad: penalize)."""
        root = op.tree_hash_root()
        if self.is_banned(source) or root in self._seen_ops:
            return
        from ..chain.pubkey_cache import PubkeyCacheError

        try:
            validate(op)
        except (KeyError, IndexError, PubkeyCacheError):
            return  # references state we don't have yet: ignore, may recur
        except ValueError:
            self.penalize(source)
            return
        self._mark_op_seen(root)
        insert(op)

    def _on_gossip_proposer_slashing(self, slashing, source: str) -> None:
        self._handle_op_gossip(
            slashing,
            source,
            self._validate_proposer_slashing,
            self.op_pool.insert_proposer_slashing,
        )

    def _on_gossip_attester_slashing(self, slashing, source: str) -> None:
        def accept(s):
            self.op_pool.insert_attester_slashing(s)
            # a proven equivocation also strips the equivocators'
            # fork-choice weight immediately (spec on_attester_slashing)
            self.chain.fork_choice.on_attester_slashing(s)
            if self.chain.validator_monitor is not None:
                common = set(s.attestation_1.attesting_indices) & set(
                    s.attestation_2.attesting_indices
                )
                self.chain.validator_monitor.on_slashing_observed(
                    [int(i) for i in common],
                    int(self.chain.current_slot)
                    // self.chain.preset.slots_per_epoch,
                )

        self._handle_op_gossip(
            slashing,
            source,
            self._validate_attester_slashing,
            accept,
        )

    def _on_gossip_voluntary_exit(self, signed_exit, source: str) -> None:
        def accept(e):
            self.op_pool.insert_voluntary_exit(e)
            if self.chain.validator_monitor is not None:
                self.chain.validator_monitor.on_exit_observed(
                    int(e.message.validator_index), int(e.message.epoch)
                )

        self._handle_op_gossip(
            signed_exit,
            source,
            self._validate_voluntary_exit,
            accept,
        )

    def _validate_proposer_slashing(self, slashing) -> None:
        from ..crypto.bls import verify_signature_sets
        from ..state_transition.signature_sets import (
            proposer_slashing_signature_sets,
        )

        h1 = slashing.signed_header_1.message
        h2 = slashing.signed_header_2.message
        if h1.slot != h2.slot or h1.proposer_index != h2.proposer_index:
            raise ValueError("headers not slashable")
        if h1.tree_hash_root() == h2.tree_hash_root():
            raise ValueError("identical headers")
        state = self.chain.head_state
        sets = proposer_slashing_signature_sets(
            state,
            self.chain.pubkey_cache.getter(state),
            slashing,
            self.chain.preset,
            self.chain.spec,
        )
        if not verify_signature_sets(sets):
            raise ValueError("bad proposer slashing signature")

    def _validate_attester_slashing(self, slashing) -> None:
        from ..crypto.bls import verify_signature_sets
        from ..state_transition.per_block import is_slashable_attestation_data
        from ..state_transition.signature_sets import (
            attester_slashing_signature_sets,
        )

        a1, a2 = slashing.attestation_1, slashing.attestation_2
        if not is_slashable_attestation_data(a1.data, a2.data):
            raise ValueError("attestation data not slashable")
        if not set(a1.attesting_indices) & set(a2.attesting_indices):
            raise ValueError("no common attesters")
        state = self.chain.head_state
        sets = attester_slashing_signature_sets(
            state,
            self.chain.pubkey_cache.getter(state),
            slashing,
            self.chain.preset,
            self.chain.spec,
        )
        if not verify_signature_sets(sets):
            raise ValueError("bad attester slashing signature")

    def _validate_voluntary_exit(self, signed_exit) -> None:
        """The FULL process_voluntary_exit precondition set (per_block.py):
        a validly-signed but premature exit must never reach the pool, or
        it bricks every subsequent pool-packed block."""
        from ..crypto.bls import verify_signature_sets
        from ..state_transition.signature_sets import exit_signature_set
        from ..types import FAR_FUTURE_EPOCH, is_active_validator

        state = self.chain.head_state
        msg = signed_exit.message
        epoch = compute_epoch_at_slot(state.slot, self.chain.preset)
        v = state.validators[msg.validator_index]
        if not is_active_validator(v, epoch):
            raise ValueError("exiting validator not active")
        if v.exit_epoch != FAR_FUTURE_EPOCH:
            raise ValueError("validator already exiting")
        if epoch < msg.epoch:
            raise ValueError("exit epoch in the future")
        if epoch < v.activation_epoch + self.chain.spec.shard_committee_period:
            raise ValueError("validator too young to exit")
        s = exit_signature_set(
            state,
            self.chain.pubkey_cache.getter(state),
            signed_exit,
            self.chain.preset,
            self.chain.spec,
        )
        if not verify_signature_sets([s]):
            raise ValueError("bad exit signature")

    # -- workers (worker/gossip_methods.rs) ---------------------------------

    def _work_block(self, item) -> None:
        signed_block, source = item
        from ..chain.block_verification import (
            BlockAlreadyKnown,
            BlockEquivocation,
            UnknownParent,
            process_gossip_block,
        )

        try:
            process_gossip_block(
                self.chain, signed_block, self.observed_block_producers
            )
        except BlockAlreadyKnown:
            return  # benign gossip/sync overlap: never penalized
        except BlockEquivocation:
            # a SIGNATURE-VALID second distinct block from the same
            # (slot, proposer): spec gossip validation IGNOREs it (no
            # penalty — the relayer may be honest), and it must not enter
            # fork choice through gossip. The slasher sees the verified
            # header: two conflicting headers from one proposer are
            # exactly a ProposerSlashing detection (the
            # equivocation-storm scenario's safety invariant).
            M.BLOCK_EQUIVOCATIONS.inc()
            if self.slasher_service is not None:
                self.slasher_service.accept_block(signed_block)
            return
        except UnknownParent as e:
            # chase the ANCESTRY we're missing (block_lookups/), then
            # import the block we already hold -- no refetch of it
            if self.sync_manager.lookup_block(e.parent_root):
                try:
                    process_gossip_block(
                        self.chain,
                        signed_block,
                        self.observed_block_producers,
                    )
                except BlockEquivocation:
                    M.BLOCK_EQUIVOCATIONS.inc()
                    if self.slasher_service is not None:
                        self.slasher_service.accept_block(signed_block)
                    return
                except BlockError:
                    self.penalize(source)
                    return
            else:
                self.penalize(source, -1)
                return
        except BlockError:
            self.penalize(source)
            return
        # mesh re-publication happens at the bus; nothing further here
        if self.slasher_service is not None:
            self.slasher_service.accept_block(signed_block)
        self._flush_reprocess(signed_block.message.tree_hash_root())

    def _work_aggregates(self, items):
        """Submit the batch (marshal + device dispatch) under the pools
        lock, hand the processor a DeferredWork: the worker is free to
        form the next batch while the device verifies this one."""
        aggs = [a for a, _ in items]
        sources = {id(a): s for a, s in items}
        with self.pools_lock:
            pending = submit_aggregate_batch(
                self.chain,
                aggs,
                self.observed_aggregates,
                self.observed_aggregators,
            )

        def complete():
            with self.pools_lock:
                verified, rejected = pending.complete()
                self._apply_aggregate_results(verified, rejected, sources)

        return DeferredWork(pending.done, complete)

    def _apply_aggregate_results(self, verified, rejected, sources) -> None:
        for v in verified:
            self.op_pool.insert_attestation(v.signed_aggregate.message.aggregate)
            self.chain.apply_attestation(
                v.signed_aggregate.message.aggregate, v.indexed_indices
            )
            if self.slasher_service is not None:
                self.slasher_service.accept_attestation(v.indexed)
        for agg, reason in rejected:
            if "signature" in reason or "selection" in reason:
                self.penalize(sources.get(id(agg), ""))
            elif "unknown head block" in reason:
                self.reprocess.defer(
                    "gossip_aggregate",
                    (agg, sources.get(id(agg), "")),
                    bytes(agg.message.aggregate.data.beacon_block_root),
                    agg.tree_hash_root(),
                )

    def _work_attestations(self, items):
        atts = [a for a, _ in items]
        sources = {id(a): s for a, s in items}
        with self.pools_lock:
            pending = submit_unaggregated_batch(
                self.chain, atts, self.observed_attesters
            )

        def complete():
            with self.pools_lock:
                verified, rejected = pending.complete()
                self._apply_attestation_results(verified, rejected, sources)

        return DeferredWork(pending.done, complete)

    def _apply_attestation_results(self, verified, rejected, sources) -> None:
        for v in verified:
            self.naive_pool.insert(v.attestation)
            self.op_pool.insert_attestation(v.attestation)
            self.chain.apply_attestation(v.attestation, v.indexed_indices)
            if self.slasher_service is not None:
                self.slasher_service.accept_attestation(v.indexed)
        for att, reason in rejected:
            if "signature" in reason:
                self.penalize(sources.get(id(att), ""))
            elif "unknown head block" in reason:
                self.reprocess.defer(
                    "gossip_attestation",
                    (att, sources.get(id(att), "")),
                    bytes(att.data.beacon_block_root),
                    att.tree_hash_root(),
                )

    def _work_sync_messages(self, items):
        """Same deferred shape as the attestation lanes: submit under the
        pools lock, let the worker form the next batch while the device
        verifies this one (the sync lane of the continuous-batching
        scheduler when it is enabled)."""
        msgs = [(m, subnet) for m, subnet, _ in items]
        sources = {id(m): s for m, _, s in items}
        with self.pools_lock:
            pending = submit_sync_message_batch(
                self.chain, msgs, self.observed_sync_contributors
            )

        def complete():
            with self.pools_lock:
                verified, rejected = pending.complete()
                for v in verified:
                    self.sync_message_pool.insert(v)
                    if self.chain.validator_monitor is not None:
                        self.chain.validator_monitor.on_sync_committee_message(
                            int(v.message.validator_index),
                            int(v.message.slot),
                        )
                for msg, reason in rejected:
                    if "signature" in reason:
                        self.penalize(sources.get(id(msg), ""))

        return DeferredWork(pending.done, complete)

    def _work_sync_contributions(self, items):
        contributions = [c for c, _ in items]
        sources = {id(c): s for c, s in items}
        with self.pools_lock:
            pending = submit_contribution_batch(
                self.chain,
                contributions,
                self.observed_sync_aggregators,
                self.observed_contributions,
            )

        def complete():
            with self.pools_lock:
                verified, rejected = pending.complete()
                for v in verified:
                    self.sync_contribution_pool.insert(v)
                for c, reason in rejected:
                    if "signature" in reason or "selection" in reason:
                        self.penalize(sources.get(id(c), ""))

        return DeferredWork(pending.done, complete)

    # -- publish (the local node's own messages) ----------------------------

    def publish_block(self, signed_block) -> None:
        # record our OWN proposal in the equivocation filter: without
        # this, a Byzantine double-proposal gossiped back at the
        # proposer's node would count as a first sighting and import
        block = signed_block.message
        self.observed_block_producers.observe(
            block.slot, block.proposer_index, block.tree_hash_root()
        )
        self.chain.process_block(signed_block)
        if self.slasher_service is not None:
            self.slasher_service.accept_block(signed_block)
        self._flush_reprocess(signed_block.message.tree_hash_root())
        self.bus.publish(self.peer_id, self._topic_block, signed_block)

    def publish_voluntary_exit(self, signed_exit) -> None:
        self._op_fresh(signed_exit)
        self.op_pool.insert_voluntary_exit(signed_exit)
        self.bus.publish(self.peer_id, self._topic_voluntary_exit, signed_exit)

    def subnet_for_attestation(self, attestation) -> int:
        """The spec subnet for an attestation's (slot, committee index),
        from the head state's committee count. The count is memoized per
        epoch (one shuffle, not one per publish), and epochs beyond the
        head state's computable range clamp to head+1 -- the committee
        COUNT tracks the active-validator set, which is what a lagging
        head can still answer."""
        data = attestation.data
        epoch = compute_epoch_at_slot(data.slot, self.chain.preset)
        count = self._committees_per_slot.get(epoch)
        if count is None:
            from ..state_transition import ConsensusContext

            state = self.chain.head_state
            state_epoch = compute_epoch_at_slot(state.slot, self.chain.preset)
            cache = ConsensusContext(
                self.chain.preset, self.chain.spec
            ).committee_cache(state, min(epoch, state_epoch + 1))
            count = cache.committees_per_slot
            if len(self._committees_per_slot) > 8:
                self._committees_per_slot.clear()
            self._committees_per_slot[epoch] = count
        from .subnet_service import compute_subnet_for_attestation

        return compute_subnet_for_attestation(
            count,
            data.slot,
            data.index,
            self.chain.preset,
            self.chain.spec,
        )

    def publish_attestation(self, attestation, subnet: int | None = None) -> None:
        if subnet is None:
            subnet = self.subnet_for_attestation(attestation)
        self.naive_pool.insert(attestation)
        self.op_pool.insert_attestation(attestation)
        self.bus.publish(
            self.peer_id,
            topic_name("beacon_attestation", self.fork_digest, subnet),
            attestation,
        )

    def publish_sync_message(self, message, subnet: int = 0) -> None:
        self.processor.submit(
            "gossip_sync_message", (message, subnet, self.peer_id)
        )
        self.bus.publish(
            self.peer_id,
            topic_name("sync_committee", self.fork_digest, subnet),
            message,
        )

    def publish_sync_contribution(self, signed_contribution) -> None:
        self.processor.submit(
            "gossip_sync_contribution", (signed_contribution, self.peer_id)
        )
        self.bus.publish(
            self.peer_id, self._topic_contribution, signed_contribution
        )

    def publish_aggregate(self, signed_aggregate) -> None:
        self.op_pool.insert_attestation(signed_aggregate.message.aggregate)
        self.bus.publish(self.peer_id, self._topic_aggregate, signed_aggregate)

    # -- req/resp handlers (rpc/protocol.rs) --------------------------------

    def _rpc_status(self, _payload, _peer):
        head_root, head_state = self.chain.head()
        return {
            "fork_digest": self.fork_digest,
            "finalized_epoch": self.chain.finalized_checkpoint[0],
            "finalized_root": self.chain.finalized_checkpoint[1],
            "head_root": head_root,
            "head_slot": head_state.slot,
        }

    def _rpc_blocks_by_range(self, payload, _peer):
        start = payload["start_slot"]
        count = min(payload["count"], 64)  # rpc/rate_limiter.rs quota cap
        out = []
        # walk the canonical chain from head backwards through the STORE
        # (not the in-memory state map) so finalized/backfilled history
        # below the pruning boundary is served too
        root = self.chain.head_root
        chain = []
        while True:
            blk = self.chain.store.get_block_any_temperature(root)
            if blk is None:
                break
            if blk.message.slot < start:
                break
            chain.append(blk)
            root = bytes(blk.message.parent_root)
            if not any(root):
                break
        for blk in reversed(chain):
            if start <= blk.message.slot < start + count:
                out.append(blk)
        return out

    def _rpc_blocks_by_root(self, payload, _peer):
        out = []
        for root in payload["roots"]:
            blk = self.chain.store.get_block_any_temperature(root)
            if blk is not None:
                out.append(blk)
        return out

    def _rpc_light_client_bootstrap(self, payload, _peer):
        """LightClientBootstrap req/resp (rpc/protocol.rs:156): serve the
        bootstrap for a requested block root."""
        from ..chain.light_client import (
            LightClientError,
            light_client_bootstrap,
        )

        state = self.chain.state_for_block_root(bytes(payload["root"]))
        if state is None:
            raise ValueError("unknown block root")
        try:
            return light_client_bootstrap(state, self.chain.preset)
        except LightClientError as e:
            raise ValueError(str(e)) from None

    # -- sync (sync/manager.rs + range_sync + backfill_sync) ----------------

    def sync_with(self, peer: str) -> int:
        """Single-peer forward range sync (kept for callers that target a
        specific peer; multi-peer logic lives in SyncManager)."""
        return self.sync_manager.sync_from(peer)

    def range_sync(self) -> int:
        return self.sync_manager.range_sync()

    def backfill_sync(self) -> int:
        return self.sync_manager.backfill_sync()
