"""Pure-Python snappy block format (the `ssz_snappy` wire encoding of
reference lighthouse_network — rpc/codec/ssz_snappy.rs and gossip
compression in types/pubsub.rs).

The environment ships no snappy binding, so this implements the snappy
block format (github.com/google/snappy/blob/main/format_description.txt)
directly: `compress` emits a valid stream using literal tokens plus
greedy hash-matched copies; `decompress` handles the full tag set
(literals + 1/2/4-byte-offset copies), so streams from other snappy
implementations decode too. Wire-compatible, dependency-free."""

from __future__ import annotations


def _varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("snappy: truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 35:
            raise ValueError("snappy: varint too long")


def _emit_literal(out: bytearray, chunk: bytes) -> None:
    n = len(chunk) - 1
    if n < 60:
        out.append(n << 2)
    elif n < (1 << 8):
        out.append(60 << 2)
        out.append(n)
    elif n < (1 << 16):
        out.append(61 << 2)
        out += n.to_bytes(2, "little")
    elif n < (1 << 24):
        out.append(62 << 2)
        out += n.to_bytes(3, "little")
    else:
        out.append(63 << 2)
        out += n.to_bytes(4, "little")
    out += chunk


def _emit_copy(out: bytearray, offset: int, length: int) -> None:
    # prefer copy-with-2-byte-offset (tag 10); split long matches
    while length > 0:
        chunk = min(length, 64)
        if chunk < 4:
            # tags can't express length < 4 with 2-byte offset cleanly
            # when splitting; back off so the remainder is >= 4
            chunk = length
            if chunk < 4:
                break
        out.append(0b10 | ((chunk - 1) << 2) & 0xFF)
        out += offset.to_bytes(2, "little")
        length -= chunk
    return


def compress(data: bytes) -> bytes:
    """Greedy hash-table matcher (the format's reference strategy):
    4-byte hashes, literals between matches."""
    data = bytes(data)
    out = bytearray(_varint(len(data)))
    n = len(data)
    if n == 0:
        return bytes(out)
    table: dict[bytes, int] = {}
    pos = 0
    literal_start = 0
    while pos + 4 <= n:
        key = data[pos : pos + 4]
        cand = table.get(key)
        table[key] = pos
        if cand is not None and pos - cand <= 0xFFFF:
            # extend the match forward
            length = 4
            while (
                pos + length < n
                and data[cand + length] == data[pos + length]
                and length < 64
            ):
                length += 1
            if literal_start < pos:
                _emit_literal(out, data[literal_start:pos])
            _emit_copy(out, pos - cand, length)
            pos += length
            literal_start = pos
        else:
            pos += 1
    if literal_start < n:
        _emit_literal(out, data[literal_start:])
    return bytes(out)


def decompress(data: bytes) -> bytes:
    expected, pos = _read_varint(bytes(data), 0)
    out = bytearray()
    data = bytes(data)
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 0b11
        if kind == 0b00:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                if pos + extra > n:
                    raise ValueError("snappy: truncated literal length")
                length = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            if pos + length > n:
                raise ValueError("snappy: truncated literal")
            out += data[pos : pos + length]
            pos += length
            continue
        if kind == 0b01:  # copy, 1-byte offset
            length = ((tag >> 2) & 0x7) + 4
            if pos >= n:
                raise ValueError("snappy: truncated copy1")
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 0b10:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            if pos + 2 > n:
                raise ValueError("snappy: truncated copy2")
            offset = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            if pos + 4 > n:
                raise ValueError("snappy: truncated copy4")
            offset = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise ValueError("snappy: invalid copy offset")
        # overlapping copies are byte-by-byte by definition
        for _ in range(length):
            out.append(out[-offset])
    if len(out) != expected:
        raise ValueError(
            f"snappy: length mismatch (got {len(out)}, want {expected})"
        )
    return bytes(out)
