"""In-process network fabric (the transport seat of reference
beacon_node/lighthouse_network's libp2p stack, exercised the way the
reference tests distribution: testing/simulator spawns N in-process nodes
on one runtime, node_test_rig/src/lib.rs:32-60 -- not a real cluster).

`MessageBus` provides gossipsub-shaped topics (fork-digest namespaced,
types/topics.rs) with per-peer subscriptions and delivery journals, plus
direct req/resp channels (the rpc/ protocols). A real libp2p wire backend
can replace the bus behind the same Router-facing API; ICI/DCN enters only
for intra-pod signature-batch sharding (SURVEY.md section 5)."""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field


def topic_name(kind: str, fork_digest: bytes, subnet: int | None = None) -> str:
    """Gossip topic naming (reference types/topics.rs):
    /eth2/<fork_digest>/<kind>[_<subnet>]/ssz_snappy."""
    base = f"/eth2/{fork_digest.hex()}/{kind}"
    if subnet is not None:
        base += f"_{subnet}"
    return base + "/ssz_snappy"


@dataclass
class GossipMessage:
    topic: str
    payload: object
    source_peer: str


class MessageBus:
    """Broadcast plane + req/resp plane for in-process multi-node tests.

    Supports transport-level network splits (the scenario harness's
    partition phases): while a partition map is installed, gossip only
    delivers and req/resp only connects between peers in the SAME group;
    cross-group requests raise ``ConnectionError`` exactly like a dead
    TCP route, so sync's retry/penalty machinery runs for real. Peers
    absent from the map sit in the default group (healed side)."""

    def __init__(self):
        self._subs: dict[str, dict[str, object]] = defaultdict(dict)
        self._rpc_handlers: dict[str, dict[str, object]] = defaultdict(dict)
        self.published: list[GossipMessage] = []
        # peer -> partition group id; empty dict == fully connected
        self._groups: dict[str, int] = {}

    # -- partitions (scenario harness: bus-level split + heal) ---------------

    def set_partitions(self, groups) -> None:
        """Install a network split: ``groups`` is an iterable of peer-id
        collections; peers in different collections cannot reach each
        other. Replaces any previous split."""
        self._groups = {}
        for gid, peers in enumerate(groups):
            for peer in peers:
                self._groups[peer] = gid

    def heal(self) -> None:
        """Remove the split: every peer reaches every peer again."""
        self._groups = {}

    def partitioned(self) -> bool:
        return bool(self._groups)

    def join_group(self, peer_id: str, like_peer: str) -> None:
        """Place `peer_id` in the same partition group as `like_peer`
        (a Byzantine injector must share its victims' side of a split to
        reach them); no-op while the bus is unpartitioned."""
        if not self._groups:
            return
        gid = self._groups.get(like_peer)
        if gid is None:
            self._groups.pop(peer_id, None)
        else:
            self._groups[peer_id] = gid

    def reachable(self, a: str, b: str) -> bool:
        if not self._groups:
            return True
        return self._groups.get(a, -1) == self._groups.get(b, -1)

    # -- node lifecycle (scenario harness: churn + crash) --------------------

    def disconnect(self, peer_id: str) -> None:
        """Drop a peer entirely: all topic subscriptions and rpc
        registrations (node leave / simulated process death). A later
        re-subscribe under the same peer id rejoins cleanly."""
        for subs in self._subs.values():
            subs.pop(peer_id, None)
        for handlers in self._rpc_handlers.values():
            handlers.pop(peer_id, None)
        self._groups.pop(peer_id, None)

    # -- gossip --------------------------------------------------------------

    def subscribe(self, peer_id: str, topic: str, handler) -> None:
        self._subs[topic][peer_id] = handler

    def unsubscribe(self, peer_id: str, topic: str) -> None:
        self._subs[topic].pop(peer_id, None)

    def publish(self, source_peer: str, topic: str, payload) -> int:
        """Deliver to every reachable subscriber except the source;
        returns the delivery count (gossipsub loopback exclusion)."""
        self.published.append(GossipMessage(topic, payload, source_peer))
        delivered = 0
        for peer_id, handler in list(self._subs.get(topic, {}).items()):
            if peer_id == source_peer:
                continue
            if not self.reachable(source_peer, peer_id):
                continue
            handler(payload, source_peer)
            delivered += 1
        return delivered

    # -- req/resp (rpc/) -----------------------------------------------------

    def register_rpc(self, peer_id: str, protocol: str, handler) -> None:
        self._rpc_handlers[protocol][peer_id] = handler

    def request(self, from_peer: str, to_peer: str, protocol: str, payload):
        if not self.reachable(from_peer, to_peer):
            raise ConnectionError(
                f"peer {to_peer} unreachable from {from_peer} (partition)"
            )
        handler = self._rpc_handlers.get(protocol, {}).get(to_peer)
        if handler is None:
            raise ConnectionError(
                f"peer {to_peer} does not speak {protocol}"
            )
        return handler(payload, from_peer)

    def peers_on(self, topic: str) -> list[str]:
        return list(self._subs.get(topic, {}).keys())
