"""In-process network fabric (the transport seat of reference
beacon_node/lighthouse_network's libp2p stack, exercised the way the
reference tests distribution: testing/simulator spawns N in-process nodes
on one runtime, node_test_rig/src/lib.rs:32-60 -- not a real cluster).

`MessageBus` provides gossipsub-shaped topics (fork-digest namespaced,
types/topics.rs) with per-peer subscriptions and delivery journals, plus
direct req/resp channels (the rpc/ protocols). A real libp2p wire backend
can replace the bus behind the same Router-facing API; ICI/DCN enters only
for intra-pod signature-batch sharding (SURVEY.md section 5)."""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field


def topic_name(kind: str, fork_digest: bytes, subnet: int | None = None) -> str:
    """Gossip topic naming (reference types/topics.rs):
    /eth2/<fork_digest>/<kind>[_<subnet>]/ssz_snappy."""
    base = f"/eth2/{fork_digest.hex()}/{kind}"
    if subnet is not None:
        base += f"_{subnet}"
    return base + "/ssz_snappy"


@dataclass
class GossipMessage:
    topic: str
    payload: object
    source_peer: str


class MessageBus:
    """Broadcast plane + req/resp plane for in-process multi-node tests."""

    def __init__(self):
        self._subs: dict[str, dict[str, object]] = defaultdict(dict)
        self._rpc_handlers: dict[str, dict[str, object]] = defaultdict(dict)
        self.published: list[GossipMessage] = []

    # -- gossip --------------------------------------------------------------

    def subscribe(self, peer_id: str, topic: str, handler) -> None:
        self._subs[topic][peer_id] = handler

    def unsubscribe(self, peer_id: str, topic: str) -> None:
        self._subs[topic].pop(peer_id, None)

    def publish(self, source_peer: str, topic: str, payload) -> int:
        """Deliver to every subscriber except the source; returns the
        delivery count (gossipsub loopback exclusion)."""
        self.published.append(GossipMessage(topic, payload, source_peer))
        delivered = 0
        for peer_id, handler in list(self._subs.get(topic, {}).items()):
            if peer_id == source_peer:
                continue
            handler(payload, source_peer)
            delivered += 1
        return delivered

    # -- req/resp (rpc/) -----------------------------------------------------

    def register_rpc(self, peer_id: str, protocol: str, handler) -> None:
        self._rpc_handlers[protocol][peer_id] = handler

    def request(self, from_peer: str, to_peer: str, protocol: str, payload):
        handler = self._rpc_handlers.get(protocol, {}).get(to_peer)
        if handler is None:
            raise ConnectionError(
                f"peer {to_peer} does not speak {protocol}"
            )
        return handler(payload, from_peer)

    def peers_on(self, topic: str) -> list[str]:
        return list(self._subs.get(topic, {}).keys())
