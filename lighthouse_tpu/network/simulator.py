"""Multi-node in-process simulator (reference testing/simulator/src/main.rs
+ checks.rs + node_test_rig: N beacon nodes + validator shares on one
runtime, liveness/finality invariants asserted as slots progress)."""

from __future__ import annotations

from ..harness.chain import StateHarness
from ..chain.beacon_chain import BeaconChain
from ..store.hot_cold import HotColdDB
from ..store.kv import MemoryStore
from ..types import ChainSpec, compute_epoch_at_slot, interop_genesis_state
from ..types.presets import Preset
from .message_bus import MessageBus
from .node import NetworkNode


class Simulator:
    def __init__(
        self,
        node_count: int,
        validator_count: int,
        preset: Preset,
        spec: ChainSpec | None = None,
        fault_plan=None,
    ):
        self.preset = preset
        self.spec = spec or ChainSpec.interop()
        self.bus = MessageBus()
        self.fault_plan = fault_plan
        if fault_plan is not None:
            # chaos mode: every node talks to the bus through the seeded
            # FaultPlan (resilience/faults.py), so req/resp calls see
            # deterministic injected transport faults -- the sync
            # retry/penalty paths run for real instead of only on
            # hand-scripted broken handlers. Only `request` is faulted:
            # req/resp is where the retry machinery lives.
            self.bus = fault_plan.wrap(self.bus, "bus", methods=("request",))
        self.producer = StateHarness(
            validator_count, preset, self.spec, sign=False
        )
        genesis = self.producer.state
        self.nodes: list[NetworkNode] = []
        for i in range(node_count):
            from ..state_transition import clone_state

            store = HotColdDB(MemoryStore(), preset, self.spec)
            chain = BeaconChain(store, clone_state(genesis), preset, self.spec)
            self.nodes.append(NetworkNode(f"node{i}", chain, self.bus))
        # validator shares: validator v is driven through node v % N
        self.validator_count = validator_count

    def tick(self, slot: int) -> None:
        for n in self.nodes:
            n.chain.slot_clock.set_slot(slot)
            n.chain.on_tick()
            n.on_slot()  # slasher batch + other per-slot services

    def run_slot(self, slot: int, attest: bool = True) -> None:
        """One slot of the synthetic network: the proposer's node produces
        and gossips a block; every node's processor drains; attestations
        for the previous slot ride the subnets."""
        self.tick(slot)
        proposer_node = self.nodes[slot % len(self.nodes)]
        parent_state = proposer_node.chain._states[
            proposer_node.chain.head_root
        ]
        atts = []
        if attest and slot > 1:
            from ..state_transition import clone_state, process_slots

            adv = process_slots(
                clone_state(parent_state), slot, self.preset, self.spec
            )
            atts = self.producer.attestations_for_slot(adv, slot - 1)
        signed, _ = self.producer.produce_block(
            slot, atts, base_state=parent_state
        )
        proposer_node.publish_block(signed)
        self.drain()

    def drain(self) -> None:
        for n in self.nodes:
            n.processor.run_until_idle()

    def run_epochs(self, epochs: int, attest: bool = True) -> None:
        start = (
            max(n.chain.head_state.slot for n in self.nodes) + 1
        )
        for slot in range(start, start + epochs * self.preset.slots_per_epoch):
            self.run_slot(slot, attest=attest)

    # -- checks (testing/simulator/src/checks.rs) ---------------------------

    def check_all_heads_equal(self) -> bytes:
        heads = {n.chain.head_root for n in self.nodes}
        assert len(heads) == 1, f"nodes diverged: {len(heads)} heads"
        return heads.pop()

    def check_finality(self, min_epoch: int) -> None:
        for n in self.nodes:
            assert n.chain.finalized_checkpoint[0] >= min_epoch, (
                f"{n.peer_id} finalized {n.chain.finalized_checkpoint[0]}"
                f" < {min_epoch}"
            )
