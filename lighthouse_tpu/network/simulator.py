"""Multi-node in-process simulator (reference testing/simulator/src/main.rs
+ checks.rs + node_test_rig: N beacon nodes + validator shares on one
runtime, liveness/finality invariants asserted as slots progress).

Grown into the scenario harness's substrate (harness/scenario.py): the
bus supports transport-level partitions, nodes join/leave/crash/reopen
mid-run, validators are HOMED on nodes (a partitioned or offline node's
validators stop proposing and attesting — the realistic stake split),
and block production is per-partition-group so each side of a split
extends its own fork. Everything stays deterministic: same seed, same
schedule, same heads, bit-identical trace export."""

from __future__ import annotations

from ..chain.beacon_chain import BeaconChain
from ..harness.chain import StateHarness
from ..resilience.crash import CrashingStore, InjectedCrash
from ..store.hot_cold import HotColdDB
from ..store.kv import MemoryStore
from ..types import ChainSpec
from ..types.presets import Preset
from .message_bus import MessageBus
from .node import NetworkNode


class Simulator:
    def __init__(
        self,
        node_count: int,
        validator_count: int,
        preset: Preset,
        spec: ChainSpec | None = None,
        fault_plan=None,
        crash_plans: dict | None = None,
        attach_slashers: bool = False,
        migration_chunk_slots: int | None = None,
        speculate: bool = False,
        bus=None,
    ):
        self.preset = preset
        self.spec = spec or ChainSpec.interop()
        # transport seat: the default in-process MessageBus, or an
        # injected bus-compatible fabric (harness wire-transport mode
        # runs the same plans over WireBus sockets via WireFabric)
        self.raw_bus = bus if bus is not None else MessageBus()
        if hasattr(self.raw_bus, "_bind_preset"):
            self.raw_bus._bind_preset(preset)
        self.bus = self.raw_bus
        self.fault_plan = fault_plan
        if fault_plan is not None:
            # chaos mode: every node talks to the bus through the seeded
            # FaultPlan (resilience/faults.py), so req/resp calls see
            # deterministic injected transport faults -- the sync
            # retry/penalty paths run for real instead of only on
            # hand-scripted broken handlers. Only `request` is faulted:
            # req/resp is where the retry machinery lives.
            self.bus = fault_plan.wrap(self.raw_bus, "bus", methods=("request",))
        self.producer = StateHarness(
            validator_count, preset, self.spec, sign=False
        )
        self.genesis = self.producer.state
        self.validator_count = validator_count
        self.attach_slashers = attach_slashers
        self.migration_chunk_slots = migration_chunk_slots
        # duty-driven precompute on every node: the scenario-level knob
        # that proves reorg invalidation + metric sanity under storms
        self.speculate = speculate
        # seeded per-node crash schedules: node index -> CrashPlan; the
        # node's kv routes every mutation through CrashingStore so an
        # armed plan kills "the process" at exactly the Nth store op
        self.crash_plans = dict(crash_plans or {})
        self.nodes: list[NetworkNode] = []
        self.dead: list[NetworkNode] = []
        self._next_index = 0
        # storm artifacts the invariant checker audits: roots that must
        # NEVER be imported by an honest node via gossip
        self.equivocation_roots: list[bytes] = []
        self.forged_roots: list[bytes] = []
        # Byzantine validator clients (validator_client/byzantine.py):
        # a per-phase roster of homed validators whose duties run through
        # a slashing-protection-bypassing store. Counters tally EMITTED
        # slashable messages; overrides accumulate the protection layer's
        # refusals across phase rosters.
        self.byz = None
        self.byz_counts = {
            "double_proposals": 0,
            "conflicting_vote_pairs": 0,
            "surround_votes": 0,
            "equivocating_aggregates": 0,
            "honest_votes_gossiped": 0,
        }
        self.byz_overrides: list[tuple[str, int, str]] = []
        # tree roots of every byz-emitted aggregate ATTESTATION: the
        # speculation layer must never confirm one of these by lookup
        self.byz_aggregate_roots: list[bytes] = []
        # group -> homed validators is recomputed per group per slot;
        # the scan is O(validators) and dominated hundred-node profiles
        # (tools/scenario_profile.py), so memoize on the group's peer set
        self._group_validators_cache: dict[frozenset, set[int]] = {}
        # current split as node groups (None = fully connected)
        self._partition: list[list[NetworkNode]] | None = None
        for _ in range(node_count):
            self.add_node()
        # validator shares: validator v is HOMED on node v % N (it
        # proposes/attests only while that node is alive and connected)
        self.validator_home = {
            v: self.nodes[v % node_count].peer_id
            for v in range(validator_count)
        }

    # -- node lifecycle (churn / crash-recovery) -----------------------------

    def add_node(self, peer_id: str | None = None) -> NetworkNode:
        """A fresh node from genesis joining the bus (churn join: it must
        range-sync to catch up). Homed validators are only assigned at
        construction — later joiners carry no stake, like a new peer."""
        from ..state_transition import clone_state

        index = self._next_index
        self._next_index += 1
        kv = MemoryStore()
        plan = self.crash_plans.get(index)
        if plan is not None:
            kv = CrashingStore(kv, plan)
        store = HotColdDB(
            kv,
            self.preset,
            self.spec,
            migration_chunk_slots=self.migration_chunk_slots,
        )
        chain = BeaconChain(
            store, clone_state(self.genesis), self.preset, self.spec
        )
        if self.speculate:
            from ..speculate import attach_speculation

            attach_speculation(chain)
        node = NetworkNode(peer_id or f"node{index}", chain, self.bus)
        node.sim_index = index
        if self.attach_slashers:
            from ..slasher import Slasher

            node.attach_slasher(
                Slasher.open(MemoryStore(), self.preset, self.spec)
            )
        self.nodes.append(node)
        return node

    def remove_node(self, node: NetworkNode) -> None:
        """Peer leave: drop every subscription and rpc registration; its
        homed validators go silent until it rejoins."""
        self.raw_bus.disconnect(node.peer_id)
        if node in self.nodes:
            self.nodes.remove(node)
        self.dead.append(node)

    def rejoin_node(self, node: NetworkNode) -> NetworkNode:
        """A previously-removed node rejoins with its existing chain
        (fresh NetworkNode so all subscriptions re-register); it must
        range-sync to catch up. The old node's slasher (with its
        accumulated detection history) rides along."""
        fresh = NetworkNode(node.peer_id, node.chain, self.bus)
        fresh.sim_index = getattr(node, "sim_index", -1)
        if node.slasher_service is not None:
            fresh.attach_slasher(node.slasher_service.slasher)
        elif self.attach_slashers:
            from ..slasher import Slasher

            fresh.attach_slasher(
                Slasher.open(MemoryStore(), self.preset, self.spec)
            )
        if node in self.dead:
            self.dead.remove(node)
        self.nodes.append(fresh)
        self._replace_in_partition(node, fresh)
        return fresh

    def mark_dead(self, node: NetworkNode) -> None:
        """A node's simulated process died (InjectedCrash): it vanishes
        from the network mid-flight; reopen_node resurrects it."""
        self.remove_node(node)

    def _replace_in_partition(self, old: NetworkNode, new: NetworkNode) -> None:
        """A reopened/rejoined node takes the old object's seat in any
        installed split (group membership is by node object and, on the
        bus, by peer id — disconnect dropped both): partition and
        crash/churn knobs must compose, not silently isolate the node."""
        if self._partition is None:
            return
        for group in self._partition:
            if old in group:
                group[group.index(old)] = new
        self.raw_bus.set_partitions(
            [[n.peer_id for n in g] for g in self._partition]
        )

    def reopen_node(self, node: NetworkNode) -> NetworkNode:
        """Simulated process restart after a crash: reopen the dead
        node's kv the way a restarted process would (HotColdDB open runs
        write-ahead-journal recovery), resume FromStore, rejoin the bus
        under the same peer id. The caller range-syncs it afterwards.
        The CrashingStore wrapper (with its spent plan) is KEPT around
        the reopened store: re-arming the plan in a later phase models a
        node that dies again."""
        kv = node.chain.store.kv
        if isinstance(kv, CrashingStore):
            # the spent plan passes everything through until re-armed;
            # recovery's own writes therefore never re-crash
            kv = CrashingStore(kv.inner, kv.plan)
        store = HotColdDB(
            kv,
            self.preset,
            self.spec,
            migration_chunk_slots=self.migration_chunk_slots,
        )
        chain = BeaconChain.from_store(store, self.preset, self.spec)
        if self.speculate:
            from ..speculate import attach_speculation

            attach_speculation(chain)
        fresh = NetworkNode(node.peer_id, chain, self.bus)
        fresh.sim_index = getattr(node, "sim_index", -1)
        if self.attach_slashers:
            from ..slasher import Slasher

            fresh.attach_slasher(
                Slasher.open(MemoryStore(), self.preset, self.spec)
            )
        if node in self.dead:
            self.dead.remove(node)
        self.nodes.append(fresh)
        self._replace_in_partition(node, fresh)
        return fresh

    # -- partitions ----------------------------------------------------------

    def partition(self, groups) -> None:
        """Split the bus: `groups` is a list of node-index lists. Nodes in
        different groups cannot gossip or req/resp each other until
        heal(). Production becomes per-group: each side extends its own
        fork with its own homed validators."""
        node_groups = [[self.nodes[i] for i in g] for g in groups]
        self._partition = node_groups
        self.raw_bus.set_partitions(
            [[n.peer_id for n in g] for g in node_groups]
        )

    def heal(self) -> None:
        self._partition = None
        self.raw_bus.heal()

    def _node_groups(self) -> list[list[NetworkNode]]:
        if self._partition is None:
            return [list(self.nodes)] if self.nodes else []
        # drop nodes that died/left since the split was installed
        groups = [
            [n for n in g if n in self.nodes] for g in self._partition
        ]
        return [g for g in groups if g]

    def _group_validators(self, group) -> set[int]:
        """Validators homed on this group's peers. Cached per peer set
        (validator_home is fixed at construction); callers must treat
        the result as read-only."""
        peers = frozenset(n.peer_id for n in group)
        cached = self._group_validators_cache.get(peers)
        if cached is None:
            cached = {
                v for v, home in self.validator_home.items() if home in peers
            }
            self._group_validators_cache[peers] = cached
        return cached

    # -- Byzantine validator clients (validator_client/byzantine.py) ---------

    def set_byz_plan(self, plan, rng) -> None:
        """Install a fresh Byzantine roster for a phase: sample
        `plan.fraction` of each node's HOMED validators (per node, so
        every partition side gets adversaries), enrolled into a shared
        slashing-protection-bypassing store. `None` (or an inactive
        plan) clears the roster; the outgoing roster's protection
        overrides are kept for the end-of-run report."""
        from ..validator_client.byzantine import ByzRoster

        if self.byz is not None:
            self.byz_overrides.extend(self.byz.store.overrides)
        self.byz = None
        if plan is None or not plan.active():
            return
        by_home: dict[str, list[int]] = {}
        for v in range(self.validator_count):
            by_home.setdefault(self.validator_home[v], []).append(v)
        roster = ByzRoster(plan, self.preset, self.spec)
        for home in sorted(by_home):
            vs = sorted(by_home[home])
            k = int(len(vs) * plan.fraction)
            for v in sorted(rng.sample(vs, k)):
                roster.enroll(v, bytes(self.genesis.validators[v].pubkey))
        if roster.members:
            self.byz = roster

    def total_byz_overrides(self) -> int:
        n = len(self.byz_overrides)
        if self.byz is not None:
            n += len(self.byz.store.overrides)
        return n

    # -- slot driving --------------------------------------------------------

    def tick(self, slot: int) -> None:
        for n in list(self.nodes):
            n.chain.slot_clock.set_slot(slot)
            try:
                n.chain.on_tick()
                n.on_slot()  # slasher batch + other per-slot services
            except InjectedCrash:
                self.mark_dead(n)

    def run_slot(
        self,
        slot: int,
        attest: bool = True,
        active_validators=None,
        equivocate: bool = False,
        forge: bool = False,
        byzantine: bool = False,
    ) -> None:
        """One slot of the synthetic network, per partition group: the
        group holding the proposer's home node produces and gossips a
        block carrying the group's attestations for the previous slot;
        every node's processor drains. `active_validators` restricts who
        proposes/attests (long-non-finality withholding); `equivocate`
        gossips a second conflicting proposal and `forge` an invalid one
        (equivocation-storm phases), both relayed by a synthetic
        Byzantine peer that is not a real node; `byzantine` drives the
        installed ByzRoster's slashable duties through the real
        validator-store signing path (set_byz_plan)."""
        self.tick(slot)
        for group in self._node_groups():
            ctx = self._produce_for_group(
                group, slot, attest, active_validators, equivocate, forge
            )
            if byzantine and self.byz is not None and ctx is not None:
                self._run_byz_duties(group, slot, ctx)
        self.drain()

    def _produce_for_group(
        self, group, slot, attest, active_validators, equivocate, forge
    ) -> dict | None:
        """Returns the group's production context (advanced state,
        proposer, home node, attestations, the published block or None
        on an empty slot) for the byz duty driver; None only when the
        home node crashed mid-publish."""
        from ..state_transition import (
            clone_state,
            get_beacon_proposer_index,
            process_slots,
        )

        leader = group[0]
        parent_state = leader.chain._states[leader.chain.head_root]
        adv = process_slots(
            clone_state(parent_state), slot, self.preset, self.spec
        )
        proposer = get_beacon_proposer_index(adv, self.preset, self.spec)
        allowed = self._group_validators(group)
        if active_validators is not None:
            allowed = allowed & set(active_validators)
        ctx = {
            "adv": adv,
            "proposer": proposer,
            "allowed": allowed,
            "parent_state": parent_state,
            "home": leader,
            "atts": [],
            "signed": None,
        }
        if proposer not in allowed:
            return ctx  # the proposer is on the other side / offline
        home = next(
            (
                n
                for n in group
                if n.peer_id == self.validator_home.get(proposer)
            ),
            leader,
        )
        if leader.chain.head_root not in home.chain._states:
            # the proposer's home has not reconciled the group's head yet
            # (fresh heal/rejoin): the leader publishes on its behalf
            home = leader
        ctx["home"] = home
        atts = []
        if attest and slot > 1:
            atts = self.producer.attestations_for_slot(
                adv, slot - 1, validators=allowed
            )
        ctx["atts"] = atts
        signed, _ = self.producer.produce_block(
            slot, atts, base_state=parent_state
        )
        try:
            home.publish_block(signed)
        except InjectedCrash:
            self.mark_dead(home)
            return None
        ctx["signed"] = signed
        if self.speculate and atts:
            # gossip a real SignedAggregateAndProof so the aggregate
            # verification path (and with it the precompute hook) runs
            # on every receiving node, not just block-carried votes
            home.publish_aggregate(
                self.producer.make_signed_aggregate(adv, slot - 1, 0)
            )
        if equivocate or forge:
            # the Byzantine injector must sit on THIS group's side of any
            # installed split, or its gossip would reach nobody and the
            # storm invariants would pass vacuously
            self.raw_bus.join_group("byz", home.peer_id)
        if equivocate:
            # a SECOND distinct proposal by the same (slot, proposer):
            # honest nodes must IGNORE it (never import via gossip) and
            # their slashers must detect the double proposal
            double, _ = self.producer.produce_block(
                slot, atts, base_state=parent_state, graffiti=b"equivocation"
            )
            self.equivocation_roots.append(double.message.tree_hash_root())
            self.raw_bus.publish("byz", home._topic_block, double)
        if forge:
            # a provably-invalid block (wrong proposer + garbage state
            # root — a distinct proposer so the equivocation dedup does
            # not mask the invalidity path): honest nodes must reject it
            # AND penalize the Byzantine relayer
            bad, _ = self.producer.produce_block(
                slot, base_state=parent_state, graffiti=b"forged"
            )
            bad.message.proposer_index = (
                int(proposer) + 1
            ) % self.validator_count
            bad.message.state_root = b"\x66" * 32
            self.forged_roots.append(bad.message.tree_hash_root())
            self.raw_bus.publish("byz", home._topic_block, bad)
        return ctx

    # -- Byzantine duty driving (validator_client/byzantine.py) --------------

    def _run_byz_duties(self, group, slot, ctx) -> None:
        """Drive this group's Byzantine validators through the REAL
        validator-store signing path (domains, signing roots, the
        slashing-DB gate — bypassed and audited). Slashable artifacts
        are GOSSIPED by a colluding relay peer ("byzvc") sitting on the
        group's side of any split: a byz VC talks to the network through
        its relay, never through an honest node's import path, so the
        no-byz-import invariant audits exactly the gossip boundary."""
        plan = self.byz.plan
        anchor = ctx["home"]
        # place the relay on this group's side (no-op when unpartitioned)
        self.raw_bus.join_group("byzvc", anchor.peer_id)
        if (
            plan.double_propose
            and ctx["signed"] is not None
            and ctx["proposer"] in self.byz
        ):
            self._byz_double_propose(slot, ctx)
        if slot > 2:
            seats = self._byz_committee_seats(group, slot, ctx["adv"])
            if seats and (plan.conflicting_votes or plan.surround_votes):
                self._byz_votes(slot, ctx, seats)
            if seats and plan.equivocating_aggregates:
                self._byz_equivocating_aggregate(slot, ctx, seats)

    def _byz_committee_seats(self, group, slot, adv):
        """(position, validator) byz seats in committee 0 of slot-1
        homed in this group."""
        from ..state_transition import ConsensusContext
        from ..types import compute_epoch_at_slot

        att_slot = slot - 1
        ctxt = ConsensusContext(self.preset, self.spec)
        committee = ctxt.committee_cache(
            adv, compute_epoch_at_slot(att_slot, self.preset)
        ).get_beacon_committee(att_slot, 0)
        peers = {n.peer_id for n in group}
        return [
            (pos, v)
            for pos, v in enumerate(committee)
            if v in self.byz and self.validator_home.get(v) in peers
        ]

    def _byz_sign_aggregate(self, aggregator: int, attestation, adv):
        """SignedAggregateAndProof through the byz store's real
        selection-proof + aggregate-and-proof signing path."""
        from ..types import types_for

        t = types_for(self.preset)
        pk = self.byz.pubkey_of(aggregator)
        proof = self.byz.store.sign_selection_proof(
            pk, attestation.data.slot, adv
        )
        msg = t.AggregateAndProof(
            aggregator_index=aggregator,
            aggregate=attestation,
            selection_proof=proof.to_bytes(),
        )
        sig = self.byz.store.sign_aggregate_and_proof(pk, msg, adv)
        return t.SignedAggregateAndProof(
            message=msg, signature=sig.to_bytes()
        )

    def _byz_double_propose(self, slot, ctx) -> None:
        """A SECOND distinct proposal for the slot, signed by the byz
        proposer through the store: the honest proposal is signed first
        (the real duty, cleanly recorded), so the double is exactly the
        message the slashing DB refuses — the refusal is overridden and
        audited. Honest nodes must IGNORE the double via gossip and
        their slashers must emit a ProposerSlashing."""
        store = self.byz.store
        proposer = ctx["proposer"]
        pk = self.byz.pubkey_of(proposer)
        store.sign_block(pk, ctx["signed"].message, ctx["adv"])
        double, _ = self.producer.produce_block(
            slot,
            ctx["atts"],
            base_state=ctx["parent_state"],
            graffiti=b"byz-vc-double",
        )
        sig = store.sign_block(pk, double.message, ctx["adv"])
        double.signature = sig.to_bytes()
        self.equivocation_roots.append(double.message.tree_hash_root())
        self.raw_bus.publish("byzvc", ctx["home"]._topic_block, double)
        self.byz_counts["double_proposals"] += 1

    def _byz_votes(self, slot, ctx, seats) -> None:
        """Per-seat slashable voting on the attestation subnet. Gossip
        dedup admits ONE unaggregated vote per (target epoch, attester),
        so each byz seat gossips a single vote per epoch: an honest one
        while justification is young (building the history a surround
        needs), then — with surround_votes on — a vote whose source is
        dragged back to genesis, surrounding its own earlier honest vote.
        Conflicting DOUBLE votes ride the aggregate lane instead
        (_byz_conflicting_aggregates): two distinct byz aggregators pass
        the per-aggregator dedup where a second subnet vote cannot."""
        from ..types import types_for
        from ..types.containers import AttestationData, Checkpoint
        from .message_bus import topic_name

        adv = ctx["adv"]
        att_slot = slot - 1
        plan = self.byz.plan
        store = self.byz.store
        t = types_for(self.preset)
        anchor = ctx["home"]
        topic = topic_name("beacon_attestation", anchor.fork_digest, 0)
        genesis_root = bytes(anchor.chain.genesis_block_root)
        honest = self.producer.attestation_data_for(adv, att_slot, 0)
        for pos, v in seats:
            pk = self.byz.pubkey_of(v)
            if plan.surround_votes and honest.source.epoch >= 1:
                # source dragged back to genesis: (0, target) surrounds
                # this validator's own earlier honest (>=1, target') vote
                data = AttestationData(
                    slot=honest.slot,
                    index=honest.index,
                    beacon_block_root=bytes(honest.beacon_block_root),
                    source=Checkpoint(epoch=0, root=genesis_root),
                    target=Checkpoint(
                        epoch=honest.target.epoch,
                        root=bytes(honest.target.root),
                    ),
                )
                self.byz_counts["surround_votes"] += 1
            else:
                data = honest
                self.byz_counts["honest_votes_gossiped"] += 1
            sig = store.sign_attestation(pk, data, adv)
            att = self.producer.make_unaggregated(adv, att_slot, 0, pos)
            att = t.Attestation(
                aggregation_bits=att.aggregation_bits,
                data=data,
                signature=sig.to_bytes(),
            )
            self.raw_bus.publish("byzvc", topic, att)
        if plan.conflicting_votes and len(seats) >= 2:
            self._byz_conflicting_aggregates(
                slot, ctx, seats, honest, genesis_root
            )

    def _byz_conflicting_aggregates(
        self, slot, ctx, seats, honest, genesis_root
    ) -> None:
        """The conflicting DOUBLE vote: the group's byz seats vote two
        different heads for the same (slot, target), each variant relayed
        by a DIFFERENT byz aggregator — the (epoch, aggregator) gossip
        dedup admits both, every honest slasher sees both verified
        indexed attestations, and the shared attesting indices are a
        double-vote detection (AttesterSlashing)."""
        from ..crypto.bls import INFINITY_SIGNATURE
        from ..state_transition import ConsensusContext
        from ..types import compute_epoch_at_slot, types_for
        from ..types.containers import AttestationData, Checkpoint

        adv = ctx["adv"]
        att_slot = slot - 1
        store = self.byz.store
        t = types_for(self.preset)
        ctxt = ConsensusContext(self.preset, self.spec)
        committee = ctxt.committee_cache(
            adv, compute_epoch_at_slot(att_slot, self.preset)
        ).get_beacon_committee(att_slot, 0)
        members = {v for _, v in seats}
        bits = tuple(v in members for v in committee)
        conflict = AttestationData(
            slot=honest.slot,
            index=honest.index,
            beacon_block_root=genesis_root,
            source=Checkpoint(
                epoch=honest.source.epoch, root=bytes(honest.source.root)
            ),
            target=Checkpoint(
                epoch=honest.target.epoch, root=bytes(honest.target.root)
            ),
        )
        # every seat signs the conflicting data through the store: the
        # slashing DB refuses each double vote; refusals are overridden
        # and audited (the honest variant was signed in _byz_votes)
        for _, v in seats:
            store.sign_attestation(self.byz.pubkey_of(v), conflict, adv)
        topic = ctx["home"]._topic_aggregate
        # aggregators from the tail of the seat list: the speculation
        # path's honest aggregator is the committee head, and one
        # (epoch, aggregator) dedup slot must not eat the byz pair
        agg_honest, agg_conflict = seats[-1][1], seats[-2][1]
        for aggregator, data in (
            (agg_honest, honest),
            (agg_conflict, conflict),
        ):
            att = t.Attestation(
                aggregation_bits=bits, data=data, signature=INFINITY_SIGNATURE
            )
            signed = self._byz_sign_aggregate(aggregator, att, adv)
            self.byz_aggregate_roots.append(att.tree_hash_root())
            self.raw_bus.publish("byzvc", topic, signed)
        self.byz_counts["conflicting_vote_pairs"] += 1

    def _byz_equivocating_aggregate(self, slot, ctx, seats) -> None:
        """ONE byz aggregator signs TWO distinct aggregates for the same
        (slot, committee): full honest participation bits, then a
        single-seat subset of the same data. Honest nodes verify and
        import at most one ((epoch, aggregator) dedup IGNOREs the
        second) and speculation must never confirm either by lookup."""
        from ..types import types_for

        adv = ctx["adv"]
        att_slot = slot - 1
        t = types_for(self.preset)
        full = self.producer.attestations_for_slot(adv, att_slot)[0]
        pos, aggregator = seats[-1]
        bits = tuple(
            i == pos for i in range(len(list(full.aggregation_bits)))
        )
        subset = t.Attestation(
            aggregation_bits=bits,
            data=full.data,
            signature=bytes(full.signature),
        )
        topic = ctx["home"]._topic_aggregate
        for att in (full, subset):
            signed = self._byz_sign_aggregate(aggregator, att, adv)
            self.byz_aggregate_roots.append(att.tree_hash_root())
            self.raw_bus.publish("byzvc", topic, signed)
        self.byz_counts["equivocating_aggregates"] += 1

    def publish_conflicting_attestations(self, slot: int) -> None:
        """A Byzantine double vote: two attestations from the same
        committee seat for the same slot naming DIFFERENT head blocks,
        both relayed on the subnet. Dedup (ObservedAttesters) must keep
        fork choice single-voted; the network must keep finalizing."""
        from ..state_transition import clone_state, process_slots
        from ..types.containers import AttestationData, Checkpoint
        from ..types import types_for
        from .message_bus import topic_name

        if not self.nodes:
            return
        leader = self.nodes[0]
        head = leader.chain.head_state
        adv = process_slots(
            clone_state(head), slot, self.preset, self.spec
        )
        att = self.producer.make_unaggregated(adv, slot - 1, 0, 0)
        d = att.data
        conflicting = types_for(self.preset).Attestation(
            aggregation_bits=att.aggregation_bits,
            data=AttestationData(
                slot=d.slot,
                index=d.index,
                beacon_block_root=leader.chain.genesis_block_root,
                source=Checkpoint(
                    epoch=d.source.epoch, root=bytes(d.source.root)
                ),
                target=Checkpoint(
                    epoch=d.target.epoch, root=bytes(d.target.root)
                ),
            ),
            signature=att.signature,
        )
        topic = topic_name(
            "beacon_attestation", leader.fork_digest, 0
        )
        self.raw_bus.join_group("byz", leader.peer_id)
        self.raw_bus.publish("byz", topic, att)
        self.raw_bus.publish("byz", topic, conflicting)

    def drain(self) -> list[NetworkNode]:
        """Drain every node's processor; a node whose store kills the
        "process" mid-import (InjectedCrash) drops off the bus and is
        returned for the scenario runner to reopen."""
        crashed = []
        for n in list(self.nodes):
            try:
                n.processor.run_until_idle()
            except InjectedCrash:
                crashed.append(n)
        for n in crashed:
            self.mark_dead(n)
        return crashed

    def run_epochs(self, epochs: int, attest: bool = True) -> None:
        start = (
            max(n.chain.head_state.slot for n in self.nodes) + 1
        )
        for slot in range(start, start + epochs * self.preset.slots_per_epoch):
            self.run_slot(slot, attest=attest)

    def sync_all(self) -> int:
        """Every node range-syncs from its best peers AND reconciles
        peer forks (post-heal / post-churn catch-up): range sync only
        pulls from peers strictly AHEAD, so two equal-height forks left
        by a partition are exchanged via unknown-head block lookups (the
        reference's block_lookups path). Fork choice then converges every
        node onto the heavier fork. Returns total imported blocks."""
        from .node import STATUS_PROTOCOL

        imported = 0
        # fork reconciliation FIRST: equal-height forks are invisible to
        # range sync's strictly-ahead ranking, and a range batch from the
        # other fork without its ancestors would burn retry budget
        for n in list(self.nodes):
            try:
                for peer in self.raw_bus.peers_on(n._topic_block):
                    if peer == n.peer_id:
                        continue
                    try:
                        status = self.bus.request(
                            n.peer_id, peer, STATUS_PROTOCOL, {}
                        )
                        head = bytes(status["head_root"])
                        if head not in n.chain._states:
                            n.sync_manager.lookup_block(head)
                    except (ConnectionError, OSError):
                        # unreachable/faulted peer: reconcile the REST —
                        # one dead peer must not abort the whole round
                        continue
                n.chain.recompute_head()
            except InjectedCrash:
                self.mark_dead(n)
        for n in list(self.nodes):
            try:
                imported += n.range_sync()
            except InjectedCrash:
                self.mark_dead(n)
        self.drain()
        return imported

    # -- checks (testing/simulator/src/checks.rs) ---------------------------

    def check_all_heads_equal(self) -> bytes:
        heads = {n.chain.head_root for n in self.nodes}
        assert len(heads) == 1, f"nodes diverged: {len(heads)} heads"
        return heads.pop()

    def check_finality(self, min_epoch: int) -> None:
        for n in self.nodes:
            assert n.chain.finalized_checkpoint[0] >= min_epoch, (
                f"{n.peer_id} finalized {n.chain.finalized_checkpoint[0]}"
                f" < {min_epoch}"
            )
