"""Networking (reference beacon_node/lighthouse_network +
beacon_node/network, SURVEY.md section 2.3): gossip topics, req/resp
protocols, router, sync, peer scoring -- over an in-process message bus
(the simulator-style multi-node transport; a wire transport slots in
behind the same API)."""

from .message_bus import GossipMessage, MessageBus, topic_name  # noqa: F401
from .node import (  # noqa: F401
    BLOCKS_BY_RANGE,
    BLOCKS_BY_ROOT,
    STATUS_PROTOCOL,
    NetworkNode,
)
from .simulator import Simulator  # noqa: F401
