"""Networking (reference beacon_node/lighthouse_network +
beacon_node/network, SURVEY.md section 2.3): gossip topics, req/resp
protocols, router, sync, peer scoring -- over either transport: the
in-process message bus (simulator-style multi-node) or the TCP wire
stack (wire.py: ssz_snappy framing, bootnode discovery, flood gossip
with seen-cache relay) behind the same API."""

from .message_bus import GossipMessage, MessageBus, topic_name  # noqa: F401
from .node import (  # noqa: F401
    BLOCKS_BY_RANGE,
    BLOCKS_BY_ROOT,
    STATUS_PROTOCOL,
    NetworkNode,
)
from .simulator import Simulator  # noqa: F401
from .sync import SyncManager  # noqa: F401
from .wire import Bootnode, WireBus, WireCodec  # noqa: F401
