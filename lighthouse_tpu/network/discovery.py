"""discv5-style UDP peer discovery with signed node records.

Reference: beacon_node/lighthouse_network/src/discovery/ (the discv5 UDP
DHT; enr.rs ENR fields incl. the eth2 fork digest and attnets/syncnets
subnet bits; subnet_predicate.rs peer-for-subnet selection) and
boot_node/ (the standalone discovery-only node).

TPU-native design divergences, both deliberate: node identity keys are
BLS12-381 — the framework's native signature scheme — rather than
secp256k1 ECDSA, and the record wire format is SSZ (this repo's native
codec) rather than RLP. Everything else follows discv5's shape:

- **ENR**: signed, seq-versioned node records carrying (ip, udp, tcp,
  fork_digest, attnets, syncnets). Higher seq supersedes; records are
  verified against the embedded pubkey (memoized — a BLS verify on the
  pure-Python oracle costs ~2 s, so each distinct record body is checked
  at most once per process).
- **Routing table**: XOR-metric k-buckets over sha256 node ids
  (log2-distance buckets, k=16).
- **Protocol**: PING/PONG liveness with observed-address feedback (the
  ip-vote that lets a node learn its external address), FINDNODE by
  log2 distance → NODES batches, iterative alpha-parallel LOOKUP.
- **Subnet advertisement**: attnets bits in the record;
  `peers_on_subnet` filters the live table the way the reference's
  subnet predicate gates peer dials.

Transport is one UDP socket per service; messages are JSON envelopes
(control metadata) carrying hex-encoded SSZ ENRs (the signed payload —
signatures cover SSZ bytes, never the JSON framing).
"""

import hashlib
import json
import os
import secrets
import socket
import threading
import time

from ..crypto.bls import api as bls
from ..ssz import Bytes4, Bytes48, Bytes96, ByteVector, container, uint64

Bytes8 = ByteVector(8)

K_BUCKET = 16
MAX_NODES_REPLY = 16
ATT_SUBNET_COUNT = 64
SYNC_SUBNET_COUNT = 4


def _make_enr_content():
    @container
    class EnrContent:
        seq: uint64
        pubkey: Bytes48
        ip: Bytes4
        udp_port: uint64
        tcp_port: uint64
        fork_digest: Bytes4
        attnets: Bytes8
        syncnets: Bytes8

    return EnrContent


EnrContent = _make_enr_content()


def _ip_bytes(host: str) -> bytes:
    try:
        return socket.inet_aton(host)
    except OSError:
        return socket.inet_aton("127.0.0.1")


class Enr:
    """A signed node record (discovery/enr.rs; discv5 spec shape)."""

    _verified: dict[bytes, bool] = {}  # memo: record bytes -> verdict

    def __init__(self, content: "EnrContent", signature: bytes):
        self.content = content
        self.signature = bytes(signature)

    # -- identity -------------------------------------------------------------

    @property
    def node_id(self) -> bytes:
        """sha256 of the identity pubkey (discv5 derives node ids by
        hashing the key; the metric space below is XOR over these)."""
        return hashlib.sha256(bytes(self.content.pubkey)).digest()

    @property
    def seq(self) -> int:
        return int(self.content.seq)

    @property
    def ip(self) -> str:
        return socket.inet_ntoa(bytes(self.content.ip))

    @property
    def udp_addr(self) -> tuple:
        return (self.ip, int(self.content.udp_port))

    @property
    def tcp_addr(self) -> tuple:
        return (self.ip, int(self.content.tcp_port))

    def has_attnet(self, subnet: int) -> bool:
        bits = bytes(self.content.attnets)
        return bool(bits[subnet // 8] >> (subnet % 8) & 1)

    def has_syncnet(self, subnet: int) -> bool:
        bits = bytes(self.content.syncnets)
        return bool(bits[subnet // 8] >> (subnet % 8) & 1)

    # -- wire -----------------------------------------------------------------

    def to_bytes(self) -> bytes:
        return self.content.as_ssz_bytes() + self.signature

    @classmethod
    def from_bytes(cls, data: bytes) -> "Enr":
        return cls(
            EnrContent.from_ssz_bytes(data[:-96]), data[-96:]
        )

    def verify(self) -> bool:
        """Check the BLS signature over the SSZ content bytes, memoized
        per distinct record body."""
        key = self.to_bytes()
        hit = Enr._verified.get(key)
        if hit is None:
            # pinned to the CPU oracle: identity records are control
            # plane, verified once each -- never routed through the
            # ambient batch backend (which may be `fake` under test)
            from ..crypto.bls.backends import cpu as cpu_bls

            try:
                pk = bls.PublicKey.from_bytes(bytes(self.content.pubkey))
                sig = bls.Signature.from_bytes(self.signature)
                hit = cpu_bls.verify_signature_sets(
                    [
                        bls.SignatureSet.single_pubkey(
                            sig, pk, _enr_signing_root(self.content)
                        )
                    ]
                )
            except (ValueError, IndexError):  # BlsError is a ValueError:
                hit = False  # malformed record == invalid, never fatal
            if len(Enr._verified) > 4096:
                Enr._verified.clear()
            Enr._verified[key] = hit
        return hit


def _enr_signing_root(content: "EnrContent") -> bytes:
    return hashlib.sha256(b"lighthouse-tpu-enr" + content.as_ssz_bytes()).digest()


def _subnet_bits(subnets, count: int) -> bytes:
    out = bytearray(8)
    for s in subnets or ():
        if not 0 <= s < count:
            raise ValueError(f"subnet {s} out of range")
        out[s // 8] |= 1 << (s % 8)
    return bytes(out)


def make_enr(
    sk: "bls.SecretKey",
    host: str,
    udp_port: int,
    tcp_port: int = 0,
    fork_digest: bytes = b"\x00" * 4,
    attnets=(),
    syncnets=(),
    seq: int = 1,
) -> Enr:
    content = EnrContent(
        seq=seq,
        pubkey=sk.public_key().to_bytes(),
        ip=_ip_bytes(host),
        udp_port=udp_port,
        tcp_port=tcp_port,
        fork_digest=bytes(fork_digest),
        attnets=_subnet_bits(attnets, ATT_SUBNET_COUNT),
        syncnets=_subnet_bits(syncnets, SYNC_SUBNET_COUNT),
    )
    sig = sk.sign(_enr_signing_root(content)).to_bytes()
    enr = Enr(content, sig)
    Enr._verified[enr.to_bytes()] = True  # self-signed: trivially valid
    return enr


def log2_distance(a: bytes, b: bytes) -> int:
    """discv5 log2 XOR distance: 0 for identical ids, else bit length of
    the XOR (1..256)."""
    x = int.from_bytes(a, "big") ^ int.from_bytes(b, "big")
    return x.bit_length()


class RoutingTable:
    """XOR-metric k-buckets of verified ENRs (discv5's kbucket table).
    Bucket i holds nodes at log2 distance i; each bucket keeps at most
    K_BUCKET entries, preferring incumbents (discv5 keeps long-lived
    nodes; newcomers wait for an eviction)."""

    def __init__(self, local_id: bytes, k: int = K_BUCKET):
        self.local_id = local_id
        self.k = k
        self._buckets: dict[int, dict[bytes, Enr]] = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._buckets.values())

    def add(self, enr: Enr) -> bool:
        """Insert/refresh a record; higher seq supersedes. False if the
        bucket is full of other incumbents or the record is our own."""
        nid = enr.node_id
        d = log2_distance(self.local_id, nid)
        if d == 0:
            return False
        with self._lock:
            bucket = self._buckets.setdefault(d, {})
            cur = bucket.get(nid)
            if cur is not None:
                if enr.seq >= cur.seq:
                    bucket[nid] = enr
                return True
            if len(bucket) >= self.k:
                return False
            bucket[nid] = enr
            return True

    def remove(self, node_id: bytes) -> None:
        d = log2_distance(self.local_id, node_id)
        with self._lock:
            self._buckets.get(d, {}).pop(node_id, None)

    def at_distance(self, d: int) -> list:
        with self._lock:
            return list(self._buckets.get(d, {}).values())

    def enrs(self) -> list:
        with self._lock:
            return [e for b in self._buckets.values() for e in b.values()]

    def closest(self, target: bytes, n: int) -> list:
        return sorted(
            self.enrs(),
            key=lambda e: int.from_bytes(e.node_id, "big")
            ^ int.from_bytes(target, "big"),
        )[:n]


class DiscoveryService:
    """One UDP discovery endpoint: serves PING/FINDNODE, issues
    PING/FINDNODE/LOOKUP, maintains the routing table and the local
    signed record (discovery/mod.rs Discovery behaviour + discv5)."""

    def __init__(
        self,
        sk: "bls.SecretKey",
        host: str = "127.0.0.1",
        udp_port: int = 0,
        tcp_port: int = 0,
        fork_digest: bytes = b"\x00" * 4,
        attnets=(),
        syncnets=(),
        verify_sigs: bool = True,
        rpc_timeout: float = 2.0,
    ):
        self.sk = sk
        self.verify_sigs = verify_sigs
        self.rpc_timeout = rpc_timeout
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, udp_port))
        self.host, self.udp_port = self._sock.getsockname()
        self.local_enr = make_enr(
            sk,
            self.host,
            self.udp_port,
            tcp_port,
            fork_digest,
            attnets,
            syncnets,
        )
        self.node_id = self.local_enr.node_id
        self.table = RoutingTable(self.node_id)
        self._waiters: dict[str, list] = {}  # rpc id -> [event, reply]
        self._ip_votes: dict[str, set] = {}  # observed ip -> voting peers
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._recv_loop, daemon=True)
        self._thread.start()
        self.stats = {"pings": 0, "findnodes": 0, "bad_sigs": 0}

    # -- local record maintenance --------------------------------------------

    def update_local_enr(
        self, attnets=None, syncnets=None, fork_digest=None, ip=None
    ) -> None:
        """Re-sign the local record with bumped seq (enr.rs
        update_local_enr; how subnet subscriptions are advertised)."""
        c = self.local_enr.content
        self.local_enr = Enr(
            EnrContent(
                seq=c.seq + 1,
                pubkey=c.pubkey,
                ip=_ip_bytes(ip) if ip is not None else c.ip,
                udp_port=c.udp_port,
                tcp_port=c.tcp_port,
                fork_digest=(
                    bytes(fork_digest)
                    if fork_digest is not None
                    else c.fork_digest
                ),
                attnets=(
                    _subnet_bits(attnets, ATT_SUBNET_COUNT)
                    if attnets is not None
                    else c.attnets
                ),
                syncnets=(
                    _subnet_bits(syncnets, SYNC_SUBNET_COUNT)
                    if syncnets is not None
                    else c.syncnets
                ),
            ),
            b"",
        )
        sig = self.sk.sign(_enr_signing_root(self.local_enr.content))
        self.local_enr = Enr(self.local_enr.content, sig.to_bytes())
        Enr._verified[self.local_enr.to_bytes()] = True

    # -- table ingestion -------------------------------------------------------

    def _ingest(self, enr_hex: str) -> "Enr | None":
        try:
            enr = Enr.from_bytes(bytes.fromhex(enr_hex))
        except (TypeError, ValueError, IndexError):
            # remote-controlled input: non-string json value (TypeError),
            # bad hex / truncated SSZ (SszError is a ValueError)
            return None
        if self.verify_sigs and not enr.verify():
            self.stats["bad_sigs"] += 1
            return None
        self.table.add(enr)
        return enr

    # -- outbound rpcs ---------------------------------------------------------

    def _rpc(self, addr: tuple, msg: dict) -> "dict | None":
        rid = secrets.token_hex(8)
        msg["id"] = rid
        ev = threading.Event()
        slot = [ev, None]
        with self._lock:
            self._waiters[rid] = slot
        try:
            self._sock.sendto(json.dumps(msg).encode(), addr)
            if not ev.wait(self.rpc_timeout):
                return None
            return slot[1]
        except OSError:
            return None
        finally:
            with self._lock:
                self._waiters.pop(rid, None)

    def ping(self, addr: tuple) -> "dict | None":
        """PING -> PONG: liveness + seq + observed-address feedback."""
        reply = self._rpc(
            addr,
            {"t": "ping", "enr": self.local_enr.to_bytes().hex()},
        )
        if reply is None:
            return None
        if "enr" in reply:
            self._ingest(reply["enr"])
        obs = reply.get("observed")
        if (
            obs
            and isinstance(obs, (list, tuple))
            and isinstance(obs[0], str)
            and obs[0] != self.local_enr.ip
        ):
            # the ip VOTE (discv5 majority rule, not single-reply trust):
            # re-sign the record only once a SECOND distinct peer reports
            # the same different address, and only if it parses as an ip
            # (otherwise one lying/buggy peer rewrites our reachability)
            try:
                socket.inet_aton(obs[0])
            except OSError:
                return reply
            voters = self._ip_votes.setdefault(obs[0], set())
            voters.add(addr)
            if len(voters) >= 2:
                self._ip_votes.clear()
                self.update_local_enr(ip=obs[0])
        return reply

    def find_node(self, addr: tuple, distances) -> list:
        """FINDNODE(distances) -> NODES: records from the peer's buckets."""
        reply = self._rpc(
            addr,
            {
                "t": "findnode",
                "distances": list(distances),
                "enr": self.local_enr.to_bytes().hex(),
            },
        )
        if reply is None:
            return []
        out = []
        for h in reply.get("enrs", ()):
            enr = self._ingest(h)
            if enr is not None:
                out.append(enr)
        return out

    def lookup(self, target: "bytes | None" = None, alpha: int = 3, rounds: int = 3) -> list:
        """Iterative lookup toward `target` (random walk if None): each
        round queries the alpha closest not-yet-asked nodes for the
        distances bracketing the target (discv5's recursive FINDNODE)."""
        target = target or secrets.token_bytes(32)
        asked: set[bytes] = set()
        for _ in range(rounds):
            cand = [
                e for e in self.table.closest(target, alpha * 2)
                if e.node_id not in asked
            ][:alpha]
            if not cand:
                break
            # the alpha queries of a round run CONCURRENTLY: a round costs
            # one rpc timeout even when every candidate is dead, not alpha
            threads = []
            for enr in cand:
                asked.add(enr.node_id)
                d = log2_distance(enr.node_id, target)
                ds = sorted({max(1, d - 1), d, min(256, d + 1)})
                th = threading.Thread(
                    target=self.find_node, args=(enr.udp_addr, ds), daemon=True
                )
                th.start()
                threads.append(th)
            for th in threads:
                th.join(timeout=self.rpc_timeout + 1.0)
        return self.table.closest(target, K_BUCKET)

    def bootstrap(self, boot_addr: tuple) -> int:
        """Join via a boot node: PING it, pull our neighborhood, then a
        random walk to spread across buckets. Returns table size."""
        if self.ping(boot_addr) is None:
            return len(self.table)
        self.find_node(
            boot_addr, sorted({256, 255, 254, 253, 252})
        )
        self.lookup(self.node_id)
        self.lookup(None)
        return len(self.table)

    def peers_on_subnet(self, subnet: int, sync: bool = False) -> list:
        """Records advertising the subnet bit (subnet_predicate.rs)."""
        return [
            e
            for e in self.table.enrs()
            if (e.has_syncnet(subnet) if sync else e.has_attnet(subnet))
        ]

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # -- server side -----------------------------------------------------------

    def _recv_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                data, addr = self._sock.recvfrom(65535)
            except OSError:
                return
            try:
                msg = json.loads(data)
                if not isinstance(msg, dict):
                    continue
                self._dispatch(msg, addr)
            # lint: allow[broad-except] -- datagram ingress boundary: a
            # single crafted packet must never kill the recv loop (remote
            # DoS otherwise); failures are counted, not dropped silently
            except Exception:  # noqa: BLE001
                self.stats["bad_datagrams"] = (
                    self.stats.get("bad_datagrams", 0) + 1
                )
                continue

    def _dispatch(self, msg: dict, addr: tuple) -> None:
        t = msg.get("t")
        if t == "ping":
            self.stats["pings"] += 1
            if "enr" in msg:
                self._ingest(msg["enr"])
            self._send(
                addr,
                {
                    "t": "pong",
                    "id": msg.get("id"),
                    "enr": self.local_enr.to_bytes().hex(),
                    "enr_seq": self.local_enr.seq,
                    "observed": [addr[0], addr[1]],
                },
            )
        elif t == "findnode":
            self.stats["findnodes"] += 1
            if "enr" in msg:
                self._ingest(msg["enr"])
            enrs = []
            for d in msg.get("distances", ())[:8]:
                if d == 0:
                    enrs.append(self.local_enr)
                    continue
                enrs.extend(self.table.at_distance(int(d)))
            self._send(
                addr,
                {
                    "t": "nodes",
                    "id": msg.get("id"),
                    "enrs": [
                        e.to_bytes().hex()
                        for e in enrs[:MAX_NODES_REPLY]
                    ],
                },
            )
        elif t in ("pong", "nodes"):
            with self._lock:
                slot = self._waiters.get(msg.get("id"))
            if slot is not None:
                slot[1] = msg
                slot[0].set()

    def _send(self, addr: tuple, msg: dict) -> None:
        try:
            self._sock.sendto(json.dumps(msg).encode(), addr)
        except OSError:
            pass


class DiscoveryBootNode:
    """Standalone discovery-only node (reference boot_node/): a
    DiscoveryService with no chain behind it, relaying records between
    joining peers. Signature verification stays ON unless the caller
    opts out (a boot node vouches for records it hands out)."""

    def __init__(
        self,
        sk: "bls.SecretKey | None" = None,
        host: str = "127.0.0.1",
        udp_port: int = 0,
        verify_sigs: bool = True,
    ):
        self.service = DiscoveryService(
            sk or bls.SecretKey(int.from_bytes(os.urandom(24), "big")),
            host=host,
            udp_port=udp_port,
            verify_sigs=verify_sigs,
        )
        self.host = self.service.host
        self.udp_port = self.service.udp_port

    @property
    def enr(self) -> Enr:
        return self.service.local_enr

    def stop(self) -> None:
        self.service.stop()
