"""Deterministic socket fabric for the scenario harness: the full
in-process `MessageBus` API (subscribe / publish / request / partitions /
churn) realized over one real `WireBus` TCP endpoint per peer, so every
scenario plan can run with ``transport="wire"`` — same invariants, same
bit-identical replay — while every payload actually crosses a socket as
SSZ + snappy frames through `WireCodec`.

Determinism is the design center, and it comes from ONE rule: gossip is
delivered as a SYNCHRONOUS req/resp exchange (`FABRIC_GOSSIP`), never as
a fire-and-forget push. `publish` walks the fabric's insertion-ordered
subscriber registry and performs one blocking exchange per target; the
receiver's handler runs to completion (on its server thread) before the
ack releases the sender, so the whole network advances one handler at a
time in registry order — exactly the memory bus's schedule, with TCP
framing, snappy, and SSZ decode on the path. The gossipsub mesh
machinery inside `WireBus` stays dormant: peers are cross-registered
with EMPTY topic sets (no GRAFT traffic, no mesh randomness), and the
per-connection token buckets are opened wide (the fabric is a harness
transport, not a DoS surface).

Partitions/heal/join_group are enforced at the fabric layer (the
sockets themselves stay up): an unreachable `request` raises
``ConnectionError`` exactly like the memory bus, so FaultPlan wrapping
and sync's retry/penalty machinery behave identically on both
transports. Synthetic sources ("byz", "byzvc") get a lazily-created
injector endpoint that subscribes to nothing but can dial everyone."""

from __future__ import annotations

import random

from .wire import WireBus

FABRIC_GOSSIP = "/lighthouse-tpu/fabric_gossip/1/ssz_snappy"


class WireFabric:
    """MessageBus-compatible fabric over per-peer WireBus sockets."""

    def __init__(self, seed: int = 0, host: str = "127.0.0.1"):
        self.seed = int(seed)
        self.host = host
        self._preset = None  # bound at first subscribe/register (node ctor)
        self._buses: dict[str, WireBus] = {}
        # topic -> {peer_id -> handler}; insertion order IS the delivery
        # schedule (the memory bus's defaultdict(dict) semantics)
        self._subs: dict[str, dict[str, object]] = {}
        # peer -> partition group id; empty == fully connected
        self._groups: dict[str, int] = {}
        self._spawned = 0

    # -- endpoint lifecycle --------------------------------------------------

    def _bind_preset(self, preset) -> None:
        if self._preset is None:
            self._preset = preset

    def _ensure_bus(self, peer_id: str) -> WireBus:
        bus = self._buses.get(peer_id)
        if bus is not None:
            return bus
        if self._preset is None:
            from ..types import MINIMAL

            self._preset = MINIMAL
        self._spawned += 1
        bus = WireBus(
            self._preset,
            host=self.host,
            # harness transport: rate limiting off (gossip rides req/resp)
            req_burst=1e9,
            req_rate_per_s=1e9,
            # mesh machinery is dormant but its rng must still be seeded
            # (replay) and per-peer (lint rule `nondeterminism`)
            rng=random.Random(self.seed * 1000003 + self._spawned),
        )
        bus.listen(peer_id, port=0)
        bus.register_rpc(peer_id, FABRIC_GOSSIP, self._make_delivery(peer_id))
        # cross-register with every existing endpoint, BOTH directions,
        # with empty topic interests: the fabric owns routing, the bus
        # only dials. Re-records after churn refresh a stale host/port.
        for other_id, other in self._buses.items():
            other._record_peer(
                {
                    "peer_id": peer_id,
                    "host": bus.host,
                    "port": bus.port,
                    "topics": [],
                }
            )
            bus._record_peer(
                {
                    "peer_id": other_id,
                    "host": other.host,
                    "port": other.port,
                    "topics": [],
                }
            )
        self._buses[peer_id] = bus
        return bus

    def _make_delivery(self, peer_id: str):
        def deliver(req: dict, source: str):
            handler = self._subs.get(req["topic"], {}).get(peer_id)
            if handler is not None:
                handler(req["payload"], source)
            return None

        return deliver

    def close(self) -> None:
        for bus in self._buses.values():
            bus.stop()
        self._buses.clear()
        self._subs.clear()
        self._groups.clear()

    # -- partitions (MessageBus API) -----------------------------------------

    def set_partitions(self, groups) -> None:
        self._groups = {}
        for gid, peers in enumerate(groups):
            for peer in peers:
                self._groups[peer] = gid

    def heal(self) -> None:
        self._groups = {}

    def partitioned(self) -> bool:
        return bool(self._groups)

    def join_group(self, peer_id: str, like_peer: str) -> None:
        if not self._groups:
            return
        gid = self._groups.get(like_peer)
        if gid is None:
            self._groups.pop(peer_id, None)
        else:
            self._groups[peer_id] = gid

    def reachable(self, a: str, b: str) -> bool:
        if not self._groups:
            return True
        return self._groups.get(a, -1) == self._groups.get(b, -1)

    # -- node lifecycle ------------------------------------------------------

    def disconnect(self, peer_id: str) -> None:
        for subs in self._subs.values():
            subs.pop(peer_id, None)
        self._groups.pop(peer_id, None)
        bus = self._buses.pop(peer_id, None)
        if bus is not None:
            bus.stop()

    # -- gossip --------------------------------------------------------------

    def subscribe(self, peer_id: str, topic: str, handler) -> None:
        self._ensure_bus(peer_id)
        self._subs.setdefault(topic, {})[peer_id] = handler

    def unsubscribe(self, peer_id: str, topic: str) -> None:
        self._subs.get(topic, {}).pop(peer_id, None)

    def publish(self, source_peer: str, topic: str, payload) -> int:
        src = self._ensure_bus(source_peer)
        data = src.codec.encode_gossip(topic, payload)
        delivered = 0
        for peer_id in list(self._subs.get(topic, {})):
            if peer_id == source_peer:
                continue
            if not self.reachable(source_peer, peer_id):
                continue
            if peer_id not in self._buses:
                continue  # mid-churn straggler entry
            src.request(
                source_peer,
                peer_id,
                FABRIC_GOSSIP,
                {"topic": topic, "data": data},
            )
            delivered += 1
        return delivered

    # -- req/resp ------------------------------------------------------------

    def register_rpc(self, peer_id: str, protocol: str, handler) -> None:
        self._ensure_bus(peer_id).register_rpc(peer_id, protocol, handler)

    def request(self, from_peer: str, to_peer: str, protocol: str, payload):
        if not self.reachable(from_peer, to_peer):
            raise ConnectionError(
                f"peer {to_peer} unreachable from {from_peer} (partition)"
            )
        if to_peer not in self._buses:
            raise ConnectionError(f"peer {to_peer} does not speak {protocol}")
        return self._ensure_bus(from_peer).request(
            from_peer, to_peer, protocol, payload
        )

    def peers_on(self, topic: str) -> list[str]:
        return list(self._subs.get(topic, {}).keys())
