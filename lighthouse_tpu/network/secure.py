"""Encrypted, authenticated channel for the wire stack (the reference's
noise-handshake seat: lighthouse_network/src/service/utils.rs
build_transport -- noise XX over x25519, then a muxed secure stream).

TPU-native divergences, both deliberate: the key exchange is
Diffie-Hellman over BLS12-381 G1 -- the framework's native curve, so one
keypair type serves identity, signing, and transport -- instead of
x25519, and identity binding is a BLS signature over the handshake
transcript, verified against the peer's ENR-advertised identity key
(discovery.py) rather than a separate libp2p identity. Symmetric crypto
is the in-repo AES-128-CTR (crypto/aes.py) with HMAC-SHA256 per frame;
keys derive via HKDF-SHA256.

Handshake (XX-shaped):
    I -> R:  e_i                 48-byte compressed G1 ephemeral
    R -> I:  e_r [|| sig_r]      responder ephemeral, + transcript sig
    I -> R:  [sig_i]             initiator transcript sig
Shared secret: sha256(compress(dh)) where dh = e_peer * e_own_sk; four
direction keys expand from it. Frames carry a strictly-increasing
per-direction sequence (the high 64 bits of the AES-CTR counter, so
frames never share keystream) and a truncated HMAC tag --
tampering, replay, and reordering all fail the MAC and kill the
connection.

Signatures are optional (`authenticate=False` skips them): a BLS verify
costs ~2 s on the pure-Python oracle, which multi-node simulations pay
per persistent connection only when identity binding is the thing under
test.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import secrets
import struct

from ..crypto.aes import aes128_ctr
from ..crypto.bls import api as bls
from ..crypto.bls.constants import R as CURVE_ORDER
from ..crypto.bls.curve_ref import g1_from_bytes, g1_generator, g1_to_bytes

_PROTO = b"lighthouse-tpu-secure-v1"
_TAG_LEN = 16


class SecureError(OSError):
    """Handshake or frame authentication failure: the connection is
    unusable (OSError so wire.py's redial/drop paths treat it as a dead
    peer)."""


def _hkdf(secret: bytes, info: bytes, length: int) -> bytes:
    prk = hmac_mod.new(_PROTO, secret, hashlib.sha256).digest()
    out = b""
    block = b""
    counter = 1
    while len(out) < length:
        block = hmac_mod.new(
            prk, block + info + bytes([counter]), hashlib.sha256
        ).digest()
        out += block
        counter += 1
    return out[:length]


def _send_raw(sock, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_raw(sock) -> bytes | None:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            return None
        head += chunk
    (n,) = struct.unpack(">I", head)
    if n > 1 << 24:
        raise SecureError("oversized handshake/frame")
    body = b""
    while len(body) < n:
        chunk = sock.recv(n - len(body))
        if not chunk:
            return None
        body += chunk
    return body


def _transcript_root(e_i: bytes, e_r: bytes, role: bytes) -> bytes:
    return hashlib.sha256(_PROTO + e_i + e_r + role).digest()


def _sign_transcript(identity_sk, e_i: bytes, e_r: bytes, role: bytes) -> bytes:
    sig = identity_sk.sign(_transcript_root(e_i, e_r, role))
    return identity_sk.public_key().to_bytes() + sig.to_bytes()


def _verify_transcript(
    blob: bytes, e_i: bytes, e_r: bytes, role: bytes, expect_pubkey
) -> bytes:
    """Returns the peer's identity pubkey bytes; raises SecureError on a
    bad signature or an identity mismatch. Verification is pinned to the
    CPU oracle (control plane, like ENR checks)."""
    from ..crypto.bls.backends import cpu as cpu_bls

    if len(blob) != 48 + 96:
        raise SecureError("malformed identity blob")
    pk_bytes, sig_bytes = blob[:48], blob[48:]
    if expect_pubkey is not None and bytes(expect_pubkey) != pk_bytes:
        raise SecureError("peer identity key does not match expectation")
    try:
        pk = bls.PublicKey.from_bytes(pk_bytes)
        sig = bls.Signature.from_bytes(sig_bytes)
        ok = cpu_bls.verify_signature_sets(
            [
                bls.SignatureSet.single_pubkey(
                    sig, pk, _transcript_root(e_i, e_r, role)
                )
            ]
        )
    except bls.BlsError as e:
        raise SecureError(f"invalid identity material: {e}") from None
    if not ok:
        raise SecureError("peer transcript signature failed verification")
    return pk_bytes


class SecureSocket:
    """Frame-level AEAD wrapper: seq(8) || aes128ctr(ct) || hmac_tag(16).
    One instance per connection per direction pair. The frame sequence
    occupies the high 64 bits of the CTR counter (the low 64 count the
    blocks within a frame), so no two frames ever share a keystream
    block."""

    def __init__(self, sock, send_keys, recv_keys, peer_pubkey=None):
        self.sock = sock
        self._send_key, self._send_mac = send_keys
        self._recv_key, self._recv_mac = recv_keys
        self._send_seq = 0
        self._recv_seq = 0
        self.peer_pubkey = peer_pubkey  # None when unauthenticated

    def send_frame(self, ftype: int, body: bytes) -> None:
        plain = bytes([ftype]) + body
        seq = self._send_seq
        self._send_seq += 1
        # the frame seq owns the HIGH 64 counter bits: every frame gets
        # its own 2^64-block counter space, so keystream blocks can never
        # overlap between frames (CTR reuse = two-time pad)
        iv = (seq << 64).to_bytes(16, "big")
        ct = aes128_ctr(self._send_key, iv, plain)
        seq8 = seq.to_bytes(8, "big")
        tag = hmac_mod.new(
            self._send_mac, seq8 + ct, hashlib.sha256
        ).digest()[:_TAG_LEN]
        _send_raw(self.sock, seq8 + ct + tag)

    def recv_frame(self):
        payload = _recv_raw(self.sock)
        if payload is None:
            return None, None
        if len(payload) < 8 + _TAG_LEN:
            raise SecureError("truncated secure frame")
        seq8, ct, tag = payload[:8], payload[8:-_TAG_LEN], payload[-_TAG_LEN:]
        want = hmac_mod.new(
            self._recv_mac, seq8 + ct, hashlib.sha256
        ).digest()[:_TAG_LEN]
        if not hmac_mod.compare_digest(tag, want):
            raise SecureError("frame MAC failure (tampering?)")
        seq = int.from_bytes(seq8, "big")
        if seq != self._recv_seq:
            raise SecureError("frame out of sequence (replay?)")
        self._recv_seq += 1
        plain = aes128_ctr(self._recv_key, (seq << 64).to_bytes(16, "big"), ct)
        if not plain:
            raise SecureError("empty secure frame")
        return plain[0], plain[1:]

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _derive_keys(shared_point, e_i: bytes, e_r: bytes):
    secret = hashlib.sha256(g1_to_bytes(shared_point)).digest()
    material = _hkdf(secret, e_i + e_r, 96)
    # i->r key/mac, r->i key/mac
    return (
        (material[0:16], material[32:64]),
        (material[16:32], material[64:96]),
    )


def _ephemeral():
    sk = (secrets.randbits(256) % (CURVE_ORDER - 1)) + 1
    return sk, g1_to_bytes(g1_generator().mul(sk))


def handshake_initiator(
    sock, identity_sk=None, expect_pubkey=None, authenticate: bool = False
) -> SecureSocket:
    e_sk, e_i = _ephemeral()
    _send_raw(sock, e_i)
    reply = _recv_raw(sock)
    if reply is None or len(reply) < 48:
        raise SecureError("handshake: no responder ephemeral")
    e_r, r_blob = reply[:48], reply[48:]
    peer_pk = None
    if authenticate:
        peer_pk = _verify_transcript(r_blob, e_i, e_r, b"resp", expect_pubkey)
        if identity_sk is None:
            raise SecureError("authenticate=True needs an identity key")
        _send_raw(sock, _sign_transcript(identity_sk, e_i, e_r, b"init"))
    shared = g1_from_bytes(e_r).mul(e_sk)
    i2r, r2i = _derive_keys(shared, e_i, e_r)
    return SecureSocket(sock, i2r, r2i, peer_pk)


def handshake_responder(
    sock, identity_sk=None, expect_pubkey=None, authenticate: bool = False
) -> SecureSocket:
    e_i = _recv_raw(sock)
    if e_i is None or len(e_i) != 48:
        raise SecureError("handshake: no initiator ephemeral")
    e_sk, e_r = _ephemeral()
    if authenticate:
        if identity_sk is None:
            raise SecureError("authenticate=True needs an identity key")
        _send_raw(sock, e_r + _sign_transcript(identity_sk, e_i, e_r, b"resp"))
        i_blob = _recv_raw(sock)
        if i_blob is None:
            raise SecureError("handshake: no initiator identity")
        peer_pk = _verify_transcript(i_blob, e_i, e_r, b"init", expect_pubkey)
    else:
        _send_raw(sock, e_r)
        peer_pk = None
    shared = g1_from_bytes(e_i).mul(e_sk)
    i2r, r2i = _derive_keys(shared, e_i, e_r)
    return SecureSocket(sock, r2i, i2r, peer_pk)
