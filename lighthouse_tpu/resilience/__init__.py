"""Deterministic resilience layer (reference layers 0/5/6 in SURVEY §1:
`validator_client/src/beacon_node_fallback.rs`, `beacon_node/eth1`'s
multi-endpoint cache, engine-API retries in `execution_layer/`).

Primitives (`primitives.py`) are clocked by an *injected* clock and
randomized by an *injected* rng -- never wall time, never the global
random module -- so the same seed replays the same schedule of retries,
backoff delays, breaker transitions, and health scores (the determinism
contract asserted by tests/test_resilience.py).

Fault injection (`faults.py`) wraps any provider/backend/engine duck
type in a seeded `FaultPlan` that injects errors, delays, and hangs on
a deterministic schedule, usable from tests and network/simulator.py.

Crash injection (`crash.py`) is the process-death counterpart for the
store layer: a seeded `CrashPlan`/`CrashingStore` kills the
process-under-test at the Nth kv op — including torn writes — so the
crash-safety suite can crash at EVERY op index of an atomic batch and
assert reopen-time journal recovery.
"""

from .primitives import (  # noqa: F401
    AllEndpointsFailed,
    BreakerOpen,
    CircuitBreaker,
    EventLog,
    HealthTracker,
    RetryExhausted,
    RetryPolicy,
    Timeout,
    TimeoutExceeded,
    VirtualClock,
)
from .faults import (  # noqa: F401
    FaultInjected,
    FaultPlan,
    FaultyProxy,
    InjectedHang,
)
from .crash import (  # noqa: F401
    CrashPlan,
    CrashingStore,
    InjectedCrash,
)
