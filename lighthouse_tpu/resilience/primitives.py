"""Resilience primitives: retry, timeout, circuit breaker, health scores.

Everything here is deterministic by construction:

  * time comes from an injected clock object exposing ``now()`` (the
    slot clocks in utils/slot_clock.py qualify, as does the local
    ``VirtualClock``) -- wall time never enters (lint rule `wallclock`);
  * randomness (backoff jitter) comes from an injected
    ``random.Random(seed)``;
  * every decision -- retry, backoff delay, breaker transition, health
    demotion -- can be recorded into an ``EventLog``, so two runs with
    the same seed produce byte-identical event sequences (the replay
    contract tests/test_resilience.py asserts).

The reference spreads these behaviors across beacon_node_fallback.rs
(candidate ranking + re-probe), eth1's multi-endpoint cache, and the
engine-API retry loops; here they are one reusable layer.
"""

from __future__ import annotations

import random
from collections import deque

from ..utils import metrics


class VirtualClock:
    """A manually-advanced clock: the deterministic stand-in for wall
    time. ``FaultPlan`` delay/hang injections advance it, so injected
    latency is visible to ``Timeout`` and ``CircuitBreaker`` without a
    single real sleep."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        self._now += float(seconds)


class EventLog:
    """Append-only record of resilience decisions, comparable across
    runs: the determinism contract is ``log_a.events == log_b.events``."""

    def __init__(self):
        self.events: list[tuple] = []

    def record(self, kind: str, **detail) -> None:
        self.events.append((kind,) + tuple(sorted(detail.items())))

    def kinds(self) -> list[str]:
        return [e[0] for e in self.events]

    def __len__(self) -> int:
        return len(self.events)

    def __eq__(self, other) -> bool:
        if isinstance(other, EventLog):
            return self.events == other.events
        return NotImplemented


class RetryExhausted(ConnectionError):
    """Every attempt of a retried operation failed."""


class RetryPolicy:
    """Bounded retries with exponential backoff + jitter from an
    injected rng (the anti-thundering-herd shape the `retry-no-backoff`
    lint rule enforces repo-wide).

    ``sleep`` is an injected callable; the default advances ``clock``
    when it can (VirtualClock) and otherwise just records the delay --
    the policy never blocks a real thread, so tests replay instantly.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        base_delay_s: float = 0.05,
        factor: float = 2.0,
        max_delay_s: float = 2.0,
        jitter: float = 0.25,
        rng: random.Random | None = None,
        clock=None,
        sleep=None,
        events: EventLog | None = None,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.factor = factor
        self.max_delay_s = max_delay_s
        self.jitter = jitter
        self.rng = rng if rng is not None else random.Random(0)
        self.clock = clock
        self._sleep_fn = sleep
        self.events = events

    def delay_for(self, attempt: int) -> float:
        """Deterministic (given the injected rng) backoff for `attempt`
        (0-based): min(cap, base * factor^attempt) * (1 + jitter*U[0,1))."""
        d = min(self.max_delay_s, self.base_delay_s * self.factor**attempt)
        return d * (1.0 + self.jitter * self.rng.random())

    def _sleep(self, seconds: float) -> None:
        if self._sleep_fn is not None:
            self._sleep_fn(seconds)
        elif self.clock is not None and hasattr(self.clock, "advance"):
            self.clock.advance(seconds)

    def pause(self, attempt: int) -> float:
        """One backoff pause for callers running their own attempt loop
        (e.g. the engine's SYNCING re-poll); returns the delay taken."""
        delay = self.delay_for(attempt)
        if self.events is not None:
            self.events.record(
                "backoff", attempt=attempt, delay_ms=int(delay * 1000)
            )
        self._sleep(delay)
        return delay

    def call(self, fn, retry_on=(ConnectionError, OSError), on_retry=None):
        """Run ``fn()`` with up to ``max_attempts`` tries; backs off
        between attempts and raises ``RetryExhausted`` (chaining the
        last error) when the budget runs out."""
        last = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except retry_on as e:
                last = e
                metrics.RETRY_ATTEMPTS.inc()
                if self.events is not None:
                    self.events.record(
                        "retry", attempt=attempt, error=type(e).__name__
                    )
                if on_retry is not None:
                    on_retry(attempt, e)
                if attempt + 1 < self.max_attempts:
                    delay = self.delay_for(attempt)
                    if self.events is not None:
                        self.events.record(
                            "backoff", attempt=attempt,
                            delay_ms=int(delay * 1000),
                        )
                    self._sleep(delay)
        raise RetryExhausted(
            f"operation failed after {self.max_attempts} attempts: {last!r}"
        ) from last


class TimeoutExceeded(TimeoutError):
    """An operation overran its deadline on the injected clock."""


class Timeout:
    """Cooperative deadline against the injected clock: the wrapped call
    runs to completion, then the elapsed *injected* time is checked --
    FaultPlan delay/hang injections advance the same clock, so an
    injected hang deterministically trips the deadline."""

    def __init__(self, clock, timeout_s: float):
        self.clock = clock
        self.timeout_s = timeout_s

    def call(self, fn, *args, **kwargs):
        t0 = self.clock.now()
        out = fn(*args, **kwargs)
        elapsed = self.clock.now() - t0
        if elapsed > self.timeout_s:
            raise TimeoutExceeded(
                f"operation took {elapsed:.3f}s > {self.timeout_s:.3f}s"
            )
        return out


class BreakerOpen(ConnectionError):
    """The circuit breaker is open; the protected call was not made."""


class CircuitBreaker:
    """closed -> open -> half-open breaker with a re-probe budget
    (reference: the engine/eth1 endpoint state machines that stop
    hammering a dead dependency but keep probing for recovery).

    Re-probe triggers either by injected-clock timeout
    (``reset_timeout_s`` after opening) or -- clock-free, for embedding
    in layers with no clock to thread -- after ``denied_budget``
    rejected ``allow()`` calls. Both are deterministic. The denied
    budget defaults ON so a breaker constructed with no clock still
    matures to half-open instead of denying forever; pass
    ``denied_budget=None`` with a clock for pure-timeout behavior.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(
        self,
        clock=None,
        failure_threshold: int = 3,
        reset_timeout_s: float = 30.0,
        half_open_probes: int = 1,
        denied_budget: int | None = 8,
        events: EventLog | None = None,
        name: str = "breaker",
    ):
        self.clock = clock
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_probes = half_open_probes
        self.denied_budget = denied_budget
        self.events = events
        self.name = name
        self.state = self.CLOSED
        self.transitions: list[tuple[str, str]] = []
        self._failures = 0
        self._denied = 0
        self._probes_left = 0
        self._opened_at = 0.0

    def _transition(self, new_state: str) -> None:
        old, self.state = self.state, new_state
        self.transitions.append((old, new_state))
        metrics.BREAKER_TRANSITIONS.inc()
        if self.events is not None:
            self.events.record(
                "breaker", name=self.name, frm=old, to=new_state
            )

    def allow(self) -> bool:
        """May the protected operation run right now? Open breakers deny
        until the re-probe budget matures, then admit a half-open probe."""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN:
            matured = False
            if self.clock is not None:
                matured = (
                    self.clock.now() - self._opened_at >= self.reset_timeout_s
                )
            if not matured and self.denied_budget is not None:
                self._denied += 1
                matured = self._denied >= self.denied_budget
            if not matured:
                return False
            self._transition(self.HALF_OPEN)
            self._probes_left = self.half_open_probes
        # half-open: admit probes while the budget lasts
        if self._probes_left > 0:
            self._probes_left -= 1
            return True
        return False

    def record_success(self) -> None:
        self._failures = 0
        if self.state != self.CLOSED:
            self._transition(self.CLOSED)
        self._denied = 0

    def record_failure(self) -> None:
        if self.state == self.HALF_OPEN:
            self._reopen()
            return
        self._failures += 1
        if self.state == self.CLOSED and self._failures >= self.failure_threshold:
            self._reopen()

    def _reopen(self) -> None:
        if self.state != self.OPEN:
            self._transition(self.OPEN)
        self._failures = 0
        self._denied = 0
        self._probes_left = 0
        if self.clock is not None:
            self._opened_at = self.clock.now()

    def call(self, fn, failure_types=(ConnectionError, OSError)):
        """Run ``fn()`` under the breaker: raises ``BreakerOpen`` without
        calling when open; records the outcome otherwise."""
        if not self.allow():
            raise BreakerOpen(f"{self.name} is {self.state}")
        try:
            out = fn()
        except failure_types:
            self.record_failure()
            raise
        self.record_success()
        return out


class AllEndpointsFailed(ConnectionError):
    """Every ranked endpoint failed (or was skipped) in a failover pass.
    ``last`` carries the final endpoint error, None if nothing was
    attempted."""

    def __init__(self, msg: str, last: BaseException | None = None):
        super().__init__(msg)
        self.last = last


class HealthTracker:
    """Per-endpoint health scores over a sliding window of recent call
    outcomes (the beacon_node_fallback.rs candidate-ranking seat).

    * ``score`` is the success fraction of the last ``window`` outcomes;
      unknown endpoints score 1.0 (optimistic -- a fresh endpoint is
      tried before a known-bad one).
    * ``ranked`` keeps eligible endpoints in input (priority) order and
      sinks demoted ones (score < threshold) to the back until their
      re-probe budget matures -- by injected-clock time
      (``reprobe_after_s``) or, clock-free, after being passed over
      ``reprobe_after_skips`` times -- so a recovered endpoint wins its
      priority slot back instead of being demoted forever.
    """

    def __init__(
        self,
        clock=None,
        window: int = 8,
        threshold: float = 0.5,
        reprobe_after_s: float | None = None,
        reprobe_after_skips: int = 4,
        events: EventLog | None = None,
        name: str = "endpoints",
    ):
        self.clock = clock
        self.window = window
        self.threshold = threshold
        self.reprobe_after_s = reprobe_after_s
        self.reprobe_after_skips = reprobe_after_skips
        self.events = events
        self.name = name
        self._outcomes: dict = {}
        self._last_failure: dict = {}
        self._skips: dict = {}

    def record(self, key, ok: bool) -> None:
        dq = self._outcomes.get(key)
        if dq is None:
            dq = self._outcomes[key] = deque(maxlen=self.window)
        was_healthy = self.is_healthy(key)
        dq.append(bool(ok))
        self._skips[key] = 0
        if not ok and self.clock is not None:
            self._last_failure[key] = self.clock.now()
        metrics.ENDPOINT_HEALTH.set(f"{self.name}/{key}", self.score(key))
        if self.events is not None and was_healthy and not self.is_healthy(key):
            self.events.record("demoted", name=self.name, key=str(key))

    def score(self, key) -> float:
        dq = self._outcomes.get(key)
        if not dq:
            return 1.0
        return sum(dq) / len(dq)

    def is_healthy(self, key) -> bool:
        return self.score(key) >= self.threshold

    def reprobe_due(self, key) -> bool:
        """A demoted endpoint's re-probe budget has matured."""
        if self.clock is not None and self.reprobe_after_s is not None:
            last = self._last_failure.get(key)
            return (
                last is None
                or self.clock.now() - last >= self.reprobe_after_s
            )
        return self._skips.get(key, 0) >= self.reprobe_after_skips

    def eligible(self, key) -> bool:
        return self.is_healthy(key) or self.reprobe_due(key)

    def ranked(self, keys) -> list:
        """Keys ordered best-first: ELIGIBLE endpoints in input order
        (input order is the operator's priority list -- a recovered
        primary must win its slot back from a healthy-but-lagging
        fallback, so scores demote and re-probe, they never permanently
        reorder the healthy set), then demoted-and-not-yet-reprobable
        endpoints by descending score as a last resort. A matured
        re-probe is eligible, so it actually receives a probe whose
        outcome immediately re-scores it. Each pass over a demoted key
        spends one skip of its clock-free re-probe budget."""
        keys = list(keys)
        eligible, demoted = [], []
        for k in keys:
            (eligible if self.eligible(k) else demoted).append(k)
        for k in demoted:
            self._skips[k] = self._skips.get(k, 0) + 1
        return eligible + sorted(demoted, key=lambda k: -self.score(k))

    def failover(
        self,
        targets,
        fn,
        retry_on=(ConnectionError, OSError),
        skip=None,
        on_error=None,
    ):
        """THE ranked-failover loop (shared by the eth1 multi-provider
        and the VC beacon-node fallback): try ``fn(target)`` over
        targets in ranked order, recording each outcome by index.
        Returns ``(index, result)`` of the first success; raises
        ``AllEndpointsFailed`` (carrying the last error) when every
        target failed or was skipped."""
        targets = list(targets)
        last = None
        for i in self.ranked(range(len(targets))):
            target = targets[i]
            if skip is not None and skip(target):
                continue
            try:
                out = fn(target)
            except retry_on as e:
                self.record(i, False)
                if on_error is not None:
                    on_error(i, e)
                last = e
                continue
            self.record(i, True)
            return i, out
        raise AllEndpointsFailed(
            f"all {len(targets)} endpoints failed or were skipped",
            last=last,
        ) from last
