"""Deterministic crash injection for the store layer: the process-death
counterpart of ``faults.FaultPlan``.

A seeded ``CrashPlan`` decides, per intercepted kv mutation and in op
order, whether the "process" survives the op, dies before it, dies
right after it, or tears it (a partial, unsynced write reaches the
store and THEN the process dies — the torn-FileStore-batch case). Same
seed => same crash schedule, the same determinism contract the chaos
suite asserts for FaultPlan.

``CrashingStore`` wraps any ``KeyValueStore`` and routes every put and
delete through the plan. It inherits the journaled ``do_atomically``
from the base class, so crash indices land exactly where a real crash
would: on the write-ahead intent record, between applied ops, and on
the commit-marker delete. Tests crash at EVERY op index of a batch,
"reopen" the inner store the way a restarted node would
(``HotColdDB(inner, ...)`` runs journal recovery), and assert the
result is byte-identical to either the pre-batch or post-batch state.

``InjectedCrash`` subclasses BaseException ON PURPOSE: a process death
must not be swallowable by any ``except Exception`` recovery path in
production code — only the test harness catches it.
"""

from __future__ import annotations

import random

from ..store.kv import KeyValueStore
from .primitives import EventLog


class InjectedCrash(BaseException):
    """The simulated process death (uncatchable by `except Exception`)."""


OK = "ok"
CRASH = "crash"  # die BEFORE the op: nothing reaches the store
TORN = "torn"  # half the value reaches the store, then die
AFTER = "after"  # the op completes, then die


class CrashPlan:
    """A seeded schedule of process deaths, counted in store ops.

    Pinned mode: ``crash_at=N`` kills the Nth intercepted mutation with
    ``action`` (CRASH/TORN/AFTER) — the exhaustive-matrix driver.
    Random mode: each op draws from the seeded rng and dies with
    probability ``crash_rate``. Every death is recorded in ``events``
    for replay comparison; after the first death the plan passes
    everything through (the "process" is already gone — a reopened
    store must not re-crash on recovery's own writes).
    """

    def __init__(
        self,
        seed: int = 0,
        crash_at: int | None = None,
        action: str = CRASH,
        crash_rate: float = 0.0,
        events: EventLog | None = None,
    ):
        self.seed = seed
        self.rng = random.Random(seed)
        self.crash_at = crash_at
        self.action = action
        self.crash_rate = crash_rate
        self.events = events if events is not None else EventLog()
        self.ops = 0
        self.crashed = False

    def arm(self, ops_from_now: int, action: str | None = None) -> "CrashPlan":
        """Phase-scoped (re-)arming: schedule the next death
        `ops_from_now` intercepted mutations from NOW. The scenario
        harness composes one plan across phases (arm it when the
        crash-recovery phase starts) instead of wrapping a fresh store
        mid-run; re-arming after a death models a node that dies again."""
        self.crash_at = self.ops + int(ops_from_now)
        if action is not None:
            self.action = action
        self.crashed = False
        return self

    def decide(self, op: str) -> str:
        index = self.ops
        self.ops += 1
        if self.crashed:
            return OK
        verdict = OK
        if self.crash_at is not None:
            if index == self.crash_at:
                verdict = self.action
        elif self.crash_rate and self.rng.random() < self.crash_rate:
            verdict = self.action
        if verdict != OK:
            self.crashed = True
            self.events.record("crash", op=op, index=index, action=verdict)
        return verdict


class CrashingStore(KeyValueStore):
    """KeyValueStore wrapper that dies at the Nth mutation op.

    Reads (`get`/`keys`) pass through uncounted — a crash schedule in
    store ops must not shift when a code path adds a lookup. The
    journaled base `do_atomically` is inherited unchanged, so batch
    crash points are exactly the journal write, each applied op, and
    the commit-marker delete."""

    def __init__(self, inner: KeyValueStore, plan: CrashPlan):
        self.inner = inner
        self.plan = plan

    def get(self, column, key):
        return self.inner.get(column, key)

    def keys(self, column):
        return self.inner.keys(column)

    def put(self, column, key, value):
        verdict = self.plan.decide("put")
        if verdict == CRASH:
            raise InjectedCrash(f"died before put (op {self.plan.ops - 1})")
        if verdict == TORN:
            value = bytes(value)
            self.inner.put(column, key, value[: len(value) // 2])
            raise InjectedCrash(f"torn put (op {self.plan.ops - 1})")
        self.inner.put(column, key, value)
        if verdict == AFTER:
            raise InjectedCrash(f"died after put (op {self.plan.ops - 1})")

    def delete(self, column, key):
        verdict = self.plan.decide("delete")
        if verdict in (CRASH, TORN):
            # a delete has no partial form: torn == died before
            raise InjectedCrash(f"died before delete (op {self.plan.ops - 1})")
        self.inner.delete(column, key)
        if verdict == AFTER:
            raise InjectedCrash(f"died after delete (op {self.plan.ops - 1})")
