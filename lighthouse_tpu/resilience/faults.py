"""Deterministic fault injection: seeded schedules of errors, delays,
and hangs wrappable around any provider/backend/engine duck type.

``FaultPlan`` decides, per intercepted call and in call order, whether
to pass through, raise, inject latency, or hang. Decisions come from a
``random.Random(seed)`` plus optional per-operation scripts, so the
same seed and the same call sequence replay the same fault schedule --
the determinism contract the chaos tests assert.

Fault types deliberately subclass the stdlib transport errors
(``ConnectionError`` / ``TimeoutError``) so every existing narrow
handler in the stack -- sync's ``except (ConnectionError, OSError)``,
the eth1/engine retry paths -- treats injected faults exactly like real
ones, with no test-only branches in production code.
"""

from __future__ import annotations

import random

from .primitives import EventLog


class FaultInjected(ConnectionError):
    """An injected transport/backend error."""


class InjectedHang(TimeoutError):
    """An injected hang: the call never completes within any deadline.
    The plan advances the injected clock past ``hang_s`` first, so
    ``Timeout``-style deadline checks see the elapsed time too."""


OK = "ok"
ERROR = "error"
DELAY = "delay"
HANG = "hang"


class FaultPlan:
    """A seeded schedule of faults.

    Random mode: each intercepted call draws once from the seeded rng
    and maps the draw onto (error | delay | hang | ok) by the configured
    rates. Scripted mode: ``script(op, [ERROR, OK, DELAY, ...])`` pins
    the first N decisions for one operation (matched by exact
    ``"name.method"`` or bare proxy ``name``); the rng covers the rest.

    ``clock`` (a VirtualClock or anything with ``advance``) absorbs
    injected latency; ``events`` records every non-ok decision for
    replay comparison.
    """

    def __init__(
        self,
        seed: int = 0,
        error_rate: float = 0.0,
        delay_rate: float = 0.0,
        hang_rate: float = 0.0,
        delay_s: float = 0.1,
        hang_s: float = 60.0,
        clock=None,
        events: EventLog | None = None,
    ):
        self.seed = seed
        self.rng = random.Random(seed)
        self.error_rate = error_rate
        self.delay_rate = delay_rate
        self.hang_rate = hang_rate
        self.delay_s = delay_s
        self.hang_s = hang_s
        self.clock = clock
        self.events = events if events is not None else EventLog()
        self._scripts: dict[str, list[str]] = {}
        self.calls = 0
        self.injected = 0

    # -- schedule ------------------------------------------------------------

    def script(self, op: str, actions) -> "FaultPlan":
        """Pin the next decisions for `op` ("name.method" or bare proxy
        name); entries may also be ``("delay", seconds)`` tuples."""
        self._scripts.setdefault(op, []).extend(actions)
        return self

    def fail_next(self, op: str, n: int = 1) -> "FaultPlan":
        return self.script(op, [ERROR] * n)

    def set_rates(
        self,
        error_rate: float = 0.0,
        delay_rate: float = 0.0,
        hang_rate: float = 0.0,
    ) -> "FaultPlan":
        """Phase-scoped rate swap (the scenario harness composes ONE plan
        across adversarial phases instead of re-wrapping transports
        mid-run). Determinism is preserved across phases: every
        intercepted call draws from the seeded rng exactly once whatever
        the rates, so changing a phase's rates never shifts the schedule
        of later phases."""
        self.error_rate = float(error_rate)
        self.delay_rate = float(delay_rate)
        self.hang_rate = float(hang_rate)
        return self

    def clear_scripts(self) -> None:
        """Drop all pending scripted decisions ("the outage ends"); the
        seeded rng keeps scheduling."""
        self._scripts.clear()

    def _draw(self) -> str:
        r = self.rng.random()
        if r < self.error_rate:
            return ERROR
        if r < self.error_rate + self.delay_rate:
            return DELAY
        if r < self.error_rate + self.delay_rate + self.hang_rate:
            return HANG
        return OK

    def decide(self, op: str):
        """The (action, detail) for the next call of `op`. Scripted
        decisions are consumed first; otherwise the seeded rng draws."""
        self.calls += 1
        action = None
        for key in (op, op.split(".", 1)[0]):
            queue = self._scripts.get(key)
            if queue:
                action = queue.pop(0)
                break
        if action is None:
            action = self._draw()
        seconds = None
        if isinstance(action, tuple):
            action, seconds = action
        if action == DELAY and seconds is None:
            seconds = self.delay_s
        if action == HANG and seconds is None:
            seconds = self.hang_s
        if action != OK:
            self.injected += 1
            self.events.record("fault", op=op, action=action)
        return action, seconds

    def apply(self, op: str) -> None:
        """Consume one decision for `op` and enact it (raise / advance
        the clock / pass). Called by the proxy before the real method."""
        action, seconds = self.decide(op)
        if action == OK:
            return
        if action == DELAY:
            if self.clock is not None:
                self.clock.advance(seconds)
            return
        if action == HANG:
            if self.clock is not None:
                self.clock.advance(seconds)
            raise InjectedHang(f"injected hang in {op}")
        raise FaultInjected(f"injected fault in {op}")

    # -- wrapping ------------------------------------------------------------

    def wrap(self, target, name: str, methods=None) -> "FaultyProxy":
        """A proxy over `target` whose method calls consult this plan.
        `methods` restricts interception to the named methods (all
        public callables by default)."""
        return FaultyProxy(self, target, name, methods)


class FaultyProxy:
    """Duck-type-preserving wrapper: attribute access passes through to
    the target; intercepted method calls first run the plan's decision
    for ``"{name}.{method}"``."""

    def __init__(self, plan: FaultPlan, target, name: str, methods=None):
        object.__setattr__(self, "_plan", plan)
        object.__setattr__(self, "_target", target)
        object.__setattr__(self, "_name", name)
        object.__setattr__(
            self, "_methods", set(methods) if methods is not None else None
        )

    def __getattr__(self, attr):
        value = getattr(self._target, attr)
        if not callable(value) or attr.startswith("_"):
            return value
        if self._methods is not None and attr not in self._methods:
            return value
        plan, name = self._plan, self._name

        def intercepted(*args, **kwargs):
            plan.apply(f"{name}.{attr}")
            return value(*args, **kwargs)

        return intercepted

    def __setattr__(self, attr, value):
        setattr(self._target, attr, value)
